"""Mining-side telemetry: ``pickles/job_metrics.prom`` (textfile format).

The mining job is a batch pod — there is no ``/metrics`` endpoint to
scrape because there is no server; the reference's only telemetry is
stdout log lines (SURVEY.md §5) and the rebuild added ``PhaseTimer``
log lines, which a fleet cannot aggregate. The standard k8s answer is
the node-exporter *textfile collector*: the job writes a Prometheus
text-format file to a path a sidecar/exporter watches, and the fleet's
Prometheus sees mining progress/duration/bytes like any other series.

This writer follows the repo's artifact discipline:

- every render goes through :func:`~..io.artifacts.atomic_write_text`
  (tmp + ``os.replace``), so a scrape can never read a torn file — the
  same atomic-write invariant kmls-verify enforces for every PVC write;
- the file is rewritten after every phase, so a preempted job leaves
  behind the telemetry of the phases it DID finish, and a resumed job
  (mining/checkpoint.py duration annotations) reports the compute it
  skipped as ``kmls_job_phase_resumed`` — observability of the resume
  itself, not just the fresh run;
- every series name is looked up in
  :data:`~..serving.metrics.METRIC_REGISTRY` at render time (KeyError =
  a series someone forgot to register), and kmls-verify's ``metrics``
  checker enforces the same statically, so the textfile can't drift
  from the registry any more than ``/metrics`` can.

The file deliberately stays OUT of ``artifacts.manifest.json``: the
manifest checksums the *served* artifact set frozen at publication,
while this file keeps changing across the run — manifesting it would
make every mid-run scrape look like a torn publication.
"""

from __future__ import annotations

import logging
import os
import time

from ..io import artifacts
from ..serving.metrics import METRIC_REGISTRY

logger = logging.getLogger("kmlserver_tpu.mining")

JOB_METRICS_FILENAME = "job_metrics.prom"


def _fmt(value: float) -> str:
    # Prometheus floats; integers render without a trailing .0 for
    # byte/flag series readability
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


class JobMetrics:
    """One mining run's counters, rewritten atomically as they move.
    Writer-rank only (the pipeline never constructs one on non-zero
    ranks — same discipline as artifact writes)."""

    def __init__(self, pickles_dir: str):
        self.path = os.path.join(pickles_dir, JOB_METRICS_FILENAME)
        self.t_start = time.time()
        # phase -> {"duration_s": float, "resumed": bool}
        self.phases: dict[str, dict] = {}
        self.dataset: dict[str, float] = {}
        self.artifact_bytes: dict[str, int] = {}
        # phase -> (flops, bytes_moved): analytic per-phase attribution
        # (ISSUE 12) from costmodel.phase_cost — what the phase's
        # dominant kernel computed/moved, same formulas the serving MFU
        # uses, so the two sides' numbers are comparable
        self.phase_cost: dict[str, tuple[float, float]] = {}
        self.rule_generation_s: float | None = None
        self.fencing_token: int | None = None
        # (count_path, source) from the measured dispatch (ISSUE 13)
        self.count_path: tuple[str, str] | None = None
        self.success = 0

    # ---------- accumulation ----------

    def phase_done(
        self, name: str, duration_s: float, resumed: bool = False
    ) -> None:
        """Record one pipeline phase (computed or checkpoint-resumed; a
        resumed phase reports the ORIGINAL compute duration from the
        checkpoint's span annotation, flagged ``resumed=1``), then
        persist — a preemption right after this call still leaves the
        phase's telemetry on the PVC."""
        self.phases[name] = {
            "duration_s": max(duration_s, 0.0), "resumed": bool(resumed),
        }
        self.write()

    def set_dataset(
        self, rows: int, playlists: int, tracks: int
    ) -> None:
        self.dataset = {
            "kmls_job_rows": rows,
            "kmls_job_playlists": playlists,
            "kmls_job_tracks": tracks,
        }

    def note_phase_cost(
        self, phase: str, flops: float, bytes_moved: float
    ) -> None:
        """Attach the analytic FLOPs/bytes attribution of ``phase``'s
        dominant kernel (costmodel.phase_cost), then persist — cost
        telemetry must survive a preemption exactly like durations."""
        self.phase_cost[phase] = (max(flops, 0.0), max(bytes_moved, 0.0))
        self.write()

    def note_count_path(self, path: str, source: str) -> None:
        """Record which pair-count family the measured dispatcher chose
        and why (``override``/``threshold``/``table``/``heuristic``) —
        the plan-time decision surfaced as a labeled gauge so the fleet
        can see WHICH kernel mined each generation, then persist."""
        self.count_path = (path, source)
        self.write()

    def note_artifact(self, name: str, path: str) -> None:
        try:
            self.artifact_bytes[name] = os.path.getsize(path)
        except OSError:
            pass

    def finish(
        self,
        success: bool,
        rule_generation_s: float | None = None,
        fencing_token: int | None = None,
    ) -> None:
        self.success = int(bool(success))
        if rule_generation_s is not None:
            self.rule_generation_s = rule_generation_s
        if fencing_token is not None:
            self.fencing_token = fencing_token
        self.write()

    # ---------- exposition ----------

    @staticmethod
    def _type_of(name: str) -> str:
        # "counter:mining" / "gauge:mining" — KeyError here means an
        # unregistered series, the exact drift the registry forbids
        return METRIC_REGISTRY[name].split(":", 1)[0]

    def render(self) -> str:
        lines: list[str] = []

        def series(name: str, value: float, labels: str = "") -> None:
            if not any(line.startswith(f"# TYPE {name} ") for line in lines):
                lines.append(f"# TYPE {name} {self._type_of(name)}")
            lines.append(f"{name}{labels} {_fmt(value)}")

        for phase in sorted(self.phases):
            entry = self.phases[phase]
            series(
                "kmls_job_phase_duration_seconds",
                entry["duration_s"], f'{{phase="{phase}"}}',
            )
        for phase in sorted(self.phases):
            series(
                "kmls_job_phase_resumed",
                int(self.phases[phase]["resumed"]), f'{{phase="{phase}"}}',
            )
        for phase in sorted(self.phase_cost):
            series(
                "kmls_job_phase_flops",
                self.phase_cost[phase][0], f'{{phase="{phase}"}}',
            )
        for phase in sorted(self.phase_cost):
            series(
                "kmls_job_phase_bytes_moved",
                self.phase_cost[phase][1], f'{{phase="{phase}"}}',
            )
        if self.count_path is not None:
            series(
                "kmls_job_count_path", 1,
                f'{{path="{self.count_path[0]}",'
                f'source="{self.count_path[1]}"}}',
            )
        for name, value in self.dataset.items():
            series(name, value)
        for artifact in sorted(self.artifact_bytes):
            series(
                "kmls_job_artifact_bytes",
                self.artifact_bytes[artifact],
                f'{{artifact="{artifact}"}}',
            )
        if self.rule_generation_s is not None:
            series(
                "kmls_job_rule_generation_seconds", self.rule_generation_s
            )
        if self.fencing_token is not None:
            series("kmls_job_fencing_token", self.fencing_token)
        series("kmls_job_duration_seconds", time.time() - self.t_start)
        series("kmls_job_success", self.success)
        if self.success:
            series("kmls_job_last_success_timestamp_seconds", time.time())
        return "\n".join(lines) + "\n"

    def write(self) -> None:
        # KeyError from an unregistered series must propagate (that's the
        # registry's drift protection) — render OUTSIDE the guard.
        text = self.render()
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            # atomic + retried via the shared writer, but durable=False:
            # telemetry does not need an fsync per phase, and a textfile
            # lost to a crash is regenerated by the next run anyway
            artifacts.atomic_write_text(self.path, text, durable=False)
        except OSError as exc:
            # Telemetry is best-effort BY CONTRACT: a transient PVC error
            # (ENOSPC, EIO, stale NFS handle) on this file must never fail
            # a mining run whose real artifacts are fine — especially not
            # finish(True), which runs AFTER publication succeeded.
            logger.warning("job_metrics write skipped (%s): %s", self.path, exc)
