"""Runtime-health collection: event-loop lag + inline-kernel stalls.

The PR 8 postmortem (ROADMAP "load-adaptive serving") names the blind
spot this closes: when the native CPU serve kernel computes ON the
asyncio event loop (the inline fast path — sub-millisecond when
healthy), a stalled kernel blocks the loop itself. Requests pile into
the socket accept backlog where the admission controller's queue-wait
projection cannot see them — the projection measures the batcher's
queue, and nothing ever reaches the batcher while the loop is wedged.
Verified with an injected 200 ms kernel delay: the executor path sheds
correctly, the inline path answered everything late.

:class:`LoopLagMonitor` measures the stall from two directions:

- a **timer-drift tick**: ``loop.call_later`` re-arms every
  ``interval_s``; the difference between when the tick was due and when
  it actually ran IS the time something blocked the loop (the same
  technique node.js exposes as ``eventLoopDelay``). A thread variant
  (:meth:`start_thread`) gives the threaded transport host-scheduling
  visibility with the same signal shape.
- a **direct stall note**: the async batcher's inline branch times the
  in-line ``finish()`` call and reports it via :meth:`note` — the
  synchronous ground truth, available the instant the loop unblocks
  (the drift tick only runs one loop iteration later).

The signal is a peak-hold with exponential decay (half-life
``half_life_s``): one 200 ms stall registers immediately and fades over
~a second instead of flapping per tick. It is exported at ``/metrics``
as ``kmls_loop_lag_ms`` and — the part that closes the blind spot —
folded into :class:`~..serving.batcher.AdmissionController` pressure
via ``lag_source``, so a wedged loop escalates the admission ladder
(degrade → shed) exactly like a saturated queue would. All state is
plain floats, single-writer-ish with benign races — no locks on any
hot path (the controller's documented discipline).
"""

from __future__ import annotations

import math
import threading
import time


class LoopLagMonitor:
    """Peak-hold, time-decaying lag estimate for one event loop (or the
    host scheduler, under the thread driver)."""

    def __init__(self, interval_s: float = 0.05, half_life_s: float = 1.0):
        self.interval_s = max(interval_s, 0.005)
        self.half_life_s = max(half_life_s, 0.05)
        self._lag = 0.0
        self._noted_at = 0.0
        self.ticks = 0  # drift-tick count (diagnostics/tests)
        self._running = False
        self._thread: threading.Thread | None = None

    # ---------- signal ----------

    def note(self, lag_s: float, now: float | None = None) -> None:
        """Fold one measured blockage (seconds) into the estimate.
        Peak-hold: a new stall larger than the decayed current value
        replaces it; smaller ones leave the decaying peak in place (the
        admission ladder must see the worst recent stall, not a mean
        diluted by healthy ticks)."""
        if lag_s <= 0.0:
            return
        now = time.perf_counter() if now is None else now
        if lag_s >= self._decayed(now):
            self._lag = lag_s
            self._noted_at = now

    def _decayed(self, now: float) -> float:
        if self._lag <= 0.0:
            return 0.0
        age = max(now - self._noted_at, 0.0)
        return self._lag * math.exp(-age * math.log(2) / self.half_life_s)

    def lag_s(self, now: float | None = None) -> float:
        """The current decayed lag estimate (seconds). Cheap enough for
        the admission hot path: two floats and an exp."""
        return self._decayed(time.perf_counter() if now is None else now)

    # ---------- drivers ----------

    def start_on_loop(self, loop) -> None:
        """Arm the drift tick on an asyncio loop (call from the loop
        thread). Re-arms itself forever; daemon-equivalent — the loop's
        shutdown cancels nothing because each handle is one-shot and the
        process exits with the loop."""
        if self._running:
            return
        self._running = True
        expected = [time.perf_counter() + self.interval_s]

        def tick() -> None:
            now = time.perf_counter()
            self.ticks += 1
            self.note(max(now - expected[0], 0.0), now=now)
            expected[0] = now + self.interval_s
            loop.call_later(self.interval_s, tick)

        loop.call_later(self.interval_s, tick)

    def start_thread(self) -> threading.Thread | None:
        """Thread driver for the threaded transport: the same drift
        signal measured against ``time.sleep`` — host scheduling stalls
        (CPU starvation, GIL convoy) show up the same way loop stalls
        do. Daemon thread; runs for the process lifetime. Re-entry
        safe like :meth:`start_on_loop`: the thread is immortal, so a
        second driver would double-count ticks for the process
        lifetime with no way to stop either."""
        if self._running:
            return self._thread
        self._running = True

        def loop_() -> None:
            while True:
                expected = time.perf_counter() + self.interval_s
                time.sleep(self.interval_s)
                now = time.perf_counter()
                self.ticks += 1
                self.note(max(now - expected, 0.0), now=now)

        thread = threading.Thread(
            target=loop_, daemon=True, name="kmls-loop-lag"
        )
        self._thread = thread
        thread.start()
        return thread
