"""SLO burn rates (ISSUE 12): multi-window budget consumption computed
from the counters and fixed-bucket histograms PR 9 already exports.

An SLO is a target over a window ("99% of requests under 25 ms", "99.9%
answered without error or shed"); the *burn rate* is how fast the error
budget is being consumed relative to plan — burn 1.0 means the budget
exactly runs out at the window's end, burn 14 means a 30-day budget dies
in ~2 days. The standard multi-window alerting recipe pairs a FAST
window (catches a cliff in minutes) with a SLOW window (confirms it is
not a blip); both are computed here from windowed deltas of the same
cumulative counters Prometheus would use, so a pod with no Prometheus
still gets the numbers at ``GET /debug/slo``.

Three SLOs:

- ``latency_p99`` — fraction of batched requests slower than
  ``KMLS_SLO_P99_MS`` (read from the ``kmls_e2e_seconds`` fixed-bucket
  histogram; the target is snapped UP to the nearest bucket boundary —
  fixed buckets are the whole point, and the snap is the histogram's
  honest resolution). Budget: 1% (the p99 in the name).
- ``availability`` — errors + sheds over attempts, budget
  ``KMLS_SLO_ERROR_BUDGET``.
- ``quality`` — degraded answers (deadline / replica-loss / overload,
  the 200-but-fallback contract) over attempts, budget
  ``KMLS_SLO_DEGRADE_BUDGET``.

Observability ONLY, by design: the PR 8 admission ladder stays the
actuator. Nothing here runs on the request path — the tracker samples
cumulative counters lazily when ``/metrics`` or ``/debug/slo`` reads it,
so the disabled/idle cost is structurally zero.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time

WINDOWS = ("fast", "slow")
SLOS = ("latency_p99", "availability", "quality")


class SloTracker:
    """Windowed burn rates over a :class:`~..serving.metrics
    .ServingMetrics`. Samples are (monotonic time, cumulative counters)
    pairs appended at most once per ``sample_interval_s`` whenever a
    reader shows up, pruned past the slow window — a scraper at any
    reasonable period keeps both windows live, and an unscraped pod
    costs nothing."""

    def __init__(
        self,
        metrics,
        *,
        p99_target_ms: float = 25.0,
        error_budget: float = 0.001,
        degrade_budget: float = 0.01,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        clock=time.monotonic,
    ):
        self.metrics = metrics
        self.p99_target_ms = max(p99_target_ms, 0.0)
        self.error_budget = max(error_budget, 1e-9)
        self.degrade_budget = max(degrade_budget, 1e-9)
        self.fast_window_s = max(fast_window_s, 1.0)
        self.slow_window_s = max(slow_window_s, self.fast_window_s)
        self.sample_interval_s = max(
            0.5, min(self.fast_window_s / 30.0, 10.0)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: "collections.deque[tuple[float, dict]]" = (
            collections.deque()
        )
        # the histogram boundary the latency target snapped to (seconds)
        buckets = self.metrics.e2e_hist.buckets
        target_s = self.p99_target_ms / 1e3
        idx = bisect.bisect_left(buckets, target_s)
        self.latency_boundary_s = (
            buckets[idx] if idx < len(buckets) else float("inf")
        )
        self._boundary_idx = idx

    # ---------- counter snapshots ----------

    def _counters(self) -> dict:
        """One cumulative snapshot of the SLO inputs (cheap: a few ints
        under the metrics lock + one histogram snapshot)."""
        m = self.metrics
        with m._lock:
            requests = m.requests_total
            errors = m.errors_total
            shed = m.shed_total
            degraded = sum(m.degraded_by_reason.values())
        counts, _sum, total = m.e2e_hist.snapshot()
        # counts[i] = observations in band i, band i ≤ buckets[i]; every
        # band up to (and including) the snapped boundary is within SLO
        within = sum(counts[: self._boundary_idx + 1])
        return {
            "attempts": requests + errors + shed,
            "bad_availability": errors + shed,
            "bad_quality": degraded,
            "latency_total": total,
            "latency_slow": total - within,
        }

    def _ensure_sample(self, now: float | None = None) -> dict:
        """Record a sample if the last one is stale → the CURRENT
        cumulative counters (always fresh, never the stored sample)."""
        now = self._clock() if now is None else now
        cur = self._counters()
        with self._lock:
            if (
                not self._samples
                or now - self._samples[-1][0] >= self.sample_interval_s
            ):
                self._samples.append((now, cur))
            horizon = now - self.slow_window_s - 2 * self.sample_interval_s
            while len(self._samples) > 1 and self._samples[0][0] < horizon:
                self._samples.popleft()
        return cur

    def _reference(self, now: float, window_s: float) -> dict | None:
        """The newest sample at least ``window_s`` old — the delta base.
        Falls back to the OLDEST sample when the window isn't covered
        yet (a young pod reports over its lifetime, not zeros)."""
        with self._lock:
            ref = None
            for t, snap in self._samples:
                if t <= now - window_s:
                    ref = snap
                else:
                    break
            if ref is None and self._samples:
                ref = self._samples[0][1]
        return ref

    # ---------- burn rates ----------

    @staticmethod
    def _burn(bad: float, total: float, budget: float) -> float:
        if total <= 0:
            return 0.0
        return (bad / total) / budget

    def burn_rates(self, now: float | None = None) -> dict[str, dict[str, float]]:
        """→ ``{slo: {window: burn}}`` for the three SLOs over both
        windows. Burn 1.0 = consuming the budget exactly on plan."""
        now = self._clock() if now is None else now
        cur = self._ensure_sample(now)
        out: dict[str, dict[str, float]] = {s: {} for s in SLOS}
        for window, span in (
            ("fast", self.fast_window_s), ("slow", self.slow_window_s)
        ):
            ref = self._reference(now, span) or cur
            d_attempts = cur["attempts"] - ref["attempts"]
            d_lat_total = cur["latency_total"] - ref["latency_total"]
            out["latency_p99"][window] = self._burn(
                cur["latency_slow"] - ref["latency_slow"],
                d_lat_total, 0.01,
            )
            out["availability"][window] = self._burn(
                cur["bad_availability"] - ref["bad_availability"],
                d_attempts, self.error_budget,
            )
            out["quality"][window] = self._burn(
                cur["bad_quality"] - ref["bad_quality"],
                d_attempts, self.degrade_budget,
            )
        return out

    # ---------- exposition ----------

    def render_lines(self) -> list[str]:
        """``kmls_slo_burn_rate{slo, window}`` — always all six series,
        zero-valued while idle, so dashboards can rely on them."""
        rates = self.burn_rates()
        lines = ["# TYPE kmls_slo_burn_rate gauge"]
        for slo in SLOS:
            for window in WINDOWS:
                lines.append(
                    f'kmls_slo_burn_rate{{slo="{slo}",window="{window}"}} '
                    f"{rates[slo][window]:.6g}"
                )
        return lines

    def debug_payload(self) -> dict:
        """The ``GET /debug/slo`` response body: targets, windows, the
        cumulative inputs, and both windows' burn rates."""
        rates = self.burn_rates()
        cur = self._counters()
        return {
            "targets": {
                "latency_p99": {
                    "target_ms": self.p99_target_ms,
                    "bucket_boundary_ms": (
                        self.latency_boundary_s * 1e3
                        if self.latency_boundary_s != float("inf")
                        else None
                    ),
                    "budget": 0.01,
                },
                "availability": {"budget": self.error_budget},
                "quality": {"budget": self.degrade_budget},
            },
            "windows_s": {
                "fast": self.fast_window_s, "slow": self.slow_window_s,
            },
            "counters": cur,
            "burn_rates": rates,
        }
