"""Per-request span tracing with tail-based retention.

The serving stack's metrics (serving/metrics.py) can say WHERE a latency
percentile lives (queue vs device vs e2e) but not WHY one specific p999
request was slow — the reservoirs aggregate away the request identity.
This module is the per-request view: a :class:`TraceContext` rides a
request from the HTTP front end through cache → admission → batcher
queue → replica/shard dispatch → kernel → compose, accumulating named
spans, and a :class:`SpanRecorder` keeps the *interesting* traces in a
bounded ring exposed at ``GET /debug/traces``.

Retention is TAIL-BASED, the only sampling policy that answers tail
questions: head-based sampling at p=0.01 keeps one in a hundred of the
*shed* requests too, so the trace buffer is statistically empty exactly
where the incident is. Here the retention decision happens at FINISH
time, when the outcome is known:

- every non-OK trace (shed / degraded / deadline-exceeded / error) is
  always retained;
- the slowest-N OK traces seen so far are retained (a min-heap of the
  N largest durations — a new tail entrant evicts the fastest member);
- the remaining OK traces are retained with probability
  ``KMLS_TRACE_SAMPLE`` (the baseline that keeps the buffer
  representative of normal traffic).

Zero-cost when off: ``KMLS_TRACE_SAMPLE=0`` (the default) makes
:attr:`SpanRecorder.enabled` False, and every call site checks that one
attribute before allocating anything — no context object, no id
generation, no per-request work. The ``began`` counter proves it the
same way the compile counter proves zero-compile serving: a test drives
traffic with tracing off and asserts the counter never moved.

The trace id travels in the ``X-KMLS-Trace`` header (request:
``<trace_id>`` or ``<trace_id>:<parent_id>``; response echoes the trace
id), so a replay/bench client can join its client-side timing to the
server-side span breakdown for the same request.
"""

from __future__ import annotations

import collections
import heapq
import random
import threading
import time

# ids are [-A-Za-z0-9_.]{1,64}: anything else in the header is treated
# as absent (a hostile or corrupted header must not flow into JSON
# output verbatim beyond this charset)
_ID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_."
)
_MAX_ID_LEN = 64


def _valid_id(s: str) -> bool:
    return 0 < len(s) <= _MAX_ID_LEN and all(c in _ID_OK for c in s)


class TraceContext:
    """One request's spans. Append-only; list.append is GIL-atomic, so
    the batcher's completion thread and the HTTP thread can both record
    without a lock (the same benign-race budget the batcher's in-flight
    counters run on — on the normal path spans are recorded before the
    future resolves, so the finishing thread observes a complete list).
    When the app thread finishes a trace EARLY (deadline expiry, shed),
    the completer may still be running — ``finished`` makes its late
    span() a no-op (best-effort; the check is unsynchronized). The hard
    immutability guarantee lives in :class:`SpanRecorder`, which retains
    a trace as its rendered dict frozen at finish time."""

    __slots__ = (
        "trace_id", "parent_id", "t0", "wall_start",
        "spans", "attrs", "status", "duration_s", "finished",
    )

    def __init__(self, trace_id: str, parent_id: str | None, t0: float):
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.t0 = t0  # perf_counter at begin
        self.wall_start = time.time()
        self.spans: list[tuple[str, float, float, dict | None]] = []
        self.attrs: dict[str, object] = {}
        self.status = "open"
        self.duration_s = 0.0
        self.finished = False

    def span(
        self, name: str, t_start: float, t_end: float,
        attrs: dict | None = None,
    ) -> None:
        """Record a named span (perf_counter endpoints). No-op once the
        trace is finished: a deadline-expired request is retained at
        resolve time, and the kernel's eventual completion must not
        rewrite what ``/debug/traces`` already served."""
        if self.finished:
            return
        self.spans.append((name, t_start, t_end, attrs))

    def annotate(self, key: str, value) -> None:
        self.attrs[key] = value

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "parent_id": self.parent_id,
            "status": self.status,
            "start_unix": round(self.wall_start, 6),
            "duration_ms": round(self.duration_s * 1e3, 4),
            "attrs": dict(self.attrs),
            "spans": [
                {
                    "name": name,
                    "start_ms": round((t_start - self.t0) * 1e3, 4),
                    "duration_ms": round((t_end - t_start) * 1e3, 4),
                    **({"attrs": attrs} if attrs else {}),
                }
                for name, t_start, t_end, attrs in list(self.spans)
            ],
        }


class SpanRecorder:
    """Bounded ring of finished traces with tail-based retention.

    ``sample <= 0`` disables the recorder entirely (``enabled`` False);
    call sites must check ``enabled`` before :meth:`begin` so the
    disabled hot path does literally nothing. The retention lock is
    taken at most twice per FINISHED request (never per span) and guards
    only ring + heap mutation — no I/O, no rendering, no blocking calls
    ever run under it."""

    def __init__(
        self,
        sample: float = 0.0,
        capacity: int = 512,
        slow_n: int = 32,
        rng: random.Random | None = None,
    ):
        self.sample = min(max(sample, 0.0), 1.0)
        self.capacity = max(1, capacity)
        self.slow_n = max(0, slow_n)
        self.enabled = self.sample > 0.0
        # contexts created — the zero-cost proof counter (compile-counter
        # discipline: must stay 0 while tracing is disabled)
        self.began = 0
        self.retained_total = 0
        # retained traces are stored PRE-RENDERED (to_dict at finish
        # time): the live TraceContext stays reachable from the batcher
        # completer, and its `finished` no-op guard on span() is only
        # best-effort (an unsynchronized check the completer can have
        # already passed) — freezing the rendered form is what actually
        # guarantees a scraped trace never changes between scrapes
        self._buf: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity
        )
        # min-heap of the N largest OK durations retained so far: the
        # root is the admission bar a new trace must clear to count as
        # "slowest-N"
        self._slow: list[float] = []
        self._lock = threading.Lock()
        self._rng = rng or random.Random()

    # ---------- lifecycle ----------

    def begin(self, header: str | None = None) -> TraceContext | None:
        """Open a trace for one request; ``header`` is the raw
        ``X-KMLS-Trace`` request value (``id`` or ``id:parent``). Only
        called when :attr:`enabled` — returns None defensively so a
        miswired call site degrades to untraced rather than crashing."""
        if not self.enabled:
            return None
        self.began += 1  # benign race: diagnostic counter, GIL-coalesced
        trace_id = ""
        parent_id: str | None = None
        if header:
            head, _, tail = header.partition(":")
            head = head.strip()
            tail = tail.strip()
            if _valid_id(head):
                trace_id = head
            if tail and _valid_id(tail):
                parent_id = tail
        if not trace_id:
            trace_id = f"{self._rng.getrandbits(64):016x}"
        return TraceContext(trace_id, parent_id, time.perf_counter())

    def finish(
        self, trace: TraceContext, status: str, duration_s: float
    ) -> bool:
        """Close the trace and decide retention → whether it was kept.
        ``status``: ``"ok"`` | ``"shed"`` | ``"degraded"`` | ``"error"``
        (degraded traces carry the reason in ``attrs["reason"]``)."""
        trace.status = status
        trace.duration_s = duration_s
        trace.finished = True  # best-effort: stops further span() appends
        with self._lock:
            keep = status != "ok"
            if not keep and self.slow_n > 0:
                # slowest-N admission: the heap root is the bar
                if len(self._slow) < self.slow_n:
                    heapq.heappush(self._slow, duration_s)
                    keep = True
                elif duration_s > self._slow[0]:
                    heapq.heapreplace(self._slow, duration_s)
                    keep = True
            if not keep:
                keep = self._rng.random() < self.sample
        if keep:
            # render OUTSIDE the lock (allocation-heavy), then append the
            # frozen dict: a completer thread racing past the `finished`
            # check mutates only the live context, never the retained form
            frozen = trace.to_dict()
            with self._lock:
                self._buf.append(frozen)
                self.retained_total += 1
        return keep

    # ---------- exposition ----------

    def retained(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self) -> list[dict]:
        """Retained traces, oldest first (JSON-ready; frozen at finish —
        callers must not mutate the returned dicts)."""
        with self._lock:
            return list(self._buf)

    def debug_payload(self) -> dict:
        """The ``GET /debug/traces`` response body."""
        traces = self.snapshot() if self.enabled else []
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "capacity": self.capacity,
            "slow_n": self.slow_n,
            "began": self.began,
            "retained_total": self.retained_total,
            "traces": traces,
        }
