from . import encode, rules, serve, support  # noqa: F401
