"""ctypes bindings for the native CPU mining kernels
(native/kmls_popcount.cpp) — the CPU-fallback analogue of the device
compute path.

When the backend is CPU (no TPU reachable), XLA:CPU's int8 one-hot matmul
and top_k dominate the mining bracket; the native kernels do the same
exact work an order of magnitude faster:

- :func:`pair_counts` — the ``XᵀX`` pair-count matrix, by either an
  L2-tiled POPCNT scan over bit-packed rows or a sparse per-playlist pair
  scatter whose cost is the pair mass Σ_p C(k_p, 2); a cost model picks
  (:func:`choose_method`).
- :func:`bitpack_rows` — one scatter pass over the membership rows, no
  V×P transient (little bit order: bit p of row t's words ⇔ playlist p
  contains track t; zero padding contributes zero counts).
- :func:`emit_topk` — per-row rule emission with lax.top_k's exact tie
  order via a bounded min-heap.

Build/load follows the CSV loader's pattern (data/native.py, shared
``utils.nativelib``): ``make -C native`` on demand, graceful fallback when
the toolchain or .so is absent, ``KMLS_NATIVE=0`` kills all native paths.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from ..utils import nativelib

# must match kAbiVersion in native/kmls_popcount.cpp
_ABI_VERSION = 4


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.kmls_popcount_abi_version.restype = ctypes.c_int32
    lib.kmls_popcount_abi_version.argtypes = []
    got = lib.kmls_popcount_abi_version()
    if got != _ABI_VERSION:
        raise OSError(
            f"native popcount ABI {got} != expected {_ABI_VERSION} "
            f"(stale build: run make -C native)"
        )
    lib.kmls_pair_counts.restype = None
    lib.kmls_pair_counts.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
    ]
    lib.kmls_bitpack_rows.restype = None
    lib.kmls_bitpack_rows.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.kmls_pair_counts_sparse.restype = None
    lib.kmls_pair_counts_sparse.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.kmls_emit_topk.restype = None
    lib.kmls_emit_topk.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    return lib


_loader = nativelib.NativeLib("libkmls_popcount.so", _bind)


def ensure_built(quiet: bool = True) -> bool:
    nativelib.run_make_once(quiet)
    return os.path.exists(_loader.so_path)


def _load() -> ctypes.CDLL | None:
    return _loader.load()


def available() -> bool:
    return _loader.available()


def bitpack_rows(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
) -> np.ndarray:
    """→ ``(n_tracks, ceil(P/64)) uint64``: bit p of row t set iff playlist
    p contains track t. Duplicate membership rows OR idempotently (same as
    the device one-hot's scatter-max, ops/encode.py).

    Packed by the native scatter — one linear pass over the rows with no
    V×P transient, so it scales to config-4-class shapes (a numpy
    ``packbits`` route needs the full bool matrix: 4.5 GB at a pruned
    1M-playlist input)."""
    rows = np.ascontiguousarray(playlist_rows, dtype=np.int64)
    ids = np.ascontiguousarray(track_ids, dtype=np.int32)
    if len(rows):
        _validate(rows, ids, n_playlists, n_tracks)
    return _bitpack_unchecked(
        rows, ids, n_playlists=n_playlists, n_tracks=n_tracks
    )


def _bitpack_unchecked(
    rows: np.ndarray, ids: np.ndarray, *, n_playlists: int, n_tracks: int
) -> np.ndarray:
    """The scatter itself: contiguous int64/int32 inputs, ALREADY bounds-
    validated by the caller (the C side is unchecked)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native popcount unavailable (build native/ first)")
    w64 = (n_playlists + 63) // 64
    bt = np.zeros((n_tracks, max(w64, 1)), dtype=np.uint64)
    if len(rows):
        lib.kmls_bitpack_rows(
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(len(rows)),
            ctypes.c_int64(bt.shape[1]),
            bt.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
    return bt


def emit_topk(
    counts: np.ndarray, min_count: int, *, k_max: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Native rule emission: per-row top-k by (count desc, column asc) —
    lax.top_k's exact tie order — padded to ``k_max``. Same outputs as
    ``ops.rules.emit_rule_tensors_np`` (which stays as the fallback and
    the cross-check twin).

    Raises RuntimeError when the native library is unavailable."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native popcount unavailable (build native/ first)")
    counts = np.ascontiguousarray(counts, dtype=np.int32)
    v = counts.shape[0]
    k = min(k_max, v)
    rule_ids = np.empty((v, max(k, 0)), dtype=np.int32)
    rule_counts = np.empty((v, max(k, 0)), dtype=np.int32)
    row_valid = np.empty(v, dtype=np.int32)
    if v:
        lib.kmls_emit_topk(
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(v),
            ctypes.c_int32(min_count),
            ctypes.c_int32(k),
            rule_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            rule_counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            row_valid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
    if k < k_max:  # pad up to the declared row capacity
        pad = ((0, 0), (0, k_max - k))
        rule_ids = np.pad(rule_ids, pad, constant_values=-1)
        rule_counts = np.pad(rule_counts, pad)
    return rule_ids, rule_counts, row_valid


def _validate(
    rows: np.ndarray, ids: np.ndarray, n_playlists: int, n_tracks: int
) -> None:
    """Bounds guard the unchecked C kernels (an out-of-range id would be a
    silent out-of-bounds heap write, not an IndexError)."""
    if int(rows.min()) < 0 or int(rows.max()) >= n_playlists:
        raise ValueError(f"playlist_rows out of range [0, {n_playlists})")
    if int(ids.min()) < 0 or int(ids.max()) >= n_tracks:
        raise ValueError(f"track_ids out of range [0, {n_tracks})")


def _effective_threads() -> int:
    """Threads the bitset kernel will actually use (the sparse kernel is
    single-threaded — its scatter targets collide across playlists)."""
    env = int(os.environ.get("KMLS_NATIVE_THREADS", "0"))
    if env > 0:
        return env
    try:
        return min(len(os.sched_getaffinity(0)), 16)
    except AttributeError:  # non-linux
        return min(os.cpu_count() or 4, 16)


def choose_method(
    playlist_rows: np.ndarray, *, n_playlists: int, n_tracks: int
) -> str:
    """Cost-model dispatch between the two exact counters.

    bitset cost ≈ V²/2 · ceil(P/64) sequential popcnt word-ops, divided
    across its threads; sparse cost ≈ Σ_p C(k_p, 2) random scatter-adds
    (+ one counting-sort pass + the V²/2 mirror/memset), single-threaded.
    A scatter-add is ~8× a word-op (random writes into the (V, V) matrix
    vs streamed AND+POPCNT — calibrated on this class of hardware), so
    compare word-op-equivalents. Dense-ish small inputs (ds2) still pick
    bitset; huge sparse inputs (config 4) avoid the V²·W scan entirely."""
    k = np.bincount(playlist_rows, minlength=n_playlists)
    pair_mass = float((k.astype(np.float64) * (k - 1)).sum() / 2.0)
    half_matrix = n_tracks * float(n_tracks) / 2.0
    sparse_cost = 8.0 * pair_mass + 2.0 * len(playlist_rows) + half_matrix
    bitset_cost = (
        half_matrix * ((n_playlists + 63) // 64) / _effective_threads()
    )
    return "sparse" if sparse_cost < bitset_cost else "bitset"


def pair_counts(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    n_threads: int | None = None,
    method: str = "auto",
) -> np.ndarray:
    """Exact ``XᵀX`` pair-count matrix (V, V) int32 via the native kernels.

    ``method``: "auto" (cost model, default), "bitset", or "sparse" —
    identical results, different asymptotics (see :func:`choose_method`).
    Env override ``KMLS_NATIVE_PAIR_METHOD`` beats "auto". PRECONDITION:
    (playlist, track) pairs deduplicated — the Baskets contract — or the
    sparse path double-counts where the bitset path ORs idempotently.

    Raises RuntimeError when the native library is unavailable — callers
    gate on :func:`available` and use the XLA path otherwise."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native popcount unavailable (build native/ first)")
    if n_threads is None:
        n_threads = int(os.environ.get("KMLS_NATIVE_THREADS", "0"))
    if n_tracks == 0 or len(playlist_rows) == 0:
        return np.zeros((n_tracks, n_tracks), dtype=np.int32)
    rows = np.ascontiguousarray(playlist_rows, dtype=np.int64)
    ids = np.ascontiguousarray(track_ids, dtype=np.int32)
    _validate(rows, ids, n_playlists, n_tracks)
    if method == "auto":
        method = os.environ.get("KMLS_NATIVE_PAIR_METHOD", "auto")
    if method == "auto":
        method = choose_method(
            rows, n_playlists=n_playlists, n_tracks=n_tracks
        )
    if method == "sparse":
        out = np.zeros((n_tracks, n_tracks), dtype=np.int32)  # C side adds
        lib.kmls_pair_counts_sparse(
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(len(rows)),
            ctypes.c_int64(n_playlists),
            ctypes.c_int32(n_tracks),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out
    if method != "bitset":
        raise ValueError(f"method must be auto|bitset|sparse, got {method!r}")
    out = np.empty((n_tracks, n_tracks), dtype=np.int32)  # C side fully writes
    bt = _bitpack_unchecked(
        rows, ids, n_playlists=n_playlists, n_tracks=n_tracks
    )
    lib.kmls_pair_counts(
        bt.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.c_int32(n_tracks),
        ctypes.c_int64(bt.shape[1]),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n_threads),
    )
    return out
