"""ctypes bindings for the native CPU pair-support counter
(native/kmls_popcount.cpp) — the CPU-fallback analogue of the Pallas
popcount kernel.

When the backend is CPU (no TPU reachable), XLA:CPU's int8 one-hot matmul
dominates the mining bracket; the native kernel computes the same exact
``XᵀX`` pair-count matrix from bit-packed rows with the POPCNT unit,
L2-tiled, an order of magnitude faster. Bit-packing is one native scatter
pass over the membership rows (no V×P transient, so config-4-class shapes
fit; little bit order: bit p of row t's words ⇔ playlist p contains track
t); zero padding contributes zero counts.

Build/load follows the CSV loader's pattern (data/native.py): ``make -C
native`` on demand, graceful fallback when the toolchain or .so is absent,
``KMLS_NATIVE=0`` kills all native paths.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from ..utils import nativelib

# must match kAbiVersion in native/kmls_popcount.cpp
_ABI_VERSION = 2


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.kmls_popcount_abi_version.restype = ctypes.c_int32
    lib.kmls_popcount_abi_version.argtypes = []
    got = lib.kmls_popcount_abi_version()
    if got != _ABI_VERSION:
        raise OSError(
            f"native popcount ABI {got} != expected {_ABI_VERSION} "
            f"(stale build: run make -C native)"
        )
    lib.kmls_pair_counts.restype = None
    lib.kmls_pair_counts.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
    ]
    lib.kmls_bitpack_rows.restype = None
    lib.kmls_bitpack_rows.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    return lib


_loader = nativelib.NativeLib("libkmls_popcount.so", _bind)


def ensure_built(quiet: bool = True) -> bool:
    nativelib.run_make_once(quiet)
    return os.path.exists(_loader.so_path)


def _load() -> ctypes.CDLL | None:
    return _loader.load()


def available() -> bool:
    return _loader.available()


def bitpack_rows(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
) -> np.ndarray:
    """→ ``(n_tracks, ceil(P/64)) uint64``: bit p of row t set iff playlist
    p contains track t. Duplicate membership rows OR idempotently (same as
    the device one-hot's scatter-max, ops/encode.py).

    Packed by the native scatter — one linear pass over the rows with no
    V×P transient, so it scales to config-4-class shapes (a numpy
    ``packbits`` route needs the full bool matrix: 4.5 GB at a pruned
    1M-playlist input)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native popcount unavailable (build native/ first)")
    w64 = (n_playlists + 63) // 64
    bt = np.zeros((n_tracks, max(w64, 1)), dtype=np.uint64)
    rows = np.ascontiguousarray(playlist_rows, dtype=np.int64)
    ids = np.ascontiguousarray(track_ids, dtype=np.int32)
    if len(rows):
        # the native scatter is unchecked — keep the bounds guard numpy's
        # fancy indexing used to provide (an out-of-range id would be a
        # silent out-of-bounds heap write, not an IndexError)
        if int(rows.min()) < 0 or int(rows.max()) >= n_playlists:
            raise ValueError(
                f"playlist_rows out of range [0, {n_playlists})"
            )
        if int(ids.min()) < 0 or int(ids.max()) >= n_tracks:
            raise ValueError(f"track_ids out of range [0, {n_tracks})")
        lib.kmls_bitpack_rows(
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(len(rows)),
            ctypes.c_int64(bt.shape[1]),
            bt.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
    return bt


def pair_counts(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    n_threads: int | None = None,
) -> np.ndarray:
    """Exact ``XᵀX`` pair-count matrix (V, V) int32 via the native kernel.

    Raises RuntimeError when the native library is unavailable — callers
    gate on :func:`available` and use the XLA path otherwise."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native popcount unavailable (build native/ first)")
    if n_threads is None:
        n_threads = int(os.environ.get("KMLS_NATIVE_THREADS", "0"))
    bt = bitpack_rows(
        playlist_rows, track_ids,
        n_playlists=n_playlists, n_tracks=n_tracks,
    )
    out = np.empty((n_tracks, n_tracks), dtype=np.int32)
    if n_tracks == 0:
        return out
    lib.kmls_pair_counts(
        bt.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.c_int32(n_tracks),
        ctypes.c_int64(bt.shape[1]),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n_threads),
    )
    return out
