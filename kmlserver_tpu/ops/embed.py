"""The embedding serving hot path as one jitted device call.

The second model family's lookup kernel (the rule twin is
``ops/serve.py``): seed songs' unit item vectors are gathered from the
HBM-resident factor matrix, scored against EVERY item by dot product
(cosine similarity — the factors are row-normalized at publication),
max-merged over the seeds, and the top-K extracted — batched over B
concurrent requests, same shape-bucket discipline as the rule kernel so
every (batch, length) a request can produce is pre-warmed at publish.

Semantics, mirroring the rule kernel where the models agree and
diverging only where the geometry demands it:

- ``-1``-padded seeds contribute nothing (parity with the rule kernel's
  membership filter);
- the merge is a MAX over per-seed similarities (parity with the rule
  max-merge: "how strongly does the closest seed pull this item");
- the SEED items themselves are masked out of the candidates — a unit
  vector's nearest neighbor is itself (cosine 1.0), and "you might like
  the songs you just told me about" is not a recommendation. The rule
  kernel doesn't need this mask because a rule row never contains its
  own antecedent;
- rows with no valid seed return all ``-1`` (the engine's membership
  filter degrades those to the popularity fallback before dispatch, so
  this is belt-and-braces, not the primary path).

Memory shape: the similarity pass runs as a ``lax.scan`` over the seed
axis — each step is one (B, R) × (R, V) matmul into a (B, V) running
max — so peak live memory is O(B·V), never the O(B·L·V) a one-shot
einsum would materialize (at a 100k-track vocabulary that difference is
the whole HBM budget).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# large-but-finite floor instead of -inf: masked lanes stay out of every
# max without breeding NaNs through 0·inf corners
_NEG = jnp.float32(-3.0e38)


def _embed_topk_impl(
    item_factors: jax.Array,  # f32 (V, R), rows L2-normalized
    seed_ids: jax.Array,  # int32 (B, L), -1 padded
    *,
    k_best: int,
):
    """→ ``(top_ids int32 (B, k_best) with -1 padding, top_sims f32)``."""
    v = item_factors.shape[0]
    b = seed_ids.shape[0]
    safe_seeds = jnp.where(seed_ids >= 0, seed_ids, 0)

    def step(running_max, cols):
        seed_col, safe_col = cols  # each (B,)
        vecs = item_factors[safe_col]  # (B, R)
        sims = vecs @ item_factors.T  # (B, V) — one MXU matmul per seed slot
        sims = jnp.where((seed_col >= 0)[:, None], sims, _NEG)
        return jnp.maximum(running_max, sims), None

    init = jnp.full((b, v), _NEG, dtype=item_factors.dtype)
    scores, _ = jax.lax.scan(step, init, (seed_ids.T, safe_seeds.T))
    # mask the seeds out of their own candidate set (self-similarity is
    # trivially maximal); padding dumps into an extra slot V, sliced off
    padded = jnp.concatenate(
        [scores, jnp.full((b, 1), _NEG, dtype=scores.dtype)], axis=1
    )
    targets = jnp.where(seed_ids >= 0, seed_ids, v)
    batch_idx = jnp.arange(b, dtype=jnp.int32)[:, None]
    padded = padded.at[batch_idx, targets].set(_NEG)
    scores = padded[:, :v]
    k = min(k_best, v)
    top_sims, top_ids = jax.lax.top_k(scores, k)
    valid = top_sims > _NEG / 2
    top_ids = jnp.where(valid, top_ids, -1)
    top_sims = jnp.where(valid, top_sims, 0.0)
    if k < k_best:  # static pad so callers always see k_best columns
        pad = ((0, 0), (0, k_best - k))
        top_ids = jnp.pad(top_ids, pad, constant_values=-1)
        top_sims = jnp.pad(top_sims, pad)
    return top_ids, top_sims


embed_topk = partial(jax.jit, static_argnames=("k_best",))(_embed_topk_impl)
