"""Device-side transaction encoding.

Replaces mlxtend's ``TransactionEncoder`` (reference:
machine-learning/main.py:267-269), which builds a dense boolean pandas
DataFrame on host. Here the membership pairs go to the device once and the
one-hot / bit-packed basket matrix is materialized there:

- ``onehot_matrix``  — ``X ∈ {0,1}^{P×V}`` as int8: the MXU-friendly operand
  for the pair-support matmul (int8×int8→int32 rides the systolic array).
- ``bitpack_matrix`` — ``{0,1}^{P×ceil(V/32)}`` as uint32 bit-words: 32×
  denser in HBM, operand for the popcount pair-support path (Pallas kernel)
  when ``P×V`` wouldn't fit as int8.

Membership pairs must be deduplicated (build_baskets guarantees this); the
bit-pack uses an additive scatter, which is only equal to bitwise-or when
every (playlist, track) bit is contributed once.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

WORD_BITS = 32


def n_words(n_tracks: int) -> int:
    return (n_tracks + WORD_BITS - 1) // WORD_BITS


@partial(jax.jit, static_argnames=("n_playlists", "n_tracks"))
def onehot_matrix(
    playlist_rows: jax.Array, track_ids: jax.Array, *, n_playlists: int, n_tracks: int
) -> jax.Array:
    """Scatter membership pairs into a dense int8 one-hot matrix (P, V)."""
    x = jnp.zeros((n_playlists, n_tracks), dtype=jnp.int8)
    ones = jnp.ones_like(track_ids, dtype=jnp.int8)
    return x.at[playlist_rows, track_ids].max(ones)


@partial(jax.jit, static_argnames=("n_playlists", "n_tracks"))
def bitpack_matrix(
    playlist_rows: jax.Array, track_ids: jax.Array, *, n_playlists: int, n_tracks: int
) -> jax.Array:
    """Scatter membership pairs into packed uint32 bit-words (P, ceil(V/32)).

    Track ``t`` occupies bit ``t % 32`` of word ``t // 32``; additive scatter
    == bitwise-or because pairs are unique.
    """
    words = (track_ids // WORD_BITS).astype(jnp.int32)
    bits = jnp.left_shift(
        jnp.uint32(1), (track_ids % WORD_BITS).astype(jnp.uint32)
    )
    packed = jnp.zeros((n_playlists, n_words(n_tracks)), dtype=jnp.uint32)
    return packed.at[playlist_rows, words].add(bits)


def unpack_bits(packed: jax.Array, n_tracks: int | None = None) -> jax.Array:
    """Inverse of :func:`bitpack_matrix` → int8 (P, W*32); for tests."""
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    bits = (packed[..., None] >> shifts) & jnp.uint32(1)
    flat = bits.reshape(packed.shape[0], -1).astype(jnp.int8)
    return flat if n_tracks is None else flat[:, :n_tracks]
