"""Pallas TPU kernel: pair-support counting over bit-packed baskets.

The dense int8 ``XᵀX`` path (ops/support.py) stores one byte per
(playlist, track) cell — at BASELINE.json config 4 scale (10M playlists ×
1M tracks) that's 10 TB and infeasible. Packing the PLAYLIST axis into
uint32 bit-words shrinks the operand 32× and turns pair counting into

    C[i, j] = Σ_w popcount(Bt[i, w] & Bt[j, w])

where ``Bt (V, ceil(P/32)) uint32`` holds track i's playlist membership as a
bitset. This kernel tiles that computation for the VPU:

- grid ``(i_tile, j_tile, w_chunk)``: output tile ``(TI, TJ) int32`` revisited
  across the trailing ``w_chunk`` dimension and accumulated in place
  (zero-initialized at the first chunk via ``@pl.when``);
- per step, row block A ``(TI, WK)`` and column block B ``(TJ, WK)`` live in
  VMEM; a ``fori_loop`` over the TI rows does AND + ``population_count`` +
  word-sum on the VPU — no MXU involvement, no unpacking;
- V is padded to the 128-lane tile and P to 32·WK word chunks with zero
  bits, which contribute zero counts and are sliced away by the caller.

On non-TPU backends the kernel runs in interpreter mode (tests); the public
entry point falls back gracefully.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import encode

TILE_I = 32
TILE_J = 128
WORD_CHUNK = 512  # uint32 words per grid step (= 16,384 playlists)


def _popcount_kernel(a_ref, b_ref, out_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    b_block = b_ref[:]  # (TJ, WK) uint32

    def row(i, _):
        anded = jnp.bitwise_and(a_ref[i, :], b_block)  # broadcast (TJ, WK)
        counts = jax.lax.population_count(anded).astype(jnp.int32)
        out_ref[i, :] += jnp.sum(counts, axis=1)
        return 0

    jax.lax.fori_loop(0, a_ref.shape[0], row, 0)


@partial(jax.jit, static_argnames=("interpret",))
def popcount_pair_counts_padded(bt: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Pair counts from an already-padded bitset matrix
    ``bt (V_pad, W_pad) uint32`` with V_pad % TILE_J == 0 and
    W_pad % WORD_CHUNK == 0. → int32 (V_pad, V_pad)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    v_pad, w_pad = bt.shape
    grid = (v_pad // TILE_I, v_pad // TILE_J, w_pad // WORD_CHUNK)
    return pl.pallas_call(
        _popcount_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (TILE_I, WORD_CHUNK),
                lambda i, j, k: (i, k),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (TILE_J, WORD_CHUNK),
                lambda i, j, k: (j, k),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (TILE_I, TILE_J), lambda i, j, k: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((v_pad, v_pad), jnp.int32),
        interpret=interpret,
    )(bt, bt)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def bitpack_by_track(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    v_pad: int,
    w_pad: int,
) -> jax.Array:
    """Bitset matrix (v_pad, w_pad) uint32: bit p of word ``Bt[t, p // 32]``
    set iff playlist p contains track t. The packer is the same scatter as
    ``encode.bitpack_matrix`` with the axes' roles swapped."""
    if n_playlists > w_pad * encode.WORD_BITS:
        raise ValueError(f"w_pad {w_pad} too small for {n_playlists} playlists")
    return encode.bitpack_matrix(
        jnp.asarray(track_ids),  # rows = tracks
        jnp.asarray(playlist_rows),  # bits = playlists
        n_playlists=v_pad,
        n_tracks=w_pad * encode.WORD_BITS,
    )


def popcount_pair_counts(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    interpret: bool | None = None,
) -> jax.Array:
    """Public entry: membership pairs → (V, V) int32 pair counts via the
    bit-packed popcount kernel. Interpreter mode auto-enabled off-TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v_pad = _round_up(max(n_tracks, TILE_J), max(TILE_I, TILE_J))
    w_pad = _round_up(
        (n_playlists + encode.WORD_BITS - 1) // encode.WORD_BITS, WORD_CHUNK
    )
    bt = bitpack_by_track(
        playlist_rows, track_ids,
        n_playlists=n_playlists, n_tracks=n_tracks,
        v_pad=v_pad, w_pad=w_pad,
    )
    counts = popcount_pair_counts_padded(bt, interpret=interpret)
    return counts[:n_tracks, :n_tracks]
