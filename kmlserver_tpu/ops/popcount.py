"""Pallas TPU kernel: pair-support counting over bit-packed baskets.

The dense int8 ``XᵀX`` path (ops/support.py) stores one byte per
(playlist, track) cell — at BASELINE.json config 4 scale (10M playlists ×
1M tracks) that's 10 TB and infeasible. Packing the PLAYLIST axis into
uint32 bit-words shrinks the operand 32× and turns pair counting into

    C[i, j] = Σ_w popcount(Bt[i, w] & Bt[j, w])

where ``Bt (V, ceil(P/32)) uint32`` holds track i's playlist membership as a
bitset. The kernel tiles that computation for the VPU:

- grid ``(i_tile, j_tile, w_chunk)``: output tile ``(TI, TJ) int32`` revisited
  across the trailing ``w_chunk`` dimension and accumulated in place
  (zero-initialized at the first chunk via ``@pl.when``);
- per step, row block A ``(TI, WK)`` and column block B ``(TJ, WK)`` live in
  VMEM; AND + popcount + word-sum run on the VPU — no MXU, no unpacking;
- V is padded to the 128-lane tile and P to 32·WK word chunks with zero
  bits, which contribute zero counts and are sliced away by the caller.

Two kernel variants (``variant=``), identical results, different lowering
risk/perf profiles — selectable so the on-hardware bench can pick whichever
actually lowers fastest (this environment has no local TPU to pre-verify
Mosaic lowering):

- ``"bcast"`` (default): fully vectorized — slices the word chunk into
  SUB-wide pieces and broadcasts ``(TI, 1, SUB) & (1, TJ, SUB)``; only
  static shapes, no dynamic VMEM indexing.
- ``"row"``: a ``fori_loop`` over the TI rows with dynamic sublane reads
  (``a_ref[i, :]``) — smaller intermediates, more loop overhead.

``swar=True`` replaces ``jax.lax.population_count`` with an adds-and-shifts
SWAR popcount (Hacker's Delight fig. 5-2, public-domain identity) in case
the popcount primitive doesn't lower in Mosaic.

On non-TPU backends the kernel runs in interpreter mode (tests); the public
entry point falls back gracefully.

Tile sizes are env-tunable (``KMLS_POPCOUNT_TILE_I/TILE_J/WORD_CHUNK``) for
on-hardware tuning without a code change; defaults keep every operand on
the (8, 128) 32-bit tile grid and the per-step VMEM footprint ≈ 0.3 MB.
"""

from __future__ import annotations

import math
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import encode

TILE_I = int(os.environ.get("KMLS_POPCOUNT_TILE_I", "32"))
TILE_J = int(os.environ.get("KMLS_POPCOUNT_TILE_J", "128"))
WORD_CHUNK = int(os.environ.get("KMLS_POPCOUNT_WORD_CHUNK", "512"))
_SUB = 128  # lane-aligned word slice for the bcast variant's 3D intermediate
# the vocab axis must pad to a multiple of BOTH tile sizes — rounding to
# max() silently leaves output rows unwritten when TILE_I ∤ TILE_J
V_TILE = math.lcm(TILE_I, TILE_J)
if WORD_CHUNK > _SUB and WORD_CHUNK % _SUB != 0:
    raise ValueError(
        f"KMLS_POPCOUNT_WORD_CHUNK={WORD_CHUNK} must be a multiple of "
        f"{_SUB} (or at most {_SUB}): the bcast kernel slices word chunks "
        f"in {_SUB}-wide pieces and a ragged tail would be dropped"
    )

VARIANTS = ("bcast", "row")


def resolve_kernel_opts(
    variant: str | None, swar: bool | None
) -> tuple[str, bool]:
    """Kernel variant/popcount-impl selection with env-var defaults
    (``KMLS_POPCOUNT_VARIANT``, ``KMLS_POPCOUNT_SWAR``) — shared by the
    single-chip entry AND the dp-sharded path so a deployment can be
    retargeted without a code change on either."""
    if variant is None:
        variant = os.environ.get("KMLS_POPCOUNT_VARIANT", "bcast")
    if variant not in VARIANTS:
        raise ValueError(f"variant must be one of {VARIANTS}, got {variant!r}")
    if swar is None:
        swar = os.environ.get("KMLS_POPCOUNT_SWAR", "0") == "1"
    return variant, swar


def _popcount_words(x: jax.Array, swar: bool) -> jax.Array:
    """Per-word popcount → int32. ``swar=False`` uses the hardware/XLA
    primitive; ``swar=True`` uses shifts+adds only (no multiply, no
    popcount primitive), for backends where the primitive won't lower."""
    if not swar:
        return jax.lax.population_count(x).astype(jnp.int32)
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    x = x + (x >> 16)
    x = x + (x >> 8)
    return (x & jnp.uint32(0x3F)).astype(jnp.int32)


def _kernel_row(a_ref, b_ref, out_ref, *, swar: bool):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    b_block = b_ref[:]  # (TJ, WK) uint32

    def row(i, _):
        anded = jnp.bitwise_and(a_ref[i, :], b_block)  # broadcast (TJ, WK)
        out_ref[i, :] += jnp.sum(_popcount_words(anded, swar), axis=1)
        return 0

    jax.lax.fori_loop(0, a_ref.shape[0], row, 0)


def _kernel_bcast(a_ref, b_ref, out_ref, *, swar: bool):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    a = a_ref[:]  # (TI, WK)
    b = b_ref[:]  # (TJ, WK)
    ti, wk = a.shape
    tj = b.shape[0]
    sub = min(_SUB, wk)

    # static Python unroll (wk/sub is a compile-time constant, default 4):
    # Mosaic's TC lowering has no dynamic_slice, so a fori_loop with traced
    # slice starts fails to compile on real hardware — verified on v5e
    acc = jnp.zeros((ti, tj), jnp.int32)
    for c in range(wk // sub):
        a_c = a[:, c * sub:(c + 1) * sub]  # (TI, SUB)
        b_c = b[:, c * sub:(c + 1) * sub]  # (TJ, SUB)
        anded = a_c[:, None, :] & b_c[None, :, :]  # (TI, TJ, SUB)
        acc = acc + jnp.sum(_popcount_words(anded, swar), axis=2)
    out_ref[:] += acc


_KERNELS = {"row": _kernel_row, "bcast": _kernel_bcast}


@partial(jax.jit, static_argnames=("interpret", "variant", "swar"))
def popcount_pair_counts_padded(
    bt: jax.Array,
    *,
    interpret: bool = False,
    variant: str = "bcast",
    swar: bool = False,
) -> jax.Array:
    """Pair counts from an already-padded bitset matrix
    ``bt (V_pad, W_pad) uint32`` with V_pad % TILE_J == 0 and
    W_pad % WORD_CHUNK == 0. → int32 (V_pad, V_pad)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    v_pad, w_pad = bt.shape
    if v_pad % TILE_I or v_pad % TILE_J or w_pad % WORD_CHUNK:
        raise ValueError(
            f"bt {bt.shape} must pad V to a multiple of lcm(TILE_I, TILE_J)"
            f"={V_TILE} and W to a multiple of WORD_CHUNK={WORD_CHUNK}; a "
            f"truncating grid would silently skip output tiles"
        )
    grid = (v_pad // TILE_I, v_pad // TILE_J, w_pad // WORD_CHUNK)
    return pl.pallas_call(
        partial(_KERNELS[variant], swar=swar),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (TILE_I, WORD_CHUNK),
                lambda i, j, k: (i, k),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (TILE_J, WORD_CHUNK),
                lambda i, j, k: (j, k),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (TILE_I, TILE_J), lambda i, j, k: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((v_pad, v_pad), jnp.int32),
        interpret=interpret,
    )(bt, bt)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def padded_shape(n_tracks: int, n_playlists: int) -> tuple[int, int]:
    """``(v_pad, w_pad)`` the kernel actually allocates: the vocabulary
    padded to ``V_TILE = lcm(TILE_I, TILE_J)`` and the bitset word count
    ``ceil(P/32)`` padded to ``WORD_CHUNK``. The ONE copy of this math —
    bench/demo HBM accounting must call it, not re-derive it (the two
    hand-derived copies drifted twice)."""
    v_pad = _round_up(max(n_tracks, V_TILE), V_TILE)
    w_pad = _round_up(
        (n_playlists + encode.WORD_BITS - 1) // encode.WORD_BITS, WORD_CHUNK
    )
    return v_pad, w_pad


def bitpack_by_track(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    v_pad: int,
    w_pad: int,
) -> jax.Array:
    """Bitset matrix (v_pad, w_pad) uint32: bit p of word ``Bt[t, p // 32]``
    set iff playlist p contains track t. The packer is the same scatter as
    ``encode.bitpack_matrix`` with the axes' roles swapped."""
    if n_playlists > w_pad * encode.WORD_BITS:
        raise ValueError(f"w_pad {w_pad} too small for {n_playlists} playlists")
    return encode.bitpack_matrix(
        jnp.asarray(track_ids),  # rows = tracks
        jnp.asarray(playlist_rows),  # bits = playlists
        n_playlists=v_pad,
        n_tracks=w_pad * encode.WORD_BITS,
    )


def popcount_pair_counts(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    interpret: bool | None = None,
    variant: str | None = None,
    swar: bool | None = None,
) -> jax.Array:
    """Public entry: membership pairs → (V, V) int32 pair counts via the
    bit-packed popcount kernel. Interpreter mode auto-enabled off-TPU;
    variant/swar default from ``KMLS_POPCOUNT_VARIANT`` / ``KMLS_POPCOUNT_SWAR``
    so the deployed job can be retargeted without a code change."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    variant, swar = resolve_kernel_opts(variant, swar)
    v_pad, w_pad = padded_shape(n_tracks, n_playlists)
    bt = bitpack_by_track(
        playlist_rows, track_ids,
        n_playlists=n_playlists, n_tracks=n_tracks,
        v_pad=v_pad, w_pad=w_pad,
    )
    counts = popcount_pair_counts_padded(
        bt, interpret=interpret, variant=variant, swar=swar
    )
    return counts[:n_tracks, :n_tracks]
