"""Pallas TPU kernel: pair-support counting over bit-packed baskets.

The dense int8 ``XᵀX`` path (ops/support.py) stores one byte per
(playlist, track) cell — at BASELINE.json config 4 scale (10M playlists ×
1M tracks) that's 10 TB and infeasible. Packing the PLAYLIST axis into
uint32 bit-words shrinks the operand 32× and turns pair counting into

    C[i, j] = Σ_w popcount(Bt[i, w] & Bt[j, w])

where ``Bt (V, ceil(P/32)) uint32`` holds track i's playlist membership as a
bitset. The kernel tiles that computation for the VPU:

- grid ``(i_tile, j_tile, w_chunk)``: output tile ``(TI, TJ) int32`` revisited
  across the trailing ``w_chunk`` dimension and accumulated in place
  (zero-initialized at the first chunk via ``@pl.when``);
- per step, row block A ``(TI, WK)`` and column block B ``(TJ, WK)`` live in
  VMEM; AND + popcount + word-sum run on the VPU — no MXU, no unpacking;
- V is padded to the 128-lane tile and P to 32·WK word chunks with zero
  bits, which contribute zero counts and are sliced away by the caller.

Two implementations share the bit-packed operand (``impl=`` /
``KMLS_BITPACK_IMPL``): ``"mxu"`` (default) is a pure-XLA blocked
unpack-matmul (:func:`mxu_pair_counts_padded`) that puts the contraction on
the MXU; ``"vpu"`` is the Pallas AND+popcount kernel below. The VPU kernel
itself has two variants (``variant=``), identical results, different
lowering risk/perf profiles — selectable so the on-hardware bench can pick
whichever actually lowers fastest (this environment has no local TPU to
pre-verify Mosaic lowering):

- ``"bcast"`` (default): fully vectorized — slices the word chunk into
  SUB-wide pieces and broadcasts ``(TI, 1, SUB) & (1, TJ, SUB)``; only
  static shapes, no dynamic VMEM indexing.
- ``"row"``: a ``fori_loop`` over the TI rows with dynamic sublane reads
  (``a_ref[i, :]``) — smaller intermediates, more loop overhead.

``swar=True`` replaces ``jax.lax.population_count`` with an adds-and-shifts
SWAR popcount (Hacker's Delight fig. 5-2, public-domain identity) in case
the popcount primitive doesn't lower in Mosaic.

On non-TPU backends the kernel runs in interpreter mode (tests); the public
entry point falls back gracefully.

Tile sizes are env-tunable (``KMLS_POPCOUNT_TILE_I/TILE_J/WORD_CHUNK``) for
on-hardware tuning without a code change; defaults keep every operand on
the (8, 128) 32-bit tile grid and the per-step VMEM footprint ≈ 0.3 MB.
Like ``KMLS_POPCOUNT_VARIANT``, the tile knobs are read LAZILY at
kernel-build time (:func:`resolve_tiles`) — an env change after import
takes effect on the next call, and because the resolved sizes ride the
jit static arguments, a changed tile can never silently reuse a program
compiled for the old one. (They were read once at module import until
ISSUE 13; tests now pin the lazy behavior.)
"""

from __future__ import annotations

import math
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import encode

TILE_I_DEFAULT = 32
TILE_J_DEFAULT = 128
WORD_CHUNK_DEFAULT = 512
_SUB = 128  # lane-aligned word slice for the bcast variant's 3D intermediate

VARIANTS = ("bcast", "row")
COUNT_IMPLS = ("mxu", "vpu")


def resolve_tiles(
    tile_i: int | None = None,
    tile_j: int | None = None,
    word_chunk: int | None = None,
) -> tuple[int, int, int]:
    """``(TILE_I, TILE_J, WORD_CHUNK)`` — explicit args > env knobs >
    defaults, validated. THE one read point for the tile knobs, called
    at kernel-build time (never at import: a deployment that exports
    the knobs after importing the package must still be heard)."""
    if tile_i is None:
        tile_i = int(os.environ.get("KMLS_POPCOUNT_TILE_I", TILE_I_DEFAULT))
    if tile_j is None:
        tile_j = int(os.environ.get("KMLS_POPCOUNT_TILE_J", TILE_J_DEFAULT))
    if word_chunk is None:
        word_chunk = int(
            os.environ.get("KMLS_POPCOUNT_WORD_CHUNK", WORD_CHUNK_DEFAULT)
        )
    if tile_i < 1 or tile_j < 1 or word_chunk < 1:
        raise ValueError(
            f"popcount tiles must be positive, got "
            f"{tile_i}x{tile_j}x{word_chunk}"
        )
    if word_chunk > _SUB and word_chunk % _SUB != 0:
        raise ValueError(
            f"KMLS_POPCOUNT_WORD_CHUNK={word_chunk} must be a multiple of "
            f"{_SUB} (or at most {_SUB}): the bcast kernel slices word "
            f"chunks in {_SUB}-wide pieces and a ragged tail would be "
            "dropped"
        )
    return tile_i, tile_j, word_chunk


def v_tile(tile_i: int | None = None, tile_j: int | None = None) -> int:
    """The vocab-axis padding unit: the vocab must pad to a multiple of
    BOTH tile sizes — rounding to max() silently leaves output rows
    unwritten when TILE_I ∤ TILE_J."""
    ti, tj, _ = resolve_tiles(tile_i, tile_j)
    return math.lcm(ti, tj)


def word_chunk() -> int:
    """The resolved word-chunk size (lazy env read)."""
    return resolve_tiles()[2]


def resolve_counts_impl(impl: str | None = None) -> str:
    """Bit-packed counting implementation (``KMLS_BITPACK_IMPL``):

    - ``"mxu"`` (default): blocked unpack-matmul — scan over word-chunk
      slabs, unpack each uint32 slab to int8 bits in registers, one native
      int8×int8→int32 MXU contraction per slab (:func:`mxu_pair_counts_padded`).
      Pure XLA (no Mosaic lowering risk), runs natively on every backend,
      and puts the FLOPs where the chip has them: at config-4 scale the MXU
      peak is ~3.4 s where the VPU popcount kernel's measured rate gives
      minutes. It is fast off-TPU too — measured 1.1 s vs 43 s for the
      dense int8 matmul on XLA:CPU at 100k×2k (the compressed operand
      streams through cache where the dense one thrashes it), so it is
      also the right fallback when the native CPU counter can't build.
    - ``"vpu"``: the Pallas AND+popcount kernel (``variant``/``swar``
      selectable) — no unpacked intermediate at all; kept as the
      cross-check twin and for shapes where unpacked slabs are unwelcome.
    """
    if impl is None:
        impl = os.environ.get("KMLS_BITPACK_IMPL", "mxu")
    if impl not in COUNT_IMPLS:
        raise ValueError(f"impl must be one of {COUNT_IMPLS}, got {impl!r}")
    return impl


def resolve_kernel_opts(
    variant: str | None, swar: bool | None
) -> tuple[str, bool]:
    """Kernel variant/popcount-impl selection with env-var defaults
    (``KMLS_POPCOUNT_VARIANT``, ``KMLS_POPCOUNT_SWAR``) — shared by the
    single-chip entry AND the dp-sharded path so a deployment can be
    retargeted without a code change on either."""
    if variant is None:
        variant = os.environ.get("KMLS_POPCOUNT_VARIANT", "bcast")
    if variant not in VARIANTS:
        raise ValueError(f"variant must be one of {VARIANTS}, got {variant!r}")
    if swar is None:
        swar = os.environ.get("KMLS_POPCOUNT_SWAR", "0") == "1"
    return variant, swar


def _popcount_words(x: jax.Array, swar: bool) -> jax.Array:
    """Per-word popcount → int32. ``swar=False`` uses the hardware/XLA
    primitive; ``swar=True`` uses shifts+adds only (no multiply, no
    popcount primitive), for backends where the primitive won't lower."""
    if not swar:
        return jax.lax.population_count(x).astype(jnp.int32)
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    x = x + (x >> 16)
    x = x + (x >> 8)
    return (x & jnp.uint32(0x3F)).astype(jnp.int32)


def _kernel_row(a_ref, b_ref, out_ref, *, swar: bool):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    b_block = b_ref[:]  # (TJ, WK) uint32

    def row(i, _):
        anded = jnp.bitwise_and(a_ref[i, :], b_block)  # broadcast (TJ, WK)
        out_ref[i, :] += jnp.sum(_popcount_words(anded, swar), axis=1)
        return 0

    jax.lax.fori_loop(0, a_ref.shape[0], row, 0)


def _kernel_bcast(a_ref, b_ref, out_ref, *, swar: bool):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    a = a_ref[:]  # (TI, WK)
    b = b_ref[:]  # (TJ, WK)
    ti, wk = a.shape
    tj = b.shape[0]
    sub = min(_SUB, wk)

    # static Python unroll (wk/sub is a compile-time constant, default 4):
    # Mosaic's TC lowering has no dynamic_slice, so a fori_loop with traced
    # slice starts fails to compile on real hardware — verified on v5e
    acc = jnp.zeros((ti, tj), jnp.int32)
    for c in range(wk // sub):
        a_c = a[:, c * sub:(c + 1) * sub]  # (TI, SUB)
        b_c = b[:, c * sub:(c + 1) * sub]  # (TJ, SUB)
        anded = a_c[:, None, :] & b_c[None, :, :]  # (TI, TJ, SUB)
        acc = acc + jnp.sum(_popcount_words(anded, swar), axis=2)
    out_ref[:] += acc


_KERNELS = {"row": _kernel_row, "bcast": _kernel_bcast}


def popcount_pair_counts_padded(
    bt: jax.Array,
    *,
    interpret: bool = False,
    variant: str = "bcast",
    swar: bool = False,
    tile_i: int | None = None,
    tile_j: int | None = None,
    word_chunk: int | None = None,
) -> jax.Array:
    """Pair counts from an already-padded bitset matrix
    ``bt (V_pad, W_pad) uint32`` with V_pad % lcm(TILE_I, TILE_J) == 0
    and W_pad % WORD_CHUNK == 0. → int32 (V_pad, V_pad). Tile sizes
    resolve HERE (env or explicit) and ride the jit static args, so a
    knob change after import builds — and caches — a new program."""
    ti, tj, wk = resolve_tiles(tile_i, tile_j, word_chunk)
    return _popcount_padded_jit(
        bt, interpret=interpret, variant=variant, swar=swar,
        tile_i=ti, tile_j=tj, word_chunk=wk,
    )


@partial(
    jax.jit,
    static_argnames=("interpret", "variant", "swar", "tile_i", "tile_j", "word_chunk"),
)
def _popcount_padded_jit(
    bt: jax.Array,
    *,
    interpret: bool,
    variant: str,
    swar: bool,
    tile_i: int,
    tile_j: int,
    word_chunk: int,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    v_pad, w_pad = bt.shape
    if v_pad % tile_i or v_pad % tile_j or w_pad % word_chunk:
        raise ValueError(
            f"bt {bt.shape} must pad V to a multiple of lcm(TILE_I, TILE_J)"
            f"={math.lcm(tile_i, tile_j)} and W to a multiple of "
            f"WORD_CHUNK={word_chunk}; a truncating grid would silently "
            "skip output tiles"
        )
    grid = (v_pad // tile_i, v_pad // tile_j, w_pad // word_chunk)
    return pl.pallas_call(
        partial(_KERNELS[variant], swar=swar),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (tile_i, word_chunk),
                lambda i, j, k: (i, k),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (tile_j, word_chunk),
                lambda i, j, k: (j, k),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (tile_i, tile_j), lambda i, j, k: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((v_pad, v_pad), jnp.int32),
        interpret=interpret,
    )(bt, bt)


def mxu_pair_counts_padded(
    bt: jax.Array, *, word_chunk: int | None = None
) -> jax.Array:
    """Pair counts from a padded bitset via blocked unpack-matmul on the MXU.

    Identical contract to :func:`popcount_pair_counts_padded` —
    ``bt (V_pad, W_pad) uint32`` → int32 ``(V_pad, V_pad)`` — but the
    compute lands on the MXU instead of the VPU:

        C = Σ_k U_k · U_kᵀ,   U_k = unpack_bits(bt[:, k·WK:(k+1)·WK]) int8

    Each scan step slices one word-chunk slab, unpacks its 32 bit-planes
    into an ``(V_pad, WK·32)`` int8 operand (the bit→column order is
    irrelevant: both operands of the self-contraction use the same order),
    and issues one native int8×int8→int32 contraction. Exact: every
    partial product is 0/1 and accumulation is integer. The unpacked slab
    is 8× the bitset slab but only one slab exists at a time — HBM holds
    the 32×-compressed bitset, which is the whole point of the path.

    Pure XLA: no Pallas/Mosaic involvement, so it runs natively (not
    interpreted) on CPU test backends and carries zero lowering risk on
    TPU generations. The word-chunk knob resolves here (lazy env read)
    and rides the inner jit's static arg.
    """
    wk = min(resolve_tiles(word_chunk=word_chunk)[2], bt.shape[1])
    return _mxu_padded_jit(bt, word_chunk=wk)


@partial(jax.jit, static_argnames=("word_chunk",))
def _mxu_padded_jit(bt: jax.Array, *, word_chunk: int) -> jax.Array:
    v_pad, w_pad = bt.shape
    wk = word_chunk
    if w_pad % wk:
        raise ValueError(
            f"W_pad {w_pad} must be a multiple of the word chunk {wk} "
            f"(padded_shape guarantees this); a ragged tail would be dropped"
        )
    bits = jnp.arange(32, dtype=jnp.uint32)

    def step(acc: jax.Array, k: jax.Array):
        slab = jax.lax.dynamic_slice(bt, (0, k * wk), (v_pad, wk))
        unpacked = (
            ((slab[:, :, None] >> bits[None, None, :]) & jnp.uint32(1))
            .astype(jnp.int8)
            .reshape(v_pad, wk * 32)
        )
        acc = acc + jax.lax.dot_general(
            unpacked,
            unpacked,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return acc, None

    acc0 = jnp.zeros((v_pad, v_pad), jnp.int32)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(w_pad // wk))
    return acc


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def padded_shape(n_tracks: int, n_playlists: int) -> tuple[int, int]:
    """``(v_pad, w_pad)`` the kernel actually allocates: the vocabulary
    padded to ``lcm(TILE_I, TILE_J)`` and the bitset word count
    ``ceil(P/32)`` padded to ``WORD_CHUNK`` (tiles resolved lazily, so
    this tracks the env knobs call-by-call). The ONE copy of this math —
    bench/demo HBM accounting must call it, not re-derive it (the two
    hand-derived copies drifted twice)."""
    ti, tj, wk = resolve_tiles()
    vt = math.lcm(ti, tj)
    v_pad = _round_up(max(n_tracks, vt), vt)
    w_pad = _round_up(
        (n_playlists + encode.WORD_BITS - 1) // encode.WORD_BITS, wk
    )
    return v_pad, w_pad


def bitpack_by_track(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    v_pad: int,
    w_pad: int,
) -> jax.Array:
    """Bitset matrix (v_pad, w_pad) uint32: bit p of word ``Bt[t, p // 32]``
    set iff playlist p contains track t. The packer is the same scatter as
    ``encode.bitpack_matrix`` with the axes' roles swapped."""
    if n_playlists > w_pad * encode.WORD_BITS:
        raise ValueError(f"w_pad {w_pad} too small for {n_playlists} playlists")
    return encode.bitpack_matrix(
        jnp.asarray(track_ids),  # rows = tracks
        jnp.asarray(playlist_rows),  # bits = playlists
        n_playlists=v_pad,
        n_tracks=w_pad * encode.WORD_BITS,
    )


def popcount_pair_counts(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    interpret: bool | None = None,
    variant: str | None = None,
    swar: bool | None = None,
    impl: str | None = None,
) -> jax.Array:
    """Public entry: membership pairs → (V, V) int32 pair counts from the
    bit-packed operand. Pairs must be DEDUPLICATED (the ``build_baskets``
    invariant, ops/encode.py): a duplicate would add twice in the dense
    one-hot but OR to one bit here, silently diverging the counts.
    ``impl`` (default ``KMLS_BITPACK_IMPL``, "mxu")
    selects :func:`mxu_pair_counts_padded` (blocked unpack-matmul) or the
    Pallas VPU popcount kernel; interpreter mode auto-enables off-TPU for
    the VPU kernel only (the MXU path is pure XLA and runs natively
    everywhere). variant/swar default from ``KMLS_POPCOUNT_VARIANT`` /
    ``KMLS_POPCOUNT_SWAR`` so the deployed job can be retargeted without a
    code change."""
    impl = resolve_counts_impl(impl)
    v_pad, w_pad = padded_shape(n_tracks, n_playlists)
    bt = bitpack_by_track(
        playlist_rows, track_ids,
        n_playlists=n_playlists, n_tracks=n_tracks,
        v_pad=v_pad, w_pad=w_pad,
    )
    if impl == "mxu":
        counts = mxu_pair_counts_padded(bt)
    else:
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        variant, swar = resolve_kernel_opts(variant, swar)
        counts = popcount_pair_counts_padded(
            bt, interpret=interpret, variant=variant, swar=swar
        )
    return counts[:n_tracks, :n_tracks]
