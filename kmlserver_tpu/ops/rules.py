"""Rule-tensor emission — the device-side replacement for the reference's
pure-Python itemset→rule-dict expansion loops
(reference: machine-learning/main.py:284-296).

The output layout is a padded dense set of arrays resident in HBM:

    rule_ids    int32 (V, K_max) — consequent track ids, -1 padding
    rule_counts int32 (V, K_max) — co-occurrence counts (pair support × P)
    item_counts int32 (V,)       — singleton supports (the matrix diagonal)

Key semantic detail (reference: machine-learning/main.py:287-291): the
reference creates a rule-dict KEY for every member of every frequent itemset
— including frequent singletons, whose value stays an EMPTY dict. Those keys
matter downstream: the API's seed-membership filter treats them as known (an
all-known-but-empty request returns an empty list, NOT the static fallback —
rest_api/app/main.py:235-238), and the printed missing-songs counter is
``total_songs - len(keys)`` (main.py:304), i.e. it counts items below
min_support, not items without partners. Hence ``item_counts`` (the matrix
diagonal) travels with the rule rows: frequent items ARE the key set.

Per the dominance argument in ``ops/support.py``, row *i*'s contents are
exactly {j ≠ i : pair_count[i, j] ≥ min_count} with stored "confidence"
pair_count[i, j] / P. Emission is one masked row-wise ``top_k``. Counts (not
float supports) travel to host so dict expansion can reproduce the
reference's float64 ``count / P`` arithmetic bit-for-bit.

Two confidence modes:

- ``"support"``   — the reference fast path's semantics: symmetric rules
  carrying the itemset support (machine-learning/main.py:286).
- ``"confidence"`` — the dormant slow path's true asymmetric confidence
  (machine-learning/main.py:224-260, fpgrowth_py at :226-227):
  conf(a→b) = support({a,b}) / support({a}), thresholded at
  ``min_confidence``; rules are no longer symmetric.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .support import min_count_for


@partial(jax.jit, static_argnames=("k_max",))
def emit_rule_tensors(pair_count_matrix: jax.Array, min_count: jax.Array, *, k_max: int):
    """Threshold + per-row top-k over the pair-count matrix.

    Returns ``(rule_ids, rule_counts, row_valid_counts)`` where
    ``row_valid_counts[i]`` is the TRUE number of frequent consequents of i
    (may exceed ``k_max``; the caller detects truncation overflow).
    """
    v = pair_count_matrix.shape[0]
    offdiag = ~jnp.eye(v, dtype=bool)
    valid = offdiag & (pair_count_matrix >= min_count)
    row_valid_counts = valid.sum(axis=1, dtype=jnp.int32)
    score = jnp.where(valid, pair_count_matrix, -1)
    k = min(k_max, v)
    top_counts, top_ids = jax.lax.top_k(score, k)
    keep = top_counts > 0
    rule_ids = jnp.where(keep, top_ids, -1).astype(jnp.int32)
    rule_counts = jnp.where(keep, top_counts, 0)
    if k < k_max:  # static pad up to the declared row capacity
        pad = ((0, 0), (0, k_max - k))
        rule_ids = jnp.pad(rule_ids, pad, constant_values=-1)
        rule_counts = jnp.pad(rule_counts, pad)
    return rule_ids, rule_counts, row_valid_counts


def derive_confs(
    rule_counts: np.ndarray,
    item_counts: np.ndarray,
    n_playlists: int,
    mode: str,
) -> np.ndarray:
    """THE count→confidence arithmetic, shared by the miner and every npz
    consumer (float64 division, then float32 for the serving tensors)."""
    if mode == "support":
        return (rule_counts.astype(np.float64) / n_playlists).astype(np.float32)
    denom = np.maximum(item_counts, 1)[:, None].astype(np.float64)
    return (rule_counts / denom).astype(np.float32)


def expand_rules_dict(
    vocab_names: list[str],
    rule_ids: np.ndarray,
    rule_counts: np.ndarray,
    item_counts: np.ndarray,
    *,
    n_playlists: int,
    min_support: float,
    mode: str = "support",
    rule_confs64: np.ndarray | None = None,
) -> dict[str, dict[str, float]]:
    """THE canonical tensor→dict expansion, shared by the mining artifact
    writer and every npz consumer. Reproduces the reference pickle exactly:
    every frequent item is a key (empty dict when it has no partners),
    confidences are float64 ``count / P`` (support mode) or
    ``count / item_count`` (confidence mode). When ``rule_confs64`` is given
    (triple-antecedent merge: per-rule denominators), the stored float64
    confidences are used verbatim instead of re-deriving from counts."""
    min_count = min_count_for(min_support, n_playlists)
    # infrequent items are not keys (reference main.py:284 loop); all the
    # vectorized work below touches ONLY the frequent rows — with pruning
    # disabled at large V the full (V, K_max) float64 temporary would be
    # gigabytes for rows that are never expanded
    freq_rows = np.flatnonzero(item_counts >= min_count)
    if rule_confs64 is not None:
        conf_rows = rule_confs64[freq_rows]
    elif mode == "support":
        # IEEE-identical to the reference's per-entry int(c)/P float
        # division (int32 counts are exactly representable in float64),
        # vectorized — the expansion is inside the timed mining bracket
        conf_rows = rule_counts[freq_rows] / float(n_playlists)
    else:
        conf_rows = rule_counts[freq_rows] / np.maximum(
            item_counts[freq_rows], 1
        )[:, None].astype(np.float64)
    ids_rows = rule_ids[freq_rows]
    valid_rows = ids_rows >= 0
    # one C-level gather for every name/conf in the dict, then per-row
    # slicing — the expansion runs inside the timed mining bracket, and
    # per-entry Python lookups were ~20% of it. An object array makes
    # names_arr[idx].tolist() a single fancy-index + materialize.
    names_arr = np.asarray(vocab_names, dtype=object)
    rk, ck = np.nonzero(valid_rows)
    flat_names = names_arr[ids_rows[rk, ck]].tolist()
    flat_confs = conf_rows[rk, ck].tolist()
    bounds = np.concatenate(
        [[0], np.cumsum(valid_rows.sum(axis=1))]
    ).tolist()
    key_names = names_arr[freq_rows].tolist()
    out: dict[str, dict[str, float]] = {}
    for k in range(len(freq_rows)):
        lo, hi = bounds[k], bounds[k + 1]
        out[key_names[k]] = dict(zip(flat_names[lo:hi], flat_confs[lo:hi]))
    return out


@dataclasses.dataclass
class RuleTensors:
    """Host-side mined result + provenance."""

    rule_ids: np.ndarray  # int32 (V, K_max)
    rule_counts: np.ndarray  # int32 (V, K_max)
    rule_confs: np.ndarray  # float32 (V, K_max), serving-ready
    item_counts: np.ndarray  # int32 (V,)
    n_playlists: int
    min_support: float
    min_count: int
    mode: str  # "support" | "confidence"
    min_confidence: float
    n_frequent_items: int  # == len(keys) of the expanded dict
    n_songs_missing: int  # total_songs - len(keys) (reference main.py:304)
    overflow_rows: int  # rows whose true consequent set exceeded K_max
    # emission-time TRUE consequent-set sizes (may exceed K_max); lets the
    # multi-antecedent merge keep the overflow count honest after it can no
    # longer see the entries emission truncated away
    row_valid_counts: np.ndarray | None = None  # int32 (V,)
    # set when confidences can NOT be re-derived from counts alone — i.e.
    # triple-antecedent contributions are merged in (conf = s3/c_ab has a
    # per-rule denominator); float64 so dict expansion keeps full precision
    rule_confs64: np.ndarray | None = None

    @property
    def frequent_item_mask(self) -> np.ndarray:
        return self.item_counts >= self.min_count

    def to_rules_dict(self, vocab_names: list[str]) -> dict[str, dict[str, float]]:
        return expand_rules_dict(
            vocab_names,
            self.rule_ids,
            self.rule_counts,
            self.item_counts,
            n_playlists=self.n_playlists,
            min_support=self.min_support,
            mode=self.mode,
            rule_confs64=self.rule_confs64,
        )


def antecedent_contributions(
    members: tuple[np.ndarray, ...],  # each int (E,), -1 padded
    ant_counts: np.ndarray,  # int (E,) support of the antecedent itemset
    ext_counts: np.ndarray,  # int (E, V) support of antecedent ∪ {col}
    *,
    min_count: int,
    min_confidence: float,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Directed rule contributions from one antecedent size, vectorized.

    For each row e — an antecedent itemset A = {members[0][e], …} — and each
    column c with ``ext_counts[e, c] ≥ min_count``, the rule A→c holds at
    conf = ext/ant. The reference slow path assigns that confidence from
    EVERY member of A to c (machine-learning/main.py:247-255), so each hit
    yields ``len(members)`` directed (row, col, conf) entries. Columns that
    are themselves members hold the antecedent's own support, not a proper
    extension, and are masked out. → (rows, cols, vals).
    """
    e_valid = np.flatnonzero((members[0] >= 0) & (ant_counts > 0))
    ext = ext_counts[e_valid]  # (E', V)
    ms = [m[e_valid].astype(np.int64) for m in members]
    ac = ant_counts[e_valid].astype(np.int64)
    mask = ext >= min_count
    if e_valid.size:
        e_rows = np.arange(e_valid.size)
        for m in ms:
            mask[e_rows, m] = False
    conf = ext.astype(np.int64) / ac[:, None].astype(np.float64)
    mask &= conf >= min_confidence
    e_hit, k_hit = np.nonzero(mask)
    vals_hit = conf[e_hit, k_hit]
    rows = np.concatenate([m[e_hit] for m in ms])
    cols = np.tile(k_hit.astype(np.int64), len(ms))
    vals = np.tile(vals_hit, len(ms))
    return rows, cols, vals


def merge_confidence_contributions(
    tensors: "RuleTensors",
    contributions: list[tuple[np.ndarray, np.ndarray, np.ndarray]],
    *,
    k_max: int,
) -> "RuleTensors":
    """Fold multi-antecedent rule contributions into the pairwise confidence
    tensors — the part of the reference slow path's semantics
    (machine-learning/main.py:224-260) that pairwise mining cannot dominate:
    conf({a,b}→c) = s3/s(ab) (and conf({a,b,c}→d) = s4/s(abc), …) may
    exceed every pairwise confidence involving the consequent. Rules whose
    antecedent is a PROPER SUBSET of another frequent itemset's antecedent
    at the same size-or-less ARE dominated (sL/c_A ≤ s(A∪{c})/c_A), so
    (L-1)-antecedent contributions per itemset length L are sufficient for
    exactness at that max length.

    Contributions max-merge with the pairwise rows, re-rank per row
    (confidence descending, ties by lower consequent id), truncate to
    ``k_max``.
    """
    v = tensors.rule_ids.shape[0]
    denom = np.maximum(tensors.item_counts, 1).astype(np.float64)

    # sparse (row, col, conf) entries from the pairwise emission
    rb, kb = np.nonzero(tensors.rule_ids >= 0)
    cols_b = tensors.rule_ids[rb, kb].astype(np.int64)
    vals_b = tensors.rule_counts[rb, kb].astype(np.int64) / denom[rb]

    rows = np.concatenate([rb.astype(np.int64)] + [c[0] for c in contributions])
    cols = np.concatenate([cols_b] + [c[1] for c in contributions])
    vals = np.concatenate([vals_b] + [c[2] for c in contributions])

    # max-dedup per (row, col): sort by (row, col, conf desc), keep first
    order = np.lexsort((-vals, cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    keep_first = np.ones(len(rows), dtype=bool)
    keep_first[1:] = (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])
    rows, cols, vals = rows[keep_first], cols[keep_first], vals[keep_first]

    # per-row rank by conf desc (ties: lower col id — deterministic)
    order = np.lexsort((cols, -vals, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    row_start = np.ones(len(rows), dtype=bool)
    row_start[1:] = rows[1:] != rows[:-1]
    seg_id = np.cumsum(row_start) - 1
    rank = np.arange(len(rows)) - np.flatnonzero(row_start)[seg_id]
    # honest overflow: a row is truncated if the MERGED candidate set
    # exceeds k_max, or if emission already truncated it (the merge can't
    # see those dropped entries — tensors.row_valid_counts remembers them)
    overflow_mask = np.zeros(v, dtype=bool)
    if len(rows):
        row_sizes = np.bincount(seg_id)
        overflow_mask[rows[row_start]] = row_sizes > k_max
    if tensors.row_valid_counts is not None:
        overflow_mask |= tensors.row_valid_counts > k_max
    overflow = int(overflow_mask.sum())
    keep = rank < k_max
    rows, cols, vals, rank = rows[keep], cols[keep], vals[keep], rank[keep]

    rule_ids = np.full((v, k_max), -1, dtype=np.int32)
    rule_confs64 = np.zeros((v, k_max), dtype=np.float64)
    rule_ids[rows, rank] = cols
    rule_confs64[rows, rank] = vals
    return dataclasses.replace(
        tensors,
        rule_ids=rule_ids,
        # counts cannot back these confidences (per-rule denominators);
        # consumers MUST use rule_confs64 — artifacts.load_rule_tensors
        # refuses an artifact where this invariant is broken
        rule_counts=np.zeros((v, k_max), dtype=np.int32),
        rule_confs=rule_confs64.astype(np.float32),
        rule_confs64=rule_confs64,
        overflow_rows=overflow,
    )


@partial(jax.jit, static_argnames=("n_playlists", "n_tracks", "k_max"))
def fused_dense_rule_tensors(
    playlist_rows: jax.Array,
    track_ids: jax.Array,
    min_count: jax.Array,
    *,
    n_playlists: int,
    n_tracks: int,
    k_max: int,
):
    """One-hot encode → MXU pair matmul → threshold/top-k emission as ONE
    compiled program: membership pairs in, finished rule tensors out.

    The unfused path (``pair_count_fn`` + :func:`mine_rules_from_counts`)
    dispatches eager encode ops, syncs on the count matrix, then issues four
    separate device→host fetches — each paying a full host<->device round
    trip, which dominates the mining bracket when the link is a remote-TPU
    tunnel (~65 ms/trip). Fusing also lets XLA schedule encode/matmul/top-k
    without host turnarounds. Used by ``mining.miner.mine`` whenever no
    intermediate (one-hot matrix, count matrix) is needed downstream."""
    from . import encode, support

    x = encode.onehot_matrix(
        playlist_rows, track_ids, n_playlists=n_playlists, n_tracks=n_tracks
    )
    counts = support.pair_counts(x)
    rule_ids, rule_counts, row_valid = emit_rule_tensors(
        counts, min_count, k_max=k_max
    )
    # compact the device→host transfer (VERDICT r3 next-round #4): ids and
    # row sizes fit int16 whenever V ≤ 32767, counts whenever P ≤ 32767 —
    # both static at trace time — halving the fetch through a tunneled
    # backend. The host upcasts back to the int32 RuleTensors contract.
    id_dt = jnp.int16 if n_tracks <= 32767 else jnp.int32
    ct_dt = jnp.int16 if n_playlists <= 32767 else jnp.int32
    return (
        rule_ids.astype(id_dt),
        rule_counts.astype(ct_dt),
        row_valid.astype(id_dt),
        jnp.diagonal(counts).astype(ct_dt),
    )


def emit_rule_tensors_np(
    pair_count_matrix: np.ndarray, min_count: int, *, k_max: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Numpy twin of :func:`emit_rule_tensors` for the native-CPU mining
    path — XLA:CPU's ``top_k`` costs ~400 ms at ds2 shape where
    argpartition costs ~100 ms.

    Tie semantics replicated EXACTLY (equal counts rank by ascending column
    index, like lax.top_k) via a composite integer key ``score·V + (V-1-j)``
    that is strictly totally ordered, so partition/sort order is unique."""
    v = pair_count_matrix.shape[0]
    # int32 end to end when the key range fits (counts ≤ P make this the
    # common case): the (V, V) passes are memory-bound, and int64
    # intermediates double every one of them. The bound uses the
    # OFF-diagonal max — the diagonal holds item supports, which dominate
    # pair counts and never enter the score, so including them would flip
    # to int64 needlessly (diagonal zeroed in place and restored: one O(V)
    # touch instead of a (V, V) masked copy).
    if pair_count_matrix.flags.writeable:
        diag_save = np.diagonal(pair_count_matrix).copy()
        np.fill_diagonal(pair_count_matrix, 0)
        try:
            max_count = int(pair_count_matrix.max(initial=0))
        finally:
            np.fill_diagonal(pair_count_matrix, diag_save)
    else:  # read-only input (e.g. a jax-backed view): masked copy instead
        masked = pair_count_matrix.copy()
        np.fill_diagonal(masked, 0)
        max_count = int(masked.max(initial=0))
        del masked
    key_dtype = (
        np.int32
        if (max_count + 1) * v < np.iinfo(np.int32).max
        else np.int64
    )
    counts = pair_count_matrix.astype(key_dtype, copy=False)
    valid = counts >= min_count
    np.fill_diagonal(valid, False)
    row_valid_counts = valid.sum(axis=1, dtype=np.int32)
    score = np.where(valid, counts, key_dtype(-1))
    key = score * key_dtype(v) + (
        v - 1 - np.arange(v, dtype=key_dtype)[None, :]
    )
    k = min(k_max, v)
    if k < v:
        part = np.argpartition(-key, k - 1, axis=1)[:, :k]
    else:
        part = np.broadcast_to(np.arange(v)[None, :], (v, v)).copy()
    part_key = np.take_along_axis(key, part, axis=1)
    order = np.argsort(-part_key, axis=1)
    top_ids = np.take_along_axis(part, order, axis=1)
    top_counts = np.take_along_axis(score, top_ids, axis=1)
    keep = top_counts > 0
    rule_ids = np.where(keep, top_ids, -1).astype(np.int32)
    rule_counts = np.where(keep, top_counts, 0).astype(np.int32)
    if k < k_max:  # pad up to the declared row capacity
        pad = ((0, 0), (0, k_max - k))
        rule_ids = np.pad(rule_ids, pad, constant_values=-1)
        rule_counts = np.pad(rule_counts, pad)
    return rule_ids, rule_counts, row_valid_counts


def mine_rules_from_counts_np(
    pair_count_matrix: np.ndarray,
    *,
    n_playlists: int,
    min_support: float,
    k_max: int,
    mode: str = "support",
    min_confidence: float = 0.0,
    n_total_songs: int | None = None,
) -> RuleTensors:
    """Host-only emission from a host count matrix (the native-CPU path):
    no device round trip anywhere. Prefers the native C++ top-k (a bounded
    per-row heap, ~5 ms at ds2 shape vs ~82 ms for the numpy argpartition
    route); :func:`emit_rule_tensors_np` remains the fallback and the
    cross-check twin — all three emitters are pinned identical by test."""
    min_count = min_count_for(min_support, n_playlists)
    emitted = None
    from . import cpu_popcount

    if cpu_popcount.available():
        try:
            emitted = cpu_popcount.emit_topk(
                pair_count_matrix, min_count, k_max=k_max
            )
        except RuntimeError:
            emitted = None
    if emitted is None:
        emitted = emit_rule_tensors_np(
            pair_count_matrix, min_count, k_max=k_max
        )
    rule_ids, rule_counts, row_valid = emitted
    return assemble_rule_tensors(
        rule_ids, rule_counts, row_valid,
        np.diagonal(pair_count_matrix).astype(np.int32, copy=True),
        n_playlists=n_playlists, min_support=min_support, k_max=k_max,
        mode=mode, min_confidence=min_confidence,
        n_total_songs=n_total_songs,
        n_tracks=int(pair_count_matrix.shape[0]),
    )


def assemble_rule_tensors(
    rule_ids: np.ndarray,
    rule_counts: np.ndarray,
    row_valid: np.ndarray,
    item_counts: np.ndarray,
    *,
    n_playlists: int,
    min_support: float,
    k_max: int,
    mode: str = "support",
    min_confidence: float = 0.0,
    n_total_songs: int | None = None,
    n_tracks: int | None = None,
) -> RuleTensors:
    """Host-side assembly shared by the fused and unfused emission paths:
    confidence filtering/derivation + provenance/overflow stats."""
    if mode not in ("support", "confidence"):
        raise ValueError(f"confidence mode must be 'support' or 'confidence', got {mode!r}")
    min_count = min_count_for(min_support, n_playlists)
    n_frequent = int((item_counts >= min_count).sum())
    if mode == "confidence":
        # confidence filter applied HOST-SIDE in float64, so device float32
        # rounding can never flip a min_confidence decision (the same
        # no-float-flip rule integer min_count enforces for support). Within
        # a row, conf ordering == count ordering (fixed denominator), so the
        # device top-k's ranking is already correct and the filter removes a
        # suffix of each row.
        conf64 = rule_counts / np.maximum(item_counts, 1)[:, None].astype(np.float64)
        keep = (rule_ids >= 0) & (conf64 >= min_confidence)
        rule_ids = np.where(keep, rule_ids, -1).astype(np.int32)
        rule_counts = np.where(keep, rule_counts, 0)
    confs = derive_confs(rule_counts, item_counts, n_playlists, mode)
    return RuleTensors(
        rule_ids=rule_ids,
        rule_counts=rule_counts,
        rule_confs=confs,
        item_counts=item_counts,
        n_playlists=n_playlists,
        min_support=min_support,
        min_count=min_count,
        mode=mode,
        min_confidence=min_confidence,
        n_frequent_items=n_frequent,
        n_songs_missing=(
            n_total_songs if n_total_songs is not None else int(n_tracks)
        ) - n_frequent,
        overflow_rows=int((row_valid > k_max).sum()),
        row_valid_counts=row_valid.astype(np.int32),
    )


def mine_rules_from_counts(
    pair_count_matrix: jax.Array,
    *,
    n_playlists: int,
    min_support: float,
    k_max: int,
    mode: str = "support",
    min_confidence: float = 0.0,
    n_total_songs: int | None = None,
) -> RuleTensors:
    """Full emission from a materialized count matrix: device
    threshold/top-k, then host assembly + stats. The path for sharded and
    bit-packed mining (where the counts already exist); the dense
    single-device path uses :func:`fused_dense_rule_tensors` instead.

    ``n_total_songs``: the dataset's full unique-track count when the count
    matrix covers a PRUNED vocabulary (Apriori pre-filter) — keeps the
    missing-songs counter meaning what the reference prints
    (total_songs - frequent keys, machine-learning/main.py:304)."""
    min_count = min_count_for(min_support, n_playlists)
    rule_ids, rule_counts, row_valid = emit_rule_tensors(
        pair_count_matrix, jnp.int32(min_count), k_max=k_max
    )
    diag = jnp.diagonal(pair_count_matrix)
    # one batched fetch — four sequential np.asarray calls would pay four
    # host<->device round trips on a tunneled backend
    rule_ids, rule_counts, row_valid, item_counts = jax.device_get(
        (rule_ids, rule_counts, row_valid, diag)
    )
    return assemble_rule_tensors(
        rule_ids, rule_counts, row_valid, item_counts,
        n_playlists=n_playlists, min_support=min_support, k_max=k_max,
        mode=mode, min_confidence=min_confidence,
        n_total_songs=n_total_songs,
        n_tracks=int(pair_count_matrix.shape[0]),
    )
