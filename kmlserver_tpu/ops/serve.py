"""The serving hot path as one jitted device call.

Replaces the reference's per-request pure-Python dict max-merge + sort
(reference: rest_api/app/main.py:224-254): seed songs' rule rows are gathered
from the HBM-resident rule tensors, max-merged by scatter-max into a dense
per-request score vector, and the top-K names extracted — batched over B
concurrent requests so 1k QPS rides a handful of device calls.

Semantics parity notes:
- seeds absent from the rule tensors contribute nothing (the reference
  filters seeds by dict membership, rest_api/app/main.py:235);
- a recommendation may be another seed song (the reference's merge does not
  exclude seeds — only each row's own antecedent is absent from its row);
- merge is max over per-seed confidences (defaultdict max-merge at :240-247),
  then descending sort, then top ``K_BEST_TRACKS`` (:250-253). ``top_k``'s
  tie order (by index) stands in for Python's stable sort order on ties; the
  set of returned confidences is identical.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp


def _masked_topk_from_candidates(
    cand_ids: jax.Array,  # int32 (B, N) GLOBAL ids, -1 = dead lane
    cand_confs: jax.Array,  # float32 (B, N), 0 = dead lane
    *,
    v: int,
    k_best: int,
):
    """THE kernel epilogue, shared by every lookup variant: max-merge
    (id, conf) candidate lanes into a (B, V) score vector (dead lanes —
    id < 0 or conf ≤ 0 — dump into a spill slot V, sliced off), then the
    canonical masked top-k: ids with conf ≤ 0 become -1, columns
    statically padded up to ``k_best``. One copy on purpose — the
    replicated kernel, the per-shard partials, and the cross-shard merge
    all route through it, which is what makes the layout bit-identity
    contract (tests/test_shard_layout.py) a structural property instead
    of three hand-kept copies."""
    b = cand_ids.shape[0]
    live = (cand_ids >= 0) & (cand_confs > 0)
    targets = jnp.where(live, cand_ids, v)
    confs = jnp.where(live, cand_confs, 0.0)
    scores = jnp.zeros((b, v + 1), dtype=cand_confs.dtype)
    batch_idx = jnp.arange(b, dtype=jnp.int32)[:, None]
    scores = scores.at[batch_idx, targets].max(confs)[:, :v]
    k = min(k_best, v)
    top_confs, top_ids = jax.lax.top_k(scores, k)
    top_ids = jnp.where(top_confs > 0, top_ids, -1)
    if k < k_best:  # static pad so callers always see k_best columns
        pad = ((0, 0), (0, k_best - k))
        top_ids = jnp.pad(top_ids, pad, constant_values=-1)
        top_confs = jnp.pad(top_confs, pad)
    return top_ids, top_confs


def _recommend_batch_impl(
    rule_ids: jax.Array,  # int32 (V, K_max), -1 padded
    rule_confs: jax.Array,  # float32 (V, K_max), 0 padded
    seed_ids: jax.Array,  # int32 (B, L), -1 padded
    *,
    k_best: int,
):
    """→ ``(top_ids int32 (B, k_best) with -1 padding, top_confs f32)``."""
    v = rule_ids.shape[0]
    b = seed_ids.shape[0]
    safe_seeds = jnp.where(seed_ids >= 0, seed_ids, 0)
    gathered_ids = rule_ids[safe_seeds]  # (B, L, K)
    gathered_confs = rule_confs[safe_seeds]  # (B, L, K)
    valid = (gathered_ids >= 0) & (seed_ids >= 0)[..., None]
    return _masked_topk_from_candidates(
        jnp.where(valid, gathered_ids, -1).reshape(b, -1),
        jnp.where(valid, gathered_confs, 0.0).reshape(b, -1),
        v=v, k_best=k_best,
    )


recommend_batch = partial(jax.jit, static_argnames=("k_best",))(
    _recommend_batch_impl
)

# Donating twin: the padded seed buffer is consumed by the call, letting XLA
# reuse its device memory for the outputs — steady-state batches then do no
# fresh HBM allocation on the seed path. Each dispatch stages a new seed
# array anyway (the host staging buffer is what gets reused), so donation
# costs nothing. Kept separate from `recommend_batch` because donation on
# the CPU backend is unimplemented and warns per call; the engine picks the
# donating variant only on accelerator backends.
recommend_batch_donated = partial(
    jax.jit, static_argnames=("k_best",), donate_argnums=(2,)
)(_recommend_batch_impl)


# ---------------------------------------------------------------------------
# Vocab-sharded layout (KMLS_MODEL_LAYOUT=sharded): the rule tensors are
# partitioned along the vocab (antecedent) axis across a 1-D device mesh —
# per-device HBM holds V/S rows instead of V, so the servable catalog scales
# with the mesh instead of capping at one device (the ALX sharding recipe,
# PAPERS.md). Lookup runs as one shard_map program:
#
#   1. each shard maps the replicated seed batch onto its own row range
#      (seeds outside the range contribute nothing — exactly the replicated
#      kernel's membership semantics, partitioned),
#   2. gathers + scatter-maxes its rows into a GLOBAL-width score vector
#      (consequent ids span the full vocab; the transient (B, V) scores are
#      ~K_max× smaller than the resident rule rows, so full width per shard
#      is the cheap axis), and takes a per-shard top-k partial,
#   3. all_gather of the (B, k) partials over the shard axis, then a
#      max-merge rescatter + final top-k — replicated on every shard.
#
# Exactness, including lax.top_k's index tie order: for any consequent in
# the true global top-k, the shard where it attains its max partial score
# must rank it inside ITS top-k (fewer than k competitors beat it there, or
# they would beat it globally too), so the gathered candidate set contains
# every true winner at its exact global score, and the merge's scatter-max
# + top_k reproduces the replicated kernel's output bit for bit (pinned by
# tests/test_shard_layout.py).
# ---------------------------------------------------------------------------


def _shard_partial_topk_impl(
    rule_ids_loc: jax.Array,  # int32 (V_loc, K) — GLOBAL consequent ids
    rule_confs_loc: jax.Array,  # float32 (V_loc, K)
    seed_ids: jax.Array,  # int32 (B, L), -1 padded, GLOBAL ids, replicated
    lo: jax.Array,  # int32 scalar: this shard's first global row
    *,
    v: int,
    k_best: int,
):
    """One shard's (B, k_best) top-k partial at GLOBAL ids and width.

    The seed batch is mapped onto this shard's row range [lo, lo+V_loc)
    (seeds outside contribute nothing — the replicated kernel's
    membership semantics, partitioned), its rows gathered, and the
    candidates pushed through THE shared epilogue at the full vocab
    width. ``lo`` is a traced scalar so one compiled program serves
    every shard — inside shard_map it is ``axis_index * v_loc``; on a
    serve-mesh gang member it is ``rank * v_loc``."""
    v_loc = rule_ids_loc.shape[0]
    b = seed_ids.shape[0]
    in_shard = (seed_ids >= lo) & (seed_ids < lo + v_loc)
    local_seeds = jnp.where(in_shard, seed_ids - lo, -1)
    safe_seeds = jnp.where(local_seeds >= 0, local_seeds, 0)
    gathered_ids = rule_ids_loc[safe_seeds]  # (B, L, K)
    gathered_confs = rule_confs_loc[safe_seeds]
    valid = (gathered_ids >= 0) & (local_seeds >= 0)[..., None]
    return _masked_topk_from_candidates(
        jnp.where(valid, gathered_ids, -1).reshape(b, -1),
        jnp.where(valid, gathered_confs, 0.0).reshape(b, -1),
        v=v, k_best=k_best,
    )


def _merge_partial_topk_impl(
    all_ids: jax.Array,  # int32 (S, B, k_best) partials, SHARD order
    all_confs: jax.Array,  # float32 (S, B, k_best)
    *,
    v: int,
    k_best: int,
):
    """Cross-shard max-merge of per-shard partials → final (B, k_best).

    Every shard's masked partial lanes become candidates for one more
    pass through the shared epilogue. The leading axis must be in shard
    order (all_gather's axis order inside shard_map; ascending gang rank
    on the serve mesh) — the epilogue's scatter-max is order-invariant
    in value, and top_k's index tie order sees only GLOBAL ids, so the
    merge is bit-identical either way."""
    s, b, k = all_ids.shape
    return _masked_topk_from_candidates(
        jnp.swapaxes(all_ids, 0, 1).reshape(b, s * k),
        jnp.swapaxes(all_confs, 0, 1).reshape(b, s * k),
        v=v, k_best=k_best,
    )


# Jitted module-level twins for the multi-process serve mesh
# (serving/mesh.py): each gang member runs shard_partial_topk over its
# resident vocab slab, the coordinator stacks the partials in rank order
# and runs merge_partial_topk — the SAME two functions the shard_map
# kernel below composes, which is what makes gang answers bit-identical
# to the single-process sharded kernel by construction rather than by
# parallel maintenance (pinned in tests/test_mesh.py).
shard_partial_topk = partial(jax.jit, static_argnames=("v", "k_best"))(
    _shard_partial_topk_impl
)
merge_partial_topk = partial(jax.jit, static_argnames=("v", "k_best"))(
    _merge_partial_topk_impl
)


def _sharded_recommend_local(
    rule_ids_loc: jax.Array,  # int32 (V_loc, K) — GLOBAL consequent ids
    rule_confs_loc: jax.Array,  # float32 (V_loc, K)
    seed_ids: jax.Array,  # int32 (B, L), -1 padded, GLOBAL ids, replicated
    *,
    k_best: int,
    axis: str,
    n_shards: int,
):
    v_loc = rule_ids_loc.shape[0]
    v = v_loc * n_shards  # padded global vocab width
    lo = jax.lax.axis_index(axis).astype(jnp.int32) * v_loc
    part_ids, part_confs = _shard_partial_topk_impl(
        rule_ids_loc, rule_confs_loc, seed_ids, lo, v=v, k_best=k_best,
    )
    all_ids = jax.lax.all_gather(part_ids, axis)  # (S, B, k_best)
    all_confs = jax.lax.all_gather(part_confs, axis)
    return _merge_partial_topk_impl(
        all_ids, all_confs, v=v, k_best=k_best,
    )


@functools.lru_cache(maxsize=8)
def sharded_recommend_fn(mesh, k_best: int, axis: str = "shard"):
    """The jitted sharded lookup for one (mesh, k_best) — cached so the
    serving engine resolves it ONCE at bundle build (publication side) and
    every dispatch reuses the same compiled program: rebuilding the
    jit(shard_map(...)) closure per call would retrace on the hot path.

    Contract: ``rule_ids``/``rule_confs`` laid out
    ``NamedSharding(mesh, P(axis, None))`` with the padded vocab length a
    multiple of the shard count; ``seed_ids`` replicated. Output
    (replicated) is bit-identical to :func:`recommend_batch` on the same
    (unpadded) tensors."""
    from jax.sharding import PartitionSpec as P

    from ..utils.jaxcompat import shard_map

    n_shards = mesh.shape[axis]
    local = partial(
        _sharded_recommend_local,
        k_best=k_best, axis=axis, n_shards=n_shards,
    )
    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(P(axis, None), P(axis, None), P(None, None)),
            out_specs=(P(None, None), P(None, None)),
            # the all_gather makes both outputs mesh-invariant; the scatter
            # updates carry no vma annotation the checker could follow
            check_vma=False,
        )
    )
