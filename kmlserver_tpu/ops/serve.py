"""The serving hot path as one jitted device call.

Replaces the reference's per-request pure-Python dict max-merge + sort
(reference: rest_api/app/main.py:224-254): seed songs' rule rows are gathered
from the HBM-resident rule tensors, max-merged by scatter-max into a dense
per-request score vector, and the top-K names extracted — batched over B
concurrent requests so 1k QPS rides a handful of device calls.

Semantics parity notes:
- seeds absent from the rule tensors contribute nothing (the reference
  filters seeds by dict membership, rest_api/app/main.py:235);
- a recommendation may be another seed song (the reference's merge does not
  exclude seeds — only each row's own antecedent is absent from its row);
- merge is max over per-seed confidences (defaultdict max-merge at :240-247),
  then descending sort, then top ``K_BEST_TRACKS`` (:250-253). ``top_k``'s
  tie order (by index) stands in for Python's stable sort order on ties; the
  set of returned confidences is identical.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _recommend_batch_impl(
    rule_ids: jax.Array,  # int32 (V, K_max), -1 padded
    rule_confs: jax.Array,  # float32 (V, K_max), 0 padded
    seed_ids: jax.Array,  # int32 (B, L), -1 padded
    *,
    k_best: int,
):
    """→ ``(top_ids int32 (B, k_best) with -1 padding, top_confs f32)``."""
    v = rule_ids.shape[0]
    b = seed_ids.shape[0]
    safe_seeds = jnp.where(seed_ids >= 0, seed_ids, 0)
    gathered_ids = rule_ids[safe_seeds]  # (B, L, K)
    gathered_confs = rule_confs[safe_seeds]  # (B, L, K)
    valid = (gathered_ids >= 0) & (seed_ids >= 0)[..., None]
    # dump padding into an extra slot V, sliced off after the scatter
    targets = jnp.where(valid, gathered_ids, v)
    confs = jnp.where(valid, gathered_confs, 0.0)
    scores = jnp.zeros((b, v + 1), dtype=rule_confs.dtype)
    batch_idx = jnp.arange(b, dtype=jnp.int32)[:, None, None]
    scores = scores.at[batch_idx, targets].max(confs)
    scores = scores[:, :v]
    k = min(k_best, v)
    top_confs, top_ids = jax.lax.top_k(scores, k)
    top_ids = jnp.where(top_confs > 0, top_ids, -1)
    if k < k_best:  # static pad so callers always see k_best columns
        pad = ((0, 0), (0, k_best - k))
        top_ids = jnp.pad(top_ids, pad, constant_values=-1)
        top_confs = jnp.pad(top_confs, pad)
    return top_ids, top_confs


recommend_batch = partial(jax.jit, static_argnames=("k_best",))(
    _recommend_batch_impl
)

# Donating twin: the padded seed buffer is consumed by the call, letting XLA
# reuse its device memory for the outputs — steady-state batches then do no
# fresh HBM allocation on the seed path. Each dispatch stages a new seed
# array anyway (the host staging buffer is what gets reused), so donation
# costs nothing. Kept separate from `recommend_batch` because donation on
# the CPU backend is unimplemented and warns per call; the engine picks the
# donating variant only on accelerator backends.
recommend_batch_donated = partial(
    jax.jit, static_argnames=("k_best",), donate_argnums=(2,)
)(_recommend_batch_impl)
