"""Sparsity-adaptive pair-support counting — the third kernel family.

The dense MXU contraction (ops/support.py) and the bit-packed popcount
pair (ops/popcount.py) both pay DENSE-shaped work: ``O(P·V)`` operand
bytes for the one-hot and ``O(V²·P/32)`` word-ANDs for the bitset, no
matter how empty the basket matrix actually is. At realistic playlist
scale the matrix is >99% sparse (mean basket length ≪ V), so almost all
of that work multiplies zeros.

This module counts only what exists. ``C = XᵀX`` decomposes per basket:

    C = Σ_b e_b e_bᵀ,   e_b = the indicator of basket b's tracks

so a basket of length k contributes its k(k-1)/2 unordered track pairs
(C is symmetric — one count per pair, mirrored at the end) plus its k
diagonal singles (item supports — one bincount over the track ids). The
CSR-style half of the hybrid expands those pair events straight from the
(sorted) membership rows — repeats, one arange, gathers; no division —
and accumulates them with one integer bincount per chunk:
``O(Σ_b k_b²/2)`` work total, versus ``O(P·V²)`` dense FLOPs. Integer
accumulation in any order is exact, so the counts are BIT-IDENTICAL to
the dense and bit-packed paths — pinned by tests/test_sparse.py at four
densities in both layouts.

**The long-basket guard (the × bitpacked half of the hybrid):** pair
expansion is quadratic per basket, so one pathological 50k-track basket
would generate 1.25G events on its own. Baskets longer than
``long_basket_threshold`` are split out, their rows gathered into a
COMPACT sub-problem (only the occupied playlists exist in it), and
counted densely there — through the native bit-packed POPCNT kernel when
it's available, or an exact float64 BLAS contraction otherwise — then
summed into the sparse counts. Both halves are exact integer math, so
the split point changes performance, never results.

Everything here is host-side numpy by design, like the native-CPU
counter: the whole point of the sparse path is that the ``(P, V)``
operand never exists anywhere — only the nnz membership pairs and the
``(V_f, V_f)`` count matrix (post-Apriori ``V_f`` is the few thousand
frequent items) are ever materialized. A jitted device twin
(:func:`sparse_pair_counts_device`) scatter-adds the same event stream
on an accelerator backend for jobs whose emission stays on device; same
events + integer adds = bit-identical by construction.

Which of the three families runs is a MEASURED decision —
``mining/dispatch.py`` — not a hand-set threshold; see the README
"Sparse kernels & dispatch" section.
"""

from __future__ import annotations

import numpy as np

# Baskets longer than this leave the CSR pair expansion for the gathered
# dense/bitpacked sub-count (quadratic-per-basket guard). Env-tunable via
# KMLS_SPARSE_LONG_BASKET (read per call, not at import — the popcount
# tile knobs' import-time-read bug is not repeated here).
LONG_BASKET_DEFAULT = 256

# Pair events expanded per accumulation chunk: bounds the transient
# expansion arrays (~5 words/event) and amortizes the per-chunk
# ``O(V²)`` bincount sweep. Larger is faster until the chunk's key
# array stops fitting cache-adjacent memory.
EVENT_CHUNK = 16_000_000


def resolve_long_basket(threshold: int | None = None) -> int:
    """``KMLS_SPARSE_LONG_BASKET`` (lazy read) with the module default."""
    import os

    if threshold is not None:
        return max(int(threshold), 2)
    raw = os.environ.get("KMLS_SPARSE_LONG_BASKET")
    return max(int(raw), 2) if raw not in (None, "") else LONG_BASKET_DEFAULT


def _sorted_by_playlist(
    playlist_rows: np.ndarray, track_ids: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Membership rows grouped by playlist (stable, so equal-playlist
    order is preserved). ``build_baskets`` already emits sorted rows —
    the monotonicity probe keeps that case a no-op."""
    rows = np.asarray(playlist_rows)
    tids = np.asarray(track_ids)
    if rows.size and np.any(np.diff(rows) < 0):
        order = np.argsort(rows, kind="stable")
        rows, tids = rows[order], tids[order]
    return rows, tids


def basket_lengths(playlist_rows: np.ndarray, n_playlists: int) -> np.ndarray:
    """Per-playlist membership counts (int64, O(nnz) host bincount)."""
    return np.bincount(
        np.asarray(playlist_rows, dtype=np.int64), minlength=n_playlists
    )


def pair_event_count(
    playlist_rows: np.ndarray,
    n_playlists: int,
    long_basket_threshold: int | None = None,
) -> tuple[int, int]:
    """``(pair_events, long_rows)`` the hybrid would process: the exact
    Σ k(k-1)/2 over short baskets, and the membership rows the
    long-basket sub-count gathers. The dispatcher's plan-time work
    estimate — exact, not a distributional guess, and O(nnz) to
    compute."""
    thr = resolve_long_basket(long_basket_threshold)
    lengths = basket_lengths(playlist_rows, n_playlists)
    short = lengths[lengths <= thr].astype(np.int64)
    long_rows = int(lengths[lengths > thr].sum())
    return int(np.sum(short * (short - 1) // 2)), long_rows


def _segments(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """``(starts, counts)`` of the contiguous playlist segments in the
    sorted membership rows (unique preserves first-occurrence order)."""
    _, starts, counts = np.unique(rows, return_index=True, return_counts=True)
    return starts.astype(np.int64), counts.astype(np.int64)


def _split_long(
    rows: np.ndarray, tids: np.ndarray, starts: np.ndarray,
    counts: np.ndarray, thr: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """→ ``(short_rows, short_tids, starts, counts, long_rows, long_tids)``
    with the segment structure recomputed for the short remainder."""
    long_seg = counts > thr
    if not np.any(long_seg):
        return rows, tids, starts, counts, rows[:0], tids[:0]
    sel = np.zeros(len(rows), dtype=bool)
    for s, c in zip(starts[long_seg], counts[long_seg]):
        sel[s : s + c] = True
    keep = ~sel
    short_rows, short_tids = rows[keep], tids[keep]
    if short_rows.size:
        starts, counts = _segments(short_rows)
    else:
        starts = counts = np.zeros(0, dtype=np.int64)
    return short_rows, short_tids, starts, counts, rows[sel], tids[sel]


def _iter_pair_keys(
    tids: np.ndarray,
    starts: np.ndarray,
    counts: np.ndarray,
    n_tracks: int,
    event_chunk: int,
    both_directions: bool = False,
):
    """Yield flat ``i·V + j`` keys for every unordered intra-basket pair,
    one POSITION-triangle event per pair (positions i < j inside each
    basket's segment — ids may come out either order; the caller mirrors,
    or asks for ``both_directions`` and skips the mirror pass).
    Division-free vectorized expansion in bounded chunks whose
    boundaries respect element granularity."""
    nnz = len(tids)
    if nnz == 0:
        return
    key_dtype = (
        np.int32
        if n_tracks * n_tracks < np.iinfo(np.int32).max
        else np.int64
    )
    seg_of = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    pos = np.arange(nnz, dtype=np.int64)
    # pairs each element opens: the elements AFTER it in its own basket
    rep_all = starts[seg_of] + counts[seg_of] - 1 - pos
    cum = np.cumsum(rep_all)
    lo = 0
    while lo < nnz:
        target = (cum[lo - 1] if lo else 0) + event_chunk
        hi = int(np.searchsorted(cum, target, side="left")) + 1
        hi = min(max(hi, lo + 1), nnz)
        rep = rep_all[lo:hi]
        n_events = int(rep.sum())
        if n_events:
            off = np.concatenate([[0], np.cumsum(rep[:-1])])
            within = np.arange(n_events, dtype=np.int64) - np.repeat(off, rep)
            left = np.repeat(tids[lo:hi], rep).astype(key_dtype)
            right = tids[np.repeat(pos[lo:hi] + 1, rep) + within].astype(
                key_dtype
            )
            v = key_dtype(n_tracks)
            if both_directions:
                yield np.concatenate([left * v + right, right * v + left])
            else:
                yield left * v + right
        lo = hi


def _count_long_dense(
    rows: np.ndarray, tids: np.ndarray, n_tracks: int
) -> np.ndarray:
    """Bitpacked/dense half over the GATHERED long baskets: only the
    occupied playlists exist in the sub-problem. Native POPCNT when the
    library is there; otherwise an exact float64 contraction (counts ≤ P
    ≪ 2^53, so the cast back to int32 is lossless)."""
    from . import cpu_popcount

    _, compact = np.unique(rows, return_inverse=True)
    p_long = int(compact.max()) + 1 if compact.size else 0
    if p_long == 0:
        return np.zeros((n_tracks, n_tracks), dtype=np.int32)
    if cpu_popcount.available():
        try:
            return np.asarray(
                cpu_popcount.pair_counts(
                    compact.astype(np.int32), tids.astype(np.int32),
                    n_playlists=p_long, n_tracks=n_tracks,
                ),
                dtype=np.int32,
            )
        except RuntimeError:
            pass
    x = np.zeros((p_long, n_tracks), dtype=np.float64)
    x[compact, tids] = 1.0
    return (x.T @ x).astype(np.int32)


def sparse_pair_counts_np(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    long_basket_threshold: int | None = None,
    event_chunk: int = EVENT_CHUNK,
) -> np.ndarray:
    """Pair counts ``(V, V) int32`` from membership pairs, touching only
    the nnz that exist. Pairs must be DEDUPLICATED (the ``build_baskets``
    invariant shared with the bitpack path): a duplicate would double-
    count here exactly as it would in the dense one-hot."""
    thr = resolve_long_basket(long_basket_threshold)
    rows, tids = _sorted_by_playlist(playlist_rows, track_ids)
    out = np.zeros((n_tracks, n_tracks), dtype=np.int32)
    if rows.size == 0:
        return out
    starts, counts = _segments(rows)
    rows, tids, starts, counts, lrows, ltids = _split_long(
        rows, tids, starts, counts, thr
    )
    if lrows.size:
        out += _count_long_dense(lrows, ltids, n_tracks)
    # short-basket diagonal = item supports; the long block above carries
    # its own diagonal (it is a complete sub-count)
    if tids.size:
        out[np.diag_indices(n_tracks)] += np.bincount(
            tids.astype(np.int64), minlength=n_tracks
        ).astype(np.int32, copy=False)
    e_total = int(np.sum(counts * (counts - 1) // 2))
    v2 = n_tracks * n_tracks
    # accumulator selection: the bincount path sweeps O(V²) PER CHUNK
    # (plus one V²-strided mirror), which is the right trade only while
    # event volume dominates the matrix; past that, sort-unique touches
    # O(E log E) regardless of V — the regime the sparse path exists for
    if v2 <= min(4 * max(e_total, 1), 1 << 28):
        upper = np.zeros(v2, dtype=np.int32)
        for keys in _iter_pair_keys(
            tids, starts, counts, n_tracks, event_chunk
        ):
            upper += np.bincount(keys, minlength=v2).astype(
                np.int32, copy=False
            )
        u = upper.reshape(n_tracks, n_tracks)
        out += u
        out += u.T
    else:
        flat = out.reshape(-1)
        for keys in _iter_pair_keys(
            tids, starts, counts, n_tracks, event_chunk,
            both_directions=True,
        ):
            uniq, cnt = np.unique(keys, return_counts=True)
            flat[uniq] += cnt.astype(np.int32, copy=False)
    return out


def sparse_rule_rows(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    min_count: int,
    k_max: int,
    long_basket_threshold: int | None = None,
    event_chunk: int = EVENT_CHUNK,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray] | None:
    """FULLY sparse count→emit: membership pairs straight to
    ``(rule_ids, rule_counts, row_valid, item_counts)`` without ever
    materializing the ``(V, V)`` count matrix — the matrix's only
    consumer is a per-row threshold + top-k, and the sorted unique
    (key, count) stream IS the matrix in CSR form. At large frequent
    vocabularies this skips both the O(V²) memory and the O(V²)
    emission sweep, which is where the dense-shaped paths (including
    the native C sparse-scatter method, whose output is still the dense
    matrix) spend most of their time.

    Bit-identical to ``ops.rules.emit_rule_tensors`` by construction:
    absent pairs count 0 < min_count (never emitted), the per-row
    ordering is (count desc, column asc) — exactly ``lax.top_k``'s tie
    order — via one lexsort over only the THRESHOLD SURVIVORS, and the
    diagonal/item supports come from the same integer bincount.

    Returns None when long baskets exist under the hybrid threshold:
    their sub-count is a dense block, so the caller falls back to the
    materialized-matrix route (still sparse counting, dense emission).
    """
    rows, tids = _sorted_by_playlist(playlist_rows, track_ids)
    rule_ids = np.full((n_tracks, k_max), -1, dtype=np.int32)
    rule_counts = np.zeros((n_tracks, k_max), dtype=np.int32)
    if rows.size == 0:
        return (
            rule_ids, rule_counts,
            np.zeros(n_tracks, dtype=np.int32),
            np.zeros(n_tracks, dtype=np.int32),
        )
    starts, counts = _segments(rows)
    thr = resolve_long_basket(long_basket_threshold)
    if np.any(counts > thr):
        return None
    item_counts = np.bincount(
        tids.astype(np.int64), minlength=n_tracks
    ).astype(np.int32)
    keys = [
        k for k in _iter_pair_keys(
            tids, starts, counts, n_tracks, event_chunk,
            both_directions=True,
        )
    ]
    if not keys:
        return (
            rule_ids, rule_counts,
            np.zeros(n_tracks, dtype=np.int32), item_counts,
        )
    uq, ct = np.unique(np.concatenate(keys), return_counts=True)
    del keys
    keep = ct >= min_count
    uq, ct = uq[keep], ct[keep].astype(np.int64)
    v = np.int64(n_tracks)
    r_surv = (uq.astype(np.int64) // v).astype(np.int32)
    c_surv = (uq.astype(np.int64) - r_surv.astype(np.int64) * v).astype(
        np.int32
    )
    row_valid = np.bincount(
        r_surv.astype(np.int64), minlength=n_tracks
    ).astype(np.int32)
    # (row asc, count desc, col asc) — lax.top_k's exact tie order;
    # survivors only, so this sort is tiny relative to the event stream
    order = np.lexsort((c_surv, -ct, r_surv))
    r_o = r_surv[order]
    rank = np.arange(len(r_o), dtype=np.int64) - np.repeat(
        np.concatenate([[0], np.cumsum(row_valid.astype(np.int64))[:-1]]),
        row_valid,
    )
    sel = rank < k_max
    rule_ids[r_o[sel], rank[sel]] = c_surv[order][sel]
    rule_counts[r_o[sel], rank[sel]] = ct[order][sel].astype(np.int32)
    return rule_ids, rule_counts, row_valid, item_counts


def sparse_pair_counts_device(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    long_basket_threshold: int | None = None,
    event_chunk: int = 1 << 20,
):
    """Device twin: the same event stream scatter-added on the default
    backend → ``(V, V) int32`` jax array. Events are generated host-side
    (they ARE the compressed representation — that's the point), padded
    to fixed-size chunks so the jit shape set stays bounded, and
    accumulated with integer ``.at[].add`` — exact in any order, so the
    result is bit-identical to :func:`sparse_pair_counts_np`. The long-
    basket block and the diagonal land host-side first (just more terms
    of the integer sum)."""
    import jax.numpy as jnp

    thr = resolve_long_basket(long_basket_threshold)
    rows, tids = _sorted_by_playlist(playlist_rows, track_ids)
    base = np.zeros((n_tracks, n_tracks), dtype=np.int32)
    if rows.size == 0:
        return jnp.asarray(base)
    starts, counts = _segments(rows)
    rows, tids, starts, counts, lrows, ltids = _split_long(
        rows, tids, starts, counts, thr
    )
    if tids.size:
        base[np.diag_indices(n_tracks)] += np.bincount(
            tids.astype(np.int64), minlength=n_tracks
        ).astype(np.int32, copy=False)
    if lrows.size:
        base += _count_long_dense(lrows, ltids, n_tracks)
    upper = jnp.zeros(n_tracks * n_tracks, dtype=jnp.int32)
    for keys in _iter_pair_keys(tids, starts, counts, n_tracks, event_chunk):
        pad = event_chunk - (len(keys) % event_chunk or event_chunk)
        padded = np.concatenate(
            [keys.astype(np.int64), np.full(pad, -1, np.int64)]
        )
        for c0 in range(0, len(padded), event_chunk):
            upper = _scatter_events(
                upper, jnp.asarray(padded[c0 : c0 + event_chunk])
            )
    u = upper.reshape(n_tracks, n_tracks)
    return u + u.T + jnp.asarray(base)


_scatter_step = None


def _scatter_events(flat, keys):
    """One fixed-shape scatter-add chunk: ``keys`` are flat ``i·V + j``
    event indices, -1 = padding (dropped via a sentinel row). The jitted
    step lives at module scope (built once, lazily — this module must
    import without jax work), so the jit cache genuinely keys on the
    (flat size, chunk shape) pair instead of recompiling per call."""
    global _scatter_step
    if _scatter_step is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(flat, keys):
            n = flat.shape[0]
            valid = keys >= 0
            idx = jnp.where(valid, keys, n)
            grown = jnp.concatenate([flat, jnp.zeros((1,), flat.dtype)])
            grown = grown.at[idx].add(valid.astype(flat.dtype))
            return grown[:n]

        _scatter_step = step
    return _scatter_step(flat, keys)


def sparse_restricted_pair_counts_np(
    playlist_rows: np.ndarray,
    track_ids: np.ndarray,
    row_ids: np.ndarray,
    *,
    n_playlists: int,
    n_tracks: int,
    event_chunk: int = EVENT_CHUNK,
) -> np.ndarray:
    """Rows ``row_ids`` of ``C = XᵀX`` → ``(R, V) int32`` — the sparse
    twin of the delta recount (``parallel.support.restricted_pair_counts``):
    only baskets containing a requested antecedent generate events, and
    each generates ``hits_b · k_b`` of them instead of the dense path's
    full ``P × R`` contraction. Bit-identical (integer accumulation)."""
    row_ids = np.asarray(row_ids, dtype=np.int64)
    r = len(row_ids)
    out = np.zeros((r, n_tracks), dtype=np.int32)
    if r == 0:
        return out
    rank = np.full(n_tracks, -1, dtype=np.int64)
    rank[row_ids] = np.arange(r, dtype=np.int64)
    rows, tids = _sorted_by_playlist(playlist_rows, track_ids)
    if rows.size == 0:
        return out
    starts, counts = _segments(rows)
    # per-element basket handle: which segment each membership row lives in
    seg_of = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    hits = np.flatnonzero(rank[tids] >= 0)  # membership rows that are antecedents
    if hits.size == 0:
        return out
    v = np.int64(n_tracks)
    rep_all = counts[seg_of[hits]]
    cum = np.cumsum(rep_all)
    lo = 0
    n_hits = len(hits)
    while lo < n_hits:
        target = (cum[lo - 1] if lo else 0) + event_chunk
        hi = int(np.searchsorted(cum, target, side="left")) + 1
        hi = min(max(hi, lo + 1), n_hits)
        h = hits[lo:hi]
        rep = rep_all[lo:hi]
        n_events = int(rep.sum())
        off = np.concatenate([[0], np.cumsum(rep[:-1])])
        within = np.arange(n_events, dtype=np.int64) - np.repeat(off, rep)
        left = np.repeat(rank[tids[h]], rep)
        right = tids[np.repeat(starts[seg_of[h]], rep) + within]
        out += np.bincount(
            left * v + right, minlength=r * n_tracks
        ).reshape(r, n_tracks).astype(np.int32, copy=False)
        lo = hi
    return out
