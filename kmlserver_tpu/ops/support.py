"""Frequent-itemset support counting — the mining compute core.

Replaces mlxtend's FP-Growth call (reference: machine-learning/main.py:272).
An FP-tree is a pointer-chasing recursion over conditional pattern bases —
hostile to XLA's static-shape compilation model — so this module uses an
exact dense formulation that lives on the MXU instead:

    pair_counts[i, j] = Σ_p X[p, i]·X[p, j]  =  (XᵀX)[i, j]

one int8×int8→int32 matmul. Higher-order itemsets extend frequent pairs by a
second matmul over masked column products (``triple_counts``).

**Why pairs are sufficient for output parity** (the dominance argument): the
reference's fast path walks every frequent itemset and max-merges the
*itemset support* into each member's recommendation row symmetrically
(reference: machine-learning/main.py:284-296 — note ``row.support`` at :286
is stored as the "confidence"). For any itemset S with |S| ≥ 2 and any two
members a, b ∈ S, the pair {a, b} ⊇-dominates S in support
(support({a,b}) ≥ support(S)) and is itself frequent whenever S is. Under a
max-merge, every contribution from S to (a → b) is therefore already covered
by the pair {a, b}. Singletons contribute nothing (no "other" member —
reference main.py:288 yields an empty loop). Hence the thresholded pair-
support matrix IS the reference's final rule mapping, exactly. Triples and
beyond only matter for the itemset census and for the dormant slow path's
true-confidence semantics (machine-learning/main.py:224-260), both of which
``triple_counts`` serves.

Support thresholding is done in INTEGER counts (``min_count``) computed on
host in float64, so device float32 rounding can never flip a frequency
decision vs. the CPU oracle.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp


def min_count_for(min_support: float, n_playlists: int) -> int:
    """Smallest integer count c with c / n_playlists >= min_support, computed
    in float64 exactly as a CPU oracle would compare (mlxtend keeps itemsets
    with support >= min_support). Clamped to at least 1."""
    c = int(math.ceil(min_support * n_playlists))
    # ceil can overshoot when min_support * n is an exact integer in f64
    while c > 1 and (c - 1) / n_playlists >= min_support:
        c -= 1
    return max(c, 1)


@jax.jit
def pair_counts(x_onehot: jax.Array) -> jax.Array:
    """``XᵀX`` over the playlist axis: int8 (P, V) → int32 (V, V).

    Diagonal = singleton supports; off-diagonal = pair supports. Contraction
    over P is dimension 0 of both operands, emitted as a single MXU matmul
    with int32 accumulation.
    """
    return jax.lax.dot_general(
        x_onehot,
        x_onehot,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


@jax.jit
def item_counts(x_onehot: jax.Array) -> jax.Array:
    """Per-item singleton supports: int32 (V,)."""
    return jnp.sum(x_onehot.astype(jnp.int32), axis=0)


@jax.jit
def triple_counts(x_onehot: jax.Array, pair_i: jax.Array, pair_j: jax.Array) -> jax.Array:
    """Supports of {i, j, k} for E candidate pairs × all k: int32 (E, V).

    ``Y[p, e] = X[p, i_e]·X[p, j_e]`` (elementwise on the VPU), then
    ``YᵀX`` on the MXU. Rows for invalid (padded) pairs are garbage and must
    be masked by the caller; columns k ∈ {i_e, j_e} hold pair supports, not
    proper triples, and must likewise be masked.
    """
    y = x_onehot[:, pair_i] * x_onehot[:, pair_j]  # (P, E) int8
    return jax.lax.dot_general(
        y,
        x_onehot,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


@jax.jit
def quad_counts(
    x_onehot: jax.Array,
    trip_i: jax.Array,
    trip_j: jax.Array,
    trip_k: jax.Array,
) -> jax.Array:
    """Supports of {i, j, k, l} for E candidate triples × all l: int32 (E, V).

    Same shape of computation as :func:`triple_counts` one level up:
    ``Y[p, e] = X[p, i_e]·X[p, j_e]·X[p, k_e]`` on the VPU, then ``YᵀX`` on
    the MXU. Rows for padded triples are garbage and must be masked by the
    caller; columns l ∈ {i_e, j_e, k_e} hold the triple support itself.
    """
    y = x_onehot[:, trip_i] * x_onehot[:, trip_j] * x_onehot[:, trip_k]
    return jax.lax.dot_general(
        y,
        x_onehot,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


@partial(jax.jit, static_argnames=("capacity",))
def frequent_pairs(counts: jax.Array, min_count: jax.Array, *, capacity: int):
    """Extract up to ``capacity`` frequent off-diagonal pairs (i < j) from the
    pair-count matrix, XLA-shape-statically.

    Returns ``(pair_i, pair_j, pair_count, n_frequent)``; entries past
    ``n_frequent`` are -1/-1/0 padding. ``n_frequent`` may exceed
    ``capacity`` — the caller must check for overflow.
    """
    v = counts.shape[0]
    upper = jnp.triu(jnp.ones((v, v), dtype=bool), k=1)
    valid = upper & (counts >= min_count)
    n_frequent = valid.sum(dtype=jnp.int32)
    flat_score = jnp.where(valid, counts, -1).reshape(-1)
    k = min(capacity, v * v)
    top_counts, top_idx = jax.lax.top_k(flat_score, k)
    keep = top_counts > 0
    pair_i = jnp.where(keep, top_idx // v, -1).astype(jnp.int32)
    pair_j = jnp.where(keep, top_idx % v, -1).astype(jnp.int32)
    top_counts = jnp.where(keep, top_counts, 0)
    if k < capacity:  # static pad so the declared capacity shape holds
        pad = capacity - k
        pair_i = jnp.pad(pair_i, (0, pad), constant_values=-1)
        pair_j = jnp.pad(pair_j, (0, pad), constant_values=-1)
        top_counts = jnp.pad(top_counts, (0, pad))
    return pair_i, pair_j, top_counts, n_frequent
