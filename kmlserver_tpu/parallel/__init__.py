from . import distributed, mesh, support  # noqa: F401
