from . import mesh, support  # noqa: F401
