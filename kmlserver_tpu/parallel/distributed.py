"""Multi-host distributed runtime — coordinator bootstrap + hybrid DCN×ICI
meshes.

The reference has no distributed communication backend at all: its
inter-process "bus" is a shared RWX filesystem plus a polled token file
(reference: kubernetes/pvc.yaml:10-11, machine-learning/main.py:406-408,
rest_api/app/main.py:82-97; SURVEY.md §2.4 documents the absence of
NCCL/MPI/Gloo explicitly). The rebuild's equivalent is the JAX/XLA
distributed runtime: one process per TPU host, a gRPC coordinator for
process bootstrap, and XLA collectives for all data-plane communication —
riding ICI within a slice and DCN across slices/hosts. The PVC + token
protocol is deliberately retained for the batch→serve artifact handoff (it
is the reference's versioning mechanism); this module only replaces what the
reference *couldn't* do: scaling one mining computation across hosts.

Bootstrap is env-driven so the same container works as a single-host job, an
indexed k8s Job (`JOB_COMPLETION_INDEX`), or a GKE TPU multi-host node pool
(where jax.distributed auto-detects from the TPU metadata server):

- ``KMLS_COORDINATOR_ADDRESS`` — host:port of process 0. Unset → no-op
  single-process mode.
- ``KMLS_NUM_PROCESSES`` — world size.
- ``KMLS_PROCESS_ID`` — explicit rank; falls back to
  ``JOB_COMPLETION_INDEX`` (k8s indexed Job downward API).

Mesh layout rule (the scaling-book recipe): the mesh axis with the highest
communication volume per step — here ``tp``, whose ring/all-gather moves
pair-count blocks every step — must map to ICI (devices within a host/slice,
the innermost mesh dimension); ``dp``, which communicates only in the final
``psum`` of partial counts, tolerates DCN and maps to the outermost (cross-
host) dimension. ``make_hybrid_mesh`` encodes exactly that.
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AXIS_DP, AXIS_TP

logger = logging.getLogger("kmlserver_tpu.distributed")

COORDINATOR_ENV = "KMLS_COORDINATOR_ADDRESS"
NUM_PROCESSES_ENV = "KMLS_NUM_PROCESSES"
PROCESS_ID_ENV = "KMLS_PROCESS_ID"
K8S_INDEX_ENV = "JOB_COMPLETION_INDEX"

_initialized = False


def distributed_env() -> tuple[str, int, int] | None:
    """→ (coordinator, num_processes, process_id) or None (single-process)."""
    coordinator = os.getenv(COORDINATOR_ENV)
    if not coordinator:
        return None
    num = int(os.getenv(NUM_PROCESSES_ENV, "1"))
    raw_id = os.getenv(PROCESS_ID_ENV) or os.getenv(K8S_INDEX_ENV) or "0"
    process_id = int(raw_id)
    if process_id >= num:
        # e.g. an indexed k8s Job where KMLS_NUM_PROCESSES was forgotten:
        # fail with a clear config error instead of a bootstrap hang
        raise ValueError(
            f"process_id {process_id} >= num_processes {num}: set "
            f"{NUM_PROCESSES_ENV} to the Job's completion count"
        )
    return coordinator, num, process_id


def maybe_initialize() -> bool:
    """Join the distributed runtime when configured; idempotent; False when
    running single-process. Must run before the first device access."""
    global _initialized
    if _initialized:
        return True
    env = distributed_env()
    if env is None:
        return False
    coordinator, num_processes, process_id = env
    logger.info(
        "joining distributed runtime: coordinator=%s rank=%d/%d",
        coordinator, process_id, num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def make_hybrid_mesh(
    dp_per_host: int | None = None,
    tp: int | None = None,
    devices: list | None = None,
) -> Mesh:
    """A ``(dp, tp)`` mesh laid out for the hardware fabric: ``tp`` packed
    within each host's devices (ICI), ``dp`` spanning hosts (DCN) × the
    leftover intra-host factor.

    Defaults: ``tp`` = all of one host's local devices (max ICI width for
    the block-exchange axis), ``dp`` = number of hosts. Works identically on
    one process (then dp×tp just factors the local device count).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    n_hosts = max(len({d.process_index for d in devices}), 1)
    local = n // n_hosts
    if tp is None:
        tp = local if dp_per_host is None else max(local // dp_per_host, 1)
    if local % tp != 0:
        raise ValueError(
            f"tp={tp} must divide the per-host device count {local}"
        )
    dp = n // tp
    # order devices host-major, so reshape(dp, tp) keeps each tp row within
    # one host: tp collectives ride ICI, never DCN
    ordered = sorted(devices, key=lambda d: (d.process_index, d.id))
    grid = np.asarray(ordered).reshape(dp, tp)
    return Mesh(grid, (AXIS_DP, AXIS_TP))


def resolve_mesh(mesh_shape: str, distributed: bool = False) -> Mesh | None:
    """The ONE ``KMLS_MESH_SHAPE``-string → mesh resolution, shared by the
    mining job and the sweep harness: ``""``/``"1x1"`` = explicit
    single-device (None); ``"hybrid"``/``"hybrid:tpN"`` = DCN×ICI layout
    (tp pinned intra-host); ``"auto"`` = hybrid when the multi-host runtime
    is active, every local device otherwise (None when only one);
    anything else = an explicit ``DPxTP`` shape."""
    if mesh_shape in ("", "1x1"):
        return None  # explicit single-device
    if mesh_shape.startswith("hybrid"):
        # anything else hybrid-shaped is a config error, fail fast
        if mesh_shape == "hybrid":
            return make_hybrid_mesh()
        if mesh_shape.startswith("hybrid:tp") and mesh_shape[9:].isdigit():
            return make_hybrid_mesh(tp=int(mesh_shape[9:]))
        raise ValueError(
            f"mesh shape must be 'hybrid' or 'hybrid:tpN', got {mesh_shape!r}"
        )
    if mesh_shape == "auto":
        if distributed:
            # multi-host: the hybrid layout is the only correct default —
            # the tp block-exchange axis must ride ICI, never DCN
            return make_hybrid_mesh()
        if len(jax.devices()) > 1:  # shard over every chip present
            from .mesh import make_mesh

            return make_mesh("auto")
        return None
    from .mesh import make_mesh

    return make_mesh(mesh_shape)
