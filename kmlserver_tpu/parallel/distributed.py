"""Multi-host distributed runtime — coordinator bootstrap + hybrid DCN×ICI
meshes.

The reference has no distributed communication backend at all: its
inter-process "bus" is a shared RWX filesystem plus a polled token file
(reference: kubernetes/pvc.yaml:10-11, machine-learning/main.py:406-408,
rest_api/app/main.py:82-97; SURVEY.md §2.4 documents the absence of
NCCL/MPI/Gloo explicitly). The rebuild's equivalent is the JAX/XLA
distributed runtime: one process per TPU host, a gRPC coordinator for
process bootstrap, and XLA collectives for all data-plane communication —
riding ICI within a slice and DCN across slices/hosts. The PVC + token
protocol is deliberately retained for the batch→serve artifact handoff (it
is the reference's versioning mechanism); this module only replaces what the
reference *couldn't* do: scaling one mining computation across hosts.

Bootstrap is env-driven so the same container works as a single-host job, an
indexed k8s Job (`JOB_COMPLETION_INDEX`), or a GKE TPU multi-host node pool
(where jax.distributed auto-detects from the TPU metadata server):

- ``KMLS_COORDINATOR_ADDRESS`` — host:port of process 0. Unset → no-op
  single-process mode.
- ``KMLS_NUM_PROCESSES`` — world size.
- ``KMLS_PROCESS_ID`` — explicit rank; falls back to
  ``JOB_COMPLETION_INDEX`` (k8s indexed Job downward API).

Mesh layout rule (the scaling-book recipe): the mesh axis with the highest
communication volume per step — here ``tp``, whose ring/all-gather moves
pair-count blocks every step — must map to ICI (devices within a host/slice,
the innermost mesh dimension); ``dp``, which communicates only in the final
``psum`` of partial counts, tolerates DCN and maps to the outermost (cross-
host) dimension. ``make_hybrid_mesh`` encodes exactly that.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time

import jax
import numpy as np
from jax.sharding import Mesh

from .. import faults
from .mesh import AXIS_DP, AXIS_TP

logger = logging.getLogger("kmlserver_tpu.distributed")

COORDINATOR_ENV = "KMLS_COORDINATOR_ADDRESS"
NUM_PROCESSES_ENV = "KMLS_NUM_PROCESSES"
PROCESS_ID_ENV = "KMLS_PROCESS_ID"
K8S_INDEX_ENV = "JOB_COMPLETION_INDEX"

# Serve-gang bootstrap (ISSUE 16): the SERVING twin of the mining env
# triple above — a StatefulSet gang of API pods whose vocab slabs form
# one logical replica (kubernetes/serve-gang.yaml). Kept as separate env
# names so a pod can, in principle, belong to a mining world AND a serve
# gang without the two bootstraps clobbering each other.
SERVE_GANG_COORDINATOR_ENV = "KMLS_SERVE_GANG_COORDINATOR"
SERVE_GANG_SIZE_ENV = "KMLS_SERVE_GANG_SIZE"
SERVE_GANG_RANK_ENV = "KMLS_SERVE_GANG_RANK"

_initialized = False


def distributed_env() -> tuple[str, int, int] | None:
    """→ (coordinator, num_processes, process_id) or None (single-process)."""
    coordinator = os.getenv(COORDINATOR_ENV)
    if not coordinator:
        return None
    num = int(os.getenv(NUM_PROCESSES_ENV, "1"))
    raw_id = os.getenv(PROCESS_ID_ENV) or os.getenv(K8S_INDEX_ENV) or "0"
    process_id = int(raw_id)
    if process_id >= num:
        # e.g. an indexed k8s Job where KMLS_NUM_PROCESSES was forgotten:
        # fail with a clear config error instead of a bootstrap hang
        raise ValueError(
            f"process_id {process_id} >= num_processes {num}: set "
            f"{NUM_PROCESSES_ENV} to the Job's completion count"
        )
    return coordinator, num, process_id


def maybe_initialize() -> bool:
    """Join the distributed runtime when configured; idempotent; False when
    running single-process. Must run before the first device access."""
    global _initialized
    if _initialized:
        return True
    env = distributed_env()
    if env is None:
        return False
    coordinator, num_processes, process_id = env
    logger.info(
        "joining distributed runtime: coordinator=%s rank=%d/%d",
        coordinator, process_id, num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def gang_rank_fallback(default: int = 0) -> int:
    """The serve gang's rank-from-identity recipe when
    ``KMLS_SERVE_GANG_RANK`` is unset: under a StatefulSet the hostname
    IS the stable ordinal identity (``serve-gang-1`` → rank 1) — the
    serving twin of the mining Job's ``JOB_COMPLETION_INDEX`` fallback
    (indexed Jobs inject that; StatefulSets don't, but their pod name
    carries the same information)."""
    raw = os.getenv(K8S_INDEX_ENV)
    if raw is not None and raw.isdigit():
        return int(raw)
    import socket

    host = socket.gethostname()
    _, _, ordinal = host.rpartition("-")
    return int(ordinal) if ordinal.isdigit() else default


def serve_gang_env() -> tuple[str, int, int] | None:
    """→ (coordinator, gang_size, rank) or None (no gang armed) — the
    serve-mesh twin of :func:`distributed_env`, same fail-fast contract:
    a rank outside the declared gang size is a config error surfaced at
    boot, never a bootstrap hang."""
    coordinator = os.getenv(SERVE_GANG_COORDINATOR_ENV)
    if not coordinator:
        return None
    size = int(os.getenv(SERVE_GANG_SIZE_ENV, "1"))
    raw = os.getenv(SERVE_GANG_RANK_ENV)
    rank = int(raw) if raw not in (None, "") else gang_rank_fallback()
    if rank >= size:
        raise ValueError(
            f"serve gang rank {rank} >= gang size {size}: set "
            f"{SERVE_GANG_SIZE_ENV} to the StatefulSet's replica count"
        )
    return coordinator, size, rank


def maybe_initialize_serve_gang(
    coordinator: str, size: int, rank: int
) -> bool:
    """Join the REAL-collectives serve mesh (pjit/GSPMD over DCN): reuse
    the mining bootstrap's ``jax.distributed.initialize`` with the serve
    gang's triple, so on TPU the vocab axis of the sharded bundle spans
    the gang's pods as one global mesh. Idempotent via the same
    ``_initialized`` latch (one process joins ONE world — a pod is
    either a mining rank or a serve-gang member, and re-entry from a
    reload is a no-op either way).

    Returns False without initializing when the backend cannot run
    multi-process GSPMD (the CPU sandbox) — there the engine serves the
    gang through the simulation transport (serving/mesh.py), which is
    bit-identical by construction. On-chip validation of this path is
    the standing TPU-window item."""
    global _initialized
    if size <= 1:
        return False
    if _initialized:
        return True
    # Gate on the platform ENV, not jax.default_backend(): probing the
    # backend would initialize it, and jax.distributed.initialize must
    # run before any backend touch on a real accelerator gang.
    platforms = {
        p.strip() for p in os.getenv("JAX_PLATFORMS", "").lower().split(",")
        if p.strip()
    }
    if platforms and platforms <= {"cpu"}:
        logger.info(
            "serve gang %d/%d on the CPU backend: multi-process GSPMD "
            "unavailable — serving via the simulation transport",
            rank, size,
        )
        return False
    logger.info(
        "joining serve-gang runtime: coordinator=%s rank=%d/%d",
        coordinator, rank, size,
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=size,
            process_id=rank,
        )
    except Exception as exc:  # fail soft: the sim transport still serves
        logger.warning(
            "serve-gang collective bootstrap failed (%s); falling back "
            "to the simulation transport", exc,
        )
        return False
    _initialized = True
    return True


# ---------- dead-rank watchdog ----------


class RankWatchdog:
    """Bounded-time abort for the multi-host forever-hang.

    XLA collectives have no application-level timeout: when one rank of a
    multi-host mining job dies (TPU preemption, pod eviction, OOM-kill),
    every surviving rank blocks in the next collective FOREVER — the Job
    never fails, never retries, and holds its TPU slice until a human
    notices. This watchdog turns that into a bounded-time, *retryable*
    failure with two independent detectors:

    - **peer heartbeats**: every rank's writer thread touches
      ``<dir>/rank<N>.hb`` (a shared-PVC file carrying ``time.time()``)
      every ``heartbeat_interval_s``; the monitor thread aborts when any
      peer's heartbeat is older than ``timeout_s``. Catches a DEAD
      process (its heartbeat thread died with it).
    - **collective guard**: :meth:`guard` brackets a collective section
      with a deadline (``collective_timeout_s``, default 6× the
      staleness timeout); the monitor aborts when the section is still
      open past it. Catches a HUNG peer whose process (and heartbeat
      thread) is still alive — stale heartbeats can't, because heartbeats
      come from a side thread, not the blocked main thread. The guard
      deadline is deliberately SEPARATE from (and much larger than) the
      staleness timeout: the guard brackets real compute, and a
      legitimately long mine must not read as a hang — with a shared
      timeout, every restarted gang would recompute the same too-long
      mine and abort identically, a retry livelock.

    Abort = ``on_abort(reason)``, default ``os._exit(exit_code)`` —
    ``sys.exit`` would only raise in the monitor thread while the main
    thread stays wedged in the C++ collective. The exit code is the
    mining job's resumable EXIT_RANK_DEAD (mining/job.py), which k8s
    converts into a clean retry-from-checkpoint.

    Heartbeat freshness compares the WRITER's ``time.time()`` (stored in
    the file) against the READER's — cross-pod wall clocks, NTP-bounded
    skew; timeouts are minutes, skew is milliseconds. A peer that never
    wrote at all is aged from this watchdog's start, so a slow-scheduling
    pod gets the full ``timeout_s`` to appear before it is declared dead —
    and a heartbeat file STAMPED BEFORE this watchdog started (a leftover
    from the previous gang incarnation on the PVC) gets the same grace,
    not an instant stale verdict against a pod that simply hasn't booted
    yet. ``stop`` best-effort unlinks this rank's own file so clean exits
    leave nothing behind; hard kills rely on the stamp comparison.

    The ``rank.heartbeat`` fault site (``KMLS_FAULT_RANK_DEAD=rank``)
    silences a rank's writer thread permanently — the deterministic
    dead-process stand-in the chaos suite kills multi-host jobs with.
    """

    def __init__(
        self,
        directory: str,
        rank: int,
        num_processes: int,
        heartbeat_interval_s: float = 5.0,
        timeout_s: float = 300.0,
        collective_timeout_s: float | None = None,
        exit_code: int = 76,
        on_abort=None,
    ):
        self.directory = directory
        self.rank = rank
        self.num_processes = num_processes
        self.heartbeat_interval_s = heartbeat_interval_s
        self.timeout_s = timeout_s
        self.collective_timeout_s = (
            collective_timeout_s
            if collective_timeout_s is not None
            else 6 * timeout_s
        )
        self.exit_code = exit_code
        self.on_abort = on_abort or self._default_abort
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._t0 = 0.0
        self._t0_wall = 0.0
        self._guard_lock = threading.Lock()
        self._guard_name: str | None = None
        self._guard_deadline: float | None = None
        self.aborted_reason: str | None = None

    def _default_abort(self, reason: str) -> None:
        # visible in the pod log right before the process dies
        print(
            f"RANK WATCHDOG ABORT (rank {self.rank}): {reason} — exiting "
            f"{self.exit_code} (resumable; k8s retries from the checkpoint)",
            flush=True,
        )
        os._exit(self.exit_code)

    def _beat_path(self, rank: int) -> str:
        return os.path.join(self.directory, f"rank{rank}.hb")

    def beat_once(self) -> bool:
        """Write this rank's heartbeat; False once the rank is fault-dead."""
        try:
            faults.fire("rank.heartbeat", replica=self.rank)
        except faults.FaultInjected:
            logger.warning(
                "rank %d heartbeat silenced by injected fault", self.rank
            )
            return False
        from ..io.artifacts import atomic_write_text

        try:
            atomic_write_text(self._beat_path(self.rank), repr(time.time()))
        except OSError as exc:
            # a full/unwritable PVC must not kill the job via its own
            # watchdog; peers will age this rank out if it persists
            logger.warning("heartbeat write failed: %s", exc)
        return True

    def peer_ages(self) -> dict[int, float]:
        """Seconds since each PEER rank's last heartbeat. Never-seen peers
        — no file, an unreadable file, or a file stamped BEFORE this
        watchdog started (the previous gang's leftover on the PVC) — are
        aged from watchdog start instead, so a pod that hasn't booted yet
        gets the full ``timeout_s`` grace rather than being condemned by
        its predecessor's stale heartbeat."""
        now = time.time()
        since_start = time.monotonic() - self._t0
        ages: dict[int, float] = {}
        for rank in range(self.num_processes):
            if rank == self.rank:
                continue
            try:
                with open(self._beat_path(rank), "r", encoding="utf-8") as fh:
                    stamp = float(fh.read().strip())
            except (OSError, ValueError):
                ages[rank] = since_start
                continue
            ages[rank] = now - stamp if stamp >= self._t0_wall else since_start
        return ages

    def stale_peers(self) -> list[int]:
        return sorted(
            r for r, age in self.peer_ages().items() if age > self.timeout_s
        )

    @contextlib.contextmanager
    def guard(self, name: str):
        """Deadline-bracket a collective section: still open after
        ``collective_timeout_s`` → abort. One section at a time (mining
        is serial)."""
        with self._guard_lock:
            self._guard_name = name
            self._guard_deadline = time.monotonic() + self.collective_timeout_s
        try:
            yield
        finally:
            with self._guard_lock:
                self._guard_name = None
                self._guard_deadline = None

    def _abort(self, reason: str) -> None:
        if self.aborted_reason is None:
            self.aborted_reason = reason
            self.on_abort(reason)

    def _beat_loop(self) -> None:
        while not self._stop.is_set():
            if not self.beat_once():
                return  # fault-dead: silence forever, thread exits
            self._stop.wait(self.heartbeat_interval_s)

    def _monitor_loop(self) -> None:
        poll = min(self.heartbeat_interval_s, max(self.timeout_s / 10, 0.05))
        while not self._stop.wait(poll):
            with self._guard_lock:
                g_name, g_deadline = self._guard_name, self._guard_deadline
            if g_deadline is not None and time.monotonic() > g_deadline:
                self._abort(
                    f"collective section {g_name!r} exceeded "
                    f"{self.collective_timeout_s:.0f}s — a peer rank is "
                    "hung or dead"
                )
                return
            stale = self.stale_peers()
            if stale:
                ages = self.peer_ages()
                detail = ", ".join(f"rank {r}: {ages[r]:.0f}s" for r in stale)
                self._abort(
                    f"peer heartbeat(s) stale past {self.timeout_s:.0f}s "
                    f"({detail}) — dead rank(s), collectives would hang"
                )
                return

    def start(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        self._t0 = time.monotonic()
        self._t0_wall = time.time()
        self.beat_once()  # first beat before any peer could judge us stale
        for target, name in (
            (self._beat_loop, "kmls-rank-heartbeat"),
            (self._monitor_loop, "kmls-rank-monitor"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        try:
            # clean exits leave no stale stamp for the next gang to read;
            # hard kills rely on peer_ages' stamped-before-start grace
            os.unlink(self._beat_path(self.rank))
        except OSError:
            pass


def make_hybrid_mesh(
    dp_per_host: int | None = None,
    tp: int | None = None,
    devices: list | None = None,
) -> Mesh:
    """A ``(dp, tp)`` mesh laid out for the hardware fabric: ``tp`` packed
    within each host's devices (ICI), ``dp`` spanning hosts (DCN) × the
    leftover intra-host factor.

    Defaults: ``tp`` = all of one host's local devices (max ICI width for
    the block-exchange axis), ``dp`` = number of hosts. Works identically on
    one process (then dp×tp just factors the local device count).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    n_hosts = max(len({d.process_index for d in devices}), 1)
    local = n // n_hosts
    if tp is None:
        tp = local if dp_per_host is None else max(local // dp_per_host, 1)
    if local % tp != 0:
        raise ValueError(
            f"tp={tp} must divide the per-host device count {local}"
        )
    dp = n // tp
    # order devices host-major, so reshape(dp, tp) keeps each tp row within
    # one host: tp collectives ride ICI, never DCN
    ordered = sorted(devices, key=lambda d: (d.process_index, d.id))
    grid = np.asarray(ordered).reshape(dp, tp)
    return Mesh(grid, (AXIS_DP, AXIS_TP))


def resolve_mesh(mesh_shape: str, distributed: bool = False) -> Mesh | None:
    """The ONE ``KMLS_MESH_SHAPE``-string → mesh resolution, shared by the
    mining job and the sweep harness: ``""``/``"1x1"`` = explicit
    single-device (None); ``"hybrid"``/``"hybrid:tpN"`` = DCN×ICI layout
    (tp pinned intra-host); ``"auto"`` = hybrid when the multi-host runtime
    is active, every local device otherwise (None when only one);
    anything else = an explicit ``DPxTP`` shape."""
    if mesh_shape in ("", "1x1"):
        return None  # explicit single-device
    if mesh_shape.startswith("hybrid"):
        # anything else hybrid-shaped is a config error, fail fast
        if mesh_shape == "hybrid":
            return make_hybrid_mesh()
        if mesh_shape.startswith("hybrid:tp") and mesh_shape[9:].isdigit():
            return make_hybrid_mesh(tp=int(mesh_shape[9:]))
        raise ValueError(
            f"mesh shape must be 'hybrid' or 'hybrid:tpN', got {mesh_shape!r}"
        )
    if mesh_shape == "auto":
        if distributed:
            # multi-host: the hybrid layout is the only correct default —
            # the tp block-exchange axis must ride ICI, never DCN
            return make_hybrid_mesh()
        if len(jax.devices()) > 1:  # shard over every chip present
            from .mesh import make_mesh

            return make_mesh("auto")
        return None
    from .mesh import make_mesh

    return make_mesh(mesh_shape)
