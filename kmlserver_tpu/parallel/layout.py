"""Model-layout resolution — replicated vs vocab-sharded tensors.

PR 2 scaled *data* parallelism: every device holds a full replica of the
rule (and embedding) tensors and the dispatcher spreads batches. That
caps the servable catalog — and the minable input — at what ONE device
can hold. The ``sharded`` layout is the model-parallel counterpart (the
ALX recipe, PAPERS.md: matrix-shaped recommendation state partitioned
across a TPU mesh with batched solves and collectives): the rule /
consequent / score tensors shard along the VOCAB axis, lookups run as a
sharded gather + per-shard top-k with a cross-device max-merge, and
mining's one-hot / support counting shards the same axis so the encode
and mine phases accept inputs the dense replicated path cannot.

THE one copy of the layout decision, shared by the serving engine and
the mining dispatch so the two sides can never resolve the same knobs
differently:

- ``KMLS_MODEL_LAYOUT=replicated`` — the legacy layout (default).
- ``KMLS_MODEL_LAYOUT=sharded``    — force vocab sharding (needs > 1
  local device; silently resolves to replicated on a single device —
  there is nothing to shard across).
- ``KMLS_MODEL_LAYOUT=auto``       — shard exactly when the measured
  tensor bytes exceed ``KMLS_DEVICE_BUDGET_BYTES`` for one device
  (Misam's framing, PAPERS.md: layout selection is a *measured*
  decision, not a vibe) — small catalogs keep the replicated layout's
  zero-collective dispatch, oversized ones transparently spread.
"""

from __future__ import annotations

import logging

LAYOUTS = ("replicated", "sharded", "auto")

logger = logging.getLogger("kmlserver_tpu.layout")


def validate_layout(layout: str) -> str:
    """Normalize a layout knob value; an unrecognized spelling fails SAFE
    to ``replicated`` (the legacy path) with a loud warning — a typo must
    never silently enable cross-device collectives."""
    word = (layout or "").strip().lower()
    if word in LAYOUTS:
        return word
    logger.warning(
        "KMLS_MODEL_LAYOUT=%r is not one of %s; using 'replicated'",
        layout, "/".join(LAYOUTS),
    )
    return "replicated"


def resolve_layout(
    layout: str, tensor_bytes: int, budget_bytes: int, n_devices: int
) -> str:
    """→ ``"replicated"`` or ``"sharded"``, from the knob value, the
    MEASURED model-tensor bytes, the per-device budget, and the devices
    actually available. ``budget_bytes <= 0`` disables the auto trigger
    (no budget: nothing measurable to exceed)."""
    word = validate_layout(layout)
    if n_devices <= 1:
        if word == "sharded":
            logger.warning(
                "KMLS_MODEL_LAYOUT=sharded with a single device: "
                "nothing to shard across — serving replicated"
            )
        return "replicated"
    if word == "sharded":
        return "sharded"
    if word == "auto" and budget_bytes > 0 and tensor_bytes > budget_bytes:
        logger.info(
            "auto layout: model tensors (%d bytes) exceed the %d-byte "
            "device budget — sharding across %d devices",
            tensor_bytes, budget_bytes, n_devices,
        )
        return "sharded"
    return "replicated"


def resolve_serve_span(
    layout: str,
    tensor_bytes: int,
    budget_bytes: int,
    n_devices: int,
    gang_size: int = 1,
) -> str:
    """→ ``"mesh"``, ``"sharded"``, or ``"replicated"`` — the serving
    engine's layout decision with the pod-spanning serve mesh (ISSUE 16)
    layered on top of :func:`resolve_layout`.

    An armed serve gang (``KMLS_SERVE_GANG_SIZE`` > 1) is decisive: each
    gang member holds only its own vocab slab, so replicating or
    locally sharding the full tensors on any one member would defeat the
    deployment (and double-serve rows another member owns). The layout
    knob keeps steering the SINGLE-process question — how this member's
    slab sits on its local devices is a follow-up the mesh bundle keeps
    trivial (one slab, default placement) until a pod has more than one
    local device to matter."""
    if gang_size > 1:
        return "mesh"
    return resolve_layout(layout, tensor_bytes, budget_bytes, n_devices)


def mining_mesh(cfg, mesh):
    """Apply the model-layout knob to the mining mesh: under the
    ``sharded`` layout the vocab (``tp``) axis is the one that must span
    devices, so a layout-sharded run with no mesh — or with the default
    dp-major auto mesh — gets a vocab-major ``1xN`` mesh over the local
    devices instead. Explicit ``DPxTP``/hybrid shapes (tp already > 1,
    or a multi-host hybrid mesh) are respected as given. Idempotent —
    the pipeline and the miner may both call it."""
    import jax

    from .mesh import AXIS_TP, make_mesh

    word = validate_layout(getattr(cfg, "model_layout", "replicated"))
    if word == "replicated":
        return mesh
    if mesh is not None and mesh.shape.get(AXIS_TP, 1) > 1:
        return mesh  # already vocab-sharded (explicit shape or hybrid)
    if mesh is not None and jax.process_count() > 1:
        # multi-host: the hybrid DCN×ICI axis discipline (tp rides ICI)
        # must stand — never rewrite a cross-host mesh onto the vocab
        # axis, even under the sharded layout (a tp=1-per-host topology
        # would put the block exchange on DCN)
        return mesh
    if word == "auto":
        # auto never invents a mesh: mining's memory routing (bitpack
        # dispatch, Apriori prune) already covers the oversized-input
        # case, so auto only engages the sharded mining path when the
        # operator's mesh already spans the vocab axis (handled above)
        return mesh
    devices = (
        list(mesh.devices.flatten()) if mesh is not None
        else jax.local_devices()
    )
    if len(devices) <= 1:
        return mesh
    return make_mesh((1, len(devices)), devices=devices)


def wants_sharded_mining(cfg, mesh) -> bool:
    """True when the miner should take the vocab-sharded count+emit path
    for this (config, mesh): the mesh spans the vocab axis and the layout
    knob is not pinned to replicated."""
    from .mesh import AXIS_TP

    if mesh is None or mesh.shape.get(AXIS_TP, 1) <= 1:
        return False
    return validate_layout(
        getattr(cfg, "model_layout", "replicated")
    ) != "replicated"
