"""Device-mesh construction.

The reference has no collective backend at all — its only "parallelism" is
Kubernetes replica scaling, and its inter-process bus is a shared filesystem
(reference: kubernetes/deployment.yaml:10, kubernetes/pvc.yaml:10-11;
SURVEY.md §2.4). The rebuild's mining compute shards over a 2-D
``(dp, tp)`` mesh instead:

- ``dp`` — data parallelism over the *transaction* (playlist) axis; partial
  pair-count matrices are combined with ``psum`` over ICI;
- ``tp`` — tensor parallelism over the *item* (track vocabulary) axis for
  large vocabularies; pair-count blocks are exchanged with ``all_gather`` or
  a ``ppermute`` ring.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_TP = "tp"


def parse_mesh_shape(shape: str) -> tuple[int, int]:
    """Parse ``"4x2"`` → ``(4, 2)`` = (dp, tp)."""
    parts = shape.lower().split("x")
    if len(parts) != 2:
        raise ValueError(f"mesh shape must be 'DPxTP', got {shape!r}")
    return int(parts[0]), int(parts[1])


def make_mesh(
    shape: str | tuple[int, int] = "auto",
    devices: list | None = None,
) -> Mesh:
    """Build a ``(dp, tp)`` mesh. ``"auto"`` puts every device on ``dp``
    (transaction sharding scales furthest for the reference's workload
    profile: many baskets, modest vocab)."""
    devices = devices if devices is not None else jax.devices()
    if shape == "auto":
        dp, tp = len(devices), 1
    elif isinstance(shape, str):
        dp, tp = parse_mesh_shape(shape)
    else:
        dp, tp = shape
    if dp * tp != len(devices):
        raise ValueError(
            f"mesh {dp}x{tp} needs {dp * tp} devices, have {len(devices)}"
        )
    grid = np.asarray(devices).reshape(dp, tp)
    return Mesh(grid, (AXIS_DP, AXIS_TP))


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple
