"""Sharded pair-support counting over a ``(dp, tp)`` device mesh.

The distributed replacement for what the reference cannot do at all (its
mining is single-process CPU — SURVEY.md §2.4): the one-hot basket matrix
``X (P, V)`` is laid out ``P('dp', 'tp')`` — transactions sharded over
``dp``, vocabulary columns over ``tp`` — and the pair-count matrix
``C = XᵀX`` is produced column-sharded ``P(None, 'tp')``.

Three interchangeable implementations, all exact:

- ``impl="gspmd"`` — annotate shardings on the plain matmul and let XLA's
  SPMD partitioner insert the collectives. The idiomatic default.
- ``impl="allgather"`` — explicit ``shard_map``: ``all_gather`` the column
  shards over ``tp`` (one ICI hop, Ulysses-style all-to-all analogue), one
  local matmul, ``psum`` partial counts over ``dp``.
- ``impl="ring"`` — explicit ``shard_map`` ring: column blocks rotate around
  the ``tp`` axis via ``ppermute`` (ring-attention-style neighbor exchange),
  computing one ``(V_loc, V_loc)`` output block per step, overlapping
  compute with neighbor transfers and never materializing the full ``X`` on
  any chip. Peak per-chip memory O(P/dp · V/tp), vs O(P/dp · V) for
  all-gather — the path for 1M-track vocabularies.

All variants ``psum`` over ``dp``, so the collective volume rides ICI, and
pad P to a multiple of dp and V to a multiple of tp with zero rows/columns
(zero rows/columns contribute zero counts; padding columns are sliced off).
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..mining.vocab import Baskets
from ..ops import encode
from ..utils.jaxcompat import pcast_varying, shard_map
from .mesh import AXIS_DP, AXIS_TP, round_up


def _onehot_padded(baskets: Baskets, p_pad: int, v_pad: int, mesh: Mesh) -> jax.Array:
    """Build the one-hot matrix directly into the ``P('dp','tp')`` layout."""
    build = jax.jit(
        partial(encode.onehot_matrix, n_playlists=p_pad, n_tracks=v_pad),
        out_shardings=NamedSharding(mesh, P(AXIS_DP, AXIS_TP)),
    )
    return build(
        jnp.asarray(baskets.playlist_rows), jnp.asarray(baskets.track_ids)
    )


def _dot_pt(a: jax.Array, b: jax.Array) -> jax.Array:
    """Contract dim 0 (playlists) of both operands → int32 counts."""
    return jax.lax.dot_general(
        a, b, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def _gspmd_counts(mesh: Mesh):
    return jax.jit(
        _dot_pt,
        in_shardings=(
            NamedSharding(mesh, P(AXIS_DP, AXIS_TP)),
            NamedSharding(mesh, P(AXIS_DP, AXIS_TP)),
        ),
        out_shardings=NamedSharding(mesh, P(None, AXIS_TP)),
    )


def _allgather_counts(mesh: Mesh):
    def local(x_local: jax.Array) -> jax.Array:
        # (P_loc, V_loc) → gather full columns (P_loc, V), one matmul,
        # psum partials over dp → (V, V_loc)
        x_cols = jax.lax.all_gather(x_local, AXIS_TP, axis=1, tiled=True)
        c_local = _dot_pt(x_cols, x_local)
        return jax.lax.psum(c_local, AXIS_DP)

    return jax.jit(
        shard_map(
            local, mesh=mesh, in_specs=P(AXIS_DP, AXIS_TP),
            out_specs=P(None, AXIS_TP),
        )
    )


def _ring_counts(mesh: Mesh):
    tp = mesh.shape[AXIS_TP]

    def local(x_local: jax.Array) -> jax.Array:
        v_loc = x_local.shape[1]
        my = jax.lax.axis_index(AXIS_TP)
        perm = [(j, (j + 1) % tp) for j in range(tp)]

        def step(i, carry):
            block, out = carry
            # `block` currently holds shard (my - i) mod tp's columns
            src = jax.lax.rem(my - i + tp, tp)
            c = _dot_pt(block, x_local)  # (V_loc, V_loc) block of C
            out = jax.lax.dynamic_update_slice(out, c, (src * v_loc, 0))
            block = jax.lax.ppermute(block, AXIS_TP, perm)
            return block, out

        # mark the accumulator device-varying so the fori_loop carry type
        # matches after blocks of `c` (which varies per shard) land in it
        out0 = pcast_varying(
            jnp.zeros((v_loc * tp, v_loc), dtype=jnp.int32),
            (AXIS_DP, AXIS_TP),
        )
        _, out = jax.lax.fori_loop(0, tp, step, (x_local, out0))
        return jax.lax.psum(out, AXIS_DP)

    return jax.jit(
        shard_map(
            local, mesh=mesh, in_specs=P(AXIS_DP, AXIS_TP),
            out_specs=P(None, AXIS_TP),
        )
    )


_IMPLS = {
    "gspmd": _gspmd_counts,
    "allgather": _allgather_counts,
    "ring": _ring_counts,
}


def sharded_bitpack_pair_counts(
    baskets: Baskets,
    mesh: Mesh,
    interpret: bool | None = None,
    variant: str | None = None,
    swar: bool | None = None,
    impl: str | None = None,
) -> jax.Array:
    """Pair counts over the mesh with BIT-PACKED operands: the playlist
    (word) axis is sharded over ``dp``, each chip counts its slab (MXU
    unpack-matmul or the Pallas VPU kernel, ``impl``), partial counts
    ``psum`` over ICI.

    Per-chip memory is O(V · P/(32·dp)) — 32× below the sharded dense
    int8 path — which is what makes BASELINE.json config 4 (10M baskets,
    1M-track vocabulary Apriori-pruned to the frequent items) fit in HBM.
    Requires a ``Nx1`` mesh: the word axis shards over ``dp`` only, and a
    ``tp > 1`` mesh would silently replicate the full slab on every tp chip
    (defeating the memory budget), so it is rejected — callers flatten all
    devices onto ``dp`` first (mining.miner.pair_count_fn does).
    """
    from ..ops import popcount as pc

    if mesh.shape.get(AXIS_TP, 1) > 1:
        raise ValueError(
            f"sharded_bitpack_pair_counts needs a dp-only (Nx1) mesh, got "
            f"{dict(mesh.shape)}; flatten devices onto dp first"
        )
    # impl/kernel-opt resolution happens in counts_from_sharded_bitset
    # (the ONE copy of that gating)
    dp = mesh.shape[AXIS_DP]
    v = baskets.n_tracks
    vt = pc.v_tile()
    v_pad = round_up(max(v, vt), vt)
    w_total = round_up(
        (baskets.n_playlists + 31) // 32, dp * pc.word_chunk()
    )
    build = jax.jit(
        lambda pr, ti: pc.bitpack_by_track(
            pr, ti,
            n_playlists=baskets.n_playlists, n_tracks=v,
            v_pad=v_pad, w_pad=w_total,
        ),
        out_shardings=NamedSharding(mesh, P(None, AXIS_DP)),
    )
    bt = build(
        jnp.asarray(baskets.playlist_rows), jnp.asarray(baskets.track_ids)
    )

    return counts_from_sharded_bitset(
        bt, mesh, impl=impl, interpret=interpret, variant=variant, swar=swar
    )[:v, :v]


def counts_from_sharded_bitset(
    bt: jax.Array,
    mesh: Mesh,
    impl: str | None = None,
    interpret: bool | None = None,
    variant: str | None = None,
    swar: bool | None = None,
) -> jax.Array:
    """Pair counts from an ALREADY word-axis-dp-sharded padded bitset
    ``(v_pad, w_pad) uint32``: each chip counts its slab, partials
    ``psum`` over ICI. The compute core of
    :func:`sharded_bitpack_pair_counts`, exposed for callers whose bitset
    never existed as membership pairs (device-side workload generation,
    data/device_synthetic.py). Returns the full padded ``(v_pad, v_pad)``
    counts (replicated)."""
    from ..ops import popcount as pc

    if mesh.shape.get(AXIS_TP, 1) > 1:
        raise ValueError(
            f"counts_from_sharded_bitset needs a dp-only (Nx1) mesh, got "
            f"{dict(mesh.shape)}; flatten devices onto dp first"
        )
    impl = pc.resolve_counts_impl(impl)
    if impl == "vpu":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        variant, swar = pc.resolve_kernel_opts(variant, swar)
    return _sharded_counts_fn(mesh, impl, interpret, variant, swar)(bt)


@functools.lru_cache(maxsize=32)
def _sharded_counts_fn(mesh, impl, interpret, variant, swar):
    """Cached jitted program per (mesh, impl, kernel opts): rebuilding the
    jit(shard_map(...)) closure per call would retrace + recompile every
    invocation — a warm pass would silently pay full compile time."""
    from ..ops import popcount as pc

    def local(bt_local: jax.Array) -> jax.Array:
        if impl == "mxu":
            # per-shard blocked unpack-matmul (pure XLA — composes under
            # shard_map on any backend, no interpret mode involved)
            c = pc.mxu_pair_counts_padded(bt_local)
        else:
            c = pc.popcount_pair_counts_padded(
                bt_local, interpret=interpret, variant=variant, swar=swar
            )
        return jax.lax.psum(c, AXIS_DP)

    return jax.jit(
        shard_map(
            local, mesh=mesh, in_specs=P(None, AXIS_DP),
            out_specs=P(None, None),
            # the pallas_call's out_shape carries no vma annotation; the
            # psum makes the output mesh-invariant, checked by the tests
            check_vma=False,
        )
    )


def _padded_sharded_counts(
    baskets: Baskets, mesh: Mesh, impl: str = "gspmd"
) -> tuple[jax.Array, int]:
    """Pair counts over the mesh, still PADDED (``v_pad`` a multiple of
    ``tp``) and still column-sharded ``P(None, 'tp')`` → ``(counts, v)``.
    The sharded rule emission consumes the padded sharded matrix directly
    (slicing would gather it); :func:`sharded_pair_counts` slices for
    callers that want the plain (V, V) result."""
    if impl not in _IMPLS:
        raise ValueError(f"impl must be one of {sorted(_IMPLS)}, got {impl!r}")
    p_pad = round_up(max(baskets.n_playlists, 1), mesh.shape[AXIS_DP])
    v_pad = round_up(max(baskets.n_tracks, 1), mesh.shape[AXIS_TP])
    x = _onehot_padded(baskets, p_pad, v_pad, mesh)
    counts = _IMPLS[impl](mesh)(x) if impl != "gspmd" else _IMPLS[impl](mesh)(x, x)
    return counts, baskets.n_tracks


def sharded_pair_counts(
    baskets: Baskets, mesh: Mesh, impl: str = "gspmd"
) -> jax.Array:
    """Pair-count matrix (V, V) int32, computed over the mesh. The result
    keeps its ``P(None, 'tp')`` sharding; downstream rule emission is a
    row/column-local threshold+top-k that composes under the same jit."""
    counts, v = _padded_sharded_counts(baskets, mesh, impl)
    return counts[:v, :v]


@functools.lru_cache(maxsize=16)
def _sharded_emit_fn(mesh: Mesh, k_max: int):
    """Vocab-sharded rule emission (the model-parallel layout's miner
    half): each ``tp`` shard emits the rule rows for ITS slice of the
    antecedent axis from its resident block of the count matrix — the
    full (V, V) counts never exist on one device, which is what lets the
    mine phase accept inputs the dense replicated path cannot hold.

    The count matrix arrives column-sharded ``P(None, 'tp')`` (each shard
    holds ``C[:, lo:hi]``); ``C = XᵀX`` is symmetric, so the transpose of
    the local block IS the shard's row slab ``C[lo:hi, :]`` — no
    collective needed between counting and emission. Per-row semantics
    are exactly ``ops.rules.emit_rule_tensors`` (global-index diagonal
    masking, threshold, top-k with lax.top_k's index tie order), so the
    gathered tensors are bit-identical to the dense emission (pinned by
    tests/test_shard_layout.py). Outputs come back row-sharded
    ``P('tp', None)`` — the exact layout the sharded SERVING bundle
    wants, one vocab axis end to end."""

    def local(c_block: jax.Array, min_count: jax.Array):
        rows = c_block.T  # (V_loc, v_pad) = C[lo:hi, :] by symmetry
        v_loc, v_pad = rows.shape
        lo = jax.lax.axis_index(AXIS_TP).astype(jnp.int32) * v_loc
        row_ids = lo + jnp.arange(v_loc, dtype=jnp.int32)[:, None]
        col_ids = jnp.arange(v_pad, dtype=jnp.int32)[None, :]
        valid = (col_ids != row_ids) & (rows >= min_count)
        row_valid = valid.sum(axis=1, dtype=jnp.int32)
        score = jnp.where(valid, rows, -1)
        k = min(k_max, v_pad)
        top_counts, top_ids = jax.lax.top_k(score, k)
        keep = top_counts > 0
        rule_ids = jnp.where(keep, top_ids, -1).astype(jnp.int32)
        rule_counts = jnp.where(keep, top_counts, 0)
        if k < k_max:  # static pad up to the declared row capacity
            pad = ((0, 0), (0, k_max - k))
            rule_ids = jnp.pad(rule_ids, pad, constant_values=-1)
            rule_counts = jnp.pad(rule_counts, pad)
        # the slab's diagonal — element (r, lo + r) — = singleton supports
        item_counts = jnp.take_along_axis(rows, row_ids, axis=1)[:, 0]
        return rule_ids, rule_counts, row_valid, item_counts

    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(P(None, AXIS_TP), P()),
            out_specs=(
                P(AXIS_TP, None), P(AXIS_TP, None), P(AXIS_TP), P(AXIS_TP)
            ),
            # outputs are per-shard slabs of dp-invariant data; the
            # transpose/top_k chain carries no vma annotation to check
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=16)
def _restricted_counts_fn(mesh: Mesh):
    """Cached jitted restricted recount per mesh: gather the requested
    columns of the ``P('dp','tp')`` one-hot (replicated over tp) and
    contract the playlist axis against the full sharded matrix —
    ``C[R, :] = X[:, R]ᵀ X``, the row slice of the same int32 MXU
    contraction the full count path runs."""
    return jax.jit(
        lambda x, ids: _dot_pt(jnp.take(x, ids, axis=1), x),
        in_shardings=(
            NamedSharding(mesh, P(AXIS_DP, AXIS_TP)),
            NamedSharding(mesh, P()),
        ),
        out_shardings=NamedSharding(mesh, P(None, AXIS_TP)),
    )


def restricted_pair_counts(
    baskets: Baskets, row_ids, mesh: "Mesh | None" = None,
    count_path: str | None = None,
):
    """Rows ``row_ids`` of the pair-count matrix ``C = XᵀX`` → host
    ``(R, V) int32`` — the delta-mining recount (freshness/delta.py):
    only the affected baskets' vocab columns are recounted, against ALL
    baskets, so each returned row is bit-identical to the corresponding
    row of the full count matrix. With ``mesh`` the one-hot rides the
    same ``P('dp','tp')`` layout as the full sharded count path; without
    one it is a single jit over the dense encode.

    ``count_path="sparse"`` (the freshness route consults the SAME
    measured dispatcher as the full mine — mining/dispatch.py — so a
    sparse-eligible delta never silently pays the dense recount) expands
    only the baskets that contain a requested antecedent
    (ops/sparse.py); exact integer accumulation keeps every row
    bit-identical to the dense contraction, mesh or not — and since no
    one-hot is built at all, the mesh adds nothing it needs."""
    import numpy as _np

    row_ids = _np.asarray(row_ids, dtype=_np.int32)
    v = baskets.n_tracks
    if row_ids.size == 0:
        return _np.zeros((0, v), dtype=_np.int32)
    if _np.any(row_ids < 0) or _np.any(row_ids >= v):
        raise ValueError(f"row_ids outside the vocabulary (V={v})")
    if count_path == "sparse":
        from ..ops import sparse as sparse_mod

        return sparse_mod.sparse_restricted_pair_counts_np(
            baskets.playlist_rows, baskets.track_ids, row_ids,
            n_playlists=baskets.n_playlists, n_tracks=v,
        )
    if mesh is None:
        # small-work host path: a delta job is a COLD process, and a jit
        # compile (~0.3 s) would dwarf a thin row-slice recount — scatter
        # the one-hot in numpy and BLAS the slice instead. float64 keeps
        # every count exact (≤ n_playlists ≪ 2^53), so the int32 result
        # is bit-identical to the device contraction.
        if baskets.n_playlists * v <= 16_000_000:
            x = _np.zeros((baskets.n_playlists, v), dtype=_np.float64)
            x[baskets.playlist_rows, baskets.track_ids] = 1.0
            return (x[:, row_ids].T @ x).astype(_np.int32)
        x = encode.onehot_matrix(
            jnp.asarray(baskets.playlist_rows),
            jnp.asarray(baskets.track_ids),
            n_playlists=baskets.n_playlists,
            n_tracks=v,
        )
        counts = _dot_pt(jnp.take(x, jnp.asarray(row_ids), axis=1), x)
        return _np.asarray(jax.device_get(counts))
    p_pad = round_up(max(baskets.n_playlists, 1), mesh.shape[AXIS_DP])
    v_pad = round_up(max(v, 1), mesh.shape[AXIS_TP])
    x = _onehot_padded(baskets, p_pad, v_pad, mesh)
    counts = _restricted_counts_fn(mesh)(x, jnp.asarray(row_ids))
    return _np.asarray(jax.device_get(counts))[:, :v]


def sparse_sharded_rule_tensors(
    baskets: Baskets,
    mesh: Mesh,
    min_count: int,
    k_max: int,
    long_basket_threshold: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The SPARSE count feeding the SAME vocab-sharded emission: counts
    come from the CSR×bitpacked hybrid (ops/sparse.py — only the nnz
    membership pairs are ever touched; the ``(P, V)`` one-hot never
    exists in any layout), then ride ``P(None, 'tp')`` into the exact
    per-shard emission kernel the dense sharded path uses
    (:func:`_sharded_emit_fn`), so the emitted tensors are bit-identical
    to every other path by construction. What the sharded layout buys
    here is the EMISSION memory shape (each device holds only its
    ``C[:, lo:hi]`` block and emits its own antecedent rows); what the
    sparse count buys is skipping the dense/bitpack count FLOPs — the
    two compose."""
    import numpy as np

    from ..ops import sparse as sparse_mod

    tp = mesh.shape[AXIS_TP]
    v = baskets.n_tracks
    v_pad = round_up(max(v, 1), tp)
    counts_np = sparse_mod.sparse_pair_counts_np(
        baskets.playlist_rows, baskets.track_ids,
        n_playlists=baskets.n_playlists, n_tracks=v,
        long_basket_threshold=long_basket_threshold,
    )
    if v_pad != v:
        counts_np = np.pad(counts_np, ((0, v_pad - v), (0, v_pad - v)))
    counts = jax.device_put(
        counts_np, NamedSharding(mesh, P(None, AXIS_TP))
    )
    emitted = _sharded_emit_fn(mesh, k_max)(counts, jnp.int32(min_count))
    rule_ids, rule_counts, row_valid, item_counts = jax.device_get(emitted)
    return (
        np.asarray(rule_ids[:v]),
        np.asarray(rule_counts[:v]),
        np.asarray(row_valid[:v]),
        np.asarray(item_counts[:v]),
    )


def sharded_rule_tensors(
    baskets: Baskets,
    mesh: Mesh,
    min_count: int,
    k_max: int,
    impl: str = "gspmd",
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The vocab-sharded count→emit mining core
    (``KMLS_MODEL_LAYOUT=sharded``): one-hot sharded ``P('dp','tp')``,
    counts sharded ``P(None,'tp')``, emission per row shard — only the
    (V, K_max) rule tensors (K_max ≪ V) ever reach one host. Returns
    host ``(rule_ids, rule_counts, row_valid, item_counts)`` sliced to
    the true vocab, bit-identical to the dense single-device emission."""
    import numpy as _np

    counts, v = _padded_sharded_counts(baskets, mesh, impl)
    emitted = _sharded_emit_fn(mesh, k_max)(counts, jnp.int32(min_count))
    rule_ids, rule_counts, row_valid, item_counts = jax.device_get(emitted)
    return (
        _np.asarray(rule_ids[:v]),
        _np.asarray(rule_counts[:v]),
        _np.asarray(row_valid[:v]),
        _np.asarray(item_counts[:v]),
    )
