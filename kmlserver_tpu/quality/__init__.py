"""Quality loop (ISSUE 14): offline ranking evaluation, the measured
blend optimum, and the artifact lifecycle (delta-chain compaction +
per-artifact staleness bounds) — the fourth writer/reader pair on the
PR 2–4 artifact spine.

- ``quality/eval.py``  — deterministic held-out basket-completion
  harness (leave-n-out per playlist, leakage-guarded by construction)
  scoring every serving mode through the SAME jitted kernels production
  dispatches; runs as the optional checkpointed ``eval`` pipeline phase
  and publishes a versioned ``quality.report.json`` through the
  manifest + lease path.
- ``quality/sweep.py`` — the blend-weight sweep over the held-out
  split; its argmax is the measured optimum ``KMLS_HYBRID_BLEND_WEIGHT=
  measured`` serves.
- ``quality/lifecycle.py`` — the snapshotting delta-chain compactor
  (base ∘ chain folded into a new base bundle without a full re-mine,
  bit-identity guaranteed by reusing the ONE canonical delta
  application) plus the staleness-bound constants /readyz enforces.
"""
