"""Offline ranking evaluation — the measured half of the quality loop.

Nothing in PRs 1–13 measures whether the answers are any GOOD: the blend
weight is a knob nobody swept, and "serves fast" says nothing about
"serves well". This module is the offline evaluation harness the Google
ads-infra paper (PAPERS.md, arXiv:2501.10546) grounds as a first-class
production pipeline stage, and ALX (arXiv:2112.02194) is the precedent
for running TPU-batched factorization evaluation inside the training
loop rather than as an offline afterthought.

Design contract, in order of importance:

- **deterministic split** — leave-``n``-out per playlist, selected by a
  keyed blake2 hash over ``(salt, playlist row, track name)``: no RNG
  state, no dict order, no host dependence — two runs (or two ranks, or
  a checkpoint resume on a different machine) produce byte-identical
  splits. Playlists shorter than ``min_basket`` are not evaluated (a
  1-track basket has nothing to complete).
- **zero leakage by construction** — the evaluated models are trained
  on the TRAIN membership pairs only (the held-out pairs are removed
  before the miner/ALS ever see them) and :func:`holdout_split` asserts
  the two pair sets are disjoint before returning.
- **production kernels** — candidates come from the SAME jitted device
  kernels the serving engine dispatches (``ops.serve.recommend_batch``,
  ``ops.embed.embed_topk``) and the blend merge is the engine's own
  :func:`~kmlserver_tpu.serving.engine.blend_candidates` (one copy of
  the tie-order-critical math), so an offline number can never describe
  a ranking production would not serve.
- **deterministic report** — the ``eval`` phase payload carries no
  timestamps or tokens, so a checkpoint-resumed publication writes a
  byte-identical ``quality.report.json`` (the mining chaos suite's
  bit-identity bar covers it via the manifest sha256).

Metrics per serving mode (rules / embed / blend / popularity fallback):
``recall@k`` (hits over min(k, |targets|)), ``mrr`` (reciprocal rank of
the first hit within the top-k), and ``coverage`` (fraction of eval
playlists answered by the MODEL rather than the fallback).
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
from typing import Any

import numpy as np

from ..config import MiningConfig
from ..mining.vocab import Baskets, Vocab

QUALITY_REPORT_VERSION = 1
# split identity salt: versioned so a future split change is a LOUD
# report-version bump, never a silent drift of the evaluated population
SPLIT_SALT = "kmls-eval-v1"
# seed cap per eval request — mirrors serving's KMLS_MAX_SEED_TRACKS
# default (the harness measures what a production request could carry)
EVAL_SEED_CAP = 128
# kernel batch rows per device call (power-of-two, serving-bucket style)
EVAL_BATCH = 64


def _pair_digest(row: int, name: str) -> int:
    """Stable per-(playlist, track) hold-out key — blake2, not
    ``hash()`` (process-salted), not RNG (order-dependent)."""
    h = hashlib.blake2b(
        f"{SPLIT_SALT}|{row}|{name}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(h, "big")


@dataclasses.dataclass
class HoldoutSplit:
    """One deterministic held-out split: train-side baskets plus the
    per-playlist (seeds, targets) the harness completes."""

    train: Baskets
    # aligned lists, one entry per evaluated playlist
    eval_rows: list[int]
    seed_names: list[list[str]]
    target_names: list[list[str]]
    n_eligible: int  # playlists long enough to evaluate (pre-cap)


def holdout_split(
    baskets: Baskets,
    n_holdout: int = 1,
    min_basket: int = 3,
    max_playlists: int = 0,
) -> HoldoutSplit:
    """Leave-``n_holdout``-out per playlist, deterministically.

    Within each eligible playlist (≥ ``min_basket`` tracks, floored so
    at least two seed tracks always remain) the ``n_holdout`` member
    tracks with the smallest pair digest are held out; the rest stay as
    seeds AND as training membership. ``max_playlists`` > 0 caps the
    evaluated set to the playlists with the smallest row digests (again
    hash-selected — a prefix slice would bias toward low pids)."""
    min_basket = max(min_basket, n_holdout + 2)
    rows = baskets.playlist_rows.astype(np.int64)
    tids = baskets.track_ids.astype(np.int64)
    order = np.lexsort((tids, rows))
    rows_s, tids_s = rows[order], tids[order]
    sizes = np.bincount(rows_s, minlength=baskets.n_playlists)
    starts = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    eligible = np.flatnonzero(sizes >= min_basket)
    n_eligible = len(eligible)
    if max_playlists > 0 and len(eligible) > max_playlists:
        keyed = sorted(
            eligible.tolist(),
            key=lambda r: _pair_digest(r, "<row>"),
        )
        eligible = np.asarray(sorted(keyed[:max_playlists]), dtype=np.int64)
    names = baskets.vocab.names
    heldout_mask = np.zeros(len(rows_s), dtype=bool)
    eval_rows: list[int] = []
    seed_names: list[list[str]] = []
    target_names: list[list[str]] = []
    for r in eligible.tolist():
        lo = int(starts[r])
        hi = lo + int(sizes[r])
        member = tids_s[lo:hi]
        digests = [_pair_digest(r, names[int(t)]) for t in member]
        picked = sorted(range(len(member)), key=lambda i: digests[i])
        held = set(picked[:n_holdout])
        heldout_mask[lo + np.asarray(sorted(held), dtype=np.int64)] = True
        eval_rows.append(r)
        seed_names.append(
            [names[int(member[i])] for i in range(len(member)) if i not in held]
        )
        target_names.append([names[int(member[i])] for i in sorted(held)])
    keep = ~heldout_mask
    train = Baskets(
        playlist_rows=rows_s[keep].astype(np.int32),
        track_ids=tids_s[keep].astype(np.int32),
        n_playlists=baskets.n_playlists,
        vocab=baskets.vocab,
    )
    # leakage guard, asserted by construction: the held-out pairs and the
    # train pairs partition the membership set — an intersection would
    # mean the models train on the answers they are scored against
    v = np.int64(baskets.n_tracks)
    train_keys = set((rows_s[keep] * v + tids_s[keep]).tolist())
    held_keys = set((rows_s[heldout_mask] * v + tids_s[heldout_mask]).tolist())
    if train_keys & held_keys:
        raise AssertionError(
            "held-out pairs leaked into the train split — the split is "
            "broken, refusing to evaluate"
        )
    return HoldoutSplit(
        train=train,
        eval_rows=eval_rows,
        seed_names=seed_names,
        target_names=target_names,
        n_eligible=n_eligible,
    )


def _batched_candidates(kernel, tensor_args, seed_id_lists, k: int):
    """Run a jitted top-k kernel over padded (EVAL_BATCH, L) seed
    batches → per-request ``(top_ids, top_scores)`` host rows. One fixed
    shape per harness run, so the kernel compiles once."""
    import jax.numpy as jnp

    n = len(seed_id_lists)
    length = max(
        1, min(max((len(s) for s in seed_id_lists), default=1), EVAL_SEED_CAP)
    )
    out_ids = np.full((n, k), -1, dtype=np.int32)
    out_scores = np.zeros((n, k), dtype=np.float32)
    for lo in range(0, n, EVAL_BATCH):
        chunk = seed_id_lists[lo:lo + EVAL_BATCH]
        arr = np.full((EVAL_BATCH, length), -1, dtype=np.int32)
        for r, ids in enumerate(chunk):
            ids = ids[:length]
            arr[r, : len(ids)] = ids
        ids_d, scores_d = kernel(*tensor_args, jnp.asarray(arr), k_best=k)
        out_ids[lo:lo + len(chunk)] = np.asarray(ids_d)[: len(chunk)]
        out_scores[lo:lo + len(chunk)] = np.asarray(scores_d)[: len(chunk)]
    return out_ids, out_scores


def _rank_metrics(
    answer: list[str], targets: list[str], k: int
) -> tuple[float, float]:
    """→ (recall@k, reciprocal rank of the first hit in the top-k)."""
    target_set = set(targets)
    top = answer[:k]
    hits = sum(1 for name in top if name in target_set)
    recall = hits / max(min(k, len(target_set)), 1)
    rr = 0.0
    for rank, name in enumerate(top, start=1):
        if name in target_set:
            rr = 1.0 / rank
            break
    return recall, rr


def _fallback_answer(best_names: list[str], seeds: list[str], k: int) -> list[str]:
    """The popularity fallback, exactly as serving composes it: a
    stable-seeded sample over the popularity ranking (engine
    .static_recommendation's arithmetic, deadline path excluded)."""
    from ..serving.engine import stable_seed

    if not best_names:
        return []
    kk = min(k, len(best_names))
    rng = random.Random(stable_seed(seeds))
    return rng.sample(best_names, kk)


def run_eval_phase(
    cfg: MiningConfig,
    baskets: Baskets,
    mesh=None,
) -> dict[str, Any]:
    """The ``eval`` pipeline phase: split → train both model families on
    the train half → score every serving mode on basket completion →
    sweep the blend weight → the deterministic quality report (the
    phase's checkpoint payload AND the ``quality.report.json`` body)."""
    from ..mining import als as als_mod
    from ..mining.miner import mine
    from ..ops.embed import embed_topk
    from ..ops.serve import recommend_batch
    from ..ops.support import min_count_for
    from ..serving.engine import blend_candidates
    from .sweep import DEFAULT_BLEND_WEIGHT, sweep_blend_weight

    k = max(1, cfg.eval_k)
    split = holdout_split(
        baskets,
        n_holdout=max(1, cfg.eval_holdout_n),
        max_playlists=cfg.eval_max_playlists,
    )
    n_eval = len(split.eval_rows)
    print(
        f"Eval split: {n_eval} playlists evaluated "
        f"({split.n_eligible} eligible), leave-{max(1, cfg.eval_holdout_n)}"
        f"-out, {len(split.train.playlist_rows)} train pairs"
    )
    report: dict[str, Any] = {
        "version": QUALITY_REPORT_VERSION,
        "split": {
            "salt": SPLIT_SALT,
            "holdout_n": max(1, cfg.eval_holdout_n),
            "n_eval_playlists": n_eval,
            "n_eligible_playlists": split.n_eligible,
            "n_train_pairs": int(len(split.train.playlist_rows)),
        },
        "k": k,
        "modes": {},
        "sweep": None,
        "measured_blend_weight": None,
    }
    if n_eval == 0:
        print("Eval: no playlist long enough to hold out — empty report")
        return report

    # ---- train both model families on the TRAIN split only ----
    result = mine(split.train, cfg, mesh=mesh)
    tensors = result.tensors
    rule_vocab = result.vocab_names
    rule_index = {n: i for i, n in enumerate(rule_vocab)}
    known = tensors.item_counts >= min_count_for(
        tensors.min_support, tensors.n_playlists
    )
    emb = None
    if cfg.embed_enabled:
        emb_payload = als_mod.train_embeddings(split.train, cfg, mesh=mesh)
        if emb_payload.get("item_factors") is not None:
            emb = {
                "factors": np.asarray(
                    emb_payload["item_factors"], dtype=np.float32
                ),
                "vocab": list(split.train.vocab.names),
            }
    # popularity ranking for the fallback mode: same tie order (count
    # desc, name asc) and same no-minimum percentile TRUNCATION as
    # production's most_frequent_tracks — a tiny vocabulary can
    # legitimately keep nothing, exactly like a production PVC. One
    # DELIBERATE divergence, for leakage-freedom: counts come from the
    # TRAIN membership pairs (deduplicated — Baskets dedups by
    # construction), not the full CSV's raw rows, so a held-out pair
    # can never vote for its own popularity.
    pop_counts = np.bincount(
        split.train.track_ids, minlength=split.train.n_tracks
    )
    pop_order = np.lexsort(
        (np.asarray(split.train.vocab.names, dtype=object), -pop_counts)
    )
    keep_n = int(len(pop_order) * cfg.top_tracks_save_percentile)
    best_names = [
        split.train.vocab.names[int(i)] for i in pop_order[:keep_n]
    ]

    # ---- candidates through the production kernels, batched ----
    import jax.numpy as jnp

    rule_seed_ids = [
        [
            rule_index[n]
            for n in seeds
            if n in rule_index and bool(known[rule_index[n]])
        ][:EVAL_SEED_CAP]
        for seeds in split.seed_names
    ]
    rule_args = (
        jnp.asarray(tensors.rule_ids), jnp.asarray(tensors.rule_confs),
    )
    r_ids, r_confs = _batched_candidates(
        recommend_batch, rule_args, rule_seed_ids, k
    )
    rule_pairs: list[list[tuple[str, float]]] = [
        [
            (rule_vocab[int(i)], float(c))
            for i, c in zip(r_ids[e], r_confs[e])
            if i >= 0
        ]
        for e in range(n_eval)
    ]
    emb_pairs: list[list[tuple[str, float]]] | None = None
    emb_seed_ids: list[list[int]] = []
    if emb is not None:
        emb_index = {n: i for i, n in enumerate(emb["vocab"])}
        emb_seed_ids = [
            [emb_index[n] for n in seeds if n in emb_index][:EVAL_SEED_CAP]
            for seeds in split.seed_names
        ]
        e_ids, e_sims = _batched_candidates(
            embed_topk, (jnp.asarray(emb["factors"]),), emb_seed_ids, k
        )
        emb_pairs = [
            [
                (emb["vocab"][int(i)], float(s))
                for i, s in zip(e_ids[e], e_sims[e])
                if i >= 0
            ]
            for e in range(n_eval)
        ]

    # ---- per-mode composition (the engine's serving semantics) ----
    def compose(mode: str, weight: float, e: int) -> tuple[list[str], bool]:
        """→ (answer names, answered-by-model) for eval playlist ``e``,
        mirroring engine._compose_answer mode for mode."""
        rk = bool(rule_seed_ids[e])
        ek = emb_pairs is not None and bool(emb_seed_ids[e])
        seeds = split.seed_names[e]
        if mode == "popularity" or (not rk and not ek):
            return _fallback_answer(best_names, seeds, k), False
        if mode == "rules":
            if not rk:
                return _fallback_answer(best_names, seeds, k), False
            return [n for n, _ in rule_pairs[e]], True
        if mode == "embed":
            if not ek:
                return _fallback_answer(best_names, seeds, k), False
            return [n for n, _ in emb_pairs[e]], True
        # blend: union of both families (embed-only when the rules have
        # never seen the seeds — the cold-start path; rules-only when no
        # embedding candidates exist)
        if not ek:
            return [n for n, _ in rule_pairs[e]], True
        if not rk:
            return [n for n, _ in emb_pairs[e]], True
        return (
            blend_candidates(rule_pairs[e], emb_pairs[e], weight, k), True
        )

    def score_mode(mode: str, weight: float = DEFAULT_BLEND_WEIGHT) -> dict:
        recalls, rrs, covered = [], [], 0
        for e in range(n_eval):
            answer, by_model = compose(mode, weight, e)
            recall, rr = _rank_metrics(answer, split.target_names[e], k)
            recalls.append(recall)
            rrs.append(rr)
            covered += int(by_model and bool(answer))
        return {
            "recall_at_k": round(float(np.mean(recalls)), 6),
            "mrr": round(float(np.mean(rrs)), 6),
            "coverage": round(covered / n_eval, 6),
        }

    report["modes"]["rules"] = score_mode("rules")
    report["modes"]["popularity"] = score_mode("popularity")
    if emb_pairs is not None:
        report["modes"]["embed"] = score_mode("embed")
        report["modes"]["blend"] = score_mode("blend")
        sweep = sweep_blend_weight(
            lambda w, e: compose("blend", w, e)[0],
            split.target_names, n_eval, k,
        )
        report["sweep"] = sweep
        report["measured_blend_weight"] = sweep["best_weight"]
    else:
        # no second model family this generation: blend degenerates to
        # rules-only and there is no weight to measure — the serving
        # side's `measured` mode falls back to its default, loudly
        report["modes"]["blend"] = report["modes"]["rules"]
    for mode in ("rules", "embed", "blend", "popularity"):
        stats = report["modes"].get(mode)
        if stats:
            print(
                f"Eval {mode}: recall@{k} {stats['recall_at_k']:.4f}, "
                f"MRR {stats['mrr']:.4f}, coverage {stats['coverage']:.3f}"
            )
    if report["measured_blend_weight"] is not None:
        print(
            f"Eval blend sweep: measured optimum w="
            f"{report['measured_blend_weight']} "
            f"(recall@{k} {report['sweep']['best_recall_at_k']:.4f})"
        )
    return report


__all__ = [
    "EVAL_SEED_CAP",
    "HoldoutSplit",
    "QUALITY_REPORT_VERSION",
    "SPLIT_SALT",
    "holdout_split",
    "run_eval_phase",
]
