"""Artifact lifecycle — the snapshotting delta-chain compactor.

Continuous freshness (PR 10) publishes ``delta-<seq>.bundle`` patches
between full re-mines, and ``KMLS_DELTA_MAX_CHAIN`` eventually forces a
full re-mine — the expensive hammer. This module adds the cheap middle:
once the chain reaches ``KMLS_DELTA_COMPACT_AFTER`` bundles, the WRITER
folds base ∘ chain into a new base bundle WITHOUT re-mining anything —
the fold is :func:`~kmlserver_tpu.freshness.delta.apply_delta_to_tensors`
(the ONE canonical delta application both mining and serving already
use), so ``compacted snapshot ≡ base ∘ chain ≡ full re-mine`` is a
structural property, not a second implementation to keep honest
(bit-identity pinned in both layouts by tests/test_quality.py).

The compacted publication is a normal full publication to readers: new
npz + recommendations pickle, manifest re-stamped, invalidation token
rewritten (serving does its ordinary hot swap — zero 5xx through a
mid-replay compaction is chaos-tested), the delta chain retired, and
the freshness base state rolled onto the new token so the NEXT delta
extends the compacted base — selective cache invalidation keeps working
across the swap. The dataset rotation history is deliberately NOT
appended: compaction re-publishes the same logical generation, it does
not mine a dataset.

Lease discipline matches every other writer: fencing-token checks
before the first artifact write and before the token rewrite, so a
zombie compactor cannot tear what a newer run published.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any

import numpy as np

from ..config import MiningConfig
from ..io import artifacts, registry
from ..utils.timeutil import get_current_time_str_precise


def manifest_filenames(cfg: MiningConfig) -> list[str]:
    """THE manifest file set of a full publication — one copy, shared by
    the mining pipeline and the compactor, so a compacted generation can
    never manifest a different artifact set than a mined one."""
    return [
        cfg.best_tracks_file,
        cfg.recommendations_file,
        cfg.recommendations_file + artifacts.TENSOR_ARTIFACT_SUFFIX,
        cfg.artists_mapping_file,
        cfg.track_info_file,
        cfg.repeated_tracks_file,
        artifacts.EMBEDDINGS_FILENAME,
        artifacts.QUALITY_REPORT_FILENAME,
    ]


@dataclasses.dataclass
class CompactionResult:
    """What one compaction did."""

    n_folded: int  # delta bundles folded into the new base
    token: str  # the new invalidation token published
    npz_sha256: str  # digest of the compacted tensor artifact
    duration_s: float


class CompactionIneligible(RuntimeError):
    """The chain cannot be compacted right now (empty, torn, or bound to
    a generation that is no longer published) — callers fall through to
    the normal full-re-mine posture."""


def _folded_tensors(
    cfg: MiningConfig, state: dict[str, Any], token: str
) -> dict[str, Any]:
    """base npz ∘ every chain bundle → the logical tensors, via the one
    canonical application. Raises :class:`CompactionIneligible` on any
    binding/validation failure — a torn chain compacts nothing."""
    from ..freshness import delta as delta_mod

    npz_path = artifacts.tensor_artifact_path(
        os.path.join(cfg.pickles_dir, cfg.recommendations_file)
    )
    if not os.path.exists(npz_path):
        raise CompactionIneligible("no tensor artifact to fold onto")
    if artifacts.file_digest(npz_path)["sha256"] != state.get(
        "base_npz_sha256"
    ):
        raise CompactionIneligible("chain bound to different base bytes")
    loaded = artifacts.load_rule_tensors(npz_path)
    if loaded.get("rule_confs64") is not None:
        raise CompactionIneligible(
            "merged-confidence artifact (delta-ineligible lineage)"
        )
    prev: dict[str, Any] = {
        "vocab": list(loaded["vocab"]),
        "rule_ids": np.asarray(loaded["rule_ids"], dtype=np.int32),
        "rule_counts": np.asarray(loaded["rule_counts"], dtype=np.int32),
        "item_counts": np.asarray(loaded["item_counts"], dtype=np.int32),
        "n_playlists": int(loaded["n_playlists"]),
        "min_support": float(loaded["min_support"]),
        "mode": str(loaded["mode"]),
        "min_confidence": float(loaded["min_confidence"]),
    }
    for entry in sorted(state["entries"], key=lambda e: e.get("seq", 0)):
        path = os.path.join(cfg.pickles_dir, str(entry.get("file", "")))
        try:
            bundle = artifacts.load_delta_bundle(
                path, expect_sha256=entry.get("sha256")
            )
            if bundle["base_token"] != token:
                raise ValueError("bundle bound to another generation")
            prev = delta_mod.apply_delta_to_tensors(prev, bundle)
        except (OSError, ValueError) as exc:
            raise CompactionIneligible(
                f"chain entry {entry.get('seq')} unusable: {exc}"
            )
    return prev


def compact_delta_chain(cfg: MiningConfig) -> CompactionResult:
    """Fold the current delta chain into a new base bundle (writer side,
    lease-fenced). Raises :class:`CompactionIneligible` when there is
    nothing sound to compact."""
    t0 = time.perf_counter()
    state = artifacts.read_delta_state(cfg.pickles_dir)
    if state is None or not state.get("entries"):
        raise CompactionIneligible("no delta chain on the PVC")
    token_path = registry.token_path_for(
        cfg.base_dir, cfg.data_invalidation_file
    )
    try:
        token = artifacts.read_text(token_path)
    except FileNotFoundError:
        raise CompactionIneligible("no invalidation token on the PVC")
    if state.get("base_token") != token:
        raise CompactionIneligible("chain bound to another generation")

    folded = _folded_tensors(cfg, state, token)

    lease = None
    if cfg.lease_enabled:
        lease = artifacts.PublicationLease.acquire(
            cfg.pickles_dir,
            ttl_s=cfg.lease_ttl_s,
            heartbeat_interval_s=cfg.lease_heartbeat_interval_s or None,
        )
        lease.start_heartbeat()
        print(
            f"Compaction lease acquired (fencing token {lease.fencing_token})"
        )
    try:
        if lease is not None:
            lease.check()  # fence point 1: before the first write
        new_token = get_current_time_str_precise()
        rec_path = os.path.join(cfg.pickles_dir, cfg.recommendations_file)
        npz_path = artifacts.tensor_artifact_path(rec_path)
        # the pickle twin expands through the ONE canonical dict
        # expansion (ops/rules.py via rules_dict_from_tensors), exactly
        # like a load of the npz would — npz and pickle cannot drift
        rules_dict = artifacts.rules_dict_from_tensors(
            {**folded, "rule_confs64": None}
        )
        artifacts.save_pickle(rules_dict, rec_path)
        artifacts.save_rule_tensors(
            npz_path,
            vocab=folded["vocab"],
            rule_ids=folded["rule_ids"],
            rule_counts=folded["rule_counts"],
            item_counts=folded["item_counts"],
            n_playlists=folded["n_playlists"],
            min_support=folded["min_support"],
            mode=folded["mode"],
            min_confidence=folded["min_confidence"],
        )
        npz_sha = artifacts.file_digest(npz_path)["sha256"]
        if cfg.write_manifest:
            artifacts.write_manifest(
                cfg.pickles_dir,
                manifest_filenames(cfg),
                token=new_token,
                fencing_token=lease.fencing_token if lease else None,
            )
        if lease is not None:
            lease.check()  # fence point 2: before the token rewrite
        # token rewrite WITHOUT a history append: compaction re-publishes
        # the same logical generation — the dataset rotation must not
        # advance (the next mining run still rotates from the last MINED
        # index)
        artifacts.atomic_write_text(token_path, new_token)
        # the chain is folded in; stale bundles must not outlive it
        artifacts.retire_delta_chain(cfg.pickles_dir)
        # roll the freshness base state onto the new token so the next
        # delta extends the COMPACTED base (its `published` is already
        # base ∘ chain — the delta route rolled it forward per bundle)
        from ..freshness import delta as delta_mod

        base = delta_mod.load_base_state(cfg.pickles_dir)
        if base is not None and base.get("token") == token:
            base["token"] = new_token
            base["npz_sha256"] = npz_sha
            base["published"] = folded
            artifacts.save_pickle(
                base, delta_mod.base_state_path(cfg.pickles_dir)
            )
        if lease is not None:
            lease.release()
        duration = time.perf_counter() - t0
        print(
            f"Delta chain compacted: {len(state['entries'])} bundles "
            f"folded into a new base ({duration:.2f}s, token {new_token})"
        )
        return CompactionResult(
            n_folded=len(state["entries"]),
            token=new_token,
            npz_sha256=npz_sha,
            duration_s=duration,
        )
    except BaseException:
        if lease is not None:
            lease.stop_heartbeat()
            try:
                lease.release()
            except (artifacts.LeaseLostError, OSError):
                pass
        raise
    finally:
        if lease is not None:
            lease.stop_heartbeat()


def maybe_compact(cfg: MiningConfig) -> CompactionResult | None:
    """The pipeline's trigger: compact when the chain has reached
    ``KMLS_DELTA_COMPACT_AFTER`` bundles (0 = compaction disabled).
    Never raises — a failed compaction keeps the chain; the next delta
    run re-triggers, and ``KMLS_DELTA_MAX_CHAIN`` remains the hard
    backstop."""
    threshold = cfg.delta_compact_after
    if threshold <= 0:
        return None
    state = artifacts.read_delta_state(cfg.pickles_dir)
    if state is None or len(state.get("entries", ())) < threshold:
        return None
    try:
        return compact_delta_chain(cfg)
    except CompactionIneligible as exc:
        print(f"Delta compaction skipped ({exc})")
        return None
    except artifacts.LeaseHeldError as exc:
        print(f"Delta compaction deferred (lease held: {exc})")
        return None
    except Exception as exc:
        print(f"WARNING: delta compaction failed: {exc!r}")
        return None


__all__ = [
    "CompactionIneligible",
    "CompactionResult",
    "compact_delta_chain",
    "manifest_filenames",
    "maybe_compact",
]
