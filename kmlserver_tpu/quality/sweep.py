"""Blend-weight sweep — the measured optimum the bundle publishes.

``KMLS_HYBRID_BLEND_WEIGHT`` was a knob nobody swept: PR 6 shipped the
hybrid rule∪embedding blend with ``w = 0.5`` because 0.5 is what you
write when you have no measurement. This module sweeps the weight over
the held-out basket-completion split (``quality/eval.py``) and its
argmax becomes the published ``measured_blend_weight`` in
``quality.report.json`` — the serve-time blend then becomes a measured
decision exactly like ISSUE 13's dispatch table: the serving engine
reads it under ``KMLS_HYBRID_BLEND_WEIGHT=measured``, an explicit float
still wins, and an absent report fails safe to the default.

The sweep re-MERGES host-side only: the expensive kernel candidates are
computed once by the harness, and each grid point re-ranks them through
the engine's own ``blend_candidates`` — so a 21-point sweep costs 21
host merges, not 21 device passes.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

# the serving default (ServingConfig.hybrid_blend_weight) — the sweep's
# baseline point and the fail-safe when no report is published
DEFAULT_BLEND_WEIGHT = 0.5

# 21-point grid over [0, 1]: w=0 is NOT rules-only (embeddings still
# backfill rule-less candidates) and w=1 is NOT embed-only (rule-only
# rows keep their answers), so the endpoints are legitimate candidates
WEIGHT_GRID = tuple(round(w, 2) for w in np.arange(0.0, 1.0001, 0.05))


def sweep_blend_weight(
    compose_at: Callable[[float, int], list[str]],
    target_names: list[list[str]],
    n_eval: int,
    k: int,
) -> dict[str, Any]:
    """Sweep ``WEIGHT_GRID`` → the full recall curve + the argmax.

    ``compose_at(w, e)`` returns the blended answer for eval playlist
    ``e`` at weight ``w`` (the harness passes its production-semantics
    composer). Ties argmax toward the LOWEST weight — deterministic, and
    biased toward the rule model the reference system is built on."""
    from .eval import _rank_metrics

    weights: list[float] = []
    recalls: list[float] = []
    mrrs: list[float] = []
    for w in WEIGHT_GRID:
        per_recall, per_rr = [], []
        for e in range(n_eval):
            recall, rr = _rank_metrics(compose_at(w, e), target_names[e], k)
            per_recall.append(recall)
            per_rr.append(rr)
        weights.append(float(w))
        recalls.append(round(float(np.mean(per_recall)), 6))
        mrrs.append(round(float(np.mean(per_rr)), 6))
    best_i = max(range(len(weights)), key=lambda i: (recalls[i], -weights[i]))
    return {
        "weights": weights,
        "recall_at_k": recalls,
        "mrr": mrrs,
        "best_weight": weights[best_i],
        "best_recall_at_k": recalls[best_i],
        "best_mrr": mrrs[best_i],
    }


__all__ = ["DEFAULT_BLEND_WEIGHT", "WEIGHT_GRID", "sweep_blend_weight"]
