"""Asyncio HTTP/1.1 transport for :class:`~.app.RecommendApp` — the
production serving front end.

Why not the stdlib ``ThreadingHTTPServer`` (kept in ``serving.server`` as
the ``KMLS_HTTP_IMPL=threaded`` fallback): thread-per-connection collapses
under concurrency on small pods — measured this round on a 2-core host,
``/healthz`` throughput FELL from ~800 QPS at 1 connection to ~300 at 32
(GIL convoy + context-switch storm), a ceiling far below the 1k-QPS
config-5 target before the engine does any work at all. A single-threaded
event loop holds ~700+ QPS flat at the same concurrency because each
request costs one parse + one dispatch, no thread handoffs.

The recommendation path never blocks the loop: ``app.submit_recommend``
first consults the epoch-keyed answer cache (a hit resolves inline on the
loop — no batcher, no executor, no thread handoff; concurrent identical
misses singleflight onto one shared future), then the micro-batcher's
non-blocking ``submit()`` (→ Future); the loop attaches a done-callback,
and the batcher's completion thread hands the finished result back via
``call_soon_threadsafe``. Every other route is sub-millisecond and runs
inline. One request is outstanding per connection (HTTP/1.1 without
pipelining — what real clients speak); further bytes buffer until the
response is written.

SIGTERM drain parity with the threaded transport (k8s rollout semantics):
on ``drain()`` the listener closes immediately (racing connects are
refused, not parked), every subsequent response carries ``Connection:
close`` so keep-alive clients migrate off the pod, and shutdown settles
until in-flight requests hit zero (bounded by ``KMLS_DRAIN_SETTLE_S``).
"""

from __future__ import annotations

import asyncio
import logging
import socket

from .. import faults
from .app import RecommendApp

logger = logging.getLogger("kmlserver_tpu.serving")

_REASONS = {
    200: "OK", 307: "Temporary Redirect", 400: "Bad Request",
    403: "Forbidden", 404: "Not Found", 422: "Unprocessable Entity",
    429: "Too Many Requests", 500: "Internal Server Error",
    503: "Service Unavailable",
}
_MAX_HEAD = 32 * 1024
_MAX_BODY = 10 * 1024 * 1024
_RECOMMEND_PATHS = ("/api/recommend/", "/api/recommend")


class _ServerState:
    """Shared across connections: drain flag + in-flight accounting (the
    loop is single-threaded, so plain ints are safe)."""

    def __init__(self, app: RecommendApp):
        self.app = app
        self.draining = False
        self.inflight = 0
        self.idle = asyncio.Event()
        self.idle.set()
        self._engine_pool = None

    @property
    def engine_pool(self):
        """Small thread pool for the BATCHERLESS recommend path
        (KMLS_BATCH_WINDOW_MS=0): engine.recommend blocks on the device —
        through a remote-TPU tunnel for hundreds of ms — and running it
        on the loop would freeze every connection, health probes
        included. Lazy: the batched default never needs it."""
        if self._engine_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._engine_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="kmls-aio-engine"
            )
        return self._engine_pool

    def enter(self) -> None:
        self.inflight += 1
        self.idle.clear()

    def leave(self) -> None:
        self.inflight -= 1
        if self.inflight <= 0:
            self.idle.set()


# bound on requests parsed-but-unanswered per connection: keeps a
# misbehaving pipeliner from queueing unbounded work
_MAX_PIPELINE = 128


class _Conn(asyncio.Protocol):
    """One HTTP/1.1 connection, with PIPELINING: every complete request in
    the buffer is dispatched immediately, responses are staged by sequence
    number, and every contiguous ready prefix goes out as ONE
    ``transport.write``. Syscalls are the dominant per-request cost in a
    sandboxed runtime (measured ~0.5 ms per ``recv``/``send`` here — a
    gVisor-style trap per call), so a client that bursts K requests per
    write costs this server ~2 syscalls per K requests instead of 2K;
    non-pipelining clients behave exactly as before."""

    def __init__(self, state: _ServerState):
        self.state = state
        self.buf = b""
        self.transport: asyncio.Transport | None = None
        self.peer_host: str | None = None
        self.closed = False
        self._next_seq = 0    # next request sequence number to assign
        self._next_write = 0  # next sequence number to write out
        self._staged: dict[int, tuple[tuple, bool]] = {}
        self._reading_paused = False

    # ---------- transport events ----------

    def connection_made(self, transport) -> None:
        self.transport = transport
        self.loop = asyncio.get_running_loop()
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        peer = transport.get_extra_info("peername")
        self.peer_host = peer[0] if peer else None

    def connection_lost(self, exc) -> None:
        self.closed = True

    def data_received(self, data: bytes) -> None:
        self.buf += data
        self._process_buffer()
        self._update_read_flow()

    def _update_read_flow(self) -> None:
        """Backpressure the SOCKET, not just the parser: with parsing
        stopped at the pipeline cap, un-paused reads would still grow
        ``self.buf`` without bound for a client that keeps streaming."""
        if self.closed or self.transport is None:
            return
        backlogged = (
            self._next_seq - self._next_write >= _MAX_PIPELINE
            or len(self.buf) > _MAX_HEAD + _MAX_BODY
        )
        if backlogged and not self._reading_paused:
            try:
                self.transport.pause_reading()
                self._reading_paused = True
            except RuntimeError:
                pass
        elif not backlogged and self._reading_paused:
            try:
                self.transport.resume_reading()
                self._reading_paused = False
            except RuntimeError:
                pass

    # ---------- request framing ----------

    def _process_buffer(self) -> None:
        while (
            not self.closed
            and self._next_seq - self._next_write < _MAX_PIPELINE
        ):
            end = self.buf.find(b"\r\n\r\n")
            if end < 0:
                if len(self.buf) > _MAX_HEAD:
                    self._bad_request("headers too large")
                return
            head = self.buf[:end]
            try:
                request_line, _, header_block = head.partition(b"\r\n")
                method, path, _ = request_line.decode("latin1").split(" ", 2)
            except ValueError:
                self._bad_request("malformed request line")
                return
            content_length = 0
            close_after = False
            trace_header: str | None = None
            budget_header: str | None = None
            for line in header_block.split(b"\r\n"):
                key, _, value = line.partition(b":")
                lowered = key.strip().lower()
                if lowered == b"content-length":
                    try:
                        content_length = int(value.strip())
                    except ValueError:
                        self._bad_request("bad Content-Length")
                        return
                elif lowered == b"connection":
                    close_after = value.strip().lower() == b"close"
                elif lowered == b"x-kmls-trace":
                    # span-trace propagation (ISSUE 9): the raw value;
                    # the recorder validates the charset before any byte
                    # of it can reach JSON output
                    trace_header = value.strip().decode("latin1")
                elif lowered == b"x-kmls-deadline-budget":
                    # deadline propagation (ISSUE 18): remaining budget
                    # (ms) forwarded by an upstream hop; the app parses
                    # and ignores malformed values
                    budget_header = value.strip().decode("latin1")
            if content_length > _MAX_BODY:
                self._bad_request("body too large")
                return
            total = end + 4 + content_length
            if len(self.buf) < total:
                return  # body still arriving
            body = self.buf[end + 4: total] or None
            self.buf = self.buf[total:]
            self._dispatch(
                method, path, body, close_after, trace_header, budget_header
            )

    def _bad_request(self, detail: str) -> None:
        seq = self._next_seq
        self._next_seq += 1
        self.buf = b""
        self._stage(
            seq,
            (400, {"Content-Type": "application/json"},
             b'{"detail": "' + detail.encode() + b'"}'),
            close_after=True,
        )

    # ---------- dispatch ----------

    def _dispatch(
        self, method: str, path: str, body: bytes | None, close_after: bool,
        trace_header: str | None = None, budget_header: str | None = None,
    ) -> None:
        state = self.state
        app = state.app
        state.enter()
        seq = self._next_seq
        self._next_seq += 1
        route = path.split("?", 1)[0]
        if method == "POST" and route in _RECOMMEND_PATHS:
            # gray-failure chaos site (ISSUE 18), loop-native form: an
            # armed per-replica stall delays THIS request on the loop
            # timer — pipelined neighbours and other connections keep
            # flowing, which is what a slow-but-alive replica looks
            # like from outside. fire()'s blocking sleep would stall
            # the whole loop and turn a per-request stall into a full
            # replica outage.
            try:
                delay = faults.take("fleet.peer", replica=app._fleet_index)
            except Exception:
                logger.exception("unhandled error for %s %s", method, path)
                app.metrics.record_error()
                self._stage(seq, (
                    500, {"Content-Type": "application/json"},
                    b'{"detail": "Internal Server Error"}',
                ), close_after)
                state.leave()
                return
            if delay > 0:
                self.loop.call_later(
                    delay, self._recommend, seq, path, body, close_after,
                    trace_header, budget_header,
                )
                return
            self._recommend(
                seq, path, body, close_after, trace_header, budget_header
            )
            return
        try:
            response = app.handle(
                method, path, body, client_host=self.peer_host
            )
        except Exception:
            logger.exception("unhandled error for %s %s", method, path)
            app.metrics.record_error()
            response = (
                500, {"Content-Type": "application/json"},
                b'{"detail": "Internal Server Error"}',
            )
        self._stage(seq, response, close_after)
        state.leave()

    def _recommend(
        self, seq: int, path: str, body: bytes | None, close_after: bool,
        trace_header: str | None = None, budget_header: str | None = None,
    ) -> None:
        """The recommend-POST tail of :meth:`_dispatch`, split out so an
        armed fault stall can re-enter it from a loop timer with its
        response slot (``seq``) already reserved — pipelined responses
        still leave in request order through ``_stage``."""
        state = self.state
        app = state.app
        if self.closed:  # connection dropped during a fault stall
            state.leave()
            return
        try:
            if app.batcher is None:
                # batching disabled: the blocking engine call must
                # still stay off the loop
                # the fleet.peer stall was already take()n in _dispatch:
                # the handler must not fire the site a second time
                task = state.engine_pool.submit(
                    app.handle, "POST", path, body, self.peer_host,
                    trace_header, budget_header, False,
                )
                task.add_done_callback(
                    lambda f: self.loop.call_soon_threadsafe(
                        self._finish_handled, seq, f, close_after
                    )
                )
                return
            response, future, t0, trace = app.submit_recommend(
                body, trace_header, budget_header
            )
            if response is None:
                if isinstance(future, asyncio.Future):
                    # loop-native batcher: resolved ON the loop, the
                    # callback is already loop-scheduled
                    future.add_done_callback(
                        lambda f: self._finish_recommend(
                            seq, f, t0, close_after, trace
                        )
                    )
                else:
                    # threaded batcher: its completion thread fires
                    # the callback → hop back onto the loop
                    future.add_done_callback(
                        lambda f: self.loop.call_soon_threadsafe(
                            self._finish_recommend, seq, f, t0,
                            close_after, trace,
                        )
                    )
                return
        except Exception:
            logger.exception("unhandled error for POST %s", path)
            app.metrics.record_error()
            response = (
                500, {"Content-Type": "application/json"},
                b'{"detail": "Internal Server Error"}',
            )
        self._stage(seq, response, close_after)
        state.leave()

    def _finish_recommend(
        self, seq: int, future, t0: float, close_after: bool, trace=None
    ) -> None:
        if not self.closed:
            response = self.state.app.finish_recommend(future, t0, trace=trace)
            self._stage(seq, response, close_after)
        self.state.leave()
        if not self.closed:
            self._process_buffer()  # pipeline slots freed — keep parsing
            self._update_read_flow()

    def _finish_handled(self, seq: int, task, close_after: bool) -> None:
        """Completion for the batcherless off-loop ``app.handle`` call."""
        if not self.closed:
            try:
                # kmls-verify: allow[loopblock] — this callback only runs
                # via call_soon_threadsafe AFTER the engine-pool task
                # completed, so result() returns immediately
                response = task.result()
            except Exception:
                logger.exception("engine-pool request failed")
                self.state.app.metrics.record_error()
                response = (
                    500, {"Content-Type": "application/json"},
                    b'{"detail": "Internal Server Error"}',
                )
            self._stage(seq, response, close_after)
        self.state.leave()
        if not self.closed:
            self._process_buffer()
            self._update_read_flow()

    # ---------- response writing ----------

    def _stage(self, seq: int, response, close_after: bool) -> None:
        """Stage response ``seq``; flush the contiguous ready prefix as a
        single write (responses must leave in request order)."""
        if self.closed or self.transport is None:
            return
        self._staged[seq] = (response, close_after)
        if seq != self._next_write:
            return
        chunks: list[bytes] = []
        closing = False
        while self._next_write in self._staged:
            response, close_after = self._staged.pop(self._next_write)
            self._next_write += 1
            closing = close_after or self.state.draining
            chunks.append(self._encode(response, closing))
            if closing:
                break
        self.transport.write(b"".join(chunks))
        if closing:
            self.transport.close()
            self.closed = True

    def _encode(self, response, closing: bool) -> bytes:
        status, headers, payload = response
        reason = _REASONS.get(status, "OK")
        parts = [f"HTTP/1.1 {status} {reason}\r\nContent-Length: {len(payload)}\r\n"]
        for key, value in headers.items():
            parts.append(f"{key}: {value}\r\n")
        if closing:
            # during a SIGTERM drain keep-alive clients must re-connect
            # elsewhere — k8s endpoint removal only diverts NEW connections
            parts.append("Connection: close\r\n")
        parts.append("\r\n")
        return "".join(parts).encode("latin1") + payload


async def run_async(app: RecommendApp, port: int, ready=None) -> int:
    """Bind + serve until SIGTERM/SIGINT, then drain; → exit code.
    ``ready(port)`` is called once the socket is bound (tests use it)."""
    import os
    import signal

    loop = asyncio.get_running_loop()
    if app.batcher is None and app.cfg.batch_window_ms > 0:
        # the loop-native batcher (built here, where the loop exists):
        # admission/collection/resolution on the loop, compute in one
        # executor hop, one loop wakeup per batch
        from .batcher import AsyncMicroBatcher

        cfg = app.cfg
        app.batcher = AsyncMicroBatcher(
            app.engine, max_size=cfg.batch_max_size,
            window_ms=cfg.batch_window_ms,
            max_inflight=cfg.batch_max_inflight,
            adaptive=cfg.batch_adaptive_window,
            window_min_ms=cfg.batch_window_min_ms,
            shed_queue_budget_ms=cfg.shed_queue_budget_ms,
            shed_retry_after_s=cfg.shed_retry_after_s,
            shed_soft_ratio=cfg.shed_soft_ratio,
            shed_hard_ratio=cfg.shed_hard_ratio,
            shed_retry_jitter=cfg.shed_retry_jitter,
            eject_threshold=cfg.replica_eject_threshold,
            probe_interval_s=cfg.replica_probe_interval_s,
            redispatch_max=cfg.redispatch_max_retries,
            metrics=app.metrics,
            lag_monitor=app.loop_lag,
            forecaster=getattr(app, "forecaster", None),
        )
    if app.loop_lag is not None:
        # arm the drift tick on THIS loop: timer-due minus timer-ran is
        # the time something blocked the loop (kmls_loop_lag_ms at
        # /metrics, and the admission ladder's runtime-health term —
        # closing the PR 8 inline-path blind spot)
        app.loop_lag.start_on_loop(loop)
    state = _ServerState(app)
    server = await loop.create_server(
        lambda: _Conn(state), "0.0.0.0", port, backlog=256,
    )
    bound_port = server.sockets[0].getsockname()[1]
    logger.info(
        "serving on 0.0.0.0:%d (version %s, async)", bound_port, app.cfg.version
    )
    if ready is not None:
        ready(bound_port)

    stop = asyncio.Event()

    def _drain() -> None:
        logger.info("SIGTERM: draining in-flight requests, then exiting")
        state.draining = True
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, _drain)
        except (NotImplementedError, RuntimeError):
            pass  # non-main thread / exotic platform

    async with server:
        await stop.wait()
        # listener closes NOW: racing connects get an instant refusal
        server.close()
        await server.wait_closed()
        settle_s = float(os.getenv("KMLS_DRAIN_SETTLE_S") or 2.0)
        # floor before the zero-exit (threaded-transport parity): a
        # keep-alive client that raced the signal may still be writing its
        # request — give it a beat to land and be answered with
        # Connection: close before the idle check can end the settle
        await asyncio.sleep(min(0.5, settle_s))
        try:
            await asyncio.wait_for(state.idle.wait(), timeout=settle_s)
        except asyncio.TimeoutError:
            logger.warning(
                "drain settle expired after %.1fs with %d requests still "
                "in flight (raise KMLS_DRAIN_SETTLE_S to match "
                "terminationGracePeriodSeconds)", settle_s, state.inflight,
            )
    return 0
