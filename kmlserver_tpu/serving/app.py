"""The REST serving app — the reference's FastAPI surface rebuilt on the
stdlib (this image ships no web framework), same routes, same schemas:

- ``POST /api/recommend/`` (reference: rest_api/app/main.py:176-187):
  body ``{"songs": [...]}`` → ``{"songs": [...], "model_date": <token>,
  "version": <VERSION>}``; empty song list → 400; malformed body → 422
  (FastAPI's validation status).
- ``GET /`` (reference: :190-203): HTML test client with a seed sample.
- ``GET /test`` (reference: :150-153): 307 redirect to the docs.
- ``GET /docs`` + ``GET /openapi.json``: interactive-docs equivalent with
  the reference's three canned request examples (:158-174) — rendered
  without external CDN assets (this environment is egress-free).
- ``GET /healthz`` / ``GET /readyz``: liveness + fail-soft readiness — the
  fix for the reference's documented crash-loop-on-empty-PVC (its report
  risk #2; SURVEY.md §5): the pod comes up, readiness holds traffic until
  the first artifacts land.
- ``GET /metrics``: Prometheus text (absent in the reference; SURVEY.md §5).

The app core is transport-independent (``handle()`` maps a request tuple to
a response tuple) with a thin ``ThreadingHTTPServer`` adapter — testable
in-process, multi-threaded under load, no framework dependency.
"""

from __future__ import annotations

import json
import logging
import math
import os
import random
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import faults
from ..config import ServingConfig
from ..io import iohealth
from ..observability import LoopLagMonitor, SloTracker, SpanRecorder
from .batcher import (
    DeadlineExceeded,
    NoHealthyReplicas,
    Overloaded,
    OverloadDegraded,
)
from .cache import RecommendCache
from .engine import RecommendEngine
from .mesh import MeshShardUnavailable
from .metrics import ServingMetrics

logger = logging.getLogger("kmlserver_tpu.serving")

_TEMPLATE_PATH = os.path.join(os.path.dirname(__file__), "templates", "client.html")

# The reference documents three canned request examples in its OpenAPI
# metadata (rest_api/app/main.py:158-174): typical seeds, uncommon seeds,
# and seeds absent from the rules (exercising the static fallback).
CANNED_EXAMPLES = {
    "normal": {
        "summary": "Typical seed songs",
        "value": {"songs": ["Yesterday", "Bohemian Rhapsody"]},
    },
    "uncommon": {
        "summary": "Uncommon seed songs (sparse rules)",
        "value": {"songs": ["Some Deep Cut B-Side"]},
    },
    "absent": {
        "summary": "Songs absent from the rules (static fallback)",
        "value": {"songs": ["Definitely Not A Real Song 123"]},
    },
}

Response = tuple[int, dict[str, str], bytes]


def is_loopback_host(client_host: str | None) -> bool:
    """THE loopback guard (ISSUE 12 satellite — one copy, four
    endpoints: ``/metrics/reset``, ``/debug/traces``, ``/debug/slo``,
    ``/debug/profile``). ``None`` is a direct in-process call (tests,
    embedding harnesses) — inherently local. A dual-stack server reports
    IPv4 loopback in IPv6-mapped form (``::ffff:127.0.0.1``): normalize
    before the check (ADVICE r5 #3)."""
    if client_host is None:
        return True
    host = client_host.removeprefix("::ffff:")
    return host in ("127.0.0.1", "::1")


def _json_response(status: int, obj) -> Response:
    body = json.dumps(obj).encode("utf-8")
    return status, {"Content-Type": "application/json"}, body


def _html_response(status: int, html: str) -> Response:
    return status, {"Content-Type": "text/html; charset=utf-8"}, html.encode("utf-8")


class RecommendApp:
    """Transport-independent app core."""

    # class-level defaults so hand-assembled test apps (``__new__`` +
    # attribute injection, no ``__init__``) keep working as the surface
    # grows — the affinity/routing layer is default-off anyway
    ring = None
    _ring_self = ""
    fleet_routing = False
    affinity_local_total = 0
    affinity_remote_total = 0
    misrouted_total = 0
    slo = None
    _profile_thread = None
    _profile_lock = threading.Lock()
    # predictive serving (ISSUE 17): default-off — a hand-assembled app
    # without __init__ behaves exactly reactively
    forecaster = None
    forecast_prefetch_total = 0
    # gray-failure spine (ISSUE 18): requests whose forwarded
    # X-KMLS-Deadline-Budget arrived already spent (wasted work a
    # downstream hop sheds, distinct from slow-compute "deadline"
    # degrades), and this replica's sorted-fleet index for the
    # fleet.peer stall fault site (None = fleet tier unarmed)
    deadline_expired_total = 0
    _fleet_index = None

    def __init__(
        self, cfg: ServingConfig, engine: RecommendEngine | None = None,
        *, defer_batcher: bool = False,
    ):
        self.cfg = cfg
        self.engine = engine or RecommendEngine(cfg)
        self.metrics = ServingMetrics()
        self.batcher = None
        # per-request span tracing (ISSUE 9): disabled by default
        # (KMLS_TRACE_SAMPLE=0 → recorder.enabled False, and every call
        # site checks that before allocating anything)
        self.recorder = SpanRecorder(
            sample=cfg.trace_sample,
            capacity=cfg.trace_buffer,
            slow_n=cfg.trace_slow_n,
        )
        # event-loop/scheduler stall collector: constructed here (the
        # robustness exposition reads it) but DRIVEN by the transports —
        # aioserver arms the loop drift tick, the threaded entrypoint a
        # sleep-drift thread — so in-process test apps spawn nothing
        self.loop_lag = (
            LoopLagMonitor(half_life_s=cfg.loop_lag_half_life_s)
            if cfg.loop_lag_half_life_s > 0
            else None
        )
        # SLO burn rates (ISSUE 12): multi-window budget consumption
        # computed lazily from the metrics counters/histograms whenever
        # /metrics or /debug/slo reads it — nothing on the request path
        self.slo = SloTracker(
            self.metrics,
            p99_target_ms=cfg.slo_p99_ms,
            error_budget=cfg.slo_error_budget,
            degrade_budget=cfg.slo_degrade_budget,
            fast_window_s=cfg.slo_fast_window_s,
            slow_window_s=cfg.slo_slow_window_s,
        )
        # one on-demand profiler capture at a time (/debug/profile —
        # utils/profiling.trace_session on a background thread; the lock
        # serializes check-and-start across handler threads)
        self._profile_thread = None
        self._profile_lock = threading.Lock()
        # epoch-keyed answer cache in front of the batcher (serving/cache
        # .py): a bundle hot swap invalidates it wholesale because the
        # engine's epoch is the key prefix — no flush coordination needed
        self.cache = (
            RecommendCache(cfg.cache_max_entries)
            if cfg.cache_enabled and cfg.cache_max_entries > 0
            else None
        )
        # continuous freshness (ISSUE 10): when the engine applies a delta
        # bundle in place (no epoch bump), only the keys whose seeds
        # intersect the delta's touched vocab may go stale — invalidate
        # exactly those instead of the wholesale epoch flush. The engine
        # notifies AFTER the patched bundle reference is live (the same
        # ordering contract the epoch bump rides), and `wholesale` applies
        # already invalidated via the epoch bump. getattr: engine test
        # doubles predating the delta path stay constructible.
        listeners = getattr(self.engine, "delta_listeners", None)
        if listeners is not None:
            listeners.append(self._on_delta_applied)
        # fleet cache tier (freshness/ring.py). Two arming levels over
        # ONE ring implementation — the same RendezvousRing the client
        # router and simulate_fleet use, so measurement, simulation and
        # routing can never disagree on an owner:
        #   KMLS_CACHE_AFFINITY=1        — measurement only (PR 10): count
        #       what fraction of traffic a router would keep local;
        #   KMLS_FLEET_PEERS non-empty   — owner-aware serving (ISSUE 15):
        #       the routing tier is live at the client/ingress, so a
        #       request this replica does not own is routing DRIFT —
        #       answer it locally (degrade gracefully, never fail), stamp
        #       X-KMLS-Cache-Owner, and count non-owned misses as
        #       kmls_cache_misrouted_total. The affinity counters keep
        #       running either way (local fraction ≈ routing health).
        self.ring = None
        self._ring_self = ""
        self.fleet_routing = False
        self.affinity_local_total = 0
        self.affinity_remote_total = 0
        self.misrouted_total = 0
        fleet_peers = [
            p.strip()
            for p in (getattr(cfg, "fleet_peers", "") or "").split(",")
            if p.strip()
        ]
        if fleet_peers or cfg.cache_affinity:
            import socket as socket_mod

            from ..freshness.ring import RendezvousRing

            if fleet_peers:
                me = (
                    getattr(cfg, "fleet_self", "")
                    or socket_mod.gethostname()
                )
                peers = fleet_peers
                self.fleet_routing = True
                if me not in peers:
                    # a SELF missing from PEERS means this replica would
                    # route ownership on an (N+1)-peer ring no client or
                    # sibling uses — the misrouted metric would measure
                    # the misconfig's noise, not routing drift. Keep
                    # serving (degrade, never fail) but say it loudly:
                    # this is the scaled-without-updating-PEERS drift the
                    # StatefulSet recipe warns about.
                    logger.error(
                        "KMLS_FLEET_SELF %r is not in KMLS_FLEET_PEERS "
                        "%r — this replica's ownership ring now differs "
                        "from the fleet's; kmls_cache_misrouted_total "
                        "will measure the misconfiguration, not routing "
                        "drift. Fix the peer list (it must track the "
                        "replica set exactly).", me, peers,
                    )
            else:
                me = cfg.cache_affinity_self or socket_mod.gethostname()
                peers = [
                    p.strip()
                    for p in (cfg.cache_affinity_peers or "").split(",")
                    if p.strip()
                ]
            if me not in peers:
                peers.append(me)
            self.ring = RendezvousRing(peers)
            self._ring_self = me
            if self.fleet_routing:
                # fleet.peer fault addressing (ISSUE 18): the stall site
                # keys replicas by sorted-peer index — stable across the
                # fleet regardless of each replica's KMLS_FLEET_PEERS
                # ordering, so a chaos harness can aim at exactly one
                self._fleet_index = sorted(peers).index(me)
        self.deadline_expired_total = 0
        # predictive serving (ISSUE 17): with KMLS_FORECAST=0 (default)
        # the hook stays None and every touchpoint — batcher submit,
        # utilization, post-delta pre-fetch — is one is-None check; the
        # forecast module's observation counter proves the zero cost,
        # compile-counter style (KMLS_COSTMODEL's pattern).
        self.forecaster = None
        self.forecast_prefetch_total = 0
        if getattr(cfg, "forecast_enabled", False):
            from .forecast import TrafficForecaster

            self.forecaster = TrafficForecaster(
                horizon_s=cfg.forecast_horizon_s,
                window_s=cfg.forecast_window_s,
                alpha=cfg.forecast_alpha,
                util_cap=cfg.forecast_util_cap,
                ramp_ratio=cfg.forecast_ramp_ratio,
                hot_top_n=cfg.forecast_prefetch_top_n,
            )
        # defer_batcher: the asyncio transport installs its loop-native
        # AsyncMicroBatcher instead — don't spawn the threaded pipeline
        if cfg.batch_window_ms > 0 and not defer_batcher:
            from .batcher import MicroBatcher

            self.batcher = MicroBatcher(
                self.engine, max_size=cfg.batch_max_size,
                window_ms=cfg.batch_window_ms,
                max_inflight=cfg.batch_max_inflight,
                adaptive=cfg.batch_adaptive_window,
                window_min_ms=cfg.batch_window_min_ms,
                shed_queue_budget_ms=cfg.shed_queue_budget_ms,
                shed_retry_after_s=cfg.shed_retry_after_s,
                shed_soft_ratio=cfg.shed_soft_ratio,
                shed_hard_ratio=cfg.shed_hard_ratio,
                shed_retry_jitter=cfg.shed_retry_jitter,
                eject_threshold=cfg.replica_eject_threshold,
                probe_interval_s=cfg.replica_probe_interval_s,
                redispatch_max=cfg.redispatch_max_retries,
                metrics=self.metrics,
                lag_monitor=self.loop_lag,
                forecaster=self.forecaster,
            )
        # template/static roots honor APP_PATH_FROM_ROOT like the reference
        # (rest_api/app/main.py:44-48 resolves its template/static dirs from
        # it; the static mount is :138): when that path carries
        # templates/static directories they take precedence — a deployment
        # can re-skin the client without rebuilding the image — else the
        # package's bundled copies serve.
        pkg_dir = os.path.dirname(__file__)
        root = cfg.app_path_from_root or ""
        template_path = _TEMPLATE_PATH
        self.static_dir = os.path.abspath(os.path.join(pkg_dir, "static"))
        if root:  # empty root must not probe CWD-relative paths
            custom_template = os.path.join(root, "templates", "client.html")
            if os.path.isfile(custom_template):
                template_path = custom_template
            custom_static = os.path.join(root, "static")
            if os.path.isdir(custom_static):
                self.static_dir = os.path.abspath(custom_static)
        with open(template_path, "r", encoding="utf-8") as fh:
            self._template = fh.read()

    # ---------- routing ----------

    def handle(
        self, method: str, path: str, body: bytes | None,
        client_host: str | None = None,
        trace_header: str | None = None,
        budget_header: str | None = None,
        fire_fleet_fault: bool = True,
    ) -> Response:
        path, _, query = path.partition("?")
        if method == "POST" and path in ("/api/recommend/", "/api/recommend"):
            return self._post_recommend(
                body, trace_header, budget_header,
                fire_fleet_fault=fire_fleet_fault,
            )
        if method == "POST" and path == "/metrics/reset":
            # measurement-harness hook: windows the latency percentiles
            # to one replay run (VERDICT r4 #7). Loopback-only via the
            # shared guard (is_loopback_host — one copy for all four
            # guarded endpoints).
            if not is_loopback_host(client_host):
                return _json_response(403, {"detail": "localhost only"})
            discarded = self.metrics.reset_latency()
            return _json_response(
                200, {"status": "reset", "discarded": discarded}
            )
        if method == "GET":
            if path == "/":
                return self._get_client()
            if path == "/test":
                # reference: /test deep-links into the interactive docs
                return 307, {"Location": "/docs#post-api-recommend"}, b""
            if path == "/docs":
                return self._get_docs()
            if path == "/openapi.json":
                return _json_response(200, self._openapi())
            if path == "/healthz":
                return _json_response(200, {"status": "alive"})
            if path == "/readyz":
                if self.engine.finished_loading:
                    # degraded = ready-but-flagged (HTTP 200): the pod
                    # keeps taking traffic — it still answers every
                    # request, some from the last-good bundle or the
                    # fallback — so a bad artifact on the shared PVC can
                    # never readiness-fail ALL replicas at once. A 503
                    # here would restart-loop the whole fleet over data
                    # no restart can fix.
                    ages = {
                        name: round(age, 3)
                        for name, age in self._artifact_ages().items()
                    }
                    reasons = self.degraded_reasons()
                    if reasons:
                        return _json_response(
                            200, {
                                "status": "degraded", "reasons": reasons,
                                "artifact_age_seconds": ages,
                            }
                        )
                    return _json_response(
                        200,
                        {"status": "ready", "artifact_age_seconds": ages},
                    )
                return _json_response(
                    503, {"status": "awaiting first artifacts"}
                )
            if path == "/debug/traces":
                # retained traces, JSON: the per-request WHY behind a
                # /metrics percentile (tail-based retention — see
                # observability/trace.py). Bounded payload: the ring caps
                # at KMLS_TRACE_BUFFER entries. Loopback-only, exactly
                # like /metrics/reset above: retained traces carry request
                # payloads (seed songs in span attrs and shed/degraded
                # bodies) and must not be fleet-scrapeable by default —
                # the tracejoin tooling runs next to the pod it debugs.
                if not is_loopback_host(client_host):
                    return _json_response(403, {"detail": "localhost only"})
                return _json_response(200, self.recorder.debug_payload())
            if path == "/debug/slo":
                # burn-rate detail (ISSUE 12): targets, windows, the
                # cumulative inputs, fast+slow burn per SLO. Loopback-
                # only like its /debug siblings — same policy, same
                # shared guard (fleet scraping belongs to /metrics,
                # which carries the kmls_slo_burn_rate gauges).
                if not is_loopback_host(client_host):
                    return _json_response(403, {"detail": "localhost only"})
                if self.slo is None:
                    return _json_response(
                        404, {"detail": "slo tracker not configured"}
                    )
                return _json_response(200, self.slo.debug_payload())
            if path == "/debug/profile":
                if not is_loopback_host(client_host):
                    return _json_response(403, {"detail": "localhost only"})
                return self._debug_profile(query)
            if path == "/metrics":
                # ONE age snapshot per scrape: the age gauges and the
                # stale flags must describe the same instant, and the
                # underlying os.stat pass must not run three times
                ages = self._artifact_ages()
                text = self.metrics.render(
                    self.engine.reload_counter, self.engine.finished_loading,
                    cache=self.cache,
                    dispatch_counts=getattr(
                        self.engine, "dispatch_counts", None
                    ),
                    robustness=self._robustness_state(),
                    shard_counts=getattr(
                        self.engine, "shard_dispatch_counts", None
                    ),
                    cost=getattr(self.engine, "cost_model", None),
                    slo=self.slo,
                    artifact_ages=ages,
                    artifact_stale=self._artifact_stale_flags(ages),
                    mesh_shards=self._mesh_shard_states(),
                    io=iohealth.MONITOR.snapshot(),
                )
                return 200, {"Content-Type": "text/plain; version=0.0.4"}, text.encode()
            if path.startswith("/static/"):
                return self._get_static(path[len("/static/"):])
        return _json_response(404, {"detail": "Not Found"})

    def _robustness_state(self) -> dict:
        """Engine/batcher recovery-state snapshot for /metrics (names
        ending in _total render as counters, the rest as gauges)."""
        state = {
            "artifact_quarantines_total": getattr(
                self.engine, "artifact_quarantines", 0
            ),
            "reload_failures_total": getattr(
                self.engine, "reload_failures", 0
            ),
            "reload_consecutive_failures": getattr(
                self.engine, "consecutive_reload_failures", 0
            ),
            # second model family: is the hybrid merge live, and how many
            # embedding-artifact loads degraded to rules-only
            "embedding_active": int(
                getattr(self.engine, "embedding_active", False)
            ),
            "embedding_load_failures_total": getattr(
                self.engine, "embedding_load_failures", 0
            ),
            # model layout: how many vocab shards the published bundle
            # spans (1 = replicated — a dashboard can alert on a fleet
            # unexpectedly flipping layout after a publication)
            "model_shards": getattr(self.engine, "n_shards", 1),
            # continuous freshness (ISSUE 10): delta bundles applied in
            # place vs rejected (torn/wrong-base/injected), the chain
            # position currently serving, and the age of the newest
            # APPLIED generation — the freshness-lag number the delta
            # path exists to shrink
            "delta_applied_total": getattr(
                self.engine, "delta_applied_total", 0
            ),
            "delta_rejected_total": getattr(
                self.engine, "delta_rejected_total", 0
            ),
            "delta_seq": getattr(self.engine, "delta_seq", 0),
            # quality loop (ISSUE 14): the published chain length (the
            # compaction trigger's observable) and the EFFECTIVE hybrid
            # blend weight (the measured optimum under
            # KMLS_HYBRID_BLEND_WEIGHT=measured, else the knob)
            "delta_chain_length": getattr(
                self.engine, "delta_chain_length", 0
            ),
            "hybrid_blend_weight": round(
                getattr(
                    self.engine, "blend_weight",
                    getattr(self.cfg, "hybrid_blend_weight", 0.5),
                ), 4
            ),
            "freshness_lag_seconds": round(
                getattr(self.engine, "freshness_lag_s", lambda: 0.0)(), 3
            ),
            # fleet cache affinity: what fraction of traffic a rendezvous
            # router would keep on this replica (0/0 with the layer off)
            "cache_affinity_local_total": self.affinity_local_total,
            "cache_affinity_remote_total": self.affinity_remote_total,
            # fleet cache routing (ISSUE 15): non-owned MISSES this
            # replica answered locally — routing drift at the ingress/
            # client (0 while routing is healthy or the tier is off) —
            # and the configured routing-ring size (0 = tier unarmed)
            "cache_misrouted_total": self.misrouted_total,
            "fleet_peers": (
                len(self.ring.peers)
                if (self.fleet_routing and self.ring is not None)
                else 0
            ),
        }
        ejected_fn = getattr(self.batcher, "ejected_replicas", None)
        state["replicas_ejected"] = (
            len(ejected_fn()) if callable(ejected_fn) else 0
        )
        # the autoscaling signal (ISSUE 8): kmls_utilization is what
        # kubernetes/hpa.yaml scales the fleet on — max of pipeline
        # occupancy and admission queue pressure, 1.0 = at capacity,
        # plus (forecaster armed) the bounded predictive lead term.
        # Always present (0.0 without a batcher) so the HPA's metric
        # query never comes back empty on an idle pod.
        parts_fn = getattr(self.batcher, "utilization_parts", None)
        if callable(parts_fn):
            reactive, led = parts_fn()
        else:
            util_fn = getattr(self.batcher, "utilization", None)
            reactive = led = util_fn() if callable(util_fn) else 0.0
        state["utilization"] = round(led, 4)
        if self.forecaster is not None:
            # predictive serving (ISSUE 17): the forecast's ADDED lead
            # over the reactive signal (0 at steady state — dashboards
            # see how much of kmls_utilization is prediction), the
            # rate/prediction/ratio snapshot, the zero-cost proof
            # counter, and the two actuator counters
            snap = self.forecaster.snapshot()
            state["utilization_forecast"] = round(max(0.0, led - reactive), 4)
            state["forecast_rate"] = round(snap["rate"], 3)
            state["forecast_predicted_rate"] = round(
                snap["predicted_rate"], 3
            )
            state["forecast_ratio"] = round(snap["ratio"], 4)
            state["forecast_observations_total"] = snap["observations"]
            state["forecast_prefetch_total"] = self.forecast_prefetch_total
            state["forecast_prewarm_total"] = getattr(
                self.batcher, "prewarm_total", 0
            )
        # overload-degrade admissions (the ladder rung before any 429)
        state["admission_degrade_total"] = getattr(
            self.batcher, "degrade_total", 0
        )
        # runtime health (ISSUE 9): the decayed loop/scheduler stall
        # estimate the admission ladder also folds into pressure — 0.0
        # with the collector disabled so the series always exists
        state["loop_lag_ms"] = (
            round(self.loop_lag.lag_s() * 1e3, 3)
            if self.loop_lag is not None
            else 0.0
        )
        # gray-failure spine (ISSUE 18): deadline propagation + mesh
        # hedging observables. All 0 with KMLS_HEDGE=0 / no forwarded
        # budgets — the hedge counters double as the zero-cost proof
        # (pinned by test, costmodel-counter style). expired_on_arrival
        # lives on the mesh WORKER (budget-shed frames); the hedge
        # outcome counters + slow-peer ladder on the COORDINATOR.
        state["deadline_expired_total"] = self.deadline_expired_total
        mesh = getattr(self.engine, "mesh_coordinator", None)
        worker = getattr(self.engine, "mesh_worker", None)
        state["hedge_wins_total"] = getattr(mesh, "hedge_wins", 0)
        state["hedge_losses_total"] = getattr(mesh, "hedge_losses", 0)
        state["hedge_cancelled_total"] = getattr(mesh, "hedge_cancelled", 0)
        state["peer_slow_ejections_total"] = getattr(
            mesh, "slow_ejections", 0
        )
        state["peer_slow_readmissions_total"] = getattr(
            mesh, "slow_readmissions", 0
        )
        slow_fn = getattr(mesh, "slow_ranks", None)
        state["peer_slow"] = len(slow_fn()) if callable(slow_fn) else 0
        state["mesh_straggler_degraded_total"] = getattr(
            self.engine, "mesh_straggler_degraded", 0
        )
        state["mesh_expired_on_arrival_total"] = getattr(
            worker, "expired_on_arrival", 0
        )
        # span-tracing bookkeeping: began is the zero-cost proof counter
        # (must stay 0 while KMLS_TRACE_SAMPLE=0)
        state["traces_began_total"] = self.recorder.began
        state["traces_retained_total"] = self.recorder.retained_total
        state["trace_buffer_entries"] = (
            self.recorder.retained() if self.recorder.enabled else 0
        )
        return state

    def _artifact_ages(self) -> dict:
        """Per-artifact freshness ages from the engine (empty before the
        first load, or with an engine test double predating the API)."""
        ages_fn = getattr(self.engine, "artifact_ages", None)
        return ages_fn() if callable(ages_fn) else {}

    def _stale_artifacts(
        self, ages: dict | None = None
    ) -> list[tuple[str, float]]:
        """Artifacts over the KMLS_ARTIFACT_MAX_AGE_S bound, as sorted
        (name, age) pairs — empty with the bound disabled (0). ``ages``
        lets a caller that already snapshotted the age dict reuse it
        (one os.stat pass per scrape, and age + staleness always come
        from the SAME snapshot)."""
        max_age = getattr(self.cfg, "artifact_max_age_s", 0.0)
        if max_age <= 0:
            return []
        if ages is None:
            ages = self._artifact_ages()
        return sorted(
            (name, age) for name, age in ages.items() if age > max_age
        )

    def _artifact_stale_flags(self, ages: dict) -> dict:
        """artifact → 0/1 staleness flags for the kmls_artifact_stale
        gauge, derived from the SAME age snapshot the age gauges render
        (all 0 with the bound disabled — the series still exists
        wherever ages do, so dashboards can alert on a flip)."""
        stale = {name for name, _age in self._stale_artifacts(ages)}
        return {name: int(name in stale) for name in ages}

    def _debug_profile(self, query: str) -> Response:
        """``GET /debug/profile?seconds=N`` (ISSUE 12): capture a
        ``jax.profiler`` trace of the live server for N seconds through
        ``utils/profiling.trace_session`` — the same opt-in policy as
        offline profiling: ``KMLS_PROFILE_DIR`` must be set or the
        capture is refused (409), so production serving can never be
        profiled by accident. The capture runs on a background thread
        (the async transport handles this route ON the loop — blocking
        N seconds here would freeze every connection) and the response
        returns immediately with the dump directory; one capture at a
        time."""
        from ..utils import profiling

        target = profiling.profile_dir()
        if target is None:
            return _json_response(
                409,
                {"detail": "profiling disabled: set KMLS_PROFILE_DIR "
                           "to enable /debug/profile captures"},
            )
        try:
            params = dict(
                pair.split("=", 1) for pair in query.split("&") if "=" in pair
            )
            seconds = float(params.get("seconds", "5"))
        except ValueError:
            seconds = float("nan")
        if not math.isfinite(seconds):
            # nan/inf slide through a min/max clamp (comparisons are
            # false), then kill the capture thread AFTER the 202 — reject
            # up front instead
            return _json_response(
                422, {"detail": "seconds must be a finite number"}
            )
        seconds = min(max(seconds, 0.05), 120.0)
        label = f"serve-capture-{int(time.time())}"
        # check-and-start under a lock: jax allows ONE active profiler
        # session, so two racing requests must not both start a capture
        # (the loser's thread would die after its 202 already went out)
        with self._profile_lock:
            thread = self._profile_thread
            if thread is not None and thread.is_alive():
                return _json_response(
                    409, {"detail": "a profile capture is already running"}
                )
            self._profile_thread = profiling.start_capture(label, seconds)
        return _json_response(
            202,
            {
                "status": "capturing",
                "seconds": seconds,
                "label": label,
                "dir": os.path.join(target, label),
            },
        )

    _STATIC_TYPES = {
        ".css": "text/css; charset=utf-8",
        ".js": "text/javascript; charset=utf-8",
        ".html": "text/html; charset=utf-8",
        ".json": "application/json",
        ".svg": "image/svg+xml",
        ".png": "image/png",
        ".ico": "image/x-icon",
    }

    def _get_static(self, rel: str) -> Response:
        """Static assets under the resolved static root — the reference's
        ``/static`` mount (rest_api/app/main.py:138). Paths are confined to
        the root after symlink resolution, so neither ``..`` traversal nor
        a symlink planted inside an operator-supplied static dir can reach
        outside it (ADVICE r4 #4)."""
        full = os.path.realpath(os.path.join(self.static_dir, rel))
        root = os.path.realpath(self.static_dir)
        if not full.startswith(root + os.sep):
            return _json_response(404, {"detail": "Not Found"})
        try:
            # kmls-verify: allow[loopblock] — deliberate: static assets
            # are a handful of small local files (dashboard HTML/JS) on
            # the container image, not the PVC; a sub-ms read is cheaper
            # than an executor hop and the route is cold
            with open(full, "rb") as fh:
                data = fh.read()
        except (OSError, IsADirectoryError):
            return _json_response(404, {"detail": "Not Found"})
        ctype = self._STATIC_TYPES.get(
            os.path.splitext(full)[1].lower(), "application/octet-stream"
        )
        return 200, {"Content-Type": ctype}, data

    # ---------- endpoints ----------

    def _validate_recommend(
        self, body: bytes | None
    ) -> tuple[Response | None, list[str] | None]:
        """→ (error response, None) or (None, songs)."""
        try:
            payload = json.loads(body or b"")
        except json.JSONDecodeError:
            return _json_response(
                422, {"detail": [{"msg": "request body is not valid JSON"}]}
            ), None
        songs = payload.get("songs") if isinstance(payload, dict) else None
        if not isinstance(songs, list) or not all(isinstance(s, str) for s in songs):
            return _json_response(
                422,
                {"detail": [{"loc": ["body", "songs"],
                             "msg": "field 'songs' must be a list of strings"}]},
            ), None
        if not songs:
            # reference: empty request → 400 (rest_api/app/main.py:178-179)
            return _json_response(400, {"detail": "Request with no songs"}), None
        return None, songs

    # ---------- span tracing (ISSUE 9) ----------

    def _trace_begin(self, header: str | None):
        """→ a TraceContext for this request, or None. The one
        ``enabled`` check is the ENTIRE per-request cost with tracing
        disabled (KMLS_TRACE_SAMPLE=0): no context, no id generation,
        no allocation — the recorder's ``began`` counter proves it."""
        rec = self.recorder
        return rec.begin(header) if rec.enabled else None

    def _trace_finish(self, trace, status: str, headers: dict) -> None:
        """Close the trace (tail-based retention decides whether it is
        kept) and echo ``X-KMLS-Trace`` so a replay/bench client can join
        its client-side timing to the server-side span breakdown."""
        if trace is None:
            return
        self.recorder.finish(trace, status, time.perf_counter() - trace.t0)
        headers["X-KMLS-Trace"] = trace.trace_id

    # ---------- degradation (the fault-tolerance contract) ----------

    def _deadline_for(self, t0: float) -> float | None:
        """Per-request perf_counter deadline from the configured budget
        (KMLS_REQUEST_DEADLINE_MS), propagated cache → batcher → device.
        None = deadlines off."""
        budget_ms = self.cfg.request_deadline_ms
        return t0 + budget_ms / 1e3 if budget_ms > 0 else None

    def _effective_deadline(
        self, t0: float, budget_header: str | None
    ) -> tuple[float | None, float | None, bool]:
        """Cross-hop deadline propagation (ISSUE 18): the effective
        deadline is the TIGHTER of the local budget
        (KMLS_REQUEST_DEADLINE_MS) and the remaining milliseconds an
        upstream hop forwarded on ``X-KMLS-Deadline-Budget`` →
        ``(deadline, forwarded_budget_ms, expired)``. ``expired=True``
        means the budget arrived already spent: the caller answers the
        degraded fallback IMMEDIATELY — counting wasted work
        (kmls_deadline_expired_total), not slow compute. A malformed
        header is ignored (local budget only): deadline propagation
        must never turn a bad proxy into an outage."""
        deadline = self._deadline_for(t0)
        if not budget_header:
            return deadline, None, False
        try:
            budget_ms = float(budget_header)
        except (TypeError, ValueError):
            return deadline, None, False
        if not math.isfinite(budget_ms):
            return deadline, None, False
        if budget_ms <= 0.0:
            return deadline, budget_ms, True
        remote = t0 + budget_ms / 1e3
        if deadline is None or remote < deadline:
            deadline = remote
        return deadline, budget_ms, False

    @staticmethod
    def _degrade_reason(exc: Exception) -> str | None:
        """Exceptions that degrade to a fallback answer instead of an
        error status: deadline exhaustion, total replica loss, and the
        admission controller's degrade band (the ladder rung BEFORE any
        429 — overload costs answer quality first, availability never)."""
        if isinstance(exc, DeadlineExceeded):
            return "deadline"
        if isinstance(exc, NoHealthyReplicas):
            return "replica-loss"
        if isinstance(exc, OverloadDegraded):
            return "overload"
        return None

    def _stamp_owner(
        self, headers: dict, songs: list[str] | None, cached: bool
    ) -> None:
        """Owner-aware serving (ISSUE 15): with the fleet routing tier
        armed (KMLS_FLEET_PEERS), a request whose rendezvous owner is
        another replica is mis-routed traffic — it is still ANSWERED
        locally (mis-routes degrade gracefully, never fail), but the
        response stamps ``X-KMLS-Cache-Owner`` so the router/operator
        can see the drift, and a non-owned MISS (work the owner's cache
        already holds) counts ``kmls_cache_misrouted_total``. Cache hits
        are stamped but not counted: a hit did no duplicate device work.
        GIL-coalesced adds, same benign-race budget as the affinity
        counters. This is the ONE owner computation in routing mode —
        the affinity counters ride the same digest instead of paying a
        second one in _cache_lookup_or_lead (answered requests only;
        sheds/errors never reach a response builder, which is exactly
        the traffic the ownership fraction should describe)."""
        if not self.fleet_routing or self.ring is None or not songs:
            return
        from ..freshness.ring import seeds_key

        owner = self.ring.owner(seeds_key(songs))
        if owner == self._ring_self:
            self.affinity_local_total += 1
            return
        self.affinity_remote_total += 1
        # identities come from operator env config: strip CR/LF so a
        # malformed peer list can never smuggle a header line
        headers["X-KMLS-Cache-Owner"] = (
            owner.replace("\r", "").replace("\n", "")
        )
        if not cached:
            self.misrouted_total += 1

    def _degraded_response(
        self, t0: float, songs: list[str], reason: str, trace=None
    ) -> Response:
        """200 with the latency-budgeted popularity fallback and an
        ``X-KMLS-Degraded: <reason>`` header — the degradation contract:
        a slow device or a dead replica set costs answer QUALITY, never a
        5xx. The fallback itself runs under the tighter of the request
        deadline and its own budget (KMLS_FALLBACK_BUDGET_MS), so the
        degraded path can't compound the overrun."""
        budget = time.perf_counter() + self.cfg.fallback_budget_ms / 1e3
        deadline = self._deadline_for(t0)
        deadline = budget if deadline is None else min(deadline, budget)
        recs = self.engine.static_recommendation(songs, deadline=deadline)
        self.metrics.record_degraded(reason)
        self.metrics.record("fallback", time.perf_counter() - t0)
        status, headers, payload = _json_response(
            200,
            {
                "songs": recs,
                "model_date": self.engine.cache_value,
                "version": self.cfg.version,
            },
        )
        headers["X-KMLS-Degraded"] = reason
        self._stamp_owner(headers, songs, cached=False)
        if trace is not None:
            # the ladder decision rides a span attribute: "overload" IS
            # the admission controller's degrade rung; deadline/replica-
            # loss degradations carry their reason the same way
            trace.annotate("reason", reason)
            if reason == "overload":
                trace.annotate("admission", "degrade")
            self._trace_finish(trace, "degraded", headers)
        return status, headers, payload

    # ---------- pod-spanning serve mesh (ISSUE 16) ----------

    def _mesh_missing_shards(self, probe: bool = False) -> list[int]:
        """Missing gang ranks from the engine's mesh coordinator — empty
        when the serve mesh is off, the gang is whole, or the engine is a
        test double predating the API. ``probe=True`` makes the caller a
        re-form detector: the coordinator re-auditions dark peers (rate-
        limited to one probe per interval), so a restarted gang member is
        re-admitted by the very traffic that found it missing."""
        fn = getattr(self.engine, "mesh_missing_shards", None)
        if not callable(fn):
            return []
        return fn(probe=probe)

    def _mesh_shard_states(self) -> dict | None:
        """``{"serving": n, "missing": m}`` for the
        kmls_serve_mesh_shards gauge, or None with the mesh off — the
        series only exists on gang members, so a replicated pod never
        exports a phantom one-member gang."""
        gang = getattr(self.engine, "gang", None)
        if gang is None:
            return None
        missing = self._mesh_missing_shards()
        return {
            "serving": gang.size - len(missing), "missing": len(missing)
        }

    def _mesh_shard_response(
        self, t0: float, songs: list[str], rank: int, trace=None
    ) -> Response:
        """Answer policy when a vocab shard (a gang member) is dark.
        With the fleet routing tier armed this gang is NOT the last line
        of defense — 503 + ``X-KMLS-Mesh-Unavailable: <rank>`` tells the
        router which shard to blame and spills the key to the next ring
        peer (the replay client counts it ``mesh_unavailable``, never
        http_5xx; Retry-After paces re-dispatch against the re-admission
        probe). Standalone, the degradation contract holds: shard loss
        costs answer QUALITY (popularity fallback), never availability."""
        rank = int(rank)
        if self.fleet_routing:
            status, headers, payload = _json_response(
                503,
                {"detail": f"serve mesh degraded: vocab shard {rank} "
                           "unavailable"},
            )
            headers["X-KMLS-Mesh-Unavailable"] = str(rank)
            # PR 8's Retry-After contract (the 429 path below): RFC 9110
            # delay-seconds is a non-negative INTEGER, and a bounded
            # jitter (KMLS_SHED_RETRY_JITTER) de-synchronizes the retry
            # storm — the un-jittered constant here re-synchronized
            # every spilled client onto the same probe tick
            base = max(self.cfg.replica_probe_interval_s, 1.0)
            jitter = max(0.0, getattr(self.cfg, "shed_retry_jitter", 0.0))
            if jitter > 0.0:
                base = random.uniform(
                    base * (1.0 - jitter), base * (1.0 + jitter)
                )
            headers["Retry-After"] = str(math.ceil(max(base, 0.0)))
            self.metrics.record_degraded(f"mesh-shard-missing:{rank}")
            if trace is not None:
                trace.annotate("mesh_shard_missing", rank)
                self._trace_finish(trace, "mesh-unavailable", headers)
            return status, headers, payload
        return self._degraded_response(
            t0, songs, f"mesh-shard-missing:{rank}", trace=trace
        )

    def degraded_reasons(self) -> list[str]:
        """Why /readyz says "degraded" (empty = fully healthy): reloads
        failing while the last-good bundle keeps serving, and/or replicas
        currently ejected by the batcher's circuit breaker."""
        reasons: list[str] = []
        consec = getattr(self.engine, "consecutive_reload_failures", 0)
        if consec > 0:
            reasons.append(
                f"reload failing x{consec} (serving last-good bundle)"
            )
        if getattr(self.engine, "embedding_degraded", False):
            # a PUBLISHED embeddings.npz failed validation/parse: the
            # bundle serves rules-only — answered, but flagged so the
            # operator knows the second model family is dark
            reasons.append("embedding artifact unusable (serving rules-only)")
        # staleness bound (ISSUE 14): any served artifact older than
        # KMLS_ARTIFACT_MAX_AGE_S flags ready-but-degraded BY NAME — an
        # aging embeddings.npz becomes an operator signal before it
        # misleads. 0 (the default) keeps the age gauges purely
        # observational.
        stale = self._stale_artifacts()
        if stale:
            max_age = self.cfg.artifact_max_age_s
            reasons.append(
                "artifacts stale (> "
                f"{max_age:.0f}s): "
                + ", ".join(
                    f"{name} ({age:.0f}s)" for name, age in stale
                )
            )
        ejected_fn = getattr(self.batcher, "ejected_replicas", None)
        if callable(ejected_fn):
            ejected = ejected_fn()
            if ejected:
                reasons.append(f"replicas ejected: {ejected}")
        # pod-spanning serve mesh (ISSUE 16): a dark gang member means a
        # vocab slab is unservable — ready-but-degraded BY RANK, and
        # probe=True makes every /readyz scrape double as the re-form
        # detector (the kubelet's readiness polling re-admits a restarted
        # member even on an otherwise idle pod)
        for rank in self._mesh_missing_shards(probe=True):
            reasons.append(f"serve_mesh_shard_missing:{rank}")
        # storage gray-failure spine (ISSUE 19): the IO-health monitor
        # convicted the artifact plane as slow (latency EWMA past
        # KMLS_IO_SLOW_MS). Degraded, NOT unready — serving runs from
        # memory; a slow PVC must never knock a healthy replica out of
        # the load balancer.
        if iohealth.MONITOR.storage_slow():
            reasons.append("storage-slow")
        return reasons

    def _recommend_error_response(self, exc: Exception, trace=None) -> Response:
        if isinstance(exc, Overloaded):
            # visible backpressure, not an error: the queue projection says
            # this request would outwait the shed budget — tell the client
            # when to come back instead of letting it rot in the queue
            status, headers, payload = _json_response(
                429,
                {"detail": "overloaded: projected queue wait "
                           f"{exc.projected_wait_ms:.0f}ms exceeds budget"},
            )
            # RFC 9110 delay-seconds is a non-negative INTEGER — a decimal
            # here crashes urllib3's Retry.parse_retry_after (the requests
            # default). ceil keeps the batcher's sub-second jitter
            # (KMLS_SHED_RETRY_JITTER) meaningful: uniform base·(1 ± j)
            # ceils to a spread across adjacent whole seconds instead of
            # rounding every draw back to the same synchronized value
            headers["Retry-After"] = str(math.ceil(max(exc.retry_after_s, 0.0)))
            if trace is not None:
                trace.annotate("admission", "shed")
                trace.annotate("retry_after_s", round(exc.retry_after_s, 3))
                self._trace_finish(trace, "shed", headers)
            return status, headers, payload
        logger.error("recommendation failed", exc_info=exc)
        self.metrics.record_error()
        status, headers, payload = _json_response(
            500, {"detail": "Internal Server Error"}
        )
        if trace is not None:
            trace.annotate("error", type(exc).__name__)
            self._trace_finish(trace, "error", headers)
        return status, headers, payload

    def _recommend_result_response(
        self, t0: float, recs: list[str], source: str, cached: bool = False,
        trace=None, songs: list[str] | None = None,
    ) -> Response:
        # compose span: answer-available (the future just resolved — the
        # caller invokes this immediately after) → response bytes built
        t_compose = time.perf_counter() if trace is not None else 0.0
        self.metrics.record(source, time.perf_counter() - t0)
        status, headers, payload = _json_response(
            200,
            {
                "songs": recs,
                "model_date": self.engine.cache_value,
                "version": self.cfg.version,
            },
        )
        self._stamp_owner(headers, songs, cached=cached)
        if cached:
            # lets load harnesses (serving/replay.py) split cached vs
            # computed latency without guessing from timing
            headers["X-KMLS-Cache"] = "hit"
        # gray-failure spine (ISSUE 18): a "degraded:<reason>" source is
        # an ANSWERED-but-partial result (e.g. a mesh merge that dropped
        # a straggler slab) — same contract surface as the popularity
        # fallback: X-KMLS-Degraded + the degraded counter. The cache
        # layer independently refuses to store these (cache.put), so one
        # slow moment can't pin a partial answer past the gang recovering.
        degraded = source.startswith("degraded:")
        if degraded:
            reason = source.partition(":")[2] or source
            headers["X-KMLS-Degraded"] = reason
            self.metrics.record_degraded(reason)
        if trace is not None:
            trace.span(
                "compose", t_compose, time.perf_counter(),
                {"source": source},
            )
            if cached:
                trace.annotate("cached", True)
            if degraded:
                trace.annotate("reason", source.partition(":")[2] or source)
            self._trace_finish(trace, "ok", headers)
        return status, headers, payload

    def _on_delta_applied(self, touched: set, wholesale: bool) -> None:
        """Engine callback after a delta bundle swapped in: selectively
        invalidate the touched seed keys (wholesale applies bumped the
        epoch, which already invalidates every key for free), then —
        forecaster armed — re-materialize the predicted-hot sets the
        invalidation just cooled (actuator c)."""
        if self.cache is None or wholesale:
            return
        dropped = self.cache.invalidate_seeds(set(touched))
        logger.info(
            "delta applied: %d touched names, %d cache entries invalidated "
            "selectively", len(touched), dropped,
        )
        if self.forecaster is not None:
            names = set(touched)
            loop = getattr(self.batcher, "_loop", None)
            if loop is not None:
                # loop-native batcher: submit() is loop-confined, and this
                # callback runs on the engine's reload/delta thread — hop
                try:
                    loop.call_soon_threadsafe(self._forecast_prefetch, names)
                except RuntimeError:
                    pass  # loop already closed: a missed pre-fetch is fine
            else:
                self._forecast_prefetch(names)

    def _forecast_prefetch(self, touched: set) -> int:
        """Targeted cache pre-fetch (ISSUE 17, actuator c): for each
        predicted-hot seed set that (a) the delta just cooled (its seeds
        intersect ``touched``), (b) THIS replica owns on the rendezvous
        ring (owner only, never broadcast — no ring means every key is
        local), and (c) is not still cached, lead a normal singleflight
        batcher submission so the entry is warm before the next real
        request misses on it. Competing with live traffic is forbidden:
        the first admission-ladder rejection (Overloaded/degrade/
        no-replicas — or a loop-confinement error from a mis-threaded
        call) abandons the whole pass. → pre-fetch leads started."""
        f = self.forecaster
        if (
            f is None or self.cache is None or self.batcher is None
            or not hasattr(self.batcher, "submit")
        ):
            return 0
        from ..freshness.ring import seeds_key

        started = 0
        for seeds in f.hot_seed_sets(
            getattr(self.cfg, "forecast_prefetch_top_n", 8)
        ):
            if not any(s in touched for s in seeds):
                continue  # the delta didn't cool this set — still cached
            if self.ring is not None and not self.ring.owns(
                seeds_key(seeds), self._ring_self
            ):
                continue  # another replica's key: its owner pre-fetches it
            key = self._cache_key(seeds)
            if self.cache.contains(key):
                continue
            try:
                future, joined = self.cache.join_or_lead(
                    key, lambda s=seeds: self.batcher.submit(s)
                )
            except Exception:
                break  # overloaded or unhealthy: never compete with traffic
            if not joined:
                cache = self.cache
                future.add_done_callback(
                    lambda fut, k=key: cache.finish(k, fut)
                )
                started += 1
        self.forecast_prefetch_total += started
        return started

    def _cache_key(self, songs: list[str]) -> tuple:
        if self.cache is not None:
            return self.cache.make_key(
                self.engine.bundle_epoch, songs, self.cfg.max_seed_tracks
            )
        return RecommendCache.key(
            self.engine.bundle_epoch, songs, self.cfg.max_seed_tracks
        )

    def _cache_lookup_or_lead(
        self, songs: list[str], deadline: float | None = None, trace=None,
    ):
        """The ONE copy of the cache front half, shared by both
        transports → ``("hit", (songs, source))`` | ``("flight",
        future)`` | ``("off", None)``. A miss joins the in-flight
        singleflight future for this key or leads a new batcher
        submission (the leader's done-callback stores the answer);
        raises what ``batcher.submit`` raises (Overloaded and
        NoHealthyReplicas included). ``deadline`` rides into the batcher
        only when set — test doubles keep their bare ``submit(seeds)``
        signature. "off" covers: cache disabled, no batcher, or a batcher
        without ``submit`` (test doubles) — callers compute inline there."""
        if self.ring is not None and not self.fleet_routing:
            # affinity accounting (measurement mode) on the ONE path both
            # transports share: is THIS replica the rendezvous owner of
            # the request's cache key? (counters only — no routing;
            # GIL-coalesced adds, same benign-race budget as the
            # batcher's in-flight counts). In ROUTING mode _stamp_owner
            # drives these counters from its single owner computation
            # instead — one seeds sort + N digests per request, not two.
            from ..freshness.ring import seeds_key

            if self.ring.owner(seeds_key(songs)) == self._ring_self:
                self.affinity_local_total += 1
            else:
                self.affinity_remote_total += 1
        if (
            self.cache is None
            or self.batcher is None
            or not hasattr(self.batcher, "submit")
        ):
            return "off", None
        key = self._cache_key(songs)
        if trace is not None:
            t_cache = time.perf_counter()
            hit = self.cache.get(key)
            trace.span(
                "cache", t_cache, time.perf_counter(),
                {"hit": hit is not None},
            )
        else:
            hit = self.cache.get(key)
        if hit is not None:
            return "hit", hit
        if trace is not None:
            # traced requests always use the kwarg form (test doubles
            # with a bare submit(seeds) only run with tracing off)
            lead = lambda: self.batcher.submit(  # noqa: E731
                songs, deadline=deadline, trace=trace
            )
        elif deadline is not None:
            lead = lambda: self.batcher.submit(songs, deadline=deadline)  # noqa: E731
        else:
            lead = lambda: self.batcher.submit(songs)  # noqa: E731
        future, joined = self.cache.join_or_lead(key, lead)
        if joined and trace is not None:
            # a joiner shares the leader's batch slot: it gets no
            # queue/device spans of its own (it never dispatched)
            trace.annotate("singleflight", "joined")
        if not joined:
            cache = self.cache
            future.add_done_callback(lambda f: cache.finish(key, f))
        # the seeds travel WITH the future so the async transport can
        # build a per-request degraded fallback when it resolves to a
        # DeadlineExceeded/NoHealthyReplicas (finish_recommend has no
        # other path back to the request body); the singleflight shares
        # one future across IDENTICAL seed sets, so the attribute is
        # consistent for every joiner
        future._kmls_seeds = songs
        return "flight", future

    def recommend_direct(
        self, songs: list[str], trace=None, deadline: float | None = None,
    ) -> tuple[list[str], str, bool]:
        """Blocking cached recommend → ``(songs, source, cache_hit)``.
        Used by the threaded POST path and the in-process replay harness;
        raises (Overloaded, DeadlineExceeded, NoHealthyReplicas included)
        like the underlying batcher/engine. ``deadline`` lets a caller
        that already tightened the budget with a forwarded
        X-KMLS-Deadline-Budget pass it through; None computes the local
        one (the pre-ISSUE-18 behavior exactly)."""
        if deadline is None:
            deadline = self._deadline_for(time.perf_counter())
        state, payload = self._cache_lookup_or_lead(songs, deadline, trace)
        if state == "hit":
            return payload[0], payload[1], True
        if state == "flight":
            timeout = 30.0
            if deadline is not None:
                timeout = max(deadline - time.perf_counter(), 0.0)
            try:
                recs, source = payload.result(timeout=timeout)
            except FuturesTimeout:
                if deadline is not None:
                    raise DeadlineExceeded(
                        "request exceeded its deadline budget in flight"
                    ) from None
                raise
            return recs, source, False
        if self.batcher is not None:
            if trace is not None and hasattr(self.batcher, "submit"):
                recs, source = self.batcher.recommend(
                    songs, deadline=deadline, trace=trace
                )
            elif deadline is not None and hasattr(self.batcher, "submit"):
                recs, source = self.batcher.recommend(songs, deadline=deadline)
            else:
                recs, source = self.batcher.recommend(songs)
        else:
            recs, source = self.engine.recommend(songs)
        if self.cache is not None:
            self.cache.put(self._cache_key(songs), (recs, source))
        return recs, source, False

    def _post_recommend(
        self, body: bytes | None, trace_header: str | None = None,
        budget_header: str | None = None,
        fire_fleet_fault: bool = True,
    ) -> Response:
        t0 = time.perf_counter()
        # gray-failure chaos site (ISSUE 18): a deterministic stall on
        # ONE fleet replica, addressed by sorted-peer index — the
        # slowpeer bench's fleet-side victim. The asyncio transport
        # consumes this site itself (faults.take on the loop timer) and
        # passes fire_fleet_fault=False so a times=N budget is never
        # decremented twice for one request.
        if fire_fleet_fault:
            faults.fire("fleet.peer", replica=self._fleet_index)
        err, songs = self._validate_recommend(body)
        if err is not None:
            return err
        # trace begins AFTER validation: malformed bodies never allocate
        trace = self._trace_begin(trace_header)
        deadline, budget_ms, expired = self._effective_deadline(
            t0, budget_header
        )
        if budget_ms is not None and trace is not None:
            trace.annotate("deadline_budget_ms", round(budget_ms, 3))
        if expired:
            # the budget arrived spent: shed the compute, answer the
            # fallback — wasted-work, distinct from slow-compute
            self.deadline_expired_total += 1
            return self._degraded_response(
                t0, songs, "deadline-expired", trace=trace
            )
        # serve mesh (ISSUE 16): with a gang member known-dark, answer
        # the shard-loss policy BEFORE cache/batcher — a merged answer
        # missing one slab's candidates would be silently wrong, and
        # caching it would keep it wrong past the gang re-forming
        missing = self._mesh_missing_shards(probe=True)
        if missing:
            return self._mesh_shard_response(
                t0, songs, missing[0], trace=trace
            )
        try:
            recs, source, cached = self.recommend_direct(
                songs, trace=trace, deadline=deadline
            )
        except Exception as exc:
            if isinstance(exc, MeshShardUnavailable):
                # a gang member died mid-flight (after the pre-check)
                return self._mesh_shard_response(
                    t0, songs, exc.rank, trace=trace
                )
            reason = self._degrade_reason(exc)
            if reason is not None:
                # deadline exhausted or every replica ejected: answer
                # from the popularity fallback (X-KMLS-Degraded), not 5xx
                return self._degraded_response(t0, songs, reason, trace=trace)
            return self._recommend_error_response(exc, trace=trace)
        return self._recommend_result_response(
            t0, recs, source, cached=cached, trace=trace, songs=songs
        )

    # ---------- async-transport entry points ----------

    def submit_recommend(
        self, body: bytes | None, trace_header: str | None = None,
        budget_header: str | None = None,
    ):
        """Non-blocking twin of :meth:`_post_recommend` for the asyncio
        transport: → ``(response, None, t0, trace)`` when the answer is
        immediate (validation error, cache hit, shed, or the unbatched
        path), else ``(None, future, t0, trace)`` — resolve the future
        off-loop and build the reply with :meth:`finish_recommend`.
        ``trace`` rides the TUPLE, not the future: singleflight shares
        one future across joined connections, and each connection's trace
        is its own.

        Cache semantics mirror :meth:`recommend_direct`: hit → immediate
        response; miss → singleflight through the batcher, so concurrent
        identical misses on the event loop share ONE batch slot (asyncio
        futures take any number of done-callbacks, and ``result()`` is
        re-readable — every joined connection builds its own reply off the
        same future)."""
        t0 = time.perf_counter()
        # the fleet.peer chaos site is consumed by the TRANSPORT here,
        # not fired inline: aioserver._dispatch calls faults.take() and
        # schedules the stall on the loop timer, so an armed delay slows
        # each request without blocking every other one on the loop
        # (the threaded front end fires it in _post_recommend, where the
        # sleep costs only that handler thread)
        err, songs = self._validate_recommend(body)
        if err is not None:
            return err, None, t0, None
        trace = self._trace_begin(trace_header)
        deadline, budget_ms, expired = self._effective_deadline(
            t0, budget_header
        )
        if budget_ms is not None and trace is not None:
            trace.annotate("deadline_budget_ms", round(budget_ms, 3))
        if expired:
            self.deadline_expired_total += 1
            return (
                self._degraded_response(
                    t0, songs, "deadline-expired", trace=trace
                ),
                None, t0, None,
            )
        # serve mesh (ISSUE 16): same pre-check as _post_recommend —
        # never cache/merge an answer a dark slab can't contribute to
        missing = self._mesh_missing_shards(probe=True)
        if missing:
            return (
                self._mesh_shard_response(t0, songs, missing[0], trace=trace),
                None, t0, None,
            )
        if self.batcher is None:
            try:
                recs, source, cached = self.recommend_direct(
                    songs, trace=trace, deadline=deadline
                )
            except Exception as exc:
                if isinstance(exc, MeshShardUnavailable):
                    return (
                        self._mesh_shard_response(
                            t0, songs, exc.rank, trace=trace
                        ),
                        None, t0, None,
                    )
                reason = self._degrade_reason(exc)
                if reason is not None:
                    return (
                        self._degraded_response(t0, songs, reason, trace=trace),
                        None, t0, None,
                    )
                return (
                    self._recommend_error_response(exc, trace=trace),
                    None, t0, None,
                )
            return (
                self._recommend_result_response(
                    t0, recs, source, cached=cached, trace=trace, songs=songs
                ),
                None, t0, None,
            )
        try:
            state, payload = self._cache_lookup_or_lead(songs, deadline, trace)
            if state == "off":
                if trace is not None:
                    future = self.batcher.submit(
                        songs, deadline=deadline, trace=trace
                    )
                elif deadline is not None:
                    future = self.batcher.submit(songs, deadline=deadline)
                else:
                    future = self.batcher.submit(songs)
                future._kmls_seeds = songs
                return None, future, t0, trace
        except Exception as exc:  # Overloaded / NoHealthyReplicas land here
            if isinstance(exc, MeshShardUnavailable):
                return (
                    self._mesh_shard_response(t0, songs, exc.rank, trace=trace),
                    None, t0, None,
                )
            reason = self._degrade_reason(exc)
            if reason is not None:
                return (
                    self._degraded_response(t0, songs, reason, trace=trace),
                    None, t0, None,
                )
            return (
                self._recommend_error_response(exc, trace=trace),
                None, t0, None,
            )
        if state == "hit":
            return (
                self._recommend_result_response(
                    t0, payload[0], payload[1], cached=True, trace=trace,
                    songs=songs,
                ),
                None, t0, None,
            )
        return None, payload, t0, trace

    def finish_recommend(self, future, t0: float, trace=None) -> Response:
        """Build the response for a completed :meth:`submit_recommend`
        future (which is done — ``result()`` never blocks here). A future
        resolved to DeadlineExceeded/NoHealthyReplicas degrades to the
        fallback answer for the seeds that rode in on the future."""
        try:
            # kmls-verify: allow[loopblock] — callers hand in a DONE
            # future (docstring contract above); result() only unwraps
            recs, source = future.result()
        except Exception as exc:
            if isinstance(exc, MeshShardUnavailable):
                songs = getattr(future, "_kmls_seeds", None) or []
                return self._mesh_shard_response(
                    t0, songs, exc.rank, trace=trace
                )
            reason = self._degrade_reason(exc)
            if reason is not None:
                songs = getattr(future, "_kmls_seeds", None) or []
                return self._degraded_response(t0, songs, reason, trace=trace)
            return self._recommend_error_response(exc, trace=trace)
        return self._recommend_result_response(
            t0, recs, source, trace=trace,
            songs=getattr(future, "_kmls_seeds", None),
        )

    def _get_client(self) -> Response:
        """Render the HTML test client with a sampled seed + static sample
        (reference: rest_api/app/main.py:190-203 — which sleeps 2 s when data
        isn't loaded yet; here the page renders immediately with a notice)."""
        # read finished_loading BEFORE best_tracks: load() publishes the
        # tracks first, so a True snapshot guarantees the best_tracks read
        # below sees the published value — the reverse order could blame
        # an empty ranking for what was really an in-flight load
        finished = self.engine.finished_loading
        best = self.engine.best_tracks
        if not best:
            # two distinct states render here: artifacts still loading, vs
            # loaded-but-empty popularity ranking (the reference's keep
            # count truncates with no minimum — int(N·pct) is legitimately
            # 0 on a tiny vocabulary). The old single message claimed
            # "not loaded yet" for both, telling the operator to retry
            # something that would never change.
            if finished:
                notice = (
                    "<p><em>Model loaded, but the popularity ranking kept "
                    "no tracks (vocabulary × TOP_TRACKS_SAVE_PERCENTILE "
                    "truncates to zero) — use <a href='/docs'>/docs</a> to "
                    "POST seed songs directly.</em></p>"
                )
            else:
                notice = (
                    "<p><em>Model artifacts not loaded yet — retry "
                    "shortly.</em></p>"
                )
            page = (
                self._template
                .replace("{{version}}", self.cfg.version)
                .replace("{{model_date}}", str(self.engine.cache_value))
                .replace("{{track_checkboxes}}", notice)
                .replace("{{sample_seed}}", "—")
                .replace("{{sample_recommendations}}", "")
            )
            return _html_response(200, page)
        names = [b["track_name"] for b in best]
        sample_pool = random.sample(names, min(12, len(names)))
        seed = random.choice(names)
        sample = self.engine.static_recommendation([seed])
        checkboxes = "\n".join(
            f'<label><input type="checkbox" value="{_esc(n)}"> {_esc(n)}</label>'
            for n in sample_pool
        )
        sample_html = "\n".join(f"<li>{_esc(s)}</li>" for s in sample)
        page = (
            self._template
            .replace("{{version}}", self.cfg.version)
            .replace("{{model_date}}", str(self.engine.cache_value))
            .replace("{{track_checkboxes}}", checkboxes)
            .replace("{{sample_seed}}", _esc(seed))
            .replace("{{sample_recommendations}}", sample_html)
        )
        return _html_response(200, page)

    def _get_docs(self) -> Response:
        """Interactive API docs: the three canned request examples
        (reference parity: rest_api/app/main.py:158-174, surfaced there via
        Swagger UI's "try it out") each load into an editable request body
        that can be sent to the live endpoint from the page."""
        examples = "\n".join(
            f"<h3>{_esc(ex['summary'])}</h3>"
            f"<pre>POST /api/recommend/\n{json.dumps(ex['value'], indent=2)}</pre>"
            f"<button class='load' data-body='{_esc(json.dumps(ex['value']))}'>"
            f"Try it</button>"
            for ex in CANNED_EXAMPLES.values()
        )
        first = json.dumps(
            next(iter(CANNED_EXAMPLES.values()))["value"], indent=2
        )
        html = f"""<!doctype html><html><head><meta charset="utf-8">
<title>API docs — Playlist Recommender</title>
<style>body{{font-family:system-ui;max-width:760px;margin:2rem auto;padding:0 1rem}}
pre{{background:#8881;padding:.8rem;border-radius:6px;overflow-x:auto}}
textarea{{width:100%;font-family:monospace;min-height:7rem}}
button{{margin:.3rem .3rem .3rem 0;padding:.35rem .9rem;cursor:pointer}}
#resp{{white-space:pre-wrap}}</style></head>
<body><h1>Playlist Recommender API {_esc(self.cfg.version)}</h1>
<p>Machine-readable spec: <a href="/openapi.json">/openapi.json</a></p>
<h2 id="post-api-recommend">POST /api/recommend/</h2>
<p>Request: <code>{{"songs": ["...", ...]}}</code> — at least one song
(empty → 400). Response: <code>{{"songs": [...], "model_date": "...",
"version": "..."}}</code>. Seeds found in the mined rules yield rule-based
recommendations; fully unknown seed sets fall back to a deterministic
popular-tracks sample.</p>
{examples}
<h2>Try it against this server</h2>
<textarea id="body" spellcheck="false">{_esc(first)}</textarea><br>
<button id="send">Send POST /api/recommend/</button>
<pre id="resp">(response appears here)</pre>
<script>
document.querySelectorAll('button.load').forEach(function (b) {{
  b.addEventListener('click', function () {{
    document.getElementById('body').value =
      JSON.stringify(JSON.parse(b.dataset.body), null, 2);
    document.getElementById('body').scrollIntoView({{behavior: 'smooth'}});
  }});
}});
document.getElementById('send').addEventListener('click', async function () {{
  var out = document.getElementById('resp');
  out.textContent = '...';
  try {{
    var r = await fetch('/api/recommend/', {{
      method: 'POST',
      headers: {{'Content-Type': 'application/json'}},
      body: document.getElementById('body').value,
    }});
    var text = await r.text();
    try {{ text = JSON.stringify(JSON.parse(text), null, 2); }} catch (e) {{}}
    out.textContent = 'HTTP ' + r.status + '\\n' + text;
  }} catch (e) {{
    out.textContent = 'request failed: ' + e;
  }}
}});
</script>
<h2>Other endpoints</h2>
<ul>
<li><code>GET /</code> — HTML test client</li>
<li><code>GET /test</code> — redirect here</li>
<li><code>GET /healthz</code>, <code>GET /readyz</code> — probes</li>
<li><code>GET /metrics</code> — Prometheus text metrics</li>
<li><code>GET /debug/traces</code>, <code>GET /debug/slo</code>,
<code>GET /debug/profile?seconds=N</code> — loopback-only debug views
(retained traces, SLO burn rates, on-demand profiler capture)</li>
</ul></body></html>"""
        return _html_response(200, html)

    def _openapi(self) -> dict:
        return {
            "openapi": "3.1.0",
            "info": {
                "title": "Playlist Recommender (TPU rebuild)",
                "version": self.cfg.version,
            },
            "paths": {
                "/api/recommend/": {
                    "post": {
                        "summary": "Recommend songs from seed songs",
                        "requestBody": {
                            "required": True,
                            "content": {
                                "application/json": {
                                    "schema": {
                                        "type": "object",
                                        "required": ["songs"],
                                        "properties": {
                                            "songs": {
                                                "type": "array",
                                                "items": {"type": "string"},
                                                "minItems": 1,
                                            }
                                        },
                                    },
                                    "examples": CANNED_EXAMPLES,
                                }
                            },
                        },
                        "responses": {
                            "200": {
                                "description": "Recommendations",
                                "content": {
                                    "application/json": {
                                        "schema": {
                                            "type": "object",
                                            "properties": {
                                                "songs": {
                                                    "type": "array",
                                                    "items": {"type": "string"},
                                                },
                                                "model_date": {"type": "string"},
                                                "version": {"type": "string"},
                                            },
                                        }
                                    }
                                },
                            },
                            "400": {"description": "Empty song list"},
                            "422": {"description": "Malformed body"},
                        },
                    }
                }
            },
        }


def _esc(s: str) -> str:
    return (
        str(s).replace("&", "&amp;").replace("<", "&lt;")
        .replace(">", "&gt;").replace('"', "&quot;").replace("'", "&#39;")
    )


# ---------- stdlib HTTP adapter ----------


def make_handler(app: RecommendApp):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # the handler writes headers and body as separate sends on an
        # unbuffered socket; with Nagle on, the body send sits behind the
        # peer's delayed ACK (~40ms) — at QPS scale that dominates latency
        disable_nagle_algorithm = True

        def _dispatch(self, method: str) -> None:
            # in-flight accounting for the SIGTERM drain: the settle in
            # serving.server exits as soon as this hits zero (idle
            # keep-alive connections sit BETWEEN requests and are rightly
            # not counted — the drain must not wait on them)
            track = hasattr(self.server, "active_lock")
            if track:
                with self.server.active_lock:
                    self.server.active_requests += 1
            try:
                body = None
                if method == "POST":
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                try:
                    status, headers, payload = app.handle(
                        method, self.path, body,
                        client_host=self.client_address[0],
                        trace_header=self.headers.get("X-KMLS-Trace"),
                        budget_header=self.headers.get(
                            "X-KMLS-Deadline-Budget"
                        ),
                    )
                except Exception:
                    logger.exception("unhandled error for %s %s", method, self.path)
                    app.metrics.record_error()
                    status, headers, payload = 500, {"Content-Type": "application/json"}, (
                        b'{"detail": "Internal Server Error"}'
                    )
                self.send_response(status)
                for key, value in headers.items():
                    self.send_header(key, value)
                self.send_header("Content-Length", str(len(payload)))
                # during a SIGTERM drain (server.draining set by
                # serving.server) tell keep-alive clients to re-connect
                # elsewhere — k8s endpoint removal only diverts NEW
                # connections, established flows would otherwise keep
                # sending to the terminating pod until cut off
                drain = getattr(self.server, "draining", None)
                if drain is not None and drain.is_set():
                    self.send_header("Connection", "close")
                    self.close_connection = True
                self.end_headers()
                self.wfile.write(payload)
            finally:
                if track:
                    with self.server.active_lock:
                        self.server.active_requests -= 1

        def do_GET(self) -> None:  # noqa: N802 (stdlib API)
            self._dispatch("GET")

        def do_POST(self) -> None:  # noqa: N802
            self._dispatch("POST")

        def log_message(self, fmt: str, *args) -> None:
            logger.debug("%s - %s", self.address_string(), fmt % args)

    return Handler


class _Server(ThreadingHTTPServer):
    # stdlib default listen backlog is 5 — QPS-scale bursts get connection-
    # refused before a handler thread ever sees them
    request_queue_size = 256

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # in-flight request count, read by the SIGTERM drain settle
        self.active_requests = 0
        self.active_lock = threading.Lock()


def serve(app: RecommendApp, port: int | None = None) -> ThreadingHTTPServer:
    """Bind + return the server (caller runs ``serve_forever``); port 0 picks
    an ephemeral port (used by tests and local dev)."""
    server = _Server(
        ("0.0.0.0", port if port is not None else app.cfg.port), make_handler(app)
    )
    return server
