"""Request micro-batcher: aggregate concurrent ``/api/recommend/`` calls
into one device kernel invocation.

The reference serves each request with per-request Python dict merges
(rest_api/app/main.py:240-253); the TPU hot path is a batched kernel, and at
1k QPS (BASELINE.json config 5) per-request device calls would serialize on
the device lock. This batcher collects requests for at most
``batch_window_ms`` (or until ``batch_max_size`` requests are waiting) and
issues a single :meth:`RecommendEngine.recommend_many` call for the group.

Under load the window fills instantly (batch of 32 per device call); at low
traffic a lone request pays at most the window in extra latency. A worker
failure is propagated to every waiting request — the batcher thread itself
never dies.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from concurrent.futures import Future

from .engine import RecommendEngine


@dataclasses.dataclass
class _Pending:
    seeds: list[str]
    future: Future


class MicroBatcher:
    def __init__(
        self,
        engine: RecommendEngine,
        *,
        max_size: int = 32,
        window_ms: float = 2.0,
    ):
        self.engine = engine
        self.max_size = max_size
        self.window_s = window_ms / 1e3
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="kmls-microbatcher"
        )
        self._thread.start()

    def recommend(self, seeds: list[str], timeout: float = 30.0) -> tuple[list[str], str]:
        pending = _Pending(seeds=seeds, future=Future())
        self._queue.put(pending)
        return pending.future.result(timeout=timeout)

    def _loop(self) -> None:
        import time

        while True:
            first = self._queue.get()  # block for the batch leader
            batch = [first]
            deadline = time.perf_counter() + self.window_s
            while len(batch) < self.max_size:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            try:
                results = self.engine.recommend_many([p.seeds for p in batch])
                for pending, result in zip(batch, results):
                    pending.future.set_result(result)
            except Exception as exc:  # propagate, don't die
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(exc)
