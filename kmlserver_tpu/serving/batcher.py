"""Request micro-batcher: aggregate concurrent ``/api/recommend/`` calls
into batched device kernel invocations, pipelined.

The reference serves each request with per-request Python dict merges
(rest_api/app/main.py:240-253); the TPU hot path is a batched kernel, and at
1k QPS (BASELINE.json config 5) per-request device calls would serialize on
the device lock. This batcher collects requests for at most
``batch_window_ms`` (or until ``batch_max_size`` requests are waiting) and
issues a single :meth:`RecommendEngine.recommend_many_async` call for the
group.

Dispatch and completion run on SEPARATE threads: the collector dispatches a
batch to the device (async, returns immediately) and keeps collecting while
a completion thread blocks on the in-order results and resolves futures.
With a high-latency host<->device link (a remote-TPU tunnel adds ~65 ms per
blocked call) a dispatch-block-respond loop caps throughput at
batch_size/RTT (~490 QPS at batch 32); pipelining up to ``max_inflight``
batches removes that ceiling while jax's in-order execution queue preserves
result ordering.

Under load the window fills instantly (batch of 32 per device call); at low
traffic the window is SKIPPED entirely when the device is idle — waiting
only buys throughput when a batch is already in flight, so a lone request
dispatches immediately (batch of 1) and later arrivals form their own batch
behind it. A worker failure is propagated to every waiting request — the
batcher threads themselves never die.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from concurrent.futures import Future

from .engine import RecommendEngine


@dataclasses.dataclass
class _Pending:
    seeds: list[str]
    future: Future


class MicroBatcher:
    def __init__(
        self,
        engine: RecommendEngine,
        *,
        max_size: int = 32,
        window_ms: float = 2.0,
        max_inflight: int = 4,
    ):
        self.engine = engine
        self.max_size = max_size
        self.window_s = window_ms / 1e3
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        # (batch, finish_fn) pairs awaiting their device results, FIFO —
        # jax executes dispatches in order, so completion order matches
        self._completions: "queue.Queue[tuple[list[_Pending], object]]" = (
            queue.Queue()
        )
        # clamp: Semaphore(0) would deadlock the collector on its first
        # acquire (every request then times out with no error logged);
        # "no pipelining" is depth 1, not 0
        self._inflight = threading.Semaphore(max(1, max_inflight))
        # dispatched-but-uncompleted batch count, read by the collector's
        # idle-fast-path (a stale read is benign: worst case one batch
        # waits a window it didn't need, or dispatches a little early)
        self._inflight_n = 0
        self._n_lock = threading.Lock()
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True, name="kmls-microbatcher"
        )
        self._completer = threading.Thread(
            target=self._complete_loop, daemon=True, name="kmls-batch-completer"
        )
        self._collector.start()
        self._completer.start()

    def recommend(self, seeds: list[str], timeout: float = 30.0) -> tuple[list[str], str]:
        pending = _Pending(seeds=seeds, future=Future())
        self._queue.put(pending)
        return pending.future.result(timeout=timeout)

    def _collect_loop(self) -> None:
        import time

        while True:
            first = self._queue.get()  # block for the batch leader
            batch = [first]
            # sweep everything already waiting, without blocking
            while len(batch) < self.max_size:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            with self._n_lock:
                device_idle = self._inflight_n == 0
            if not device_idle:
                # device busy: the window buys amortization — keep
                # collecting up to it (a full batch exits immediately)
                deadline = time.perf_counter() + self.window_s
                while len(batch) < self.max_size:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._queue.get(timeout=remaining))
                    except queue.Empty:
                        break
            # else: nothing in flight — waiting can't improve throughput,
            # it only adds the window to this batch's latency. Dispatch
            # now; later arrivals pipeline behind as their own batch.
            # bound the pipeline: past max_inflight undispatched-but-queued
            # device calls, block here (requests keep queueing upstream and
            # land in bigger batches — backpressure, not failure)
            self._inflight.acquire()
            try:
                finish = self.engine.recommend_many_async(
                    [p.seeds for p in batch]
                )
            except Exception as exc:  # propagate, don't die
                self._inflight.release()
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(exc)
                continue
            with self._n_lock:
                self._inflight_n += 1
            self._completions.put((batch, finish))

    def _complete_loop(self) -> None:
        while True:
            batch, finish = self._completions.get()
            try:
                results = finish()
                err = None
            except Exception as exc:  # propagate, don't die
                err = exc
            # decrement BEFORE resolving futures: set_result unblocks the
            # client, and its immediate next request must not observe a
            # counter that still says busy (it would pay a full window
            # against an idle device — ping-pong traffic regression)
            with self._n_lock:
                self._inflight_n -= 1
            self._inflight.release()
            if err is not None:
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(err)
            else:
                for pending, result in zip(batch, results):
                    pending.future.set_result(result)
