"""Request micro-batcher: aggregate concurrent ``/api/recommend/`` calls
into batched device kernel invocations, pipelined, with an adaptive
deadline-aware collection window and explicit load shedding.

The reference serves each request with per-request Python dict merges
(rest_api/app/main.py:240-253); the TPU hot path is a batched kernel, and at
1k QPS (BASELINE.json config 5) per-request device calls would serialize on
the device lock. This batcher collects requests and issues a single
:meth:`RecommendEngine.recommend_many_async` call per group.

Dispatch and completion run on SEPARATE threads: the collector dispatches a
batch to the device (async, returns immediately) and keeps collecting while
a completion thread blocks on the in-order results and resolves futures.
With a high-latency host<->device link (a remote-TPU tunnel adds ~65 ms per
blocked call) a dispatch-block-respond loop caps throughput at
batch_size/RTT (~490 QPS at batch 32); pipelining up to ``max_inflight``
batches removes that ceiling while jax's in-order execution queue preserves
result ordering.

Three tail-latency disciplines (the r05 replay showed p99 5.4x p50 at 1k
QPS with the fixed 2 ms window):

- **Idle fast path** (unchanged): the window is SKIPPED entirely when the
  device is idle — waiting only buys throughput when a batch is already in
  flight, so a lone request dispatches immediately.
- **Adaptive window**: when the device IS busy, the wait is sized from the
  observed arrival rate (mean gap over a sliding window of arrivals) —
  roughly the time the current rate needs to fill the batch — clamped to
  [``window_min_ms``, ``window_ms``]. A fixed window
  taxes every request the full window at low rates and is too short to
  amortize at high rates; the controller tracks the traffic instead. The
  wait is additionally capped so the batch LEADER's queue wait can never
  cross the shed budget — the deadline-aware part.
- **Load shedding**: when the projected queue wait for a NEW request
  (batches ahead x device-time EWMA) exceeds ``shed_queue_budget_ms``, the
  request is rejected up front with :class:`Overloaded` (HTTP 429 +
  ``Retry-After`` at the app layer). Backpressure becomes a visible,
  retryable signal instead of a silent p99 cliff.

Per-request enqueue/dispatch/complete timestamps are threaded through and
reported to :class:`~.metrics.ServingMetrics` as ``queue_wait`` /
``device`` / ``e2e`` attributions, so ``/metrics`` can say WHERE the tail
lives. A worker failure is propagated to every waiting request — the
batcher threads themselves never die.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from concurrent.futures import Future

from .engine import RecommendEngine

# EWMA smoothing for the device-batch-time estimate: new sample weighted
# 0.2 — reactive enough to track a load swing within ~10 batches, smooth
# enough that one straggler doesn't flip the shedding decision
_EWMA_ALPHA = 0.2


class Overloaded(RuntimeError):
    """Raised by :meth:`MicroBatcher.recommend` instead of enqueueing when
    the projected queue wait exceeds the shedding budget."""

    def __init__(self, retry_after_s: float, projected_wait_ms: float):
        super().__init__(
            f"projected queue wait {projected_wait_ms:.0f}ms exceeds the "
            f"shed budget; retry after {retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s
        self.projected_wait_ms = projected_wait_ms


@dataclasses.dataclass
class _Pending:
    seeds: list[str]
    future: Future
    t_enqueue: float


class MicroBatcher:
    def __init__(
        self,
        engine: RecommendEngine,
        *,
        max_size: int = 32,
        window_ms: float = 2.0,
        max_inflight: int = 4,
        adaptive: bool = True,
        window_min_ms: float = 1.0,
        shed_queue_budget_ms: float = 0.0,
        shed_retry_after_s: float = 1.0,
        metrics=None,
    ):
        self.engine = engine
        self.max_size = max_size
        self.window_s = window_ms / 1e3
        self.adaptive = adaptive
        self.window_min_s = min(window_min_ms / 1e3, self.window_s)
        self.shed_budget_s = shed_queue_budget_ms / 1e3
        self.shed_retry_after_s = shed_retry_after_s
        self.metrics = metrics
        self.shed_total = 0
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        # (batch, finish_fn, t_dispatch) triples awaiting their device
        # results, FIFO — jax executes dispatches in order, so completion
        # order matches
        self._completions: "queue.Queue[tuple[list[_Pending], object, float]]" = (
            queue.Queue()
        )
        # clamp: Semaphore(0) would deadlock the collector on its first
        # acquire (every request then times out with no error logged);
        # "no pipelining" is depth 1, not 0
        self._inflight = threading.Semaphore(max(1, max_inflight))
        # dispatched-but-uncompleted batch count, read by the collector's
        # idle-fast-path and the shedding projection (a stale read is
        # benign: worst case one batch waits a window it didn't need, or
        # one request sheds/admits marginally early)
        self._inflight_n = 0
        # dispatch times of the in-flight batches, FIFO (completion order
        # matches dispatch order): the OLDEST entry's age is a live lower
        # bound on the current device time, which lets the shedding
        # projection react to a stalled/slow device before the first
        # completion ever lands (the EWMA alone is blind while cold)
        self._dispatch_times: "collections.deque[float]" = collections.deque()
        self._n_lock = threading.Lock()
        # controller state: a sliding window of arrival timestamps
        # (written under _rate_lock by every recommend() call) and a
        # device-batch-time EWMA (written by the completion thread only).
        # The window-mean gap, not a per-gap EWMA: closed-loop clients
        # arrive in bursts (a completed batch releases its waiters at
        # once) and a per-gap EWMA saturates near zero inside a burst,
        # collapsing the window and splitting the wave into undersized
        # batches; the mean over ~64 arrivals spans several bursts and
        # tracks the true rate.
        self._rate_lock = threading.Lock()
        self._arrivals: "collections.deque[float]" = collections.deque(
            maxlen=64
        )
        self._device_s_ewma: float | None = None
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True, name="kmls-microbatcher"
        )
        self._completer = threading.Thread(
            target=self._complete_loop, daemon=True, name="kmls-batch-completer"
        )
        self._collector.start()
        self._completer.start()

    # ---------- admission ----------

    def projected_queue_wait_s(self) -> float:
        """Expected queue wait for a request enqueued NOW: batches ahead of
        it (in flight + already queued) times the per-batch device-time
        estimate — the completion EWMA, floored by the age of the oldest
        still-in-flight batch (a stalled device shows up in the age before
        any completion can move the EWMA). 0 while there's no evidence at
        all — shedding needs measurements, not guesses."""
        now = time.perf_counter()
        device_s = self._device_s_ewma or 0.0
        with self._n_lock:
            inflight = self._inflight_n
            if self._dispatch_times:
                device_s = max(device_s, now - self._dispatch_times[0])
        if device_s <= 0.0:
            return 0.0
        queued_batches = self._queue.qsize() / max(self.max_size, 1)
        return (inflight + queued_batches) * device_s

    def _arrival_gap_s(self) -> float | None:
        """Mean inter-arrival gap over the sliding window, or None before
        any rate evidence exists."""
        with self._rate_lock:
            n = len(self._arrivals)
            if n < 2:
                return None
            span = self._arrivals[-1] - self._arrivals[0]
        return span / (n - 1)

    def submit(self, seeds: list[str]) -> Future:
        """Non-blocking admission: shed-or-enqueue, → the request's
        Future. The async transport resolves it via a done-callback; the
        threaded transport blocks on it in :meth:`recommend`."""
        now = time.perf_counter()
        with self._rate_lock:
            self._arrivals.append(now)
        if self.shed_budget_s > 0:
            projected = self.projected_queue_wait_s()
            if projected > self.shed_budget_s:
                with self._rate_lock:  # += from concurrent request threads
                    self.shed_total += 1
                if self.metrics is not None:
                    self.metrics.record_shed()
                raise Overloaded(self.shed_retry_after_s, projected * 1e3)
        pending = _Pending(seeds=seeds, future=Future(), t_enqueue=now)
        self._queue.put(pending)
        return pending.future

    def recommend(self, seeds: list[str], timeout: float = 30.0) -> tuple[list[str], str]:
        return self.submit(seeds).result(timeout=timeout)

    # ---------- collection ----------

    def _busy_window_s(self, batch: list[_Pending], now: float) -> float:
        """Collection wait while a batch is in flight: the fixed ceiling,
        or (adaptive) the time the observed arrival rate needs to fill the
        rest of the batch — so a nearly-full batch stops waiting for one
        straggler; always capped so the batch leader's queue wait stays
        inside the shed budget."""
        window = self.window_s
        if self.adaptive:
            gap = self._arrival_gap_s()
            if gap is not None:
                need = (self.max_size - len(batch)) * gap
                window = min(self.window_s, max(self.window_min_s, need))
        if self.shed_budget_s > 0:
            leader_wait = now - batch[0].t_enqueue
            window = min(window, max(0.0, self.shed_budget_s - leader_wait))
        return window

    def _collect_loop(self) -> None:
        while True:
            first = self._queue.get()  # block for the batch leader
            batch = [first]
            # sweep everything already waiting, without blocking
            while len(batch) < self.max_size:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            with self._n_lock:
                device_idle = self._inflight_n == 0
            if not device_idle:
                # device busy: the window buys amortization — keep
                # collecting up to it (a full batch exits immediately)
                now = time.perf_counter()
                deadline = now + self._busy_window_s(batch, now)
                while len(batch) < self.max_size:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._queue.get(timeout=remaining))
                    except queue.Empty:
                        break
            # else: nothing in flight — waiting can't improve throughput,
            # it only adds the window to this batch's latency. Dispatch
            # now; later arrivals pipeline behind as their own batch.
            # bound the pipeline: past max_inflight undispatched-but-queued
            # device calls, block here (requests keep queueing upstream and
            # land in bigger batches — backpressure, not failure)
            self._inflight.acquire()
            t_dispatch = time.perf_counter()
            try:
                finish = self.engine.recommend_many_async(
                    [p.seeds for p in batch]
                )
            except Exception as exc:  # propagate, don't die
                self._inflight.release()
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(exc)
                continue
            with self._n_lock:
                self._inflight_n += 1
                self._dispatch_times.append(t_dispatch)
            self._completions.put((batch, finish, t_dispatch))

    def _complete_loop(self) -> None:
        while True:
            batch, finish, t_dispatch = self._completions.get()
            try:
                results = finish()
                err = None
            except Exception as exc:  # propagate, don't die
                err = exc
            t_complete = time.perf_counter()
            # decrement BEFORE resolving futures: set_result unblocks the
            # client, and its immediate next request must not observe a
            # counter that still says busy (it would pay a full window
            # against an idle device — ping-pong traffic regression)
            with self._n_lock:
                self._inflight_n -= 1
                if self._dispatch_times:
                    self._dispatch_times.popleft()
            self._inflight.release()
            if err is not None:
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(err)
                continue
            device_s = t_complete - t_dispatch
            self._device_s_ewma = (
                device_s if self._device_s_ewma is None
                else (1 - _EWMA_ALPHA) * self._device_s_ewma
                + _EWMA_ALPHA * device_s
            )
            for pending, result in zip(batch, results):
                pending.future.set_result(result)
            if self.metrics is not None:
                for pending in batch:
                    self.metrics.record_attribution(
                        queue_wait_s=t_dispatch - pending.t_enqueue,
                        device_s=device_s,
                        e2e_s=t_complete - pending.t_enqueue,
                    )


class AsyncMicroBatcher:
    """Loop-native twin of :class:`MicroBatcher` for the asyncio transport
    (serving/aioserver.py).

    Why a twin instead of putting the threaded pipeline behind the event
    loop: per-request cross-thread handoffs are exactly what the async
    front end exists to avoid. Profiled on a 2-core host, the threaded
    batcher driven from the loop spent most of its time re-acquiring the
    GIL — four thread hops per request (loop → collector → completer →
    per-request ``call_soon_threadsafe``), ~1.8 ms CPU each, capping the
    whole server near 550 QPS. Here admission, collection, and future
    resolution all run ON the loop (plain ints, no locks), the batch
    compute runs as ONE executor task, and the loop wakes once per BATCH.

    Policy-identical to :class:`MicroBatcher` — idle fast path, adaptive
    deadline-aware window, shed-before-budget, queue/device attribution —
    with the same knobs; the policy methods mirror their threaded
    namesakes line for line, minus the locking.
    """

    def __init__(
        self,
        engine: RecommendEngine,
        *,
        max_size: int = 32,
        window_ms: float = 2.0,
        max_inflight: int = 4,
        adaptive: bool = True,
        window_min_ms: float = 1.0,
        shed_queue_budget_ms: float = 0.0,
        shed_retry_after_s: float = 1.0,
        metrics=None,
    ):
        from concurrent.futures import ThreadPoolExecutor

        self.engine = engine
        self.max_size = max_size
        self.max_inflight = max(1, max_inflight)
        self.window_s = window_ms / 1e3
        self.adaptive = adaptive
        self.window_min_s = min(window_min_ms / 1e3, self.window_s)
        self.shed_budget_s = shed_queue_budget_ms / 1e3
        self.shed_retry_after_s = shed_retry_after_s
        self.metrics = metrics
        self.shed_total = 0
        self._pending: list[_Pending] = []
        self._inflight_n = 0
        self._dispatch_times: "collections.deque[float]" = collections.deque()
        self._arrivals: "collections.deque[float]" = collections.deque(maxlen=64)
        self._device_s_ewma: float | None = None
        self._flush_handle = None
        # finish() blocks (device transfer, or the GIL-releasing native
        # call) — it must run off-loop; pool depth = pipeline depth
        self._executor = ThreadPoolExecutor(
            max_workers=self.max_inflight, thread_name_prefix="kmls-abatch"
        )

    # ---------- policy (mirrors MicroBatcher, loop-confined) ----------

    def projected_queue_wait_s(self) -> float:
        now = time.perf_counter()
        device_s = self._device_s_ewma or 0.0
        if self._dispatch_times:
            device_s = max(device_s, now - self._dispatch_times[0])
        if device_s <= 0.0:
            return 0.0
        queued_batches = len(self._pending) / max(self.max_size, 1)
        return (self._inflight_n + queued_batches) * device_s

    def _arrival_gap_s(self) -> float | None:
        n = len(self._arrivals)
        if n < 2:
            return None
        return (self._arrivals[-1] - self._arrivals[0]) / (n - 1)

    def _busy_window_s(self, now: float) -> float:
        window = self.window_s
        if self.adaptive:
            gap = self._arrival_gap_s()
            if gap is not None:
                need = (self.max_size - len(self._pending)) * gap
                window = min(self.window_s, max(self.window_min_s, need))
        if self.shed_budget_s > 0 and self._pending:
            leader_wait = now - self._pending[0].t_enqueue
            window = min(window, max(0.0, self.shed_budget_s - leader_wait))
        return window

    # ---------- admission (loop thread only) ----------

    def submit(self, seeds: list[str]) -> "asyncio.Future":
        import asyncio

        loop = asyncio.get_running_loop()
        now = time.perf_counter()
        self._arrivals.append(now)
        if self.shed_budget_s > 0:
            projected = self.projected_queue_wait_s()
            if projected > self.shed_budget_s:
                self.shed_total += 1
                if self.metrics is not None:
                    self.metrics.record_shed()
                raise Overloaded(self.shed_retry_after_s, projected * 1e3)
        future = loop.create_future()
        self._pending.append(_Pending(seeds=seeds, future=future, t_enqueue=now))
        if len(self._pending) >= self.max_size:
            self._flush(loop)  # full batch: dispatch now
        elif getattr(self.engine, "host_kernel_active", False):
            # inline mode (native host kernel, computed ON the loop):
            # there is no pipeline to keep busy, so amortization comes
            # from a short scheduled window — but only when the observed
            # rate says more arrivals will actually land inside it;
            # sparse traffic dispatches immediately
            if self._flush_handle is None:
                gap = self._arrival_gap_s()
                window = self._busy_window_s(now)
                if gap is None or gap >= window or window <= 0.0:
                    self._flush(loop)
                else:
                    self._flush_handle = loop.call_later(
                        window, self._flush, loop
                    )
        elif self._inflight_n == 0:
            self._flush(loop)  # idle fast path: dispatch now
        elif self._flush_handle is None:
            self._flush_handle = loop.call_later(
                self._busy_window_s(now), self._flush, loop
            )
        return future

    # ---------- dispatch / completion (loop thread only) ----------

    def _flush(self, loop) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        if not self._pending:
            return
        if self._inflight_n >= self.max_inflight:
            # pipeline full: the next completion re-flushes — pending
            # requests pile into a bigger batch (backpressure, not failure)
            return
        batch = self._pending[: self.max_size]
        del self._pending[: len(batch)]
        t_dispatch = time.perf_counter()
        try:
            finish = self.engine.recommend_many_async([p.seeds for p in batch])
        except Exception as exc:  # propagate, don't die
            for pending in batch:
                if not pending.future.done():
                    pending.future.set_exception(exc)
            if self._pending:
                loop.call_soon(self._flush, loop)
            return
        if getattr(self.engine, "host_kernel_active", False):
            # inline: the native kernel is a sub-ms GIL-releasing C call —
            # running it here costs less than one thread handoff, and the
            # whole request lifecycle stays on a single thread
            self._inflight_n += 1
            self._dispatch_times.append(t_dispatch)
            try:
                outcome = (finish(), None)
            except Exception as exc:
                outcome = (None, exc)
            self._resolve(batch, outcome, t_dispatch, loop)
            return
        self._inflight_n += 1
        self._dispatch_times.append(t_dispatch)

        def run_finish():
            try:
                return finish(), None
            except Exception as exc:
                return None, exc

        task = self._executor.submit(run_finish)
        task.add_done_callback(
            lambda f: loop.call_soon_threadsafe(
                self._complete, batch, f, t_dispatch, loop
            )
        )
        if self._pending:
            # overflow past max_size: keep draining
            loop.call_soon(self._flush, loop)

    def _complete(self, batch, task, t_dispatch: float, loop) -> None:
        self._resolve(batch, task.result(), t_dispatch, loop)

    def _resolve(self, batch, outcome, t_dispatch: float, loop) -> None:
        results, err = outcome
        t_complete = time.perf_counter()
        self._inflight_n -= 1
        if self._dispatch_times:
            self._dispatch_times.popleft()
        if err is not None:
            for pending in batch:
                if not pending.future.done():
                    pending.future.set_exception(err)
        else:
            device_s = t_complete - t_dispatch
            self._device_s_ewma = (
                device_s if self._device_s_ewma is None
                else (1 - _EWMA_ALPHA) * self._device_s_ewma
                + _EWMA_ALPHA * device_s
            )
            for pending, result in zip(batch, results):
                if not pending.future.done():
                    pending.future.set_result(result)
            if self.metrics is not None:
                for pending in batch:
                    self.metrics.record_attribution(
                        queue_wait_s=t_dispatch - pending.t_enqueue,
                        device_s=device_s,
                        e2e_s=t_complete - pending.t_enqueue,
                    )
        if self._pending and self._flush_handle is None:
            # mirror the threaded collector waking on a completion: the
            # freed pipeline slot dispatches the waiting batch immediately
            self._flush(loop)
