"""Request micro-batcher: aggregate concurrent ``/api/recommend/`` calls
into batched device kernel invocations, pipelined, with an adaptive
deadline-aware collection window and explicit load shedding.

The reference serves each request with per-request Python dict merges
(rest_api/app/main.py:240-253); the TPU hot path is a batched kernel, and at
1k QPS (BASELINE.json config 5) per-request device calls would serialize on
the device lock. This batcher collects requests and issues a single
:meth:`RecommendEngine.recommend_many_async` call per group.

Dispatch and completion run on SEPARATE threads: the collector dispatches a
batch to the device (async, returns immediately) and keeps collecting while
a completion thread blocks on the in-order results and resolves futures.
With a high-latency host<->device link (a remote-TPU tunnel adds ~65 ms per
blocked call) a dispatch-block-respond loop caps throughput at
batch_size/RTT (~490 QPS at batch 32); pipelining up to ``max_inflight``
batches removes that ceiling while jax's in-order execution queue preserves
result ordering.

Three tail-latency disciplines (the r05 replay showed p99 5.4x p50 at 1k
QPS with the fixed 2 ms window):

- **Idle fast path** (unchanged): the window is SKIPPED entirely when the
  device is idle — waiting only buys throughput when a batch is already in
  flight, so a lone request dispatches immediately.
- **Adaptive window**: when the device IS busy, the wait is sized from the
  observed arrival rate (mean gap over a sliding window of arrivals) —
  roughly the time the current rate needs to fill the batch — clamped to
  [``window_min_ms``, ``window_ms``]. A fixed window
  taxes every request the full window at low rates and is too short to
  amortize at high rates; the controller tracks the traffic instead. The
  wait is additionally capped so the batch LEADER's queue wait can never
  cross the shed budget — the deadline-aware part.
- **Load shedding**: when the projected queue wait for a NEW request
  (batches ahead x device-time EWMA) exceeds ``shed_queue_budget_ms``, the
  request is rejected up front with :class:`Overloaded` (HTTP 429 +
  ``Retry-After`` at the app layer). Backpressure becomes a visible,
  retryable signal instead of a silent p99 cliff.

Per-request enqueue/dispatch/complete timestamps are threaded through and
reported to :class:`~.metrics.ServingMetrics` as ``queue_wait`` /
``device`` / ``e2e`` attributions, so ``/metrics`` can say WHERE the tail
lives. A worker failure is propagated to every waiting request — the
batcher threads themselves never die.

**Multi-device dispatch**: when the engine publishes more than one
replica (``KMLS_SERVE_DEVICES``), the batcher becomes a least-loaded
multi-queue dispatcher — each batch goes to the replica with the fewest
batches in flight (ties rotate so an all-idle fleet still spreads), with
per-replica in-flight accounting and one completion lane per replica
(jax's in-order execution guarantee holds per device, not across
devices). The pipeline bound and the shed projection are computed against
AGGREGATE capacity: ``max_inflight`` batches per replica, and a projected
queue wait of (batches ahead × device-time EWMA) / replica count —
N devices drain the same queue N times faster. Engines without a replica
set (``n_replicas`` absent or 1) get the exact single-lane behavior the
fakes and the native host kernel expect: the ``replica`` kwarg is only
passed when there is a choice to make.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from concurrent.futures import Future

from .engine import RecommendEngine

# EWMA smoothing for the device-batch-time estimate: new sample weighted
# 0.2 — reactive enough to track a load swing within ~10 batches, smooth
# enough that one straggler doesn't flip the shedding decision
_EWMA_ALPHA = 0.2


class Overloaded(RuntimeError):
    """Raised by :meth:`MicroBatcher.recommend` instead of enqueueing when
    the projected queue wait exceeds the shedding budget."""

    def __init__(self, retry_after_s: float, projected_wait_ms: float):
        super().__init__(
            f"projected queue wait {projected_wait_ms:.0f}ms exceeds the "
            f"shed budget; retry after {retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s
        self.projected_wait_ms = projected_wait_ms


@dataclasses.dataclass
class _Pending:
    seeds: list[str]
    future: Future
    t_enqueue: float


class MicroBatcher:
    def __init__(
        self,
        engine: RecommendEngine,
        *,
        max_size: int = 32,
        window_ms: float = 2.0,
        max_inflight: int = 4,
        adaptive: bool = True,
        window_min_ms: float = 1.0,
        shed_queue_budget_ms: float = 0.0,
        shed_retry_after_s: float = 1.0,
        metrics=None,
    ):
        self.engine = engine
        self.max_size = max_size
        self.window_s = window_ms / 1e3
        self.adaptive = adaptive
        self.window_min_s = min(window_min_ms / 1e3, self.window_s)
        self.shed_budget_s = shed_queue_budget_ms / 1e3
        self.shed_retry_after_s = shed_retry_after_s
        self.metrics = metrics
        self.shed_total = 0
        # pipeline depth PER REPLICA; the aggregate bound is this times
        # the engine's live replica count (clamped: depth 0 would deadlock
        # the collector — "no pipelining" is depth 1, not 0)
        self.max_inflight = max(1, max_inflight)
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        # one completion lane PER REPLICA: (batch, finish_fn, t_dispatch)
        # triples awaiting their device results, FIFO within a lane — jax
        # executes dispatches in order per device, so completion order
        # matches per lane (but NOT across lanes; a single global lane
        # would head-of-line-block fast devices behind a slow one).
        # Lanes + their completer threads are created on first dispatch
        # to a replica index, by the collector thread only.
        self._completions: dict[int, "queue.Queue"] = {}
        # dispatched-but-uncompleted batches per replica, read by the
        # collector's idle-fast-path, the least-loaded pick, and the
        # shedding projection (a stale read is benign: worst case one
        # batch waits a window it didn't need, or one request
        # sheds/admits marginally early)
        self._inflight_by_replica: dict[int, int] = {}
        # rotation point for least-loaded ties: an all-idle replica set
        # must still spread consecutive batches across devices
        self._rr = 0
        # per-replica dispatch times of in-flight batches, FIFO: the
        # OLDEST entry's age is a live lower bound on the current device
        # time, which lets the shedding projection react to a
        # stalled/slow device before the first completion ever lands
        # (the EWMA alone is blind while cold)
        self._dispatch_times: dict[int, "collections.deque[float]"] = {}
        self._n_lock = threading.Lock()
        # collector blocks here while every replica's pipeline is full;
        # completions notify (replaces the old single-lane semaphore,
        # whose fixed depth couldn't track a replica count that appears
        # only at the engine's first load)
        self._pipe_cond = threading.Condition(self._n_lock)
        # controller state: a sliding window of arrival timestamps
        # (written under _rate_lock by every recommend() call) and a
        # device-batch-time EWMA (written by the completion thread only).
        # The window-mean gap, not a per-gap EWMA: closed-loop clients
        # arrive in bursts (a completed batch releases its waiters at
        # once) and a per-gap EWMA saturates near zero inside a burst,
        # collapsing the window and splitting the wave into undersized
        # batches; the mean over ~64 arrivals spans several bursts and
        # tracks the true rate.
        self._rate_lock = threading.Lock()
        self._arrivals: "collections.deque[float]" = collections.deque(
            maxlen=64
        )
        self._device_s_ewma: float | None = None
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True, name="kmls-microbatcher"
        )
        self._collector.start()

    # ---------- replica bookkeeping ----------

    def _n_replicas(self) -> int:
        return max(1, getattr(self.engine, "n_replicas", 1))

    def _total_inflight_locked(self) -> int:
        return sum(self._inflight_by_replica.values())

    def _pick_replica_locked(self, n: int) -> int:
        """Least-loaded replica index; ties broken by a rotating start so
        an idle fleet spreads consecutive batches instead of hammering
        replica 0. Caller holds ``_n_lock``."""
        best, best_load = 0, None
        for off in range(n):
            i = (self._rr + off) % n
            load = self._inflight_by_replica.get(i, 0)
            if best_load is None or load < best_load:
                best, best_load = i, load
        self._rr = (best + 1) % n
        return best

    def _completion_lane(self, idx: int) -> "queue.Queue":
        """The collector is the only caller, so lane creation is
        single-writer; completer threads are per-lane and never die."""
        lane = self._completions.get(idx)
        if lane is None:
            lane = queue.Queue()
            self._completions[idx] = lane
            threading.Thread(
                target=self._complete_loop, args=(idx,), daemon=True,
                name=f"kmls-batch-completer-{idx}",
            ).start()
        return lane

    def per_replica_inflight(self) -> dict[int, int]:
        """Snapshot for tests/diagnostics."""
        with self._n_lock:
            return dict(self._inflight_by_replica)

    # ---------- admission ----------

    def projected_queue_wait_s(self) -> float:
        """Expected queue wait for a request enqueued NOW: batches ahead of
        it (in flight + already queued) times the per-batch device-time
        estimate, divided by the replica count — N devices drain the same
        queue N times faster, so the budget is against AGGREGATE capacity.
        The estimate is the completion EWMA, floored by the age of the
        oldest still-in-flight batch on any replica (a stalled device
        shows up in the age before any completion can move the EWMA).
        0 while there's no evidence at all — shedding needs measurements,
        not guesses."""
        now = time.perf_counter()
        device_s = self._device_s_ewma or 0.0
        with self._n_lock:
            inflight = self._total_inflight_locked()
            for lane in self._dispatch_times.values():
                if lane:
                    device_s = max(device_s, now - lane[0])
        if device_s <= 0.0:
            return 0.0
        queued_batches = self._queue.qsize() / max(self.max_size, 1)
        return (inflight + queued_batches) * device_s / self._n_replicas()

    def _arrival_gap_s(self) -> float | None:
        """Mean inter-arrival gap over the sliding window, or None before
        any rate evidence exists."""
        with self._rate_lock:
            n = len(self._arrivals)
            if n < 2:
                return None
            span = self._arrivals[-1] - self._arrivals[0]
        return span / (n - 1)

    def submit(self, seeds: list[str]) -> Future:
        """Non-blocking admission: shed-or-enqueue, → the request's
        Future. The async transport resolves it via a done-callback; the
        threaded transport blocks on it in :meth:`recommend`."""
        now = time.perf_counter()
        with self._rate_lock:
            self._arrivals.append(now)
        if self.shed_budget_s > 0:
            projected = self.projected_queue_wait_s()
            if projected > self.shed_budget_s:
                with self._rate_lock:  # += from concurrent request threads
                    self.shed_total += 1
                if self.metrics is not None:
                    self.metrics.record_shed()
                raise Overloaded(self.shed_retry_after_s, projected * 1e3)
        pending = _Pending(seeds=seeds, future=Future(), t_enqueue=now)
        self._queue.put(pending)
        return pending.future

    def recommend(self, seeds: list[str], timeout: float = 30.0) -> tuple[list[str], str]:
        return self.submit(seeds).result(timeout=timeout)

    # ---------- collection ----------

    def _busy_window_s(self, batch: list[_Pending], now: float) -> float:
        """Collection wait while a batch is in flight: the fixed ceiling,
        or (adaptive) the time the observed arrival rate needs to fill the
        rest of the batch — so a nearly-full batch stops waiting for one
        straggler; always capped so the batch leader's queue wait stays
        inside the shed budget."""
        window = self.window_s
        if self.adaptive:
            gap = self._arrival_gap_s()
            if gap is not None:
                need = (self.max_size - len(batch)) * gap
                window = min(self.window_s, max(self.window_min_s, need))
        if self.shed_budget_s > 0:
            leader_wait = now - batch[0].t_enqueue
            window = min(window, max(0.0, self.shed_budget_s - leader_wait))
        return window

    def _collect_loop(self) -> None:
        while True:
            first = self._queue.get()  # block for the batch leader
            batch = [first]
            # sweep everything already waiting, without blocking
            while len(batch) < self.max_size:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            with self._n_lock:
                # idle fast path fires while ANY replica sits idle: waiting
                # only buys amortization when every device already has work
                device_idle = (
                    self._total_inflight_locked() < self._n_replicas()
                )
            if not device_idle:
                # all replicas busy: the window buys amortization — keep
                # collecting up to it (a full batch exits immediately)
                now = time.perf_counter()
                deadline = now + self._busy_window_s(batch, now)
                while len(batch) < self.max_size:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._queue.get(timeout=remaining))
                    except queue.Empty:
                        break
            # bound the pipeline AGGREGATELY: past max_inflight
            # undispatched-but-queued device calls PER replica, block here
            # (requests keep queueing upstream and land in bigger batches
            # — backpressure, not failure). Reserve the least-loaded
            # replica under the same lock so the pick and the accounting
            # can't race a concurrent completion.
            with self._pipe_cond:
                while (
                    self._total_inflight_locked()
                    >= self.max_inflight * self._n_replicas()
                ):
                    self._pipe_cond.wait(timeout=1.0)
                n = self._n_replicas()
                idx = self._pick_replica_locked(n) if n > 1 else 0
                self._inflight_by_replica[idx] = (
                    self._inflight_by_replica.get(idx, 0) + 1
                )
                t_dispatch = time.perf_counter()
                self._dispatch_times.setdefault(
                    idx, collections.deque()
                ).append(t_dispatch)
            try:
                # the replica kwarg is passed only when there's a choice:
                # single-replica engines (fakes, the native host kernel)
                # keep the bare signature they always had
                if n > 1:
                    finish = self.engine.recommend_many_async(
                        [p.seeds for p in batch], replica=idx
                    )
                else:
                    finish = self.engine.recommend_many_async(
                        [p.seeds for p in batch]
                    )
            except Exception as exc:  # propagate, don't die
                with self._pipe_cond:
                    self._inflight_by_replica[idx] -= 1
                    lane = self._dispatch_times.get(idx)
                    if lane:
                        lane.pop()
                    self._pipe_cond.notify_all()
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(exc)
                continue
            self._completion_lane(idx).put((batch, finish, t_dispatch))

    def _complete_loop(self, idx: int) -> None:
        lane = self._completions[idx]
        while True:
            batch, finish, t_dispatch = lane.get()
            try:
                results = finish()
                err = None
            except Exception as exc:  # propagate, don't die
                err = exc
            t_complete = time.perf_counter()
            # decrement BEFORE resolving futures: set_result unblocks the
            # client, and its immediate next request must not observe a
            # counter that still says busy (it would pay a full window
            # against an idle device — ping-pong traffic regression)
            device_s = t_complete - t_dispatch
            with self._pipe_cond:
                self._inflight_by_replica[idx] -= 1
                times = self._dispatch_times.get(idx)
                if times:
                    times.popleft()
                if err is None:
                    # EWMA updated under the lock: per-replica completer
                    # threads race here, and a torn read-modify-write
                    # would corrupt the shedding estimate
                    self._device_s_ewma = (
                        device_s if self._device_s_ewma is None
                        else (1 - _EWMA_ALPHA) * self._device_s_ewma
                        + _EWMA_ALPHA * device_s
                    )
                self._pipe_cond.notify_all()
            if err is not None:
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(err)
                continue
            for pending, result in zip(batch, results):
                pending.future.set_result(result)
            if self.metrics is not None:
                for pending in batch:
                    self.metrics.record_attribution(
                        queue_wait_s=t_dispatch - pending.t_enqueue,
                        device_s=device_s,
                        e2e_s=t_complete - pending.t_enqueue,
                    )


class AsyncMicroBatcher:
    """Loop-native twin of :class:`MicroBatcher` for the asyncio transport
    (serving/aioserver.py).

    Why a twin instead of putting the threaded pipeline behind the event
    loop: per-request cross-thread handoffs are exactly what the async
    front end exists to avoid. Profiled on a 2-core host, the threaded
    batcher driven from the loop spent most of its time re-acquiring the
    GIL — four thread hops per request (loop → collector → completer →
    per-request ``call_soon_threadsafe``), ~1.8 ms CPU each, capping the
    whole server near 550 QPS. Here admission, collection, and future
    resolution all run ON the loop (plain ints, no locks), the batch
    compute runs as ONE executor task, and the loop wakes once per BATCH.

    Policy-identical to :class:`MicroBatcher` — idle fast path, adaptive
    deadline-aware window, shed-before-budget, least-loaded multi-replica
    dispatch, queue/device attribution — with the same knobs; the policy
    methods mirror their threaded namesakes line for line, minus the
    locking (all state here is loop-confined: plain ints and dicts).
    """

    def __init__(
        self,
        engine: RecommendEngine,
        *,
        max_size: int = 32,
        window_ms: float = 2.0,
        max_inflight: int = 4,
        adaptive: bool = True,
        window_min_ms: float = 1.0,
        shed_queue_budget_ms: float = 0.0,
        shed_retry_after_s: float = 1.0,
        metrics=None,
    ):
        from concurrent.futures import ThreadPoolExecutor

        self.engine = engine
        self.max_size = max_size
        self.max_inflight = max(1, max_inflight)  # per replica
        self.window_s = window_ms / 1e3
        self.adaptive = adaptive
        self.window_min_s = min(window_min_ms / 1e3, self.window_s)
        self.shed_budget_s = shed_queue_budget_ms / 1e3
        self.shed_retry_after_s = shed_retry_after_s
        self.metrics = metrics
        self.shed_total = 0
        self._pending: list[_Pending] = []
        self._inflight_by_replica: dict[int, int] = {}
        self._rr = 0
        self._dispatch_times: dict[int, "collections.deque[float]"] = {}
        self._arrivals: "collections.deque[float]" = collections.deque(maxlen=64)
        self._device_s_ewma: float | None = None
        self._flush_handle = None
        # finish() blocks (device transfer, or the GIL-releasing native
        # call) — it must run off-loop; pool depth = aggregate pipeline
        # depth. The replica count isn't known until the engine's first
        # load, so the pool is sized for the largest realistic replica set
        # (threads spawn on demand — headroom costs nothing) and the
        # ADMISSION bound in _flush clamps to this same number: a batch
        # the pool couldn't run concurrently must not be admitted, or its
        # executor queue wait would masquerade as device time in the
        # attribution and the shedding EWMA.
        self._executor_workers = min(32, self.max_inflight * 8)
        self._executor = ThreadPoolExecutor(
            max_workers=self._executor_workers,
            thread_name_prefix="kmls-abatch",
        )

    # ---------- replica bookkeeping (mirrors MicroBatcher, no locks) ----

    def _n_replicas(self) -> int:
        return max(1, getattr(self.engine, "n_replicas", 1))

    def _total_inflight(self) -> int:
        return sum(self._inflight_by_replica.values())

    def _pick_replica(self, n: int) -> int:
        best, best_load = 0, None
        for off in range(n):
            i = (self._rr + off) % n
            load = self._inflight_by_replica.get(i, 0)
            if best_load is None or load < best_load:
                best, best_load = i, load
        self._rr = (best + 1) % n
        return best

    # ---------- policy (mirrors MicroBatcher, loop-confined) ----------

    def projected_queue_wait_s(self) -> float:
        now = time.perf_counter()
        device_s = self._device_s_ewma or 0.0
        for lane in self._dispatch_times.values():
            if lane:
                device_s = max(device_s, now - lane[0])
        if device_s <= 0.0:
            return 0.0
        queued_batches = len(self._pending) / max(self.max_size, 1)
        return (
            (self._total_inflight() + queued_batches)
            * device_s / self._n_replicas()
        )

    def _arrival_gap_s(self) -> float | None:
        n = len(self._arrivals)
        if n < 2:
            return None
        return (self._arrivals[-1] - self._arrivals[0]) / (n - 1)

    def _busy_window_s(self, now: float) -> float:
        window = self.window_s
        if self.adaptive:
            gap = self._arrival_gap_s()
            if gap is not None:
                need = (self.max_size - len(self._pending)) * gap
                window = min(self.window_s, max(self.window_min_s, need))
        if self.shed_budget_s > 0 and self._pending:
            leader_wait = now - self._pending[0].t_enqueue
            window = min(window, max(0.0, self.shed_budget_s - leader_wait))
        return window

    # ---------- admission (loop thread only) ----------

    def submit(self, seeds: list[str]) -> "asyncio.Future":
        import asyncio

        loop = asyncio.get_running_loop()
        now = time.perf_counter()
        self._arrivals.append(now)
        if self.shed_budget_s > 0:
            projected = self.projected_queue_wait_s()
            if projected > self.shed_budget_s:
                self.shed_total += 1
                if self.metrics is not None:
                    self.metrics.record_shed()
                raise Overloaded(self.shed_retry_after_s, projected * 1e3)
        future = loop.create_future()
        self._pending.append(_Pending(seeds=seeds, future=future, t_enqueue=now))
        if len(self._pending) >= self.max_size:
            self._flush(loop)  # full batch: dispatch now
        elif getattr(self.engine, "host_kernel_active", False):
            # inline mode (native host kernel, computed ON the loop):
            # there is no pipeline to keep busy, so amortization comes
            # from a short scheduled window — but only when the observed
            # rate says more arrivals will actually land inside it;
            # sparse traffic dispatches immediately
            if self._flush_handle is None:
                gap = self._arrival_gap_s()
                window = self._busy_window_s(now)
                if gap is None or gap >= window or window <= 0.0:
                    self._flush(loop)
                else:
                    self._flush_handle = loop.call_later(
                        window, self._flush, loop
                    )
        elif self._total_inflight() < self._n_replicas():
            self._flush(loop)  # idle fast path: some replica is free now
        elif self._flush_handle is None:
            self._flush_handle = loop.call_later(
                self._busy_window_s(now), self._flush, loop
            )
        return future

    # ---------- dispatch / completion (loop thread only) ----------

    def _flush(self, loop) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        if not self._pending:
            return
        n = self._n_replicas()
        if self._total_inflight() >= min(
            self.max_inflight * n, self._executor_workers
        ):
            # aggregate pipeline full — or past what the executor pool
            # can actually run concurrently: the next completion
            # re-flushes and pending requests pile into a bigger batch
            # (backpressure, not failure)
            return
        batch = self._pending[: self.max_size]
        del self._pending[: len(batch)]
        idx = self._pick_replica(n) if n > 1 else 0
        t_dispatch = time.perf_counter()
        try:
            # replica kwarg only when there's a choice — single-replica
            # engines (fakes, native host kernel) keep the bare signature
            if n > 1:
                finish = self.engine.recommend_many_async(
                    [p.seeds for p in batch], replica=idx
                )
            else:
                finish = self.engine.recommend_many_async(
                    [p.seeds for p in batch]
                )
        except Exception as exc:  # propagate, don't die
            for pending in batch:
                if not pending.future.done():
                    pending.future.set_exception(exc)
            if self._pending:
                loop.call_soon(self._flush, loop)
            return
        self._inflight_by_replica[idx] = (
            self._inflight_by_replica.get(idx, 0) + 1
        )
        self._dispatch_times.setdefault(
            idx, collections.deque()
        ).append(t_dispatch)
        if getattr(self.engine, "host_kernel_active", False):
            # inline: the native kernel is a sub-ms GIL-releasing C call —
            # running it here costs less than one thread handoff, and the
            # whole request lifecycle stays on a single thread
            try:
                outcome = (finish(), None)
            except Exception as exc:
                outcome = (None, exc)
            self._resolve(batch, outcome, t_dispatch, loop, idx)
            return

        def run_finish():
            try:
                return finish(), None
            except Exception as exc:
                return None, exc

        task = self._executor.submit(run_finish)
        task.add_done_callback(
            lambda f: loop.call_soon_threadsafe(
                self._complete, batch, f, t_dispatch, loop, idx
            )
        )
        if self._pending:
            # overflow past max_size: keep draining
            loop.call_soon(self._flush, loop)

    def _complete(self, batch, task, t_dispatch: float, loop, idx: int) -> None:
        self._resolve(batch, task.result(), t_dispatch, loop, idx)

    def _resolve(self, batch, outcome, t_dispatch: float, loop, idx: int) -> None:
        results, err = outcome
        t_complete = time.perf_counter()
        self._inflight_by_replica[idx] -= 1
        lane = self._dispatch_times.get(idx)
        if lane:
            lane.popleft()
        if err is not None:
            for pending in batch:
                if not pending.future.done():
                    pending.future.set_exception(err)
        else:
            device_s = t_complete - t_dispatch
            self._device_s_ewma = (
                device_s if self._device_s_ewma is None
                else (1 - _EWMA_ALPHA) * self._device_s_ewma
                + _EWMA_ALPHA * device_s
            )
            for pending, result in zip(batch, results):
                if not pending.future.done():
                    pending.future.set_result(result)
            if self.metrics is not None:
                for pending in batch:
                    self.metrics.record_attribution(
                        queue_wait_s=t_dispatch - pending.t_enqueue,
                        device_s=device_s,
                        e2e_s=t_complete - pending.t_enqueue,
                    )
        if self._pending and self._flush_handle is None:
            # mirror the threaded collector waking on a completion: the
            # freed pipeline slot dispatches the waiting batch immediately
            self._flush(loop)
