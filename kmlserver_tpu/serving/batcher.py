"""Request micro-batcher: aggregate concurrent ``/api/recommend/`` calls
into batched device kernel invocations, pipelined, with an adaptive
deadline-aware collection window and explicit load shedding.

The reference serves each request with per-request Python dict merges
(rest_api/app/main.py:240-253); the TPU hot path is a batched kernel, and at
1k QPS (BASELINE.json config 5) per-request device calls would serialize on
the device lock. This batcher collects requests and issues a single
:meth:`RecommendEngine.recommend_many_async` call per group. With the
second model family published, that one call dispatches BOTH model
kernels (rule max-merge + embedding cosine top-k) onto the chosen
replica and merges on the completion side — the batcher needs no
hybrid-awareness; a batch slot is a batch slot whichever models answer
it.

Dispatch and completion run on SEPARATE threads: the collector dispatches a
batch to the device (async, returns immediately) and keeps collecting while
a completion thread blocks on the in-order results and resolves futures.
With a high-latency host<->device link (a remote-TPU tunnel adds ~65 ms per
blocked call) a dispatch-block-respond loop caps throughput at
batch_size/RTT (~490 QPS at batch 32); pipelining up to ``max_inflight``
batches removes that ceiling while jax's in-order execution queue preserves
result ordering.

Three tail-latency disciplines (the r05 replay showed p99 5.4x p50 at 1k
QPS with the fixed 2 ms window):

- **Idle fast path** (unchanged): the window is SKIPPED entirely when the
  device is idle — waiting only buys throughput when a batch is already in
  flight, so a lone request dispatches immediately.
- **Adaptive window**: when the device IS busy, the wait is sized from the
  observed arrival rate (mean gap over a sliding window of arrivals) —
  roughly the time the current rate needs to fill the batch — clamped to
  [``window_min_ms``, ``window_ms``]. A fixed window
  taxes every request the full window at low rates and is too short to
  amortize at high rates; the controller tracks the traffic instead. The
  wait is additionally capped so the batch LEADER's queue wait can never
  cross the shed budget — the deadline-aware part.
- **Adaptive admission control** (ISSUE 8 — replaces the static
  cliff-edge shed): an :class:`AdmissionController` tracks PRESSURE =
  effective queue wait / ``shed_queue_budget_ms``, where the effective
  wait is the max of the instantaneous projection (batches ahead ×
  device-time EWMA) and a time-decaying EWMA of the queue waits admitted
  requests actually measured (the projection alone undershoots when
  batches run larger than estimated; the measured EWMA alone would hold
  stale overload after a burst drains, so it decays with a half-life of
  one budget). Admission escalates through a LADDER instead of flipping
  at the threshold: below ``soft_ratio`` every request is admitted at
  full quality; between ``soft_ratio`` and 1.0 a rising fraction of
  requests degrades (:class:`OverloadDegraded` → the app answers from
  the popularity fallback, 200 + ``X-KMLS-Degraded: overload`` — cache
  hits are untouched, so the cache-favored rung costs only the
  compute-needing tail); between 1.0 and ``hard_ratio`` a rising
  fraction sheds (:class:`Overloaded` → HTTP 429) and the rest still
  degrades; past ``hard_ratio`` everything sheds. ``Retry-After``
  carries bounded jitter (± ``retry_jitter`` of the base) — a constant
  value synchronizes every shed client into the next retry storm.
  ``soft_ratio=hard_ratio=1.0`` reproduces the legacy cliff exactly.

Per-request enqueue/dispatch/complete timestamps are threaded through and
reported to :class:`~.metrics.ServingMetrics` as ``queue_wait`` /
``device`` / ``e2e`` attributions, so ``/metrics`` can say WHERE the tail
lives. A worker failure is propagated to every waiting request — the
batcher threads themselves never die.

**Multi-device dispatch**: when the engine publishes more than one
replica (``KMLS_SERVE_DEVICES``), the batcher becomes a least-loaded
multi-queue dispatcher — each batch goes to the replica with the fewest
batches in flight (ties rotate so an all-idle fleet still spreads), with
per-replica in-flight accounting and one completion lane per replica
(jax's in-order execution guarantee holds per device, not across
devices). The pipeline bound and the shed projection are computed against
AGGREGATE capacity: ``max_inflight`` batches per replica, and a projected
queue wait of (batches ahead × device-time EWMA) / replica count —
N devices drain the same queue N times faster. Engines without a replica
set (``n_replicas`` absent or 1) get the exact single-lane behavior the
fakes and the native host kernel expect: the ``replica`` kwarg is only
passed when there is a choice to make.

**Replica health management** (``eject_threshold > 0``): a per-replica
consecutive-failure circuit breaker. A replica whose batches keep failing
is EJECTED from the least-loaded pick — its failed batch's requests are
re-dispatched to the surviving replicas (bounded per-request retries),
and the shed projection + idle fast path re-project against HEALTHY
capacity, not nominal. An ejected replica is probed for re-admission
every ``probe_interval_s``: one half-open trial batch; success re-admits,
failure re-arms the timer. With every replica ejected and no probe due,
admission raises :class:`NoHealthyReplicas` — the HTTP layer degrades
those requests to the popularity fallback instead of 500ing. Default OFF
(``eject_threshold=0``) so directly-constructed batchers (tests, replay
harnesses) keep the exact propagate-the-error behavior they always had;
the app layer wires KMLS_REPLICA_EJECT_THRESHOLD through.

**Deadlines**: ``submit(seeds, deadline=...)`` carries a per-request
perf_counter deadline through the pipeline. A request still queued at its
deadline fails with :class:`DeadlineExceeded` instead of dispatching dead
work to the device; in-flight overruns surface as the same exception from
the blocking ``recommend()`` wait (threaded) or a loop timer (async), and
the HTTP layer turns either into a degraded answer.
"""

from __future__ import annotations

import collections
import dataclasses
import inspect
import itertools
import logging
import math
import queue
import random
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout

from .engine import RecommendEngine

logger = logging.getLogger("kmlserver_tpu.serving")

# EWMA smoothing for the device-batch-time estimate: new sample weighted
# 0.2 — reactive enough to track a load swing within ~10 batches, smooth
# enough that one straggler doesn't flip the shedding decision
_EWMA_ALPHA = 0.2


class Overloaded(RuntimeError):
    """Raised by :meth:`MicroBatcher.recommend` instead of enqueueing when
    admission pressure says this request would outwait the shed budget.
    ``retry_after_s`` carries the controller's jitter — the HTTP layer
    forwards it verbatim so shed clients don't re-arrive in lockstep."""

    def __init__(self, retry_after_s: float, projected_wait_ms: float):
        super().__init__(
            f"projected queue wait {projected_wait_ms:.0f}ms exceeds the "
            f"shed budget; retry after {retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s
        self.projected_wait_ms = projected_wait_ms


class OverloadDegraded(RuntimeError):
    """Admission pressure is in the controller's degrade band: instead of
    queueing (or 429ing) this request, answer it from the popularity
    fallback — the HTTP layer maps this to 200 + ``X-KMLS-Degraded:
    overload``, one rung BEFORE any 429. Cache hits never reach admission
    (the cache sits in front), so under rising pressure cached answers
    keep full quality and only the compute-needing tail degrades."""

    def __init__(self, pressure: float):
        super().__init__(
            f"admission pressure {pressure:.2f} in the degrade band; "
            "answering from the popularity fallback"
        )
        self.pressure = pressure


class AdmissionController:
    """Pressure-proportional admission: admit → degrade → shed.

    Pressure is the effective queue wait over the shed budget. Effective
    wait = max(instantaneous projection, measured queue-wait EWMA with
    time decay). Decision bands (ratios of the budget):

    - ``p < soft_ratio``            → admit
    - ``soft_ratio <= p < 1``       → degrade with prob (p-soft)/(1-soft)
    - ``1 <= p < hard_ratio``       → shed with prob (p-1)/(hard-1),
                                      degrade otherwise
    - ``p >= hard_ratio``           → shed

    ``soft_ratio >= 1`` disables the degrade band and ``hard_ratio <= 1``
    makes the shed band a cliff at the budget — together they restore the
    pre-controller DECISION ladder (admit below the budget, shed above,
    nothing in between). The pressure ESTIMATE is still the new one:
    effective wait includes the measured queue-wait EWMA, so a cliff-mode
    controller can keep shedding for ~one decay half-life after a burst
    the projection alone would already have forgotten.

    All state is plain floats — single-writer per field (the completion
    side notes queue waits, admission only reads), and a stale read costs
    at most one request landing a band early/late, the same benign-race
    budget the batcher's in-flight counters already run on. No locks, so
    the loop-confined async twin shares the class unchanged.
    """

    def __init__(
        self,
        budget_s: float,
        *,
        soft_ratio: float = 0.6,
        hard_ratio: float = 1.5,
        retry_after_s: float = 1.0,
        retry_jitter: float = 0.5,
        rng: random.Random | None = None,
        lag_source=None,
    ):
        self.budget_s = budget_s
        self.soft_ratio = max(0.0, soft_ratio)
        self.hard_ratio = max(self.soft_ratio, hard_ratio, 1.0)
        self.retry_after_s = retry_after_s
        self.retry_jitter = min(max(retry_jitter, 0.0), 1.0)
        self._rng = rng or random.Random()
        self._wait_ewma: float | None = None
        self._wait_noted_at = 0.0
        # decay half-life: one budget width (floored so a sub-ms budget
        # doesn't make the memory vanish between completions)
        self._half_life_s = max(budget_s, 0.25)
        # runtime-health fold (ISSUE 9, closing the PR 8 inline-path
        # blind spot): an optional zero-arg callable returning the
        # current event-loop/scheduler stall estimate in SECONDS
        # (observability.runtime.LoopLagMonitor.lag_s). A wedged loop
        # means requests are ALREADY waiting at least that long in the
        # socket backlog where the queue projection cannot see them, so
        # the stall is an effective-wait floor — it escalates the
        # degrade→shed ladder exactly like a saturated queue.
        self._lag_source = lag_source

    def note_queue_wait(self, wait_s: float, now: float | None = None) -> None:
        """Completion-side: fold an admitted request's MEASURED queue wait
        into the EWMA (the projection's ground truth)."""
        now = time.perf_counter() if now is None else now
        # first sample adopted outright (the device-time EWMA does the
        # same): a cold controller must not spend ~10 batches warming up
        # while an overload is already measurable
        self._wait_ewma = (
            wait_s if self._wait_ewma is None
            else (1 - _EWMA_ALPHA) * self._decayed_wait(now)
            + _EWMA_ALPHA * wait_s
        )
        self._wait_noted_at = now

    def _decayed_wait(self, now: float) -> float:
        """The EWMA, decayed by the time since the last completion noted a
        sample — a burst's high waits must not keep degrading traffic
        after the queue has drained (completions stop, so only time can
        bring the estimate back down)."""
        if self._wait_ewma is None or self._wait_ewma <= 0.0:
            return 0.0
        age = max(now - self._wait_noted_at, 0.0)
        return self._wait_ewma * math.exp(-age * math.log(2) / self._half_life_s)

    def pressure(self, projected_s: float, now: float | None = None) -> float:
        """Effective queue wait over the budget (0 with shedding off).
        The effective wait is the max of the instantaneous projection,
        the measured queue-wait EWMA, and — when a lag source is wired —
        the decayed event-loop stall estimate."""
        if self.budget_s <= 0.0:
            return 0.0
        now = time.perf_counter() if now is None else now
        wait = max(projected_s, self._decayed_wait(now))
        if self._lag_source is not None:
            wait = max(wait, self._lag_source())
        return wait / self.budget_s

    def decide(self, projected_s: float) -> tuple[str, float]:
        """→ ``(decision, pressure)`` for a request seeing ``projected_s``
        of projected queue wait right now; decision is ``"admit"`` |
        ``"degrade"`` | ``"shed"``. The pressure that drove the decision
        rides along so callers report the value the band was judged on
        (re-computing it would both double the hot-path work and skew —
        the EWMA decays between calls)."""
        p = self.pressure(projected_s)
        if p < self.soft_ratio:
            return "admit", p
        if p < 1.0:
            span = 1.0 - self.soft_ratio
            frac = (p - self.soft_ratio) / span if span > 0 else 1.0
            return ("degrade" if self._rng.random() < frac else "admit"), p
        if p < self.hard_ratio:
            span = self.hard_ratio - 1.0
            frac = (p - 1.0) / span if span > 0 else 1.0
            return ("shed" if self._rng.random() < frac else "degrade"), p
        return "shed", p

    def retry_after_jittered_s(self) -> float:
        """Retry-After with bounded jitter: uniform on
        ``base·(1 ± retry_jitter)``, floored at 100 ms. A constant value
        re-synchronizes every shed client into one retry wave exactly one
        Retry-After later — the storm the shed was absorbing."""
        if self.retry_jitter <= 0.0:
            return self.retry_after_s
        spread = 1.0 + self.retry_jitter * (2.0 * self._rng.random() - 1.0)
        return max(self.retry_after_s * spread, 0.1)


class DeadlineExceeded(RuntimeError):
    """A request's deadline budget ran out before (or while) the device
    could answer it. The HTTP layer degrades this to the latency-budgeted
    popularity fallback with an ``X-KMLS-Degraded`` header — never a 500."""


class NoHealthyReplicas(RuntimeError):
    """Every serving replica is currently ejected by the circuit breaker
    (and no re-admission probe is due). Degraded like
    :class:`DeadlineExceeded` — total replica loss serves fallbacks, not
    errors."""


@dataclasses.dataclass
class _Pending:
    seeds: list[str]
    future: Future
    t_enqueue: float
    # perf_counter deadline (None = no budget) and how many times this
    # request has been re-dispatched after a replica failure
    deadline: float | None = None
    retries: int = 0
    # per-request TraceContext (observability.trace) riding the pipeline
    # so completion can record queue/device spans; None = untraced — the
    # default, costing nothing (tracing-off requests never construct one)
    trace: object | None = None


def _takes_deadline(engine) -> bool:
    """True when the engine's ``recommend_many_async`` accepts a
    ``deadline`` kwarg (the real engine does; test fakes with the bare
    legacy signature must keep working)."""
    try:
        sig = inspect.signature(engine.recommend_many_async)
    except (TypeError, ValueError, AttributeError):
        return False
    return "deadline" in sig.parameters


def _batch_deadline(batch: list[_Pending]) -> float | None:
    """The earliest pending deadline in the batch — the budget the whole
    device call (and any mesh hop under it) must fit inside."""
    deadlines = [p.deadline for p in batch if p.deadline is not None]
    return min(deadlines) if deadlines else None


class MicroBatcher:
    def __init__(
        self,
        engine: RecommendEngine,
        *,
        max_size: int = 32,
        window_ms: float = 2.0,
        max_inflight: int = 4,
        adaptive: bool = True,
        window_min_ms: float = 1.0,
        shed_queue_budget_ms: float = 0.0,
        shed_retry_after_s: float = 1.0,
        shed_soft_ratio: float = 0.6,
        shed_hard_ratio: float = 1.5,
        shed_retry_jitter: float = 0.5,
        eject_threshold: int = 0,
        probe_interval_s: float = 5.0,
        redispatch_max: int = 2,
        metrics=None,
        lag_monitor=None,
        forecaster=None,
    ):
        self.engine = engine
        self.max_size = max_size
        self.window_s = window_ms / 1e3
        # predictive serving (ISSUE 17): a serving.forecast
        # .TrafficForecaster, or None (the default — every forecast
        # touchpoint below is one is-None check, the zero-cost contract)
        self.forecaster = forecaster
        self.prewarm_total = 0
        self._prewarm_armed = True  # one pre-touch per ramp episode
        self.adaptive = adaptive
        self.window_min_s = min(window_min_ms / 1e3, self.window_s)
        self.shed_budget_s = shed_queue_budget_ms / 1e3
        self.shed_retry_after_s = shed_retry_after_s
        # runtime-health signal (observability.runtime.LoopLagMonitor):
        # folded into admission pressure so a host-scheduling stall the
        # queue projection can't see still escalates the ladder
        self.lag_monitor = lag_monitor
        self._admission = AdmissionController(
            self.shed_budget_s,
            soft_ratio=shed_soft_ratio,
            hard_ratio=shed_hard_ratio,
            retry_after_s=shed_retry_after_s,
            retry_jitter=shed_retry_jitter,
            lag_source=lag_monitor.lag_s if lag_monitor is not None else None,
        )
        self.metrics = metrics
        self.shed_total = 0
        self.degrade_total = 0  # OverloadDegraded raised at admission
        # replica health: consecutive-failure circuit breaker (0 = off —
        # the legacy propagate-the-error behavior, which fakes and
        # single-replica harnesses rely on)
        self.eject_threshold = eject_threshold
        self.probe_interval_s = probe_interval_s
        self.redispatch_max = max(0, redispatch_max)
        # deadline propagation (ISSUE 18): engines that accept a
        # ``deadline`` kwarg get the batch's earliest pending deadline
        # (the mesh stamps it on peer frames as remaining budget).
        # Detected once here so fakes with the bare legacy signature
        # keep working untouched.
        self._engine_takes_deadline = _takes_deadline(engine)
        self._consec_failures: dict[int, int] = {}
        self._ejected: dict[int, float] = {}  # idx -> perf_counter at eject
        self._probing: set[int] = set()  # half-open: one trial batch out
        self.eject_total = 0
        self.readmit_total = 0
        self.redispatch_total = 0
        # pipeline depth PER REPLICA; the aggregate bound is this times
        # the engine's live replica count (clamped: depth 0 would deadlock
        # the collector — "no pipelining" is depth 1, not 0)
        self.max_inflight = max(1, max_inflight)
        # priority queue of (priority, seq, pending): fresh arrivals ride
        # at priority 1, re-dispatched requests at 0 — they have waited
        # longest and must not starve behind new traffic (the async twin
        # front-inserts for the same reason). seq keeps FIFO within a
        # priority band and spares the heap from comparing _Pending.
        self._queue: "queue.PriorityQueue[tuple[int, int, _Pending]]" = (
            queue.PriorityQueue()
        )
        self._seq = itertools.count()
        # one completion lane PER REPLICA: (batch, finish_fn, t_dispatch)
        # triples awaiting their device results, FIFO within a lane — jax
        # executes dispatches in order per device, so completion order
        # matches per lane (but NOT across lanes; a single global lane
        # would head-of-line-block fast devices behind a slow one).
        # Lanes + their completer threads are created on first dispatch
        # to a replica index, by the collector thread only.
        self._completions: dict[int, "queue.Queue"] = {}
        # dispatched-but-uncompleted batches per replica, read by the
        # collector's idle-fast-path, the least-loaded pick, and the
        # shedding projection (a stale read is benign: worst case one
        # batch waits a window it didn't need, or one request
        # sheds/admits marginally early)
        self._inflight_by_replica: dict[int, int] = {}
        # rotation point for least-loaded ties: an all-idle replica set
        # must still spread consecutive batches across devices
        self._rr = 0
        # per-replica dispatch times of in-flight batches, FIFO: the
        # OLDEST entry's age is a live lower bound on the current device
        # time, which lets the shedding projection react to a
        # stalled/slow device before the first completion ever lands
        # (the EWMA alone is blind while cold)
        self._dispatch_times: dict[int, "collections.deque[float]"] = {}
        self._n_lock = threading.Lock()
        # collector blocks here while every replica's pipeline is full;
        # completions notify (replaces the old single-lane semaphore,
        # whose fixed depth couldn't track a replica count that appears
        # only at the engine's first load)
        self._pipe_cond = threading.Condition(self._n_lock)
        # controller state: a sliding window of arrival timestamps
        # (written under _rate_lock by every recommend() call) and a
        # device-batch-time EWMA (written by the completion thread only).
        # The window-mean gap, not a per-gap EWMA: closed-loop clients
        # arrive in bursts (a completed batch releases its waiters at
        # once) and a per-gap EWMA saturates near zero inside a burst,
        # collapsing the window and splitting the wave into undersized
        # batches; the mean over ~64 arrivals spans several bursts and
        # tracks the true rate.
        self._rate_lock = threading.Lock()
        self._arrivals: "collections.deque[float]" = collections.deque(
            maxlen=64
        )
        self._device_s_ewma: float | None = None
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True, name="kmls-microbatcher"
        )
        self._collector.start()

    # ---------- replica bookkeeping ----------

    def _n_replicas(self) -> int:
        return max(1, getattr(self.engine, "n_replicas", 1))

    def _total_inflight_locked(self) -> int:
        return sum(self._inflight_by_replica.values())

    def _n_healthy_locked(self, n: int) -> int:
        if self.eject_threshold <= 0:
            return n
        return n - sum(1 for i in self._ejected if i < n)

    def _n_effective_locked(self, n: int) -> int:
        """Capacity the shed projection and the idle fast path may COUNT
        ON — stricter than healthy (ISSUE 8 satellite): a replica inside
        a consecutive-failure run (breaker advancing but not yet
        tripped) is mid-incident and likely to fail its next batch too,
        and an ejected replica under a half-open probe is one trial
        batch, not a replica's worth of throughput (it stays in
        ``_ejected`` until the probe SUCCEEDS, so it is excluded here by
        construction). Counting either at full capacity over-admits
        exactly while the fleet is degraded — the old projection only
        discounted replicas already ejected."""
        if self.eject_threshold <= 0:
            return n
        return n - sum(
            1 for i in range(n)
            if i in self._ejected or self._consec_failures.get(i, 0) > 0
        )

    def _probe_due_locked(self, n: int, now: float) -> bool:
        return any(
            i < n and i not in self._probing
            and now - t >= self.probe_interval_s
            for i, t in self._ejected.items()
        )

    def ejected_replicas(self) -> list[int]:
        """Currently-ejected replica indices (readyz/metrics/tests)."""
        with self._n_lock:
            return sorted(self._ejected)

    def _pick_replica_locked(self, n: int) -> int:
        """Least-loaded HEALTHY replica index; ties broken by a rotating
        start so an idle fleet spreads consecutive batches instead of
        hammering replica 0. An ejected replica whose probe interval has
        elapsed gets ONE half-open trial batch instead. → -1 when every
        replica is ejected and no probe is due (total replica loss).
        Caller holds ``_n_lock``."""
        if self.eject_threshold > 0 and self._ejected:
            now = time.perf_counter()
            for i, t in self._ejected.items():
                if (
                    i < n and i not in self._probing
                    and now - t >= self.probe_interval_s
                ):
                    self._probing.add(i)
                    return i
        best, best_load = -1, None
        for off in range(n):
            i = (self._rr + off) % n
            if i in self._ejected:
                continue
            load = self._inflight_by_replica.get(i, 0)
            if best_load is None or load < best_load:
                best, best_load = i, load
        if best >= 0:
            self._rr = (best + 1) % n
        return best

    def _completion_lane(self, idx: int) -> "queue.Queue":
        """The collector is the only caller, so lane creation is
        single-writer; completer threads are per-lane and never die."""
        lane = self._completions.get(idx)
        if lane is None:
            lane = queue.Queue()
            self._completions[idx] = lane
            threading.Thread(
                target=self._complete_loop, args=(idx,), daemon=True,
                name=f"kmls-batch-completer-{idx}",
            ).start()
        return lane

    def per_replica_inflight(self) -> dict[int, int]:
        """Snapshot for tests/diagnostics."""
        with self._n_lock:
            return dict(self._inflight_by_replica)

    # ---------- admission ----------

    def projected_queue_wait_s(self) -> float:
        """Expected queue wait for a request enqueued NOW: batches ahead of
        it (in flight + already queued) times the per-batch device-time
        estimate, divided by the replica count — N devices drain the same
        queue N times faster, so the budget is against AGGREGATE capacity.
        The estimate is the completion EWMA, floored by the age of the
        oldest still-in-flight batch on any replica (a stalled device
        shows up in the age before any completion can move the EWMA).
        0 while there's no evidence at all — shedding needs measurements,
        not guesses."""
        now = time.perf_counter()
        device_s = self._device_s_ewma or 0.0
        n = self._n_replicas()
        with self._n_lock:
            inflight = self._total_inflight_locked()
            # neither ejected, half-open, nor mid-failure-run replicas
            # are capacity: shed capacity re-projects against the
            # replicas that can actually be EXPECTED to complete work,
            # so the budget tightens the moment a device starts failing,
            # not only once the breaker trips
            capacity = max(1, self._n_effective_locked(n))
            for lane in self._dispatch_times.values():
                if lane:
                    device_s = max(device_s, now - lane[0])
        if device_s <= 0.0:
            return 0.0
        queued_batches = self._queue.qsize() / max(self.max_size, 1)
        return (inflight + queued_batches) * device_s / capacity

    def utilization(self) -> float:
        """The HPA-compatible utilization signal (ISSUE 8), rendered at
        ``/metrics`` as the ``kmls_utilization`` gauge: the max of

        - **pipeline occupancy** — in-flight batches over the aggregate
          pipeline depth of the EFFECTIVE replica set (present even with
          shedding disabled), and
        - **queue pressure** — the admission controller's effective
          queue wait over the shed budget.

        1.0 means at capacity; shedding begins above it (the controller's
        degrade band starts at ``soft_ratio``), so an HPA target in the
        0.5–0.7 range scales the fleet out BEFORE any request degrades.
        Taking the max makes the signal rise with whichever saturates
        first: a device-bound fleet fills its pipelines, a queue-bound
        one grows its projected wait.

        With a forecaster attached (ISSUE 17, actuator b) the reactive
        max gains a bounded predictive lead: the reactive value scaled
        by the forecast growth ratio, clamped to [reactive, util_cap] —
        the HPA sees a ramp ``horizon_s`` early, the signal never drops
        below what is measured, and prediction alone never reports past
        the cap. The admission ladder does not read this value, so a
        wrong forecast can only over-provision, never shed."""
        reactive, led = self.utilization_parts()
        return led

    def utilization_parts(self) -> tuple[float, float]:
        """→ ``(reactive, forecast_led)``: the reactive occupancy/
        pressure max and the bounded forecast-led value actually
        exported as ``kmls_utilization`` (identical with no forecaster —
        the difference is the ``kmls_utilization_forecast`` gauge)."""
        n = self._n_replicas()
        with self._n_lock:
            inflight = self._total_inflight_locked()
            capacity = max(1, self._n_effective_locked(n))
        occupancy = inflight / (self.max_inflight * capacity)
        reactive = max(
            occupancy, self._admission.pressure(self.projected_queue_wait_s())
        )
        f = self.forecaster
        if f is None:
            return reactive, reactive
        return reactive, f.utilization_lead(reactive)

    def _arrival_gap_s(self) -> float | None:
        """Mean inter-arrival gap over the sliding window, or None before
        any rate evidence exists."""
        with self._rate_lock:
            n = len(self._arrivals)
            if n < 2:
                return None
            span = self._arrivals[-1] - self._arrivals[0]
        return span / (n - 1)

    def submit(
        self, seeds: list[str], deadline: float | None = None, trace=None,
    ) -> Future:
        """Non-blocking admission: shed-or-enqueue, → the request's
        Future. The async transport resolves it via a done-callback; the
        threaded transport blocks on it in :meth:`recommend`.
        ``deadline`` (perf_counter seconds) rides the pending entry
        through collection and dispatch; ``trace`` (a TraceContext, None
        when tracing is off) rides it so completion can record the
        queue/device spans."""
        now = time.perf_counter()
        with self._rate_lock:
            self._arrivals.append(now)
        f = self.forecaster
        if f is not None:
            # predictive serving (ISSUE 17): every arrival feeds the
            # rate/mix model BEFORE the shed decision — demand the
            # ladder turns away is still demand the forecast must see.
            # The forecaster keeps its own clock; its window math never
            # mixes with these perf_counter timestamps.
            f.observe(seeds)
        if self.eject_threshold > 0 and self._ejected:
            # unlocked pre-check on _ejected: the healthy common case must
            # not pay a contended _n_lock acquisition per request (same
            # benign stale-read pattern as faults._armed — worst case one
            # request's rejection shifts by a dispatch)
            with self._n_lock:
                n = self._n_replicas()
                if (
                    self._n_healthy_locked(n) == 0
                    and not self._probe_due_locked(n, now)
                ):
                    # total replica loss, nothing to probe yet: degrade NOW
                    # instead of letting the request rot in the queue
                    raise NoHealthyReplicas(
                        "all serving replicas ejected; next probe in "
                        f"<= {self.probe_interval_s:.1f}s"
                    )
        if self.shed_budget_s > 0:
            decision, pressure = self._admission.decide(
                self.projected_queue_wait_s()
            )
            if decision == "shed":
                with self._rate_lock:  # += from concurrent request threads
                    self.shed_total += 1
                if self.metrics is not None:
                    self.metrics.record_shed()
                # report the EFFECTIVE wait the decision was made on, not
                # the bare projection — an EWMA-driven shed right after a
                # burst would otherwise claim a sub-budget wait exceeded
                # the budget
                raise Overloaded(
                    self._admission.retry_after_jittered_s(),
                    pressure * self.shed_budget_s * 1e3,
                )
            if decision == "degrade":
                with self._rate_lock:
                    self.degrade_total += 1
                # the app layer answers from the popularity fallback
                # (record_degraded("overload") happens there, next to the
                # deadline/replica-loss reasons)
                raise OverloadDegraded(pressure)
        pending = _Pending(
            seeds=seeds, future=Future(), t_enqueue=now, deadline=deadline,
            trace=trace,
        )
        self._queue.put((1, next(self._seq), pending))
        return pending.future

    def recommend(
        self, seeds: list[str], timeout: float = 30.0,
        deadline: float | None = None, trace=None,
    ) -> tuple[list[str], str]:
        future = self.submit(seeds, deadline=deadline, trace=trace)
        if deadline is not None:
            timeout = max(deadline - time.perf_counter(), 0.0)
        try:
            return future.result(timeout=timeout)
        except FuturesTimeout:
            if deadline is not None:
                # in-flight overrun (a stalled device, a kernel delayed
                # past the budget): same degradation contract as a
                # queue-side expiry
                raise DeadlineExceeded(
                    f"request exceeded its deadline budget after "
                    f"{timeout * 1e3:.0f}ms in flight"
                ) from None
            raise

    # ---------- collection ----------

    def _busy_window_s(self, batch: list[_Pending], now: float) -> float:
        """Collection wait while a batch is in flight: the fixed ceiling,
        or (adaptive) the time the observed arrival rate needs to fill the
        rest of the batch — so a nearly-full batch stops waiting for one
        straggler; always capped so the batch leader's queue wait stays
        inside the shed budget.

        With a ramp forecast (ISSUE 17, actuator a) the window is sized
        from the PREDICTED arrival gap instead of the trailing measured
        one when the prediction is tighter: the trailing window-mean gap
        lags a ramp by construction, so without the forecast the batcher
        holds early-ramp batches open for stragglers that are in fact
        about to arrive in bulk — sizing to the incoming rate keeps
        batches full-and-moving through the onset instead of discovering
        the rate through queue growth. The forecast can only SHRINK the
        estimated gap (min), so the shed-budget cap and the window floor
        bind exactly as reactively."""
        window = self.window_s
        if self.adaptive:
            gap = self._forecast_gap_s(self._arrival_gap_s())
            if gap is not None:
                need = (self.max_size - len(batch)) * gap
                window = min(self.window_s, max(self.window_min_s, need))
        if self.shed_budget_s > 0:
            leader_wait = now - batch[0].t_enqueue
            window = min(window, max(0.0, self.shed_budget_s - leader_wait))
        return window

    def _forecast_gap_s(self, gap: float | None) -> float | None:
        """Fold the forecast into the arrival-gap estimate (shared by
        both twins — no batcher state touched): under a predicted ramp,
        the tighter of the measured and predicted gaps; otherwise the
        measured gap unchanged. Also drives the once-per-episode shape
        pre-touch, since this runs per batch collection — not per
        request — on both twins."""
        f = self.forecaster
        if f is None:
            return gap
        ramping = f.ramp_predicted()
        self._note_ramp(ramping)
        if not ramping:
            return gap
        predicted = f.expected_gap_s()
        if predicted == float("inf"):
            return gap
        return predicted if gap is None else min(gap, predicted)

    def _note_ramp(self, ramping: bool) -> None:
        """Once per ramp EPISODE (the signal clearing re-arms it), kick
        the engine's largest-shape pre-touch on a daemon thread — off
        both the collection loop and the event loop, because the touch
        blocks on a device dispatch."""
        if not ramping:
            self._prewarm_armed = True
            return
        if not self._prewarm_armed:
            return
        self._prewarm_armed = False
        touch = getattr(self.engine, "prewarm_touch", None)
        if touch is None:
            return

        def _touch() -> None:
            try:
                self.prewarm_total += touch()
            except Exception:
                logger.exception("predictive pre-touch failed (ignored)")

        threading.Thread(
            target=_touch, daemon=True, name="kmls-prewarm"
        ).start()

    def _collect_loop(self) -> None:
        while True:
            _, _, first = self._queue.get()  # block for the batch leader
            batch = [first]
            # sweep everything already waiting, without blocking
            while len(batch) < self.max_size:
                try:
                    batch.append(self._queue.get_nowait()[2])
                except queue.Empty:
                    break
            with self._n_lock:
                # idle fast path fires while ANY EFFECTIVE replica sits
                # idle: waiting only buys amortization when every
                # dependable device already has work (an ejected,
                # half-open, or mid-failure-run replica isn't capacity —
                # counting it here over-admitted during re-admission
                # probes, dispatching real traffic windowless onto a
                # replica still being auditioned)
                device_idle = self._total_inflight_locked() < max(
                    1, self._n_effective_locked(self._n_replicas())
                )
            if not device_idle:
                # all replicas busy: the window buys amortization — keep
                # collecting up to it (a full batch exits immediately)
                now = time.perf_counter()
                deadline = now + self._busy_window_s(batch, now)
                while len(batch) < self.max_size:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._queue.get(timeout=remaining)[2])
                    except queue.Empty:
                        break
            # bound the pipeline AGGREGATELY: past max_inflight
            # undispatched-but-queued device calls PER replica, block here
            # (requests keep queueing upstream and land in bigger batches
            # — backpressure, not failure).
            with self._pipe_cond:
                while (
                    self._total_inflight_locked()
                    >= self.max_inflight
                    * max(1, self._n_healthy_locked(self._n_replicas()))
                ):
                    self._pipe_cond.wait(timeout=1.0)
            # deadline check AFTER the capacity wait (which can block for
            # seconds under overload — exactly when deadlines matter): a
            # request already past its budget must not burn device time.
            # Outside the lock: expiry resolves futures, whose callbacks
            # take the cache's lock. The freed capacity can't be stolen —
            # this is the only dispatching thread; completions only add.
            batch = self._expire_overdue(batch)
            if not batch:
                continue
            # Reserve the least-loaded replica under the lock so the pick
            # and the accounting can't race a concurrent completion.
            with self._pipe_cond:
                n = self._n_replicas()
                if n > 1 or self.eject_threshold > 0:
                    idx = self._pick_replica_locked(n)
                else:
                    idx = 0
                if idx >= 0:
                    self._inflight_by_replica[idx] = (
                        self._inflight_by_replica.get(idx, 0) + 1
                    )
                    t_dispatch = time.perf_counter()
                    self._dispatch_times.setdefault(
                        idx, collections.deque()
                    ).append(t_dispatch)
            if idx < 0:
                # every replica ejected, no probe due: fail fast so the
                # app degrades instead of queueing dead work. Futures are
                # resolved OUTSIDE the lock — their done-callbacks (cache
                # singleflight retirement) take locks of their own.
                err = NoHealthyReplicas("all serving replicas ejected")
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(err)
                continue
            try:
                # the replica kwarg is passed only when there's a choice:
                # single-replica engines (fakes, the native host kernel)
                # keep the bare signature they always had; the deadline
                # kwarg only when the engine declared it (deadline
                # propagation across the mesh)
                kwargs = {}
                if n > 1:
                    kwargs["replica"] = idx
                if self._engine_takes_deadline:
                    kwargs["deadline"] = _batch_deadline(batch)
                finish = self.engine.recommend_many_async(
                    [p.seeds for p in batch], **kwargs
                )
            except Exception as exc:  # propagate, don't die
                with self._pipe_cond:
                    self._inflight_by_replica[idx] -= 1
                    lane = self._dispatch_times.get(idx)
                    if lane:
                        lane.pop()
                    self._pipe_cond.notify_all()
                self._on_replica_failure(idx, batch, exc)
                continue
            self._completion_lane(idx).put((batch, finish, t_dispatch))

    def _complete_loop(self, idx: int) -> None:
        lane = self._completions[idx]
        while True:
            batch, finish, t_dispatch = lane.get()
            try:
                results = finish()
                err = None
            except Exception as exc:  # propagate, don't die
                err = exc
            t_complete = time.perf_counter()
            # decrement BEFORE resolving futures: set_result unblocks the
            # client, and its immediate next request must not observe a
            # counter that still says busy (it would pay a full window
            # against an idle device — ping-pong traffic regression)
            device_s = t_complete - t_dispatch
            with self._pipe_cond:
                self._inflight_by_replica[idx] -= 1
                times = self._dispatch_times.get(idx)
                if times:
                    times.popleft()
                if err is None:
                    # EWMA updated under the lock: per-replica completer
                    # threads race here, and a torn read-modify-write
                    # would corrupt the shedding estimate
                    self._device_s_ewma = (
                        device_s if self._device_s_ewma is None
                        else (1 - _EWMA_ALPHA) * self._device_s_ewma
                        + _EWMA_ALPHA * device_s
                    )
                    self._note_replica_ok_locked(idx)
                self._pipe_cond.notify_all()
            if err is not None:
                self._on_replica_failure(idx, batch, err)
                continue
            # the batch LEADER's measured queue wait grounds the admission
            # controller's pressure estimate (it waited longest — the
            # worst wait an admitted request actually paid)
            self._admission.note_queue_wait(
                t_dispatch - batch[0].t_enqueue, now=t_complete
            )
            # span recording BEFORE the futures resolve: the finishing
            # thread (app layer) must observe a complete span list when
            # the result lands (TraceContext's documented ordering)
            # hedge outcome (ISSUE 18): the mesh finish() stamps its
            # won/lost/cancelled decision on itself; ride it onto every
            # traced request in the batch
            hedged = getattr(finish, "_kmls_hedge", None)
            for pending in batch:
                if pending.trace is not None:
                    pending.trace.span(
                        "queue", pending.t_enqueue, t_dispatch,
                        {"batch": len(batch)},
                    )
                    pending.trace.span(
                        "device", t_dispatch, t_complete, {"replica": idx},
                    )
                    if hedged is not None:
                        pending.trace.annotate("hedged", hedged)
            for pending, result in zip(batch, results):
                if not pending.future.done():  # deadline may have expired it
                    pending.future.set_result(result)
            if self.metrics is not None:
                for pending in batch:
                    self.metrics.record_attribution(
                        queue_wait_s=t_dispatch - pending.t_enqueue,
                        device_s=device_s,
                        e2e_s=t_complete - pending.t_enqueue,
                    )

    # ---------- replica health (threaded) ----------

    def _expire_overdue(self, batch: list[_Pending]) -> list[_Pending]:
        """Split out pendings whose deadline already passed; their futures
        fail with DeadlineExceeded (degraded at the app layer) and the
        survivors proceed to dispatch."""
        now = time.perf_counter()
        live: list[_Pending] = []
        for pending in batch:
            if pending.deadline is not None and now >= pending.deadline:
                if not pending.future.done():
                    pending.future.set_exception(DeadlineExceeded(
                        "deadline expired before dispatch"
                    ))
            else:
                live.append(pending)
        return live

    def _note_replica_ok_locked(self, idx: int) -> None:
        """Successful completion on ``idx`` (caller holds the lock): reset
        the breaker's consecutive-failure count; a succeeding half-open
        probe re-admits the replica."""
        if self.eject_threshold <= 0:
            return
        self._consec_failures[idx] = 0
        if idx in self._probing:
            self._probing.discard(idx)
            if self._ejected.pop(idx, None) is not None:
                self.readmit_total += 1
                if self.metrics is not None:
                    self.metrics.record_replica_readmitted()
                logger.info(
                    "replica %d re-admitted after successful probe", idx
                )

    def _on_replica_failure(
        self, idx: int, batch: list[_Pending], err: Exception
    ) -> None:
        """A batch failed on replica ``idx``: advance the circuit breaker
        (eject past the threshold; a failed half-open probe re-arms the
        timer), then RE-DISPATCH the batch's requests to the surviving
        replicas — bounded per-request retries — and only propagate the
        error to requests that are out of retries or out of replicas.
        Futures are resolved outside the lock (their done-callbacks take
        the cache's lock)."""
        healthy_other = False
        with self._pipe_cond:
            if self.eject_threshold > 0:
                if idx in self._probing:
                    # failed probe: stay ejected, timer re-armed
                    self._probing.discard(idx)
                    self._ejected[idx] = time.perf_counter()
                else:
                    fails = self._consec_failures.get(idx, 0) + 1
                    self._consec_failures[idx] = fails
                    if (
                        fails >= self.eject_threshold
                        and idx not in self._ejected
                    ):
                        self._ejected[idx] = time.perf_counter()
                        self.eject_total += 1
                        if self.metrics is not None:
                            self.metrics.record_replica_ejected()
                        logger.warning(
                            "replica %d ejected after %d consecutive "
                            "failures; re-admission probe every %.1fs",
                            idx, fails, self.probe_interval_s,
                        )
            n = self._n_replicas()
            # re-dispatch only with the breaker ON: disabled (threshold 0)
            # means the documented legacy contract — errors propagate
            # untouched, no silent retries tripling device work
            healthy_other = self.eject_threshold > 0 and any(
                i != idx and i not in self._ejected for i in range(n)
            )
            retriable: list[_Pending] = []
            dead: list[_Pending] = []
            for pending in batch:
                if healthy_other and pending.retries < self.redispatch_max:
                    pending.retries += 1
                    retriable.append(pending)
                else:
                    dead.append(pending)
            if retriable:
                self.redispatch_total += len(retriable)
                if self.metrics is not None:
                    self.metrics.record_redispatch(len(retriable))
        for pending in retriable:
            # priority 0: ahead of fresh arrivals — these have waited
            # longest (mirrors the async twin's front-insert)
            self._queue.put((0, next(self._seq), pending))
        for pending in dead:
            if not pending.future.done():
                pending.future.set_exception(err)


class AsyncMicroBatcher:
    """Loop-native twin of :class:`MicroBatcher` for the asyncio transport
    (serving/aioserver.py).

    Why a twin instead of putting the threaded pipeline behind the event
    loop: per-request cross-thread handoffs are exactly what the async
    front end exists to avoid. Profiled on a 2-core host, the threaded
    batcher driven from the loop spent most of its time re-acquiring the
    GIL — four thread hops per request (loop → collector → completer →
    per-request ``call_soon_threadsafe``), ~1.8 ms CPU each, capping the
    whole server near 550 QPS. Here admission, collection, and future
    resolution all run ON the loop (plain ints, no locks), the batch
    compute runs as ONE executor task, and the loop wakes once per BATCH.

    Policy-identical to :class:`MicroBatcher` — idle fast path, adaptive
    deadline-aware window, shed-before-budget, least-loaded multi-replica
    dispatch, queue/device attribution — with the same knobs; the policy
    methods mirror their threaded namesakes line for line, minus the
    locking (all state here is loop-confined: plain ints and dicts).
    """

    def __init__(
        self,
        engine: RecommendEngine,
        *,
        max_size: int = 32,
        window_ms: float = 2.0,
        max_inflight: int = 4,
        adaptive: bool = True,
        window_min_ms: float = 1.0,
        shed_queue_budget_ms: float = 0.0,
        shed_retry_after_s: float = 1.0,
        shed_soft_ratio: float = 0.6,
        shed_hard_ratio: float = 1.5,
        shed_retry_jitter: float = 0.5,
        eject_threshold: int = 0,
        probe_interval_s: float = 5.0,
        redispatch_max: int = 2,
        metrics=None,
        lag_monitor=None,
        forecaster=None,
    ):
        from concurrent.futures import ThreadPoolExecutor

        self.engine = engine
        self.max_size = max_size
        self.max_inflight = max(1, max_inflight)  # per replica
        # predictive serving (ISSUE 17), mirroring MicroBatcher: None =
        # every touchpoint is one is-None check (the zero-cost contract)
        self.forecaster = forecaster
        self.prewarm_total = 0
        self._prewarm_armed = True
        self.window_s = window_ms / 1e3
        self.adaptive = adaptive
        self.window_min_s = min(window_min_ms / 1e3, self.window_s)
        self.shed_budget_s = shed_queue_budget_ms / 1e3
        self.shed_retry_after_s = shed_retry_after_s
        # runtime health: the inline native path computes ON the loop, so
        # a stalled kernel blocks the loop itself and backpressure piles
        # into the socket backlog where the queue projection is blind
        # (the PR 8 postmortem). The inline branch reports its measured
        # in-line compute time here — the synchronous ground truth — and
        # the controller folds the decayed peak into pressure.
        self.lag_monitor = lag_monitor
        self._admission = AdmissionController(
            self.shed_budget_s,
            soft_ratio=shed_soft_ratio,
            hard_ratio=shed_hard_ratio,
            retry_after_s=shed_retry_after_s,
            retry_jitter=shed_retry_jitter,
            lag_source=lag_monitor.lag_s if lag_monitor is not None else None,
        )
        self.metrics = metrics
        self.shed_total = 0
        self.degrade_total = 0
        # replica health (mirrors MicroBatcher; loop-confined, no locks)
        self.eject_threshold = eject_threshold
        self.probe_interval_s = probe_interval_s
        self.redispatch_max = max(0, redispatch_max)
        # deadline propagation (ISSUE 18): engines that accept a
        # ``deadline`` kwarg get the batch's earliest pending deadline
        # (the mesh stamps it on peer frames as remaining budget).
        # Detected once here so fakes with the bare legacy signature
        # keep working untouched.
        self._engine_takes_deadline = _takes_deadline(engine)
        self._consec_failures: dict[int, int] = {}
        self._ejected: dict[int, float] = {}
        self._probing: set[int] = set()
        self.eject_total = 0
        self.readmit_total = 0
        self.redispatch_total = 0
        self._pending: list[_Pending] = []
        self._inflight_by_replica: dict[int, int] = {}
        self._rr = 0
        self._dispatch_times: dict[int, "collections.deque[float]"] = {}
        self._arrivals: "collections.deque[float]" = collections.deque(maxlen=64)
        self._device_s_ewma: float | None = None
        self._flush_handle = None
        # the loop this batcher is confined to, recorded on first submit:
        # off-loop callers that must reach submit() — the app's post-delta
        # predictive pre-fetch (ISSUE 17) — hop here via
        # call_soon_threadsafe instead of calling in from their thread
        self._loop = None
        # finish() blocks (device transfer, or the GIL-releasing native
        # call) — it must run off-loop; pool depth = aggregate pipeline
        # depth. The replica count isn't known until the engine's first
        # load, so the pool is sized for the largest realistic replica set
        # (threads spawn on demand — headroom costs nothing) and the
        # ADMISSION bound in _flush clamps to this same number: a batch
        # the pool couldn't run concurrently must not be admitted, or its
        # executor queue wait would masquerade as device time in the
        # attribution and the shedding EWMA.
        self._executor_workers = min(32, self.max_inflight * 8)
        self._executor = ThreadPoolExecutor(
            max_workers=self._executor_workers,
            thread_name_prefix="kmls-abatch",
        )

    # ---------- replica bookkeeping (mirrors MicroBatcher, no locks) ----

    def _n_replicas(self) -> int:
        return max(1, getattr(self.engine, "n_replicas", 1))

    def _total_inflight(self) -> int:
        return sum(self._inflight_by_replica.values())

    def _n_healthy(self, n: int) -> int:
        if self.eject_threshold <= 0:
            return n
        return n - sum(1 for i in self._ejected if i < n)

    def _n_effective(self, n: int) -> int:
        """Mirrors MicroBatcher._n_effective_locked: capacity excludes
        ejected, half-open-probing, AND mid-failure-run replicas."""
        if self.eject_threshold <= 0:
            return n
        return n - sum(
            1 for i in range(n)
            if i in self._ejected or self._consec_failures.get(i, 0) > 0
        )

    def _probe_due(self, n: int, now: float) -> bool:
        return any(
            i < n and i not in self._probing
            and now - t >= self.probe_interval_s
            for i, t in self._ejected.items()
        )

    def ejected_replicas(self) -> list[int]:
        return sorted(self._ejected)

    def _pick_replica(self, n: int) -> int:
        """Mirrors MicroBatcher._pick_replica_locked: half-open probe for
        an ejected replica whose interval elapsed, else least-loaded
        healthy, else -1 (total replica loss)."""
        if self.eject_threshold > 0 and self._ejected:
            now = time.perf_counter()
            for i, t in self._ejected.items():
                if (
                    i < n and i not in self._probing
                    and now - t >= self.probe_interval_s
                ):
                    self._probing.add(i)
                    return i
        best, best_load = -1, None
        for off in range(n):
            i = (self._rr + off) % n
            if i in self._ejected:
                continue
            load = self._inflight_by_replica.get(i, 0)
            if best_load is None or load < best_load:
                best, best_load = i, load
        if best >= 0:
            self._rr = (best + 1) % n
        return best

    # ---------- policy (mirrors MicroBatcher, loop-confined) ----------

    def projected_queue_wait_s(self) -> float:
        now = time.perf_counter()
        device_s = self._device_s_ewma or 0.0
        for lane in self._dispatch_times.values():
            if lane:
                device_s = max(device_s, now - lane[0])
        if device_s <= 0.0:
            return 0.0
        queued_batches = len(self._pending) / max(self.max_size, 1)
        return (
            (self._total_inflight() + queued_batches)
            * device_s / max(1, self._n_effective(self._n_replicas()))
        )

    def utilization(self) -> float:
        """Mirrors MicroBatcher.utilization (loop-confined, no locks),
        forecast lead term included — see the threaded twin's contract."""
        reactive, led = self.utilization_parts()
        return led

    def utilization_parts(self) -> tuple[float, float]:
        """Mirrors MicroBatcher.utilization_parts."""
        capacity = max(1, self._n_effective(self._n_replicas()))
        occupancy = self._total_inflight() / (self.max_inflight * capacity)
        reactive = max(
            occupancy, self._admission.pressure(self.projected_queue_wait_s())
        )
        f = self.forecaster
        if f is None:
            return reactive, reactive
        return reactive, f.utilization_lead(reactive)

    def _arrival_gap_s(self) -> float | None:
        n = len(self._arrivals)
        if n < 2:
            return None
        return (self._arrivals[-1] - self._arrivals[0]) / (n - 1)

    # the forecast fold and per-episode pre-touch are state-light and
    # lock-free, so the twins SHARE one implementation instead of
    # mirroring it (the pre-touch daemon thread is equally legal from
    # the event loop — it never blocks the caller)
    _forecast_gap_s = MicroBatcher._forecast_gap_s
    _note_ramp = MicroBatcher._note_ramp

    def _busy_window_s(self, now: float) -> float:
        window = self.window_s
        if self.adaptive:
            gap = self._forecast_gap_s(self._arrival_gap_s())
            if gap is not None:
                need = (self.max_size - len(self._pending)) * gap
                window = min(self.window_s, max(self.window_min_s, need))
        if self.shed_budget_s > 0 and self._pending:
            leader_wait = now - self._pending[0].t_enqueue
            window = min(window, max(0.0, self.shed_budget_s - leader_wait))
        return window

    # ---------- admission (loop thread only) ----------

    def submit(
        self, seeds: list[str], deadline: float | None = None, trace=None,
    ) -> "asyncio.Future":
        import asyncio

        loop = asyncio.get_running_loop()
        if self._loop is None:
            self._loop = loop
        now = time.perf_counter()
        self._arrivals.append(now)
        f = self.forecaster
        if f is not None:
            # mirrors the threaded twin: demand is observed before the
            # shed decision, on the forecaster's own clock
            f.observe(seeds)
        if self.eject_threshold > 0 and self._ejected:
            n = self._n_replicas()
            if self._n_healthy(n) == 0 and not self._probe_due(n, now):
                raise NoHealthyReplicas(
                    "all serving replicas ejected; next probe in "
                    f"<= {self.probe_interval_s:.1f}s"
                )
        if self.shed_budget_s > 0:
            decision, pressure = self._admission.decide(
                self.projected_queue_wait_s()
            )
            if decision == "shed":
                self.shed_total += 1
                if self.metrics is not None:
                    self.metrics.record_shed()
                # effective wait, mirroring the threaded twin
                raise Overloaded(
                    self._admission.retry_after_jittered_s(),
                    pressure * self.shed_budget_s * 1e3,
                )
            if decision == "degrade":
                self.degrade_total += 1
                raise OverloadDegraded(pressure)
        future = loop.create_future()
        pending = _Pending(
            seeds=seeds, future=future, t_enqueue=now, deadline=deadline,
            trace=trace,
        )
        self._pending.append(pending)
        if deadline is not None:
            # in-flight overruns included: the timer fires regardless of
            # where the request is stuck (queue, device, executor) and the
            # app degrades the DeadlineExceeded to a fallback answer.
            # Cancelled on completion — at QPS scale an uncancelled
            # ~1s timer per sub-ms answer piles thousands of live handles
            # (each pinning its pending) into the loop's heap.
            handle = loop.call_later(
                max(deadline - now, 0.0), self._expire, pending
            )
            future.add_done_callback(lambda _f: handle.cancel())
        if len(self._pending) >= self.max_size:
            self._flush(loop)  # full batch: dispatch now
        elif getattr(self.engine, "host_kernel_active", False):
            # inline mode (native host kernel, computed ON the loop):
            # there is no pipeline to keep busy, so amortization comes
            # from a short scheduled window — but only when the observed
            # rate says more arrivals will actually land inside it;
            # sparse traffic dispatches immediately
            if self._flush_handle is None:
                gap = self._arrival_gap_s()
                window = self._busy_window_s(now)
                if gap is None or gap >= window or window <= 0.0:
                    self._flush(loop)
                else:
                    self._flush_handle = loop.call_later(
                        window, self._flush, loop
                    )
        elif self._total_inflight() < max(
            1, self._n_effective(self._n_replicas())
        ):
            # idle fast path: some EFFECTIVE replica is free (ejected,
            # half-open, and mid-failure-run replicas aren't capacity)
            self._flush(loop)
        elif self._flush_handle is None:
            self._flush_handle = loop.call_later(
                self._busy_window_s(now), self._flush, loop
            )
        return future

    # ---------- dispatch / completion (loop thread only) ----------

    def _expire(self, pending: _Pending) -> None:
        """Deadline timer callback: fail the future (the app degrades it)
        unless the answer already landed. A later set_result is guarded by
        the done() checks in _flush/_resolve."""
        if not pending.future.done():
            pending.future.set_exception(
                DeadlineExceeded("request exceeded its deadline budget")
            )

    def _flush(self, loop) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        # expired/cancelled requests must not burn device time: their
        # futures are already resolved (the _expire timer ran)
        if any(p.future.done() for p in self._pending):
            self._pending = [p for p in self._pending if not p.future.done()]
        if not self._pending:
            return
        n = self._n_replicas()
        if self._total_inflight() >= min(
            self.max_inflight * max(1, self._n_healthy(n)),
            self._executor_workers,
        ):
            # aggregate pipeline full — or past what the executor pool
            # can actually run concurrently: the next completion
            # re-flushes and pending requests pile into a bigger batch
            # (backpressure, not failure)
            return
        batch = self._pending[: self.max_size]
        del self._pending[: len(batch)]
        idx = self._pick_replica(n) if (n > 1 or self.eject_threshold > 0) else 0
        if idx < 0:
            # total replica loss, no probe due: degrade, don't dispatch
            err = NoHealthyReplicas("all serving replicas ejected")
            for pending in batch:
                if not pending.future.done():
                    pending.future.set_exception(err)
            return
        t_dispatch = time.perf_counter()
        try:
            # replica kwarg only when there's a choice — single-replica
            # engines (fakes, native host kernel) keep the bare
            # signature; deadline only when the engine declared it
            # (mesh deadline propagation, mirroring the threaded twin)
            kwargs = {}
            if n > 1:
                kwargs["replica"] = idx
            if self._engine_takes_deadline:
                kwargs["deadline"] = _batch_deadline(batch)
            finish = self.engine.recommend_many_async(
                [p.seeds for p in batch], **kwargs
            )
        except Exception as exc:  # propagate, don't die
            self._on_replica_failure(idx, batch, exc, loop)
            if self._pending:
                loop.call_soon(self._flush, loop)
            return
        self._inflight_by_replica[idx] = (
            self._inflight_by_replica.get(idx, 0) + 1
        )
        self._dispatch_times.setdefault(
            idx, collections.deque()
        ).append(t_dispatch)
        if getattr(self.engine, "host_kernel_active", False) and not any(
            p.deadline is not None for p in batch
        ):
            # inline: the native kernel is a sub-ms GIL-releasing C call —
            # running it here costs less than one thread handoff, and the
            # whole request lifecycle stays on a single thread. NOT taken
            # when any request carries a deadline: inline blocks the LOOP,
            # so a genuinely stalled kernel would freeze the expiry timers
            # (and every other connection) for exactly as long as the
            # stall — the executor hop keeps the loop free to degrade
            # on time.
            try:
                outcome = (finish(), None)
            except Exception as exc:
                outcome = (None, exc)
            if self.lag_monitor is not None:
                # direct stall note: this finish() just blocked the loop
                # for exactly this long — report it NOW (the drift tick
                # only sees it one loop iteration later), so a 200 ms
                # kernel stall escalates admission before the next
                # request is even parsed
                self.lag_monitor.note(time.perf_counter() - t_dispatch)
            self._resolve(batch, outcome, t_dispatch, loop, idx, finish)
            return

        def run_finish():
            try:
                return finish(), None
            except Exception as exc:
                return None, exc

        task = self._executor.submit(run_finish)
        task.add_done_callback(
            lambda f: loop.call_soon_threadsafe(
                self._complete, batch, f, t_dispatch, loop, idx, finish
            )
        )
        if self._pending:
            # overflow past max_size: keep draining
            loop.call_soon(self._flush, loop)

    def _complete(
        self, batch, task, t_dispatch: float, loop, idx: int, finish=None
    ) -> None:
        # kmls-verify: allow[loopblock] — scheduled via
        # call_soon_threadsafe from the executor task's done-callback,
        # so the task is complete and result() returns immediately
        self._resolve(batch, task.result(), t_dispatch, loop, idx, finish)

    def _resolve(
        self, batch, outcome, t_dispatch: float, loop, idx: int, finish=None
    ) -> None:
        results, err = outcome
        t_complete = time.perf_counter()
        self._inflight_by_replica[idx] -= 1
        lane = self._dispatch_times.get(idx)
        if lane:
            lane.popleft()
        if err is not None:
            self._on_replica_failure(idx, batch, err, loop)
        else:
            self._note_replica_ok(idx)
            device_s = t_complete - t_dispatch
            self._device_s_ewma = (
                device_s if self._device_s_ewma is None
                else (1 - _EWMA_ALPHA) * self._device_s_ewma
                + _EWMA_ALPHA * device_s
            )
            # leader's measured queue wait grounds the admission pressure
            # (mirrors the threaded completer)
            if batch:
                self._admission.note_queue_wait(
                    t_dispatch - batch[0].t_enqueue, now=t_complete
                )
            # spans recorded before the futures resolve (mirrors the
            # threaded completer's ordering contract)
            hedged = getattr(finish, "_kmls_hedge", None)
            for pending in batch:
                if pending.trace is not None:
                    pending.trace.span(
                        "queue", pending.t_enqueue, t_dispatch,
                        {"batch": len(batch)},
                    )
                    pending.trace.span(
                        "device", t_dispatch, t_complete, {"replica": idx},
                    )
                    if hedged is not None:
                        pending.trace.annotate("hedged", hedged)
            for pending, result in zip(batch, results):
                if not pending.future.done():
                    pending.future.set_result(result)
            if self.metrics is not None:
                for pending in batch:
                    self.metrics.record_attribution(
                        queue_wait_s=t_dispatch - pending.t_enqueue,
                        device_s=device_s,
                        e2e_s=t_complete - pending.t_enqueue,
                    )
        if self._pending and self._flush_handle is None:
            # mirror the threaded collector waking on a completion: the
            # freed pipeline slot dispatches the waiting batch immediately
            self._flush(loop)

    # ---------- replica health (loop-confined twin of the threaded
    # helpers; no locks — all state is loop-owned) ----------

    def _note_replica_ok(self, idx: int) -> None:
        if self.eject_threshold <= 0:
            return
        self._consec_failures[idx] = 0
        if idx in self._probing:
            self._probing.discard(idx)
            if self._ejected.pop(idx, None) is not None:
                self.readmit_total += 1
                if self.metrics is not None:
                    self.metrics.record_replica_readmitted()
                logger.info(
                    "replica %d re-admitted after successful probe", idx
                )

    def _on_replica_failure(self, idx: int, batch, err, loop) -> None:
        if self.eject_threshold > 0:
            if idx in self._probing:
                # failed probe: stay ejected, timer re-armed
                self._probing.discard(idx)
                self._ejected[idx] = time.perf_counter()
            else:
                fails = self._consec_failures.get(idx, 0) + 1
                self._consec_failures[idx] = fails
                if fails >= self.eject_threshold and idx not in self._ejected:
                    self._ejected[idx] = time.perf_counter()
                    self.eject_total += 1
                    if self.metrics is not None:
                        self.metrics.record_replica_ejected()
                    logger.warning(
                        "replica %d ejected after %d consecutive failures; "
                        "re-admission probe every %.1fs",
                        idx, fails, self.probe_interval_s,
                    )
        n = self._n_replicas()
        # breaker off = legacy propagate-the-error contract (see the
        # threaded twin)
        healthy_other = self.eject_threshold > 0 and any(
            i != idx and i not in self._ejected for i in range(n)
        )
        retriable: list[_Pending] = []
        dead: list[_Pending] = []
        for pending in batch:
            if pending.future.done():  # deadline timer beat us to it
                continue
            if healthy_other and pending.retries < self.redispatch_max:
                pending.retries += 1
                retriable.append(pending)
            else:
                dead.append(pending)
        if retriable:
            self.redispatch_total += len(retriable)
            if self.metrics is not None:
                self.metrics.record_redispatch(len(retriable))
            # front of the queue: re-dispatched requests have waited
            # longest and must not starve behind fresh arrivals
            self._pending[:0] = retriable
            loop.call_soon(self._flush, loop)
        for pending in dead:
            pending.future.set_exception(err)
