"""Epoch-keyed recommendation cache with singleflight miss collapsing.

Rule lookup is deterministic per published bundle: the same seed set
against the same rule generation always yields the same answer (the
static-fallback path included — its sampling seed is a stable digest of
the seed tracks; the hybrid rule∪embedding merge too — its blend is pure
float arithmetic with a deterministic tie order, so cached hybrid
answers are exactly as replayable as rule answers). Real playlist-seed traffic is Zipf-skewed, so a bounded
LRU in front of the batcher turns the hot head of the request
distribution into dictionary lookups — the same shape of win prefix/KV
caching delivers in inference serving stacks.

Correctness comes from the key, not from invalidation machinery: entries
are keyed by ``(bundle_epoch, seed-set generation, canonicalized seed
set)``, and the engine bumps ``bundle_epoch`` on every successful hot
swap AFTER publishing the new bundle (see the ordering contract in
engine.load). A post-swap lookup therefore constructs a key no stale
entry can match — the whole cache is invalidated wholesale, for free,
without touching it. Stale old-epoch entries age out of the LRU
naturally.

**Selective invalidation** (continuous freshness, ISSUE 10) extends the
same key-freshness argument to delta applies, which deliberately do NOT
bump the epoch (a delta touches a handful of vocab rows; wholesale
invalidation would re-compute every hot head for nothing): the cache
keeps a per-seed-name GENERATION counter, and a key's generation
component is the sum over its seeds. ``invalidate_seeds(touched)`` bumps
the touched names' generations AFTER the engine swapped the patched
bundle in — exactly the epoch ordering contract in miniature — so a
post-invalidation lookup whose seeds intersect the touched set
constructs a key that no stale entry (and no in-flight pre-delta
leader's eventual store) can ever match, while untouched keys keep their
generation, their entries, and their hit ratio. Unreachable entries are
also deleted eagerly (one walk under the lock) so the LRU capacity isn't
squatted by dead keys, and the walk's count feeds
``kmls_cache_invalidated_keys_total``.

Canonicalization: answers are order-independent for seed sets within the
kernel's seed cap (the score merge is a max over seeds; the fallback
digest sorts internally), so the key sorts the seeds — requests that
permute the same seeds share one entry. Duplicates are KEPT (the fallback
digest distinguishes ``["a", "a"]`` from ``["a"]``), and oversized seed
lists keep their original order (truncation to the cap is positional, so
order changes the answer there).

Singleflight: concurrent identical misses collapse onto ONE in-flight
future — the first requester dispatches to the batcher, later identical
requests attach to the same future instead of duplicating device work.
Works for both transports because both speak futures (``concurrent
.futures.Future`` from the threaded batcher, ``asyncio.Future`` from the
loop-native one); the cache never blocks on a future itself.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable


class RecommendCache:
    """Bounded LRU of ``key → (songs, source)`` plus the in-flight
    singleflight table. Thread-safe; counters are Prometheus-monotonic
    (rendered by serving/metrics.py)."""

    def __init__(self, max_entries: int = 8192):
        self.max_entries = max(1, max_entries)
        self._lru: "OrderedDict[tuple, tuple[list[str], str]]" = OrderedDict()
        self._inflight: dict[tuple, object] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.singleflight_joins = 0
        # selective invalidation (ISSUE 10): per-seed-name generation
        # counters — a key's generation component is the sum over its
        # seeds, so bumping one name makes every key containing it
        # unconstructable. Bounded by the vocabulary; only names a delta
        # ever touched have entries.
        self._name_gen: dict[str, int] = {}
        self.selective_invalidations = 0
        self.invalidated_keys = 0

    # ---------- keys ----------

    @staticmethod
    def key(epoch: int, seeds: list[str], seed_cap: int) -> tuple:
        """Generation-less key (legacy/static form): ``(epoch, 0,
        canonical seed tuple)``. Sorted (order-free answers) with
        duplicates kept; seed lists past the kernel cap keep request
        order because truncation there is positional. Cache-owning
        callers use :meth:`make_key`, which adds the live seed-set
        generation component."""
        core = tuple(sorted(seeds)) if len(seeds) <= seed_cap else tuple(seeds)
        return (epoch, 0, core)

    def make_key(self, epoch: int, seeds: list[str], seed_cap: int) -> tuple:
        """→ ``(epoch, seed-set generation, canonical seed tuple)``. The
        generation sum is monotone non-decreasing per seed set and
        strictly increases when any member name is invalidated, so a
        stale entry's key can never be reconstructed. Lock-free reads: a
        lookup racing a bump reads the old generation, which is exactly
        equivalent to having looked up before the bump."""
        core = tuple(sorted(seeds)) if len(seeds) <= seed_cap else tuple(seeds)
        gens = self._name_gen
        if not gens:
            return (epoch, 0, core)
        get = gens.get
        gen = 0
        for s in core:
            gen += get(s, 0)
        return (epoch, gen, core)

    def invalidate_seeds(self, touched: set[str]) -> int:
        """Selectively invalidate every key whose seed set intersects
        ``touched``: bump the touched names' generations (making stale
        keys unconstructable — the correctness half) and eagerly delete
        the now-unreachable LRU entries (the capacity half). Call AFTER
        the new bundle reference is live, mirroring the epoch ordering
        contract. → entries deleted."""
        if not touched:
            return 0
        with self._lock:
            for name in touched:
                self._name_gen[name] = self._name_gen.get(name, 0) + 1
            doomed = [
                k for k in self._lru
                if any(s in touched for s in k[-1])
            ]
            for k in doomed:
                del self._lru[k]
            self.selective_invalidations += 1
            self.invalidated_keys += len(doomed)
        return len(doomed)

    # ---------- LRU ----------

    def get(self, key: tuple) -> tuple[list[str], str] | None:
        with self._lock:
            value = self._lru.get(key)
            if value is None:
                self.misses += 1
                return None
            self._lru.move_to_end(key)
            self.hits += 1
            return value

    def contains(self, key: tuple) -> bool:
        """Presence peek WITHOUT hit/miss accounting or LRU recency —
        for the predictive pre-fetch (ISSUE 17), which must skip
        still-cached keys without polluting the hit-ratio the bench and
        the affinity measurement judge real traffic by."""
        with self._lock:
            return key in self._lru

    def put(self, key: tuple, value: tuple[list[str], str]) -> None:
        # gray-failure spine (ISSUE 18): a "degraded:<reason>" source is
        # an answered-but-partial result (e.g. a mesh merge that dropped
        # a straggler slab) — storing it would pin the partial answer
        # for the key's whole cache lifetime, long past the one slow
        # moment that produced it. Degraded answers are served, never
        # remembered.
        if value[1].startswith("degraded:"):
            return
        with self._lock:
            self._lru[key] = value
            self._lru.move_to_end(key)
            while len(self._lru) > self.max_entries:
                self._lru.popitem(last=False)
                self.evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    # ---------- singleflight ----------

    def join_or_lead(
        self, key: tuple, submit: Callable[[], Any]
    ) -> tuple[Any, bool]:
        """→ ``(future, joined)``. Atomically joins the in-flight future
        for ``key``, or installs ``submit()``'s future as the new leader.
        ``submit`` may raise (e.g. the batcher's Overloaded shed) — then
        nothing is installed and followers are unaffected. The leader must
        arrange :meth:`finish` to run when its future completes."""
        with self._lock:
            future = self._inflight.get(key)
            if future is not None:
                self.singleflight_joins += 1
                return future, True
            # submit() under the lock keeps lead-election atomic; the
            # batcher's admission path never calls back into the cache,
            # so the lock order is acyclic
            future = submit()
            self._inflight[key] = future
            return future, False

    def finish(self, key: tuple, future: Any) -> None:
        """Leader's done-callback: retire the in-flight entry and store
        the answer on success (failures — sheds included — cache nothing)."""
        with self._lock:
            self._inflight.pop(key, None)
        try:
            if future.cancelled() or future.exception() is not None:
                return
            result = future.result()
        except Exception:
            return
        self.put(key, result)
