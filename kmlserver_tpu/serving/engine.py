"""The online recommendation engine: HBM-resident rule tensors, a jitted
lookup kernel, and a double-buffered hot swap driven by the reference's
polling protocol.

Reference behaviors replicated (rest_api/app/main.py):

- artifact loading (:52-80): ``best_tracks.pickle`` is required — but where
  the reference raises and crash-loops on a fresh/empty PVC (its report lists
  this as risk #2), this engine fails SOFT: ``load()`` returns False and the
  readiness endpoint gates traffic until the first mining run lands.
- staleness detection (:82-97): compare the cached token against
  ``last_execution.txt`` content; missing file counts as stale; the cached
  value doubles as the response's ``model_date``.
- reload loop (:100-122): first load at startup + periodic re-check; a
  reload builds a complete new :class:`RuleBundle` and swaps ONE reference —
  in-flight requests keep the old bundle (the double-buffer makes the
  reference's acknowledged read-mid-swap race structurally impossible).
- lookup (:224-254): seeds filtered by rule-key membership (frequent
  singletons with empty rows ARE members); no known seed → deterministic
  static fallback (:205-222); otherwise the batched device kernel
  (ops/serve.py) does the max-merge + top-k.
- the static fallback's determinism (:214): the reference seeds ``random``
  with ``hash(tuple(sorted(seeds)))``, which is process-salted in modern
  Python (deterministic only within one process); here the seed is a stable
  blake2 digest so all replicas agree — a documented deliberate fix.

The engine prefers the tensor-native npz artifact (straight ``device_put``)
and falls back to the reference-format pickle, so it can serve a PVC
populated by either the rebuild's or the reference's mining job.

Multi-device serving: a publication builds one :class:`RuleBundle` replica
per serving device (``KMLS_SERVE_DEVICES``; rule tensors ``device_put`` to
each device, every shape bucket warmed per replica) and swaps the whole
set atomically. ``recommend_many_async(..., replica=i)`` executes a batch
on replica ``i``'s device — the batcher's least-loaded dispatcher uses
this to run concurrent batches on different devices instead of
serializing them on one in-order execution queue. ``bundle_epoch`` is the
monotonic publication counter the recommendation cache keys on.

Model-parallel serving (``KMLS_MODEL_LAYOUT=sharded|auto``): instead of
one full replica per device, a publication can build ONE logical bundle
whose rule tensors are vocab-sharded across every serving device
(``NamedSharding``; ``ops/serve.py sharded_recommend_fn``) — per-device
HBM holds ``V/S`` rule rows, so the servable catalog scales with the
mesh rather than capping at a single device. ``auto`` measures the
loaded tensor bytes against ``KMLS_DEVICE_BUDGET_BYTES`` and shards only
when a replica would not fit (parallel/layout.py is the one copy of
that decision, shared with the mining side). The sharded bundle presents
as one replica to the dispatcher, pre-warms its kernel over the same
(batch, length) bucket grid — zero compiles post-publish, same contract
— and answers bit-identically to the replicated layout (pinned by
tests/test_shard_layout.py). Per-vocab-shard seed-hit counters render as
``kmls_shard_dispatch_total`` in ``/metrics``.

Hybrid serving (the second model family): when the mining job published
an ``embeddings.npz`` (ALS item factors, ``mining/als.py``), every
replica also carries the factor matrix on its device and each batch
dispatches TWO kernels — the rule max-merge and the embedding cosine
top-k (``ops/embed.py``) — whose per-request top-k lists merge on the
completion side per ``KMLS_HYBRID_MODE`` (rules | embed | blend, weight
``KMLS_HYBRID_BLEND_WEIGHT``). A seed set unknown to the rules but known
to the embedding vocabulary (cold-start / long-tail) is answered from
the embedding space instead of the popularity fallback. An absent,
torn, or checksum-failing embedding artifact degrades to rules-only —
the exact analogue of the npz→pickle fallback — and never costs the
reload.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import random
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults
from ..config import ServingConfig
from ..io import artifacts, iohealth, registry
from ..io.artifacts import ArtifactIntegrityError
from ..observability import costmodel as costmodel_mod
from ..ops.embed import embed_topk
from ..ops.serve import recommend_batch, recommend_batch_donated

logger = logging.getLogger("kmlserver_tpu.serving")


_HOST_STAGING_SAFE: bool | None = None


def _staging_buffer(shape: tuple[int, int]) -> np.ndarray:
    """int32 staging buffer at an address ≡ 4 (mod 64) — deliberately NOT
    64-byte aligned. jax's CPU client ZERO-COPIES ``device_put`` of a
    host array that meets XLA's alignment requirement (observed on
    jax 0.4.37: 64-byte-aligned int32 buffers alias, anything less
    copies), and an aliased device array turns staging-buffer reuse into
    answer corruption: the next same-shape dispatch refills the buffer
    the in-flight computation is still reading. ``np.empty`` leaves
    alignment to allocator luck — page-aligned for large buffers, so
    exactly the big batches aliased — which made the corruption a
    once-in-a-while flake instead of a loud failure. Offsetting to
    4 (mod 64) defeats every power-of-two alignment gate ≥ 8 while
    keeping the 4-byte alignment the int32 view needs, so device_put
    must copy; :func:`_staging_is_safe` probes THIS allocator so a
    future jax that aliases anyway disables reuse instead of corrupting."""
    n_bytes = int(np.prod(shape)) * 4
    raw = np.empty(n_bytes + 68, dtype=np.uint8)
    off = (4 - raw.ctypes.data) % 64
    return raw[off:off + n_bytes].view(np.int32).reshape(shape)


def _staging_is_safe() -> bool:
    """True when reusing one host staging buffer across dispatches is
    provably safe: the buffer is refilled while earlier transfers may
    still be in flight, so ``jax.device_put`` must have fully consumed it
    by the time it returns. Only the CPU backend qualifies — its
    transfers are synchronous COPIES for the misaligned buffers
    :func:`_staging_buffer` produces, and the probe below confirms the
    copy against that same allocator at a realistic size (``jnp.asarray``
    is zero-copy there, which is exactly why the staging path goes
    through ``device_put``; a sufficiently ALIGNED buffer is zero-copied
    even by device_put — the hazard the allocator's deliberate
    misalignment defeats). On accelerators the transfer may complete
    asynchronously AFTER device_put returns — a probe passing proves
    nothing about a larger buffer still in flight — so reuse stays off
    and each dispatch allocates fresh (allocation is not the bottleneck
    there; donation is the device-side win)."""
    global _HOST_STAGING_SAFE
    if _HOST_STAGING_SAFE is None:
        if jax.default_backend() != "cpu":
            _HOST_STAGING_SAFE = False
            return False
        probe = _staging_buffer((2, 64))
        probe.fill(-1)
        on_device = jax.device_put(probe)
        probe[0, 0] = 123
        # kmls-verify: allow[hotpath] — one 512-byte probe, cached for the
        # process lifetime; steady-state dispatches never reach this sync
        _HOST_STAGING_SAFE = int(np.asarray(on_device)[0, 0]) == -1
        if not _HOST_STAGING_SAFE:
            logger.warning(
                "device_put aliases host buffers on this backend; "
                "staging-buffer reuse disabled (fresh allocation per batch)"
            )
    return _HOST_STAGING_SAFE


def blend_candidates(
    rule_pairs: list[tuple[str, float]],
    emb_pairs: list[tuple[str, float]],
    weight: float,
    k_best: int,
) -> list[str]:
    """THE hybrid blend merge — union of both model families' (name,
    score) candidates with blended scores ``(1-w)·conf + w·sim`` and the
    deterministic tie order (score desc, name asc) that keeps every
    replica and epoch composing identical answers. One copy on purpose:
    the serving engine's ``_compose_answer`` AND the offline quality
    harness (quality/eval.py) both rank through it, so the measured
    blend optimum can never describe a merge production doesn't run."""
    w = min(max(weight, 0.0), 1.0)
    scores: dict[str, float] = {}
    for name, conf in rule_pairs:
        scores[name] = (1.0 - w) * float(conf)
    for name, sim in emb_pairs:
        scores[name] = scores.get(name, 0.0) + w * float(sim)
    ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
    return [n for n, _ in ranked[:k_best]]


def stable_seed(seed_tracks: list[str]) -> int:
    """Process-independent replacement for the reference's salted
    ``hash(tuple(sorted(seed_tracks)))`` (rest_api/app/main.py:214)."""
    digest = hashlib.blake2b(
        "\x1f".join(sorted(seed_tracks)).encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


@dataclasses.dataclass
class RuleBundle:
    """One immutable generation of serving state. Swapped atomically.

    With multi-device serving active (``KMLS_SERVE_DEVICES``), one bundle
    exists PER local device — the vocab/index/known-mask host state is
    shared across the replica set, the rule tensors live on each replica's
    own device, and the whole set swaps as one publication."""

    vocab: list[str]
    index: dict[str, int]
    rule_ids: jax.Array  # device, int32 (V, K)
    rule_confs: jax.Array  # device, float32 (V, K)
    known_mask: np.ndarray  # host, bool (V,) — rule-dict key membership
    model_token: str  # token value when loaded
    # the device this replica's tensors are committed to (None = host-
    # kernel bundle or default placement) and the generation counter the
    # recommendation cache keys on — monotonic per engine, bumped on every
    # successful publication, so a cache entry can never outlive its rules
    device: object = None
    epoch: int = 0
    # every (batch, length) seed shape warmed before publication — the
    # serving thread checks membership so an unwarmed dispatch (a compile
    # on the hot path) is counted and logged, never silent
    warmed_shapes: set = dataclasses.field(default_factory=set)
    # host copies of the rule tensors, present ONLY when the native CPU
    # serving kernel is active (serving/native_serve.py): XLA:CPU lowers
    # the scatter-max to ~190ns/update, which IS the serving tail on a
    # CPU pod; the native kernel does identical updates at ~2ns. None on
    # accelerator backends — their lookups stay on the device.
    host_rule_ids: np.ndarray | None = None
    host_rule_confs: np.ndarray | None = None
    # ---- model layout (KMLS_MODEL_LAYOUT, parallel/layout.py) ----
    # "replicated": this bundle is one full-tensor replica on `device`.
    # "sharded": ONE logical bundle whose rule tensors are vocab-sharded
    # across `mesh` (NamedSharding, P("shard", None)); the replica set is
    # exactly [this] and dispatch runs the sharded kernel below.
    layout: str = "replicated"
    mesh: object = None  # jax.sharding.Mesh spanning the serve devices
    n_shards: int = 1
    # padded per-shard vocab rows (v_pad / n_shards) — the divisor the
    # per-shard dispatch counters bucket seed ids by
    shard_size: int = 0
    # the jitted shard_map lookup bound to (mesh, k_best), resolved at
    # BUILD time (ops.serve.sharded_recommend_fn is lru-cached) so the
    # dispatch path never constructs a jit closure
    shard_kernel: object = None
    # replicated NamedSharding over `mesh` — the placement target for
    # staged seed batches (replicated layout uses `device` instead)
    seed_sharding: object = None
    # ---- pod-spanning serve mesh (ISSUE 16) ----
    # "mesh" layout: rule_ids/rule_confs hold ONLY this gang member's
    # vocab slab (global rows [gang_rank·shard_size, +shard_size)) on the
    # default local device; n_shards is the GANG size and shard_size the
    # slab rows, so the per-shard dispatch counters and /metrics read
    # identically to the single-process sharded layout. mesh_v is the
    # padded GLOBAL vocab width every partial scores at; mesh_lo the
    # slab's first global row as a committed device scalar (a traced
    # argument of ops.serve.shard_partial_topk — one compiled program
    # serves every rank).
    gang_rank: int = 0
    mesh_v: int = 0
    mesh_lo: object = None
    # ---- second model family (hybrid rule∪embedding serving) ----
    # ALS item factors on this replica's device (f32 (V_emb, rank), rows
    # L2-normalized) with their OWN vocabulary — the embedding id space is
    # the full encode-phase vocab, deliberately broader than the (possibly
    # Apriori-pruned) rule vocab; the hybrid merge happens at the name
    # level so the two spaces never need to agree. None = no embedding
    # artifact published (or it failed validation): rules-only serving.
    emb_factors: "jax.Array | None" = None
    emb_vocab: list[str] | None = None
    emb_index: dict[str, int] | None = None
    # (batch, length) shapes the embedding kernel was compiled for at
    # publication — same zero-compiles-post-publish discipline as
    # warmed_shapes, tracked separately because the native-rule-kernel
    # bundle has no rule shapes to warm but still jits the embed kernel
    emb_warmed_shapes: set = dataclasses.field(default_factory=set)


class RecommendEngine:
    """Holds serving state and executes lookups. Thread-safe: the bundle and
    best-tracks references are replaced atomically; readers never block."""

    def __init__(self, cfg: ServingConfig):
        self.cfg = cfg
        self.bundle: RuleBundle | None = None
        # the full replica set (one bundle per serving device); `bundle`
        # stays the primary replica for single-device callers
        self.replicas: list[RuleBundle] = []
        # monotonic publication counter — the recommendation cache's key
        # prefix. 0 = nothing published yet.
        self.bundle_epoch = 0
        # cumulative per-replica dispatch counters (Prometheus-monotonic:
        # they survive hot swaps), index-aligned with `replicas`
        self.dispatch_counts: list[int] = []
        # sharded layout: cumulative seed ids dispatched per vocab shard
        # (the load-balance signal — which shard's rows the traffic
        # actually hits), rendered as kmls_shard_dispatch_total in
        # /metrics; empty in replicated layout
        self.shard_dispatch_counts: list[int] = []
        self._dispatch_lock = threading.Lock()
        self.best_tracks: list[dict] | None = None
        self.cache_value: str | None = None  # the reference's app.cache_value
        self.finished_loading = False
        self.reload_counter = 0
        self._reload_lock = threading.Lock()
        # ---- fault-tolerance bookkeeping (rendered into /metrics) ----
        # total failed reloads: each one KEPT the last-good bundle serving
        # (the rollback counter), vs consecutive failures driving the
        # exponential retry backoff + the quarantine strike discipline
        self.reload_failures = 0
        self.consecutive_reload_failures = 0
        self.artifact_quarantines = 0
        self.last_load_error: str | None = None
        # second-model-family bookkeeping: embedding-artifact load
        # failures are SURVIVABLE (the bundle publishes rules-only), so
        # they get their own counters instead of riding reload_failures
        self.embedding_load_failures = 0
        self.last_embedding_error: str | None = None
        # True when the LAST publication wanted embeddings (file present)
        # but had to fall back to rules-only — rendered into /readyz's
        # degraded reasons and /metrics
        self.embedding_degraded = False
        # monotonic deadline before which reload_if_required() won't retry
        # a FAILED load (direct load() calls always go through — tests and
        # operator nudges must not be backoff-gated)
        self._backoff_until = 0.0
        # ---- continuous freshness (ISSUE 10) ----
        # chain position currently applied on top of the base generation:
        # the serving epoch is logically the PAIR (bundle_epoch,
        # delta_seq) — a delta apply advances delta_seq in place without
        # bumping bundle_epoch (the cache invalidates selectively instead
        # of wholesale), and a full reload resets it to 0
        self.delta_seq = 0
        self.delta_applied_total = 0
        self.delta_rejected_total = 0
        self.last_delta_error: str | None = None
        # callbacks fired AFTER a delta swap commits: (touched_names,
        # wholesale) — the app points this at the cache's selective
        # invalidation
        self.delta_listeners: list = []
        # the logical tensors deltas patch (counts included — the npz
        # load's dict shape); None when the bundle came from the pickle
        # or carries merged float64 confidences (delta-ineligible)
        self._host_state: dict | None = None
        # sha256 of the npz the host state was loaded from — the binding
        # a bundle's base_npz_sha256 must match
        self._base_npz_sha: str | None = None
        # wall-clock written_at of the newest APPLIED generation (base
        # manifest or delta chain entry) — kmls_freshness_lag_seconds
        self._applied_written_at = 0.0
        # rejection backoff for the POLLING path only (direct
        # apply_pending_deltas calls always go through, like load())
        self._delta_backoff_until = 0.0
        # bundles in the CURRENT generation's delta chain file (applied
        # or not) — the compaction trigger's observability surface,
        # rendered as kmls_delta_chain_length; 0 when no chain (or a
        # chain bound to another generation) is on the PVC
        self.delta_chain_length = 0
        # ---- quality loop (ISSUE 14) ----
        # the blend optimum read from quality.report.json at load time
        # (None: no report, unusable report, or measured mode off) —
        # committed WITH the bundle swap so answers and weight always
        # describe the same generation
        self.measured_blend_weight: float | None = None
        self._kernel = None  # resolved lazily: donation needs the backend
        # dispatches whose (batch, length) shape was never pre-warmed —
        # each one paid a jit compile on the serving path; must stay 0
        self.unwarmed_dispatches = 0
        # ---- device-truth cost attribution (ISSUE 12) ----
        # per-kernel MFU/roofline + memory/compile telemetry; None with
        # KMLS_COSTMODEL=0, making every call site one attribute check
        # (the disabled mode's zero-cost proof rides the module-level
        # OBSERVATIONS_TOTAL counter, began-counter style)
        self.cost_model = (
            costmodel_mod.CostModel() if cfg.costmodel_enabled else None
        )
        # per-artifact publication timestamps (wall clock) — the
        # freshness-age surface /readyz and kmls_artifact_age_seconds
        # report; empty before the first load
        self._artifact_written_at: dict[str, float] = {}
        # reusable host staging buffers, one per padded seed shape: steady
        # state does no fresh host allocation per batch. Guarded by the
        # lock (fill + transfer must not interleave across threads) and by
        # _staging_is_safe() (device_put must copy).
        self._staging: dict[tuple[int, int], np.ndarray] = {}
        self._staging_lock = threading.Lock()
        # ---- pod-spanning serve mesh (ISSUE 16) ----
        # armed when KMLS_SERVE_GANG_COORDINATOR + SIZE>1 name a gang this
        # process belongs to; the worker serves THIS rank's partial top-k
        # to peers, the coordinator fans a batch out and merges. Both are
        # created lazily at the first mesh publication (under the reload
        # lock) and survive hot swaps — the model token carried on every
        # partial is what keeps generations honest across the gang.
        from . import mesh as mesh_mod  # local import: keeps engine import light

        self._mesh_mod = mesh_mod
        self.gang = mesh_mod.gang_from_config(cfg)
        self.mesh_worker = None
        self.mesh_coordinator = None
        # answers served merged-without-a-straggler (ISSUE 18): degraded
        # by contract, counted for /metrics (kmls_mesh_straggler_
        # degraded_total) — stays 0 with hedging off
        self.mesh_straggler_degraded = 0
        if self.gang is not None:
            # real-collectives wiring: on an accelerator gang this joins
            # the jax.distributed coordinator (GSPMD over DCN — the
            # on-chip run folds into the standing TPU-window item); on
            # the CPU backend it logs and declines, and serving uses the
            # multi-process simulation transport below instead.
            from ..parallel.distributed import maybe_initialize_serve_gang

            maybe_initialize_serve_gang(
                self.gang.coordinator, self.gang.size, self.gang.rank
            )
        # storage gray-failure spine (ISSUE 19): point the IO-health
        # monitor's free-space gauge at the artifact volume this engine
        # polls — kmls_disk_free_bytes then tracks the PVC, and every
        # artifact read below feeds the latency EWMAs behind the
        # storage-slow conviction
        iohealth.MONITOR.watch_disk(cfg.pickles_dir)

    # ---------- artifact loading / hot swap ----------

    def _token_path(self) -> str:
        return registry.token_path_for(self.cfg.base_dir, self.cfg.data_invalidation_file)

    def _read_deadline(self) -> float | None:
        """Deadline for reload-path artifact reads (None = unbounded)."""
        return self.cfg.io_read_deadline_s or None

    def _read_token(self) -> str | None:
        try:
            return artifacts.read_text(self._token_path(), op="token_poll")
        except FileNotFoundError:
            return None
        except OSError as exc:
            # a transient EIO/stall on the per-poll token read must NOT
            # flip is_data_stale — that would turn one flaky NFS read
            # into reload churn. The poll failure decays: report the
            # cached token (no change seen) and let the next poll retry.
            logger.warning(
                "token poll failed (%s); keeping cached token", exc
            )
            return self.cache_value

    def is_data_stale(self) -> bool:
        """Token-comparison staleness (reference: rest_api/app/main.py:82-97);
        missing token file counts as stale.

        Deliberate divergence: the reference's check UPDATES its cached token
        as a side effect, so (a) a failed reload permanently swallows the
        staleness signal and (b) ``model_date`` advertises data that isn't
        being served yet. Here the check is pure — ``cache_value`` moves only
        when a new bundle actually loads, so ``model_date`` always describes
        the rules answering the request."""
        token = self._read_token()
        if token is None:
            logger.warning("invalidation token %s missing", self._token_path())
            return True
        if token != self.cache_value:
            logger.info("data stale: token changed %r -> %r", self.cache_value, token)
            return True
        return False

    def load(self) -> bool:
        """Build a fresh bundle from the PVC; atomic swap on success.
        Returns False (fail-soft) when artifacts aren't there yet."""
        with self._reload_lock:
            # re-check under the lock: concurrent "nudge" threads that queued
            # behind an in-flight load must not repeat it (their staleness
            # decision predates the load that just completed)
            if self.finished_loading and not self.is_data_stale():
                return True
            if self.cost_model is not None:
                # a (re)publication is starting: bank genuine serving-
                # path compiles seen so far, so the warmup about to run
                # is absorbed by mark_published instead of billed live
                self.cost_model.note_prepublish()
            cfg = self.cfg
            best_path = os.path.join(cfg.pickles_dir, cfg.best_tracks_file)
            rec_path = os.path.join(cfg.pickles_dir, cfg.recommendations_file)
            npz_path = artifacts.tensor_artifact_path(rec_path)
            try:
                # deterministic chaos hook: KMLS_FAULT_RELOAD_FAIL / a test's
                # faults.inject("engine.load") fails the reload exactly like
                # a torn artifact — same rollback, same retry ladder
                faults.fire("engine.load")
                use_npz, use_emb = self._verify_before_load(
                    best_path, rec_path, npz_path
                )
                best = artifacts.load_pickle(
                    best_path, deadline_s=self._read_deadline()
                )
                replicas = self._build_replicas(
                    rec_path, npz_path, use_npz=use_npz
                )
                # second model family: attach ALS item factors to every
                # replica. Fail-SOFT by design — a torn/corrupt/absent
                # embeddings.npz costs the embedding path, never the
                # reload (rules-only is the documented degradation, the
                # exact analogue of the npz→pickle fallback above). The
                # degraded/error outcome stays in LOCALS until the swap
                # commits below: a reload that fails after this point
                # (warmup raise → last-good keeps serving) must not leave
                # /readyz describing the failed CANDIDATE generation.
                emb_degraded, emb_error = self._attach_embeddings(
                    replicas, use_emb=use_emb
                )
                # warm the serving kernel for every seed-bucket shape on
                # EVERY replica BEFORE publishing: the first jit compile
                # costs seconds on TPU and must not land inside a request
                # (readiness implies warmed — on all devices). Reloads with
                # unchanged tensor shapes hit the jit cache and skip this.
                # Inside the try: tensors that np.load accepts but the
                # kernel rejects must fail-soft too.
                for bundle in replicas:
                    self._warmup(bundle)
            except FileNotFoundError as exc:
                logger.warning("artifacts not ready: %s", exc)
                return False
            except Exception as exc:
                # corrupt/torn artifact (the REFERENCE mining job writes
                # non-atomically — its report acknowledges the race; this
                # engine must serve either side's PVC): keep the current
                # bundle (last-good rollback), back off the retry, and
                # quarantine persistent offenders. The invalidation token
                # is NOT consumed (cache_value only moves on success), so
                # every retry re-sees the staleness signal.
                logger.exception("artifact load failed; keeping current bundle")
                self._note_reload_failure(
                    exc, best_path, rec_path, npz_path
                )
                return False
            # atomic publication: single reference assignments. Ordering
            # contract for the epoch-keyed cache: the bundle reference
            # lands BEFORE the epoch bump, so an answer stored under the
            # new epoch can only have been computed from the new rules —
            # a stale answer can land only under the OLD epoch key, which
            # no post-swap lookup can ever construct. (The benign inverse
            # — a new-rules answer briefly stored under the old key — just
            # serves fresher data than advertised.)
            epoch = self.bundle_epoch + 1
            for bundle in replicas:
                bundle.epoch = epoch
            self.best_tracks = best
            self.replicas = replicas
            self.bundle = replicas[0]
            self.bundle_epoch = epoch
            with self._dispatch_lock:
                while len(self.dispatch_counts) < len(replicas):
                    self.dispatch_counts.append(0)
            self.cache_value = replicas[0].model_token or self.cache_value
            # continuous freshness: a full reload starts a fresh
            # (base, delta_seq) pair at seq 0 — a pending chain for THIS
            # generation applies via apply_pending_deltas right after
            # (reload_if_required chains the two)
            self.delta_seq = 0
            self._host_state = getattr(self, "_candidate_host_state", None)
            self._base_npz_sha = getattr(self, "_candidate_npz_sha", None)
            self._delta_backoff_until = 0.0
            # chain-length gauge: bundles already published for THIS
            # generation (apply_pending_deltas keeps it current as the
            # chain grows; a chain for another generation reads as 0)
            self.delta_chain_length = 0
            if self.cfg.delta_enabled:
                chain = artifacts.read_delta_state(self.cfg.pickles_dir)
                if chain is not None and chain.get("base_token") == (
                    self.cache_value
                ):
                    self.delta_chain_length = len(chain.get("entries", ()))
            # quality loop: the measured blend optimum commits WITH the
            # bundle it was measured against (fail-soft — no report or a
            # malformed one serves the configured default, loudly)
            self.measured_blend_weight = self._read_measured_blend_weight()
            manifest = artifacts.load_manifest(
                self.cfg.pickles_dir, deadline_s=self._read_deadline()
            )
            if manifest is not None and manifest.get("token") == self.cache_value:
                self._applied_written_at = float(
                    manifest.get("written_at") or time.time()
                )
            else:
                self._applied_written_at = time.time()
            self.finished_loading = True
            # embedding status commits WITH the bundle it describes
            self.embedding_degraded = emb_degraded
            self.last_embedding_error = emb_error
            if emb_degraded:
                self.embedding_load_failures += 1
            self.reload_counter += 1
            self.consecutive_reload_failures = 0
            self.last_load_error = None
            self._backoff_until = 0.0
            # per-artifact freshness bookkeeping: rules age from the
            # manifest's written_at (just resolved above), popularity/
            # embeddings from their file mtimes (the manifest covers the
            # set, not per-file stamps); delta-chain rides
            # _applied_written_at, which deltas advance in place
            ages = {"rules": self._applied_written_at}
            ages["popularity"] = self._file_written_at(
                best_path, self._applied_written_at
            )
            if replicas[0].emb_factors is not None:
                ages["embeddings"] = self._file_written_at(
                    artifacts.embeddings_artifact_path(self.cfg.pickles_dir),
                    self._applied_written_at,
                )
            self._artifact_written_at = ages
            # cost attribution (ISSUE 12): publish-time tensor-residency
            # accounting + compile-watch snapshot (post-warmup, so the
            # kmls_compiles_total counter starts at zero for this
            # generation — any growth IS a compile on the serving path)
            if self.cost_model is not None:
                self._note_publish_cost(replicas)
            logger.info(
                "reload #%d complete (epoch %d): %d tracks, %d rule keys, "
                "%d replica(s), layout %s (%d shard(s)), embeddings %s, "
                "token %r",
                self.reload_counter, epoch, len(replicas[0].vocab),
                int(replicas[0].known_mask.sum()), len(replicas),
                self.model_layout, self.n_shards,
                (
                    f"on ({len(replicas[0].emb_vocab)} tracks)"
                    if replicas[0].emb_factors is not None else "off"
                ),
                replicas[0].model_token,
            )
            return True

    def _verify_before_load(
        self, best_path: str, rec_path: str, npz_path: str
    ) -> tuple[bool, bool]:
        """Integrity gate before any bytes are trusted: validate the
        artifact set against the mining job's manifest (sizes + sha256).
        A mismatched best/recommendations pickle ABORTS the reload (raise
        → last-good keeps serving); a mismatched npz is survivable — the
        pickle carries the same generation — so it only disables the
        tensor-artifact fast path for this reload, and a mismatched
        embeddings.npz likewise only disables the embedding path (the
        rule artifacts carry the generation; rules-only is the documented
        degradation). The CURRENT token gates the check: a manifest
        stamped for another generation (a manifest-less writer — the
        reference's job — has published since) is stale and steps aside
        rather than condemning fresh bytes. → (use_npz, use_emb)."""
        if not self.cfg.verify_manifest:
            return True, True
        emb_path = artifacts.embeddings_artifact_path(self.cfg.pickles_dir)
        bad = artifacts.verify_files(
            self.cfg.pickles_dir,
            [
                os.path.basename(p)
                for p in (best_path, rec_path, npz_path, emb_path)
            ],
            token=self._read_token(),
        )
        use_npz = True
        use_emb = True
        if npz_path in bad:
            logger.warning(
                "tensor artifact %s fails its manifest checksum; "
                "falling back to the pickle", npz_path,
            )
            use_npz = False
            bad = [p for p in bad if p != npz_path]
        if emb_path in bad:
            logger.warning(
                "embedding artifact %s fails its manifest checksum; "
                "serving rules-only this generation", emb_path,
            )
            use_emb = False
            bad = [p for p in bad if p != emb_path]
        if bad:
            raise ArtifactIntegrityError(
                f"artifact checksum mismatch vs manifest: {bad}", bad
            )
        return use_npz, use_emb

    def _attach_embeddings(
        self, replicas: list[RuleBundle], use_emb: bool = True
    ) -> tuple[bool, str | None]:
        """Load ``embeddings.npz`` (if published) and commit the item
        factors to every replica's device. NEVER raises: embedding
        problems degrade to rules-only serving — a bad second-model
        artifact must not cost the first model's reload. Fires the
        ``embed.artifact`` chaos site so the degradation is
        deterministically testable.

        → ``(degraded, error)`` for the CALLER to commit alongside the
        bundle swap — engine-level status must describe the bundle that
        actually published, never a candidate whose reload later failed."""
        if self.cfg.hybrid_mode == "rules":
            # operator pinned rules-only: don't even read the file
            return False, None
        emb_path = artifacts.embeddings_artifact_path(self.cfg.pickles_dir)
        if not os.path.exists(emb_path):
            # no second model published: rules-only, not degraded
            return False, None
        try:
            if not use_emb:
                raise ArtifactIntegrityError(
                    f"{emb_path} fails its manifest checksum", [emb_path]
                )
            faults.fire("embed.artifact")
            loaded = artifacts.load_embeddings(
                emb_path, deadline_s=self._read_deadline()
            )
        except FileNotFoundError:
            # raced a writer retiring the artifact (an embed-disabled
            # publication removes it before the token rewrite): absent,
            # not corrupt — rules-only without the degraded flag
            logger.info(
                "embedding artifact %s vanished mid-load (retired by the "
                "miner); serving rules-only", emb_path,
            )
            return False, None
        except Exception as exc:
            logger.exception(
                "embedding artifact %s unusable; serving rules-only",
                emb_path,
            )
            return True, f"{type(exc).__name__}: {exc}"
        emb_vocab = loaded["vocab"]
        emb_index = {n: i for i, n in enumerate(emb_vocab)}
        factors = jnp.asarray(loaded["item_factors"])
        for bundle in replicas:
            bundle.emb_vocab = emb_vocab
            bundle.emb_index = emb_index
            bundle.emb_factors = (
                jax.device_put(factors, bundle.device)
                if bundle.device is not None
                else factors
            )
        return False, None

    def _note_reload_failure(
        self, exc: Exception, best_path: str, rec_path: str, npz_path: str
    ) -> None:
        """Failed-reload bookkeeping (caller holds ``_reload_lock``):
        count the rollback, arm the exponential retry backoff, and — once
        the SAME artifact set has failed ``quarantine_after_failures``
        consecutive reloads — quarantine the files that are actually
        corrupt (a single mid-update mismatch heals itself next poll and
        must never cost a good file)."""
        self.reload_failures += 1
        self.consecutive_reload_failures += 1
        self.last_load_error = f"{type(exc).__name__}: {exc}"
        backoff = min(
            self.cfg.reload_backoff_base_s
            * (2 ** (self.consecutive_reload_failures - 1)),
            self.cfg.reload_backoff_max_s,
        )
        self._backoff_until = time.monotonic() + backoff
        logger.warning(
            "reload failure #%d (consecutive); retrying in %.1fs",
            self.consecutive_reload_failures, backoff,
        )
        threshold = self.cfg.quarantine_after_failures
        if threshold > 0 and self.consecutive_reload_failures >= threshold:
            self._quarantine_corrupt_artifacts(best_path, rec_path, npz_path)

    def _quarantine_corrupt_artifacts(
        self, best_path: str, rec_path: str, npz_path: str
    ) -> None:
        """Move persistently-corrupt artifacts into pickles/quarantine/ so
        the next mining run writes fresh bytes and the bad ones stay
        inspectable. Only a PARSE failure condemns a file — a manifest
        mismatch alone never does: two polls can land inside one slow
        publish window (new pickle on disk, manifest/token still the old
        generation), and condemning on the mismatch would move a fresh,
        valid artifact aside and wedge the pod until the next mining run.
        A mismatched-but-parseable file keeps failing verification at
        reload time instead — visible as the degraded state, costing no
        good bytes."""
        probes = (
            (best_path, artifacts.load_pickle),
            (rec_path, artifacts.load_pickle),
            (npz_path, artifacts.load_rule_tensors),
        )
        for path, probe in probes:
            if not os.path.exists(path):
                continue
            try:
                probe(path, deadline_s=self._read_deadline())
                continue  # parses fine: never quarantine on suspicion
            except FileNotFoundError:
                continue
            except artifacts.IoStallError:
                # a slow mount is not corruption: condemning a good file
                # because the PROBE timed out would cost real bytes
                continue
            except Exception:
                pass
            dest = artifacts.quarantine_file(path)
            if dest is not None:
                self.artifact_quarantines += 1
                logger.warning(
                    "quarantined corrupt artifact %s -> %s", path, dest
                )

    def _build_replicas(
        self, rec_path: str, npz_path: str, use_npz: bool = True
    ) -> list[RuleBundle]:
        """Load the rule tensors once, then replicate them onto every
        serving device (``device_put`` per device) — or onto the host when
        the native CPU kernel is active (one replica: the host kernel has
        no per-device state to parallelize over). Host-side state (vocab,
        index, known mask) is shared across the set."""
        token = self._read_token() or ""
        loaded = None
        if (
            self.cfg.prefer_tensor_artifact
            and use_npz
            and os.path.exists(npz_path)
        ):
            try:
                loaded = artifacts.load_rule_tensors(
                    npz_path, deadline_s=self._read_deadline()
                )
            except artifacts.IoStallError:
                # a hung read is not a torn artifact: fail the RELOAD
                # (backoff + last-good serving) instead of falling back
                # to an equally-hung pickle read
                raise
            except Exception:
                # torn/corrupt npz next to a possibly-intact pickle of the
                # same generation: fall through to the pickle rather than
                # abandoning the whole reload
                logger.exception(
                    "tensor artifact %s unreadable; trying the pickle", npz_path
                )
        # continuous freshness: the candidate host state a delta bundle
        # can patch in place — committed alongside the swap in load().
        # Only the npz path carries the counts a patch needs, and merged
        # float64 confidences (rule_confs64) cannot be re-derived after a
        # patch, so those bundles serve deltas-disabled.
        self._candidate_host_state = None
        self._candidate_npz_sha = None
        if loaded is not None:
            vocab = loaded["vocab"]
            rule_ids = loaded["rule_ids"]
            rule_confs = loaded["rule_confs"]
            from ..ops.support import min_count_for

            known = loaded["item_counts"] >= min_count_for(
                loaded["min_support"], loaded["n_playlists"]
            )
            if self.cfg.delta_enabled and loaded.get("rule_confs64") is None:
                self._candidate_host_state = {
                    "vocab": list(vocab),
                    "rule_ids": np.asarray(rule_ids, dtype=np.int32),
                    "rule_counts": np.asarray(
                        loaded["rule_counts"], dtype=np.int32
                    ),
                    "item_counts": np.asarray(
                        loaded["item_counts"], dtype=np.int32
                    ),
                    "n_playlists": int(loaded["n_playlists"]),
                    "min_support": float(loaded["min_support"]),
                    "mode": str(loaded["mode"]),
                    "min_confidence": float(loaded["min_confidence"]),
                }
                self._candidate_npz_sha = artifacts.file_digest(npz_path)[
                    "sha256"
                ]
        else:
            rules_dict = artifacts.load_pickle(
                rec_path, deadline_s=self._read_deadline()
            )
            vocab = sorted(
                set(rules_dict)
                | {o for row in rules_dict.values() for o in row}
            )
            rule_ids, rule_confs, known = artifacts.tensors_from_rules_dict(
                rules_dict, vocab, k_max=max(
                    (len(r) for r in rules_dict.values()), default=1
                ),
            )
        index = {n: i for i, n in enumerate(vocab)}
        return self._replicas_from_arrays(
            vocab, index, np.asarray(known), rule_ids, rule_confs, token
        )

    def _replicas_from_arrays(
        self, vocab, index, known_mask, rule_ids, rule_confs, token
    ) -> list[RuleBundle]:
        """Build the replica set from host arrays — shared by the
        disk-artifact load above and the in-place delta apply
        (:meth:`apply_pending_deltas`), so a patched generation commits
        to devices through exactly the code a fresh load uses."""
        devs = self._serve_devices()
        # layout decision (parallel/layout.py, the one shared copy):
        # MEASURED rule-tensor bytes vs the per-device budget. A sharded
        # resolution builds ONE logical bundle spanning every serve
        # device instead of a replica per device; an armed serve gang
        # (ISSUE 16) resolves to "mesh" — this process holds ONLY its
        # vocab slab and the gang presents one logical replica.
        from ..parallel.layout import resolve_serve_span

        layout = resolve_serve_span(
            self.cfg.model_layout,
            int(rule_ids.nbytes + rule_confs.nbytes),
            self.cfg.device_budget_bytes,
            len(devs),
            gang_size=self.gang.size if self.gang is not None else 1,
        )
        if layout == "mesh" and len(vocab) > 0:
            if jax.process_count() > 1:
                # real-collectives path: the gang joined one jax
                # distributed world (maybe_initialize_serve_gang), so the
                # PR 7 shard_map kernel over the GLOBAL device set IS the
                # pod-spanning mesh — vocab axis on DCN via GSPMD. The
                # simulation transport below is the CPU-testable twin.
                return [
                    self._build_sharded_bundle(
                        vocab, index, known_mask, rule_ids, rule_confs,
                        token, jax.devices(),
                    )
                ]
            return [
                self._build_mesh_bundle(
                    vocab, index, known_mask, rule_ids, rule_confs, token
                )
            ]
        if layout == "sharded" and len(vocab) > 0:
            return [
                self._build_sharded_bundle(
                    vocab, index, known_mask, rule_ids, rule_confs,
                    token, devs,
                )
            ]
        if self._use_native_serve():
            # rule rows are trailing-padded (emission writes the top-k
            # descending, then -1 fill) — the native kernel's early-break
            # contract; ascontiguousarray guards a sliced npz view
            host_ids = np.ascontiguousarray(rule_ids, dtype=np.int32)
            host_confs = np.ascontiguousarray(rule_confs, dtype=np.float32)
            # jnp.asarray is zero-copy on the CPU backend, so keeping the
            # "device" tensors next to the host copies costs no memory
            return [RuleBundle(
                vocab=vocab, index=index,
                rule_ids=jnp.asarray(host_ids),
                rule_confs=jnp.asarray(host_confs),
                known_mask=known_mask, model_token=token,
                host_rule_ids=host_ids, host_rule_confs=host_confs,
            )]
        ids_arr = jnp.asarray(rule_ids)
        confs_arr = jnp.asarray(rule_confs)
        return [
            RuleBundle(
                vocab=vocab, index=index,
                rule_ids=jax.device_put(ids_arr, dev),
                rule_confs=jax.device_put(confs_arr, dev),
                known_mask=known_mask, model_token=token,
                device=dev,
            )
            for dev in devs
        ]

    def _build_sharded_bundle(
        self, vocab, index, known_mask, rule_ids, rule_confs, token, devs
    ) -> RuleBundle:
        """ONE logical bundle whose rule tensors are vocab-sharded across
        ``devs`` (``NamedSharding(mesh, P("shard", None))``): per-device
        HBM holds ``V/S`` rule rows, so a catalog exceeding one device's
        budget serves as long as the MESH can hold it. The antecedent
        axis is padded to a multiple of the shard count with empty rows
        (-1 ids / 0 confs — unreachable: seed ids are always < V), and
        the lookup kernel is resolved here, at build time, so dispatch
        never constructs a jit closure (hot-path purity)."""
        from jax.sharding import NamedSharding, PartitionSpec
        from jax.sharding import Mesh as JaxMesh

        from ..ops.serve import sharded_recommend_fn

        n = len(devs)
        mesh = JaxMesh(np.asarray(devs), ("shard",))
        v, k = rule_ids.shape
        v_pad = ((v + n - 1) // n) * n
        ids = np.full((v_pad, k), -1, dtype=np.int32)
        confs = np.zeros((v_pad, k), dtype=np.float32)
        ids[:v] = rule_ids
        confs[:v] = rule_confs
        row_spec = NamedSharding(mesh, PartitionSpec("shard", None))
        bundle = RuleBundle(
            vocab=vocab, index=index,
            rule_ids=jax.device_put(ids, row_spec),
            rule_confs=jax.device_put(confs, row_spec),
            known_mask=known_mask, model_token=token,
            device=None, layout="sharded", mesh=mesh, n_shards=n,
            shard_size=v_pad // n,
            shard_kernel=sharded_recommend_fn(
                mesh, self.cfg.k_best_tracks
            ),
            seed_sharding=NamedSharding(mesh, PartitionSpec(None, None)),
        )
        logger.info(
            "sharded layout: %d rule rows (+%d pad) across %d shards "
            "(%d rows, ~%.1f MiB of rule tensors per device)",
            v, v_pad - v, n, v_pad // n,
            (ids.nbytes + confs.nbytes) / n / (1 << 20),
        )
        return bundle

    def _build_mesh_bundle(
        self, vocab, index, known_mask, rule_ids, rule_confs, token
    ) -> RuleBundle:
        """ONE gang member's slice of the pod-spanning serve mesh: the
        vocab axis is padded to a multiple of the gang size and THIS
        process keeps only rows ``[rank·slab, (rank+1)·slab)`` — the
        servable catalog scales with the gang, not with one host. The
        dispatch math is the sharded kernel's two halves verbatim
        (ops/serve.py ``shard_partial_topk`` / ``merge_partial_topk``,
        the exact functions the shard_map kernel traces), so the gang's
        merged answer is bit-identical to the single-process sharded —
        and replicated — layouts by construction, pinned by
        tests/test_mesh.py."""
        gang = self.gang
        size = gang.size
        v, k = rule_ids.shape
        v_pad = ((v + size - 1) // size) * size
        slab = v_pad // size
        lo = gang.rank * slab
        hi = min(lo + slab, v)
        ids = np.full((slab, k), -1, dtype=np.int32)
        confs = np.zeros((slab, k), dtype=np.float32)
        if hi > lo:
            ids[: hi - lo] = rule_ids[lo:hi]
            confs[: hi - lo] = rule_confs[lo:hi]
        bundle = RuleBundle(
            vocab=vocab, index=index,
            rule_ids=jax.device_put(jnp.asarray(ids)),
            rule_confs=jax.device_put(jnp.asarray(confs)),
            known_mask=known_mask, model_token=token,
            device=None, layout="mesh", n_shards=size, shard_size=slab,
            gang_rank=gang.rank, mesh_v=v_pad,
            mesh_lo=jax.device_put(jnp.asarray(lo, dtype=jnp.int32)),
        )
        self._ensure_mesh_runtime()
        logger.info(
            "mesh layout: %d rule rows (+%d pad) across a %d-member gang "
            "— this rank (%d) holds rows [%d, %d) (~%.1f MiB)",
            v, v_pad - v, size, gang.rank, lo, lo + slab,
            (ids.nbytes + confs.nbytes) / (1 << 20),
        )
        return bundle

    def _ensure_mesh_runtime(self) -> None:
        """Start the gang's partial-protocol worker + coordinator once
        (idempotent; called under the reload lock at mesh publication).
        Both outlive hot swaps — the model token on every partial is the
        generation fence, not the sockets."""
        mesh_mod = self._mesh_mod
        if self.mesh_worker is None:
            self.mesh_worker = mesh_mod.MeshWorkerServer(
                self._mesh_serve_partial, self._mesh_status,
                port=self.cfg.serve_gang_port,
            )
            self.mesh_worker.start()
            logger.info(
                "serve-mesh worker listening on :%d (gang rank %d/%d)",
                self.mesh_worker.port, self.gang.rank, self.gang.size,
            )
        if self.mesh_coordinator is None:
            self.mesh_coordinator = mesh_mod.MeshCoordinator(
                self.gang,
                hedge=self.cfg.hedge_enabled,
                hedge_delay_ms=self.cfg.hedge_delay_ms,
                hedge_max_frac=self.cfg.hedge_max_frac,
                peer_slow_ratio=self.cfg.peer_slow_ratio,
            )

    def _mesh_serve_partial(self, seeds: np.ndarray):
        """Worker-side handler: run THIS rank's partial top-k for a
        peer's staged batch. Raising is the contract for 'shard not
        servable here' — the transport maps it to MeshShardUnavailable
        at the caller, which spills to the next ring peer."""
        # gray-failure chaos hook (ISSUE 18): a delay fault here turns
        # this gang member into the classic slow-but-alive straggler —
        # fenced, correct, late — that the coordinator's hedge machinery
        # must absorb without gating the merge
        faults.fire("mesh.peer", replica=self.gang.rank if self.gang else 0)
        bundle = self.bundle
        if bundle is None or bundle.layout != "mesh":
            raise RuntimeError("no mesh bundle published on this rank")
        shape = (int(seeds.shape[0]), int(seeds.shape[1]))
        if shape not in bundle.warmed_shapes:
            self.unwarmed_dispatches += 1
            logger.warning(
                "mesh partial for unwarmed shape %s — paying a compile "
                "on the serving path", shape,
            )
        from ..ops.serve import shard_partial_topk

        seeds_dev = jax.device_put(np.ascontiguousarray(seeds, np.int32))
        part_ids, part_confs = shard_partial_topk(
            bundle.rule_ids, bundle.rule_confs, seeds_dev, bundle.mesh_lo,
            v=bundle.mesh_v, k_best=self.cfg.k_best_tracks,
        )
        return (
            np.asarray(part_ids), np.asarray(part_confs),
            bundle.model_token or "",
        )

    def _mesh_status(self) -> dict:
        """The worker's 'ready' op payload — what a peer (or the
        coordinator's half-open probe) learns about this rank."""
        bundle = self.bundle
        return {
            "rank": self.gang.rank if self.gang is not None else 0,
            "epoch": self.bundle_epoch,
            "token": bundle.model_token if bundle is not None else None,
            "layout": bundle.layout if bundle is not None else None,
        }

    def mesh_missing_shards(self, probe: bool = False) -> list:
        """Sorted ranks of gang members the coordinator cannot currently
        serve through — empty outside mesh layout.
        ``probe=True`` re-auditions missing ranks (rate-limited inside
        the coordinator) so /readyz and the fleet's half-open probe are
        the re-form detectors without any background thread."""
        coord = self.mesh_coordinator
        if coord is None:
            return []
        return coord.missing_shards(probe=probe)

    def _serve_devices(self) -> list:
        """The local devices the replica set spans. ``serve_devices == 0``
        (auto) replicates onto every local device on accelerator backends;
        on CPU it stays at one — virtual CPU devices share the same host
        cores, so extra replicas there only multiply warmup compiles unless
        an operator (or a test) opts in via KMLS_SERVE_DEVICES. Exception:
        an EXPLICIT ``KMLS_MODEL_LAYOUT=sharded`` spans every local device
        even on CPU — the operator asked for vocab sharding, and one
        device has nothing to shard across."""
        from ..parallel.layout import validate_layout

        devs = jax.local_devices()
        n = self.cfg.serve_devices
        if n <= 0:
            if validate_layout(self.cfg.model_layout) == "sharded":
                n = len(devs)
            else:
                n = 1 if jax.default_backend() == "cpu" else len(devs)
        return devs[: max(1, min(n, len(devs)))]

    @property
    def n_replicas(self) -> int:
        """Serving replicas currently published (1 before the first load —
        the batcher's least-loaded dispatcher sizes its lanes off this)."""
        return max(1, len(self.replicas))

    def _note_dispatch(self, idx: int) -> None:
        with self._dispatch_lock:
            while len(self.dispatch_counts) <= idx:
                self.dispatch_counts.append(0)
            self.dispatch_counts[idx] += 1

    def _note_shard_dispatch(self, per_shard) -> None:
        with self._dispatch_lock:
            while len(self.shard_dispatch_counts) < len(per_shard):
                self.shard_dispatch_counts.append(0)
            for i, count in enumerate(per_shard):
                self.shard_dispatch_counts[i] += int(count)

    @property
    def model_layout(self) -> str:
        """The layout of the PUBLISHED bundle ("replicated" before the
        first load — there is nothing sharded to describe yet)."""
        bundle = self.bundle
        return bundle.layout if bundle is not None else "replicated"

    @property
    def n_shards(self) -> int:
        """Vocab shards in the published bundle (1 = replicated)."""
        bundle = self.bundle
        return bundle.n_shards if bundle is not None else 1

    def _use_native_serve(self) -> bool:
        """Native host kernel iff the backend is CPU (an accelerator's
        lookups belong on the accelerator), the knob allows it, and the
        .so is loadable."""
        if not self.cfg.native_serve or jax.default_backend() != "cpu":
            return False
        from . import native_serve

        return native_serve.available()

    def _resolve_kernel(self):
        if self._kernel is None:
            # donation (seed-buffer HBM reuse) is unimplemented on the CPU
            # backend and warns per call — pick the variant once, at the
            # first load, when the backend is known
            fn = (
                recommend_batch
                if jax.default_backend() == "cpu"
                else recommend_batch_donated
            )
            self._kernel = partial(fn, k_best=self.cfg.k_best_tracks)
        return self._kernel

    def _warmup(self, bundle: RuleBundle) -> None:
        """Compile EVERY (batch-bucket, length-bucket) shape before the
        bundle publishes: no request — whatever its batch size — ever pays
        a compile or a 32-wide kernel for a batch of 3. Covers BOTH model
        families: the rule max-merge kernel (skipped for the native host
        kernel, which never compiles) and, when embeddings are attached,
        the cosine top-k kernel over the same bucket grid."""
        warm_rules = bundle.host_rule_ids is None
        warm_emb = bundle.emb_factors is not None
        if not warm_rules and not warm_emb:
            return  # native host kernel, no embeddings: nothing compiles
        # sharded layout warms ITS kernel (per-shard lookup + cross-device
        # max-merge) over the same bucket grid — every sharded bucket is
        # compiled before publication, same zero-compile contract. Mesh
        # layout warms the kernel's two factored halves instead: the
        # local slab partial (served to peers AND dispatched locally) and
        # the rank-stacked merge — every gang member compiles both for
        # every bucket before its bundle publishes.
        warm_mesh = warm_rules and bundle.layout == "mesh"
        kernel = (
            (bundle.shard_kernel or self._resolve_kernel())
            if warm_rules and not warm_mesh else None
        )
        if warm_mesh:
            from ..ops.serve import merge_partial_topk, shard_partial_topk
        for length in self._len_buckets():
            for batch in self._batch_buckets():
                seeds = jnp.full((batch, length), -1, dtype=jnp.int32)
                target = bundle.seed_sharding or bundle.device
                rule_seeds = seeds
                if target is not None:
                    # commit the seeds to the replica's device (or, in
                    # sharded layout, replicate them over the mesh) so the
                    # warmed executable is the one its dispatches will hit
                    rule_seeds = jax.device_put(seeds, target)
                if warm_mesh:
                    kb = self.cfg.k_best_tracks
                    part_ids, part_confs = shard_partial_topk(
                        bundle.rule_ids, bundle.rule_confs, rule_seeds,
                        bundle.mesh_lo, v=bundle.mesh_v, k_best=kb,
                    )
                    stack_ids = jnp.broadcast_to(
                        part_ids, (bundle.n_shards,) + part_ids.shape
                    )
                    stack_confs = jnp.broadcast_to(
                        part_confs, (bundle.n_shards,) + part_confs.shape
                    )
                    jax.block_until_ready(
                        merge_partial_topk(
                            stack_ids, stack_confs,
                            v=bundle.mesh_v, k_best=kb,
                        )
                    )
                    bundle.warmed_shapes.add((batch, length))
                elif warm_rules:
                    jax.block_until_ready(
                        kernel(bundle.rule_ids, bundle.rule_confs, rule_seeds)
                    )
                    bundle.warmed_shapes.add((batch, length))
                if warm_emb:
                    # the embedding kernel dispatches with _dispatch_embed's
                    # placement (bundle.device; default placement in the
                    # sharded layout, where only the RULE tensors span the
                    # mesh) — warm with the same placement, or the warmed
                    # executable would not be the dispatched one
                    emb_seeds = (
                        jax.device_put(seeds, bundle.device)
                        if bundle.device is not None else seeds
                    )
                    jax.block_until_ready(
                        embed_topk(
                            bundle.emb_factors, emb_seeds,
                            k_best=self.cfg.k_best_tracks,
                        )
                    )
                    bundle.emb_warmed_shapes.add((batch, length))

    def prewarm_touch(self) -> int:
        """Predictive shape pre-touch (ISSUE 17, actuator a): re-dispatch
        the LARGEST warmed (batch, length) bucket once per device replica
        on the live bundle, so the big-batch executables and every
        replica's dispatch path are hot before a predicted ramp sends
        real traffic through them. Publish-time warmup already compiled
        every bucket — this touch pays one dispatch per replica, never a
        compile (the shape is in ``warmed_shapes``). Best-effort and off
        the request path: the batcher runs it on a daemon thread once
        per ramp episode; failures are logged and ignored (a missed
        touch just means the ramp is served as reactively as before).
        Mesh bundles are skipped (their partial-fetch warmup is gang-
        coordinated at publish; a solo re-touch would not exercise the
        peer path). → shapes touched."""
        replicas = self.replicas
        if not replicas:
            return 0
        batch = self._batch_buckets()[-1]
        length = self._len_buckets()[-1]
        touched = 0
        for bundle in replicas:
            warm_rules = (
                bundle.host_rule_ids is None and bundle.layout != "mesh"
            )
            warm_emb = bundle.emb_factors is not None
            if not warm_rules and not warm_emb:
                continue
            try:
                seeds = jnp.full((batch, length), -1, dtype=jnp.int32)
                if warm_rules:
                    target = bundle.seed_sharding or bundle.device
                    rule_seeds = (
                        jax.device_put(seeds, target)
                        if target is not None else seeds
                    )
                    kernel = bundle.shard_kernel or self._resolve_kernel()
                    jax.block_until_ready(
                        kernel(bundle.rule_ids, bundle.rule_confs, rule_seeds)
                    )
                    touched += 1
                if warm_emb:
                    emb_seeds = (
                        jax.device_put(seeds, bundle.device)
                        if bundle.device is not None else seeds
                    )
                    jax.block_until_ready(
                        embed_topk(
                            bundle.emb_factors, emb_seeds,
                            k_best=self.cfg.k_best_tracks,
                        )
                    )
                    touched += 1
            except Exception:
                logger.exception("predictive pre-touch failed (ignored)")
        return touched

    def _read_measured_blend_weight(self) -> float | None:
        """The quality loop's published blend optimum (ISSUE 14), or
        None — measured mode off, no report on the PVC, or a report
        without a usable weight. Fail-SOFT: the serving default is
        always a legitimate answer; a missing measurement must degrade
        the decision, never the reload."""
        if not getattr(self.cfg, "hybrid_blend_measured", False):
            return None
        report = artifacts.load_quality_report(self.cfg.pickles_dir)
        weight = report.get("measured_blend_weight") if report else None
        if isinstance(weight, (int, float)) and 0.0 <= float(weight) <= 1.0:
            return float(weight)
        logger.warning(
            "KMLS_HYBRID_BLEND_WEIGHT=measured but no usable "
            "quality.report.json on the PVC (report %s); serving the "
            "default weight %.2f",
            "absent" if report is None else "carries no measured weight",
            self.cfg.hybrid_blend_weight,
        )
        return None

    @property
    def blend_weight(self) -> float:
        """The EFFECTIVE hybrid blend weight: the measured optimum when
        KMLS_HYBRID_BLEND_WEIGHT=measured published one, else the
        configured float (which is also the fail-safe when measurement
        was requested but no report exists)."""
        if self.measured_blend_weight is not None:
            return self.measured_blend_weight
        return self.cfg.hybrid_blend_weight

    @property
    def embedding_active(self) -> bool:
        """True when the published bundle carries ALS item factors (the
        hybrid merge path is live)."""
        bundle = self.bundle
        return bundle is not None and bundle.emb_factors is not None

    @property
    def host_kernel_active(self) -> bool:
        """True when the current bundle serves through the native host
        kernel — its ``finish()`` is a sub-millisecond, GIL-releasing C
        call, safe to run inline on an event loop (the async batcher uses
        this to skip the executor hop entirely)."""
        bundle = self.bundle
        return bundle is not None and bundle.host_rule_ids is not None

    def reload_if_required(self) -> None:
        """Reference: reload when stale or never fully loaded
        (rest_api/app/main.py:110-114). After a FAILED reload this retries
        on the exponential backoff ladder instead of every poll/nudge —
        the staleness signal survives untouched (is_data_stale is pure),
        so the retry always happens; it just stops being a busy loop
        against a poison artifact.

        Continuous freshness rides the same poll: a NOT-stale generation
        still checks the delta chain and applies new bundles in place
        (rejections back off on ``_delta_backoff_until`` so a poison
        bundle can't turn the poller into a digest-hashing busy loop;
        direct :meth:`apply_pending_deltas` calls always go through,
        mirroring load())."""
        if time.monotonic() < self._backoff_until:
            return
        if self.is_data_stale() or not self.finished_loading:
            if self.load():
                self.apply_pending_deltas()
        elif (
            self.cfg.delta_enabled
            and time.monotonic() >= self._delta_backoff_until
        ):
            self.apply_pending_deltas()

    # ---------- continuous freshness: in-place delta application ----------

    def freshness_lag_s(self) -> float:
        """Age of the newest APPLIED generation (base publication or
        delta chain entry) — what dashboards alert on as freshness lag.
        0.0 before the first load."""
        if not self._applied_written_at:
            return 0.0
        return max(time.time() - self._applied_written_at, 0.0)

    @staticmethod
    def _file_written_at(path: str, fallback: float) -> float:
        """Best-effort artifact publication stamp: the file's mtime, or
        the generation's manifest stamp when the file can't answer."""
        try:
            return os.path.getmtime(path)
        except OSError:
            return fallback

    def artifact_ages(self) -> dict[str, float]:
        """Per-artifact freshness age (seconds since publication) for
        every artifact the server currently answers from — the
        staleness-bound surface /readyz and the
        ``kmls_artifact_age_seconds`` gauge report. ``delta-chain`` is
        the age of the newest APPLIED generation (base or delta): with
        no deltas applied it equals ``rules``, and a delta apply shrinks
        it without touching the base stamp — exactly the gap the delta
        path exists to shrink. Empty before the first load."""
        if not self._artifact_written_at:
            return {}
        now = time.time()
        out = {
            name: max(now - stamp, 0.0)
            for name, stamp in self._artifact_written_at.items()
        }
        out["delta-chain"] = self.freshness_lag_s()
        return out

    def _note_publish_cost(self, replicas: list[RuleBundle]) -> None:
        """Publish-time cost-model bookkeeping (caller holds
        ``_reload_lock``; cost model known non-None): the analytic
        tensor residency the layout decision measured, the live
        bytes-in-use watermark where the backend reports one, and the
        compile-watch snapshot for every jitted kernel this generation
        dispatches (taken AFTER warmup, so post-publish cache growth is
        exactly a compile on the serving path)."""
        cm = self.cost_model
        bundle = replicas[0]
        tensor_bytes = {
            "rule_ids": int(bundle.rule_ids.nbytes),
            "rule_confs": int(bundle.rule_confs.nbytes),
        }
        if bundle.emb_factors is not None:
            tensor_bytes["embeddings"] = int(bundle.emb_factors.nbytes)
        cm.note_publish(
            tensor_bytes,
            self.cfg.device_budget_bytes,
            n_shards=bundle.n_shards,
            watermark_bytes=costmodel_mod.device_watermark_bytes(
                bundle.device
            ),
        )
        if bundle.host_rule_ids is None:
            if bundle.layout == "mesh":
                # the gang dispatch composes the kernel's two factored
                # halves — watch both jit caches under one name (the
                # snapshot sums, so any post-publish compile on either
                # half reads as serving-path compile growth)
                from ..ops.serve import merge_partial_topk, shard_partial_topk

                cm.watch_compiles("serve_mesh", shard_partial_topk)
                cm.watch_compiles("serve_mesh_merge", merge_partial_topk)
            elif bundle.shard_kernel is not None:
                cm.watch_compiles("serve_sharded", bundle.shard_kernel)
            else:
                kernel = self._resolve_kernel()
                # the engine wraps the jitted fn in a partial(k_best=);
                # the jit cache lives on the underlying function
                cm.watch_compiles(
                    "serve_rules", getattr(kernel, "func", kernel)
                )
        if bundle.emb_factors is not None:
            cm.watch_compiles("embed_topk", embed_topk)
        cm.mark_published()

    def _note_delta_rejection(self, seq: int, message: str) -> None:
        self.delta_rejected_total += 1
        self.last_delta_error = message
        self._delta_backoff_until = (
            time.monotonic() + self.cfg.reload_backoff_base_s
        )
        logger.warning(
            "delta bundle %d REJECTED (%s); base generation keeps "
            "serving, retry after %.1fs",
            seq, message, self.cfg.reload_backoff_base_s,
        )

    def apply_pending_deltas(self) -> int:
        """Apply every not-yet-applied bundle of the current generation's
        delta chain IN PLACE → bundles applied.

        Each apply rebuilds the replica set from the patched host tensors
        through the same array path a fresh load uses (per-device
        ``device_put``; vocab-sharded layout included), re-warms the
        kernel buckets (a no-op cost when shapes are unchanged — the jit
        cache hits), and swaps the replica references WITHOUT bumping
        ``bundle_epoch``: the answer cache invalidates selectively via
        ``delta_listeners`` (only keys whose seeds intersect the touched
        vocab). The one exception is a blend-mode hybrid bundle whose
        ``n_playlists`` moved — the global 1/P confidence rescale shifts
        every blended ranking, so that apply bumps the epoch (wholesale
        invalidation, the safe direction). Any validation failure — torn
        bytes, wrong base binding, chain gap, the ``delta.apply`` chaos
        site — rejects the bundle and keeps the current state serving:
        bad delta ⇒ keep base, never a 5xx."""
        if not self.cfg.delta_enabled or not self.finished_loading:
            return 0
        state = artifacts.read_delta_state(self.cfg.pickles_dir)
        if state is None:
            return 0
        from ..freshness import delta as delta_mod

        applied = 0
        with self._reload_lock:
            if state.get("base_token") != self.cache_value:
                return 0  # chain for another generation: inert here
            # chain-length gauge: the compaction trigger must be visible
            # BEFORE the compactor acts on it, whether or not anything
            # below is new enough to apply
            self.delta_chain_length = len(state.get("entries", ()))
            pending = [
                e for e in sorted(
                    state.get("entries", []), key=lambda e: e.get("seq", 0)
                )
                if e.get("seq", 0) > self.delta_seq
            ]
            if not pending:
                return 0
            if self._host_state is None:
                logger.warning(
                    "delta chain present but this bundle has no patchable "
                    "host tensors (pickle-only load or merged-confidence "
                    "artifact); serving the base generation"
                )
                return 0
            if self.cost_model is not None:
                # same pre-warmup banking as load(): the applies below
                # re-warm patched tensors legitimately
                self.cost_model.note_prepublish()
            for entry in pending:
                seq = int(entry.get("seq", 0))
                if seq != self.delta_seq + 1:
                    self._note_delta_rejection(
                        seq, f"chain gap: expected seq {self.delta_seq + 1}"
                    )
                    break
                path = os.path.join(
                    self.cfg.pickles_dir, str(entry.get("file", ""))
                )
                try:
                    # chaos hook: KMLS_FAULT_DELTA_CORRUPT rejects here
                    faults.fire("delta.apply")
                    bundle = artifacts.load_delta_bundle(
                        path, expect_sha256=entry.get("sha256")
                    )
                    if bundle["base_token"] != self.cache_value:
                        raise ValueError(
                            "bundle base token != serving generation"
                        )
                    if (
                        self._base_npz_sha is not None
                        and bundle["base_npz_sha256"] != self._base_npz_sha
                    ):
                        raise ValueError(
                            "bundle bound to different base artifact bytes"
                        )
                    patched = delta_mod.apply_delta_to_tensors(
                        self._host_state, bundle
                    )
                    vocab, rule_ids, rule_confs, known = (
                        delta_mod.derive_serving_arrays(patched)
                    )
                    index = {n: i for i, n in enumerate(vocab)}
                    old_replicas = self.replicas
                    replicas = self._replicas_from_arrays(
                        vocab, index, known, rule_ids, rule_confs,
                        self.cache_value or "",
                    )
                    # the second model family rides along untouched:
                    # factors are already committed to each replica's
                    # device, and their warmed shapes stay warmed
                    for i, nb in enumerate(replicas):
                        if i < len(old_replicas):
                            src = old_replicas[i]
                            nb.emb_factors = src.emb_factors
                            nb.emb_vocab = src.emb_vocab
                            nb.emb_index = src.emb_index
                            nb.emb_warmed_shapes = src.emb_warmed_shapes
                    for nb in replicas:
                        self._warmup(nb)
                except Exception as exc:
                    self._note_delta_rejection(
                        seq, f"{type(exc).__name__}: {exc}"
                    )
                    break
                # blend-mode hybrid + moved P: the uniform confidence
                # rescale shifts every blended ranking, so untouched keys
                # are NOT safe — bump the epoch (wholesale invalidation)
                wholesale = (
                    self.cfg.hybrid_mode == "blend"
                    and any(r.emb_factors is not None for r in replicas)
                    and patched["n_playlists"]
                    != self._host_state["n_playlists"]
                )
                epoch = self.bundle_epoch + (1 if wholesale else 0)
                for nb in replicas:
                    nb.epoch = epoch
                # ordering contract (same as load's): replica references
                # land BEFORE the invalidation signal (epoch bump or the
                # listeners' generation bump), so an answer stored under
                # a post-invalidation key can only have been computed
                # from the patched tensors
                self.replicas = replicas
                self.bundle = replicas[0]
                if wholesale:
                    self.bundle_epoch = epoch
                self._host_state = patched
                self.delta_seq = seq
                self.delta_applied_total += 1
                self.last_delta_error = None
                self._applied_written_at = float(
                    entry.get("written_at") or time.time()
                )
                # cost attribution: an in-place apply re-publishes the
                # patched tensors (new residency, possibly new warmed
                # shapes) — re-snapshot so legitimate re-warm compiles
                # are absorbed exactly like a full publication's
                if self.cost_model is not None:
                    self._note_publish_cost(replicas)
                applied += 1
                touched = delta_mod.touched_names(bundle)
                logger.info(
                    "delta %d applied in place (epoch %d/%d): %d changed "
                    "rows, %d tombstones, %d touched names%s",
                    seq, self.bundle_epoch, self.delta_seq,
                    len(bundle["changed_rows"]), len(bundle["tombstones"]),
                    len(touched),
                    " [wholesale invalidation]" if wholesale else "",
                )
                for fn in list(self.delta_listeners):
                    try:
                        fn(touched, wholesale)
                    except Exception:
                        logger.exception("delta listener failed")
        return applied

    # ---------- lookups ----------

    def _len_buckets(self) -> list[int]:
        """Coarse seed-length buckets: every (batch, length) shape a request
        can produce is warmed at load time, so no request ever pays a
        compile. The cap itself is always a member — a >128-seed bucket must
        be warmable too."""
        cap = self.cfg.max_seed_tracks
        return sorted({min(b, cap) for b in (1, 8, 32, 128)} | {cap})

    def _bucket_len(self, n: int) -> int:
        buckets = self._len_buckets()
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    def _batch_buckets(self) -> list[int]:
        """Power-of-two batch buckets 1, 2, 4, …, up to (and always
        including) ``batch_max_size`` — the full set the warmup compiles."""
        cap = max(self.cfg.batch_max_size, 1)
        buckets = []
        b = 1
        while b < cap:
            buckets.append(b)
            b *= 2
        buckets.append(cap)
        return buckets

    def _bucket_batch(self, n: int) -> int:
        """Smallest warmed batch bucket holding ``n`` rows; oversized
        batches (possible only via direct ``recommend_many`` calls — the
        micro-batcher caps at ``batch_max_size``) round up to a multiple
        of the cap, keeping the shape set bounded."""
        cap = max(self.cfg.batch_max_size, 1)
        if n > cap:
            return ((n + cap - 1) // cap) * cap
        for b in self._batch_buckets():
            if n <= b:
                return b
        return cap

    @staticmethod
    def _fill_seed_rows(
        bundle: RuleBundle, seed_sets: list[list[str]],
        arr: np.ndarray, length: int,
    ) -> np.ndarray:
        """Membership-filter each seed set into its -1-padded row of
        ``arr`` → per-row any-known-seed mask. The ONE copy of the seed
        filtering rule — the native and device paths both go through it,
        which is what keeps them bit-identical."""
        for r, seeds in enumerate(seed_sets):
            ids = [
                bundle.index[s]
                for s in seeds
                if s in bundle.index and bundle.known_mask[bundle.index[s]]
            ][:length]
            arr[r, : len(ids)] = ids
        return (arr[: len(seed_sets)] >= 0).any(axis=1)

    def _stage_seeds(
        self, bundle: RuleBundle, seed_sets: list[list[str]],
        rows: int, length: int,
    ) -> tuple[jax.Array, np.ndarray]:
        """Fill the padded (rows, length) seed-index array and transfer it
        → (device seed array, per-row any-known-seed mask, host). Reuses
        one staging buffer per shape when the backend's ``device_put``
        copies (probed); the known-row mask is snapshotted BEFORE the
        buffer can be refilled by the next dispatch. The transfer targets
        the bundle's own device, so a replica's dispatch runs on the
        replica's chip — the staging buffer is shared across replicas
        (fill + transfer are serialized under the lock either way)."""
        shape = (rows, length)
        with self._staging_lock:
            if _staging_is_safe():
                arr = self._staging.get(shape)
                if arr is None:
                    # _staging_buffer, not np.empty: a 64-byte-aligned
                    # buffer would be zero-copied (aliased) by device_put
                    arr = self._staging.setdefault(
                        shape, _staging_buffer(shape)
                    )
                arr.fill(-1)
            else:
                arr = np.full(shape, -1, dtype=np.int32)
            known_rows = self._fill_seed_rows(bundle, seed_sets, arr, length)
            if bundle.n_shards > 1 and bundle.shard_size > 0:
                # per-shard dispatch accounting: which vocab shard's rows
                # this batch's seed ids actually hit (host integer math on
                # the already-staged buffer — no device sync)
                hit = arr[arr >= 0]
                if hit.size:
                    self._note_shard_dispatch(np.bincount(
                        hit // bundle.shard_size, minlength=bundle.n_shards
                    ))
            seeds_dev = jax.device_put(
                arr, bundle.seed_sharding or bundle.device
            )
        if shape not in bundle.warmed_shapes:
            # a compile is landing on the serving path — count it loudly
            self.unwarmed_dispatches += 1
            logger.warning(
                "unwarmed seed shape %s dispatched (compile on the "
                "serving path); warmed buckets: batches %s x lengths %s",
                shape, self._batch_buckets(), self._len_buckets(),
            )
        return seeds_dev, known_rows

    # ---------- second model family: embedding dispatch + hybrid merge ----

    def _dispatch_embed(
        self, bundle: RuleBundle, seed_sets: list[list[str]],
        n_rows: int, length: int,
    ):
        """Dispatch the embedding cosine top-k for a batch → ``(device
        top_ids, device top_sims, host known-row mask)``, or None when the
        bundle carries no factors / the operator pinned rules-only. Runs
        on the DISPATCH path (no host syncs — jax dispatch is async); the
        caller's ``finish()`` converts the device results. The (n_rows,
        length) shape must come from the warmed bucket grid — an unwarmed
        shape is counted and logged exactly like the rule kernel's."""
        if bundle.emb_factors is None or self.cfg.hybrid_mode == "rules":
            return None
        arr = np.full((n_rows, length), -1, dtype=np.int32)
        known = np.zeros(len(seed_sets), dtype=bool)
        index = bundle.emb_index or {}
        for r, seeds in enumerate(seed_sets):
            ids = [index[s] for s in seeds if s in index][:length]
            arr[r, : len(ids)] = ids
            known[r] = len(ids) > 0
        if not known.any():
            # no row has an embed-known seed: the kernel's output would be
            # ignored wholesale — skip the transfer + full-vocab matmul
            return None
        seeds_dev = jax.device_put(arr, bundle.device)
        shape = (n_rows, length)
        if shape not in bundle.emb_warmed_shapes:
            self.unwarmed_dispatches += 1
            logger.warning(
                "unwarmed embedding seed shape %s dispatched (compile on "
                "the serving path); warmed buckets: batches %s x lengths %s",
                shape, self._batch_buckets(), self._len_buckets(),
            )
        top_ids, top_sims = embed_topk(
            bundle.emb_factors, seeds_dev, k_best=self.cfg.k_best_tracks
        )
        return top_ids, top_sims, known

    def _compose_answer(
        self, bundle: RuleBundle, seeds: list[str], rule_known: bool,
        ids_row, confs_row, emb_row,
    ) -> tuple[list[str], str]:
        """Merge the two model families' top-k for ONE request → (songs,
        source ∈ {"rules", "embed", "hybrid", "fallback", "empty"}).

        ``emb_row`` is ``(ids, sims, known)`` host rows or None (no
        embeddings / rules-only mode) — None reproduces the legacy
        rules-only behavior bit for bit. The merge is pure host float
        arithmetic over ≤ 2·k candidates with a deterministic tie order
        (score desc, name asc), so every replica — and every cache epoch
        over identical artifacts — composes the identical answer."""
        emb_known = emb_row is not None and bool(emb_row[2])
        if not rule_known and not emb_known:
            return self.static_recommendation(seeds), "fallback"
        if not emb_known:
            songs = [bundle.vocab[int(i)] for i in ids_row if i >= 0]
            return songs, ("rules" if songs else "empty")
        emb_pairs = [
            (bundle.emb_vocab[int(i)], float(s))
            for i, s in zip(emb_row[0], emb_row[1])
            if i >= 0
        ]
        if self.cfg.hybrid_mode == "embed" or not rule_known:
            # embed-only mode, or a cold-start seed the rules have never
            # seen: the embedding answer IS the answer (this is the
            # scenario the second model family exists for)
            songs = [n for n, _ in emb_pairs]
            return songs, ("embed" if songs else "empty")
        # blend: union of both candidate lists, scores mixed by the
        # effective weight (the knob, or the measured optimum under
        # KMLS_HYBRID_BLEND_WEIGHT=measured) — one shared merge with the
        # offline harness, so eval numbers describe this exact ranking
        rule_pairs = [
            (bundle.vocab[int(i)], float(c))
            for i, c in zip(ids_row, confs_row)
            if i >= 0
        ]
        songs = blend_candidates(
            rule_pairs, emb_pairs, self.blend_weight, self.cfg.k_best_tracks
        )
        return songs, ("hybrid" if songs else "empty")

    def recommend(self, seed_tracks: list[str]) -> tuple[list[str], str]:
        """→ (songs, source), source ∈ {"rules", "embed", "hybrid",
        "fallback", "empty"}.

        Mirrors rest_api/app/main.py:224-254, including: degraded fallback
        while rules are loading (:225-228), membership filter (:235),
        fallback only when NO seed is known to EITHER model family
        (:236-238 — the reference knows only rules), and results that may
        legitimately be empty when all known seeds have empty rows.
        """
        bundle = self.bundle
        if bundle is None:
            # degrade + nudge a reload, like the reference's late-load path
            threading.Thread(target=self.reload_if_required, daemon=True).start()
            return self.static_recommendation(seed_tracks), "fallback"
        if bundle.layout == "mesh":
            # a mesh answer needs the gang fan-out either way — route
            # through the batched dispatch/finish pair (per-request
            # semantics are identical; MeshShardUnavailable propagates)
            return self._mesh_recommend_async(bundle, [seed_tracks], 0)()[0]
        known_ids = [
            bundle.index[s]
            for s in seed_tracks
            if s in bundle.index and bundle.known_mask[bundle.index[s]]
        ]
        # dispatch the embedding kernel FIRST (async — the known mask is
        # host-computed at dispatch, no sync), then the rule kernel, and
        # only convert results after both are in flight: the two device
        # calls overlap instead of serializing, mirroring the batched
        # path's dispatch-both-then-finish discipline
        emb = self._dispatch_embed(
            bundle, [seed_tracks], 1,
            self._bucket_len(max(len(seed_tracks), 1)),
        )
        if not known_ids and (emb is None or not emb[2][0]):
            logger.info("no seed of %d known; static fallback", len(seed_tracks))
            return self.static_recommendation(seed_tracks), "fallback"
        ids = confs = None
        if known_ids:
            known_ids = known_ids[: self.cfg.max_seed_tracks]
            if bundle.host_rule_ids is not None:
                from . import native_serve

                arr = np.full((1, max(len(known_ids), 1)), -1, dtype=np.int32)
                arr[0, : len(known_ids)] = known_ids
                top_ids, top_confs = native_serve.serve_topk(
                    bundle.host_rule_ids, bundle.host_rule_confs, arr,
                    self.cfg.k_best_tracks,
                )
                ids, confs = top_ids[0], top_confs[0]
            else:
                length = self._bucket_len(len(known_ids))
                seeds_dev, _ = self._stage_seeds(bundle, [seed_tracks], 1, length)
                top_ids, top_confs = (
                    bundle.shard_kernel or self._resolve_kernel()
                )(bundle.rule_ids, bundle.rule_confs, seeds_dev)
                ids = np.asarray(top_ids[0])
                confs = np.asarray(top_confs[0])
        self._note_dispatch(0)
        emb_row = None
        if emb is not None:
            emb_row = (np.asarray(emb[0])[0], np.asarray(emb[1])[0], emb[2][0])
        return self._compose_answer(
            bundle, seed_tracks, bool(known_ids), ids, confs, emb_row
        )

    def recommend_many_async(
        self, seed_sets: list[list[str]], replica: int | None = None,
        deadline: float | None = None,
    ):
        """Batched lookup split into DISPATCH (device call enqueued, returns
        immediately — jax dispatch is asynchronous) and FINISH (a zero-arg
        callable that blocks on the result and builds the responses).

        The split lets the micro-batcher pipeline device calls: with a
        high-latency host<->device link (this environment's remote-TPU
        tunnel adds ~65 ms per blocked call) a dispatch-block-respond loop
        caps throughput at batch_size/RTT; overlapping the next dispatch
        with the previous transfer removes that ceiling. Per-request
        semantics identical to :meth:`recommend`.

        ``replica`` selects which device replica executes the batch (the
        least-loaded dispatcher in serving/batcher.py passes it); None —
        or the native host kernel — uses the primary. Concurrent batches
        on DIFFERENT replicas run on different devices instead of
        serializing on one in-order execution queue.

        ``deadline`` (perf_counter seconds, the batcher's earliest
        pending deadline) propagates across the mesh as each partial
        frame's remaining-budget field — a gang peer sheds work that
        expired in transit instead of computing it (ISSUE 18). The
        local device paths ignore it (their budget is enforced at the
        app layer, as before)."""
        replicas = self.replicas
        idx = 0
        if replica is not None and replicas:
            idx = replica % len(replicas)
        bundle = replicas[idx] if replicas else self.bundle
        if bundle is None:
            # same late-load nudge as the single-request path
            threading.Thread(target=self.reload_if_required, daemon=True).start()

            def finish_fallback() -> list[tuple[list[str], str]]:
                return [
                    (self.static_recommendation(s), "fallback")
                    for s in seed_sets
                ]

            return finish_fallback
        if bundle.layout == "mesh":
            return self._mesh_recommend_async(
                bundle, seed_sets, idx, deadline=deadline
            )
        if bundle.host_rule_ids is not None:
            # native host kernel: no compile, so no shape bucketing — the
            # seed array is exact-sized, built fresh (it must survive
            # until finish() runs on the completion thread, so it can't
            # share the device path's reusable staging buffers)
            length = min(
                max((len(s) for s in seed_sets), default=1),
                self.cfg.max_seed_tracks,
            )
            arr = np.full((len(seed_sets), length), -1, dtype=np.int32)
            known_rows = self._fill_seed_rows(bundle, seed_sets, arr, length)
            # the embedding kernel IS jitted even next to the native rule
            # kernel, so ITS seed array rides the warmed bucket grid
            emb = self._dispatch_embed(
                bundle, seed_sets,
                self._bucket_batch(max(len(seed_sets), 1)),
                self._bucket_len(
                    max((len(s) for s in seed_sets), default=1)
                ),
            )
            self._note_dispatch(idx)

            cm = self.cost_model

            def finish_native() -> list[tuple[list[str], str]]:
                from . import native_serve

                # chaos hook ON the completion path — where a real kernel
                # failure or stall surfaces (delay faults sleep here, fail
                # faults raise into the batcher's circuit breaker)
                faults.fire("replica.kernel", replica=idx)
                t_kernel = time.perf_counter() if cm is not None else 0.0
                # the ctypes call releases the GIL for the whole batch
                host_ids, host_confs = native_serve.serve_topk(
                    bundle.host_rule_ids, bundle.host_rule_confs, arr,
                    self.cfg.k_best_tracks,
                )
                if cm is not None:
                    # same algorithm as serve_rules, on the host — the
                    # synchronous call IS its own fence
                    cm.observe_kernel(
                        "serve_native",
                        time.perf_counter() - t_kernel,
                        b=len(seed_sets), l=length,
                        k_max=bundle.host_rule_ids.shape[1],
                        v=len(bundle.vocab), k_best=self.cfg.k_best_tracks,
                    )
                emb_host = None
                if emb is not None:
                    # the embed kernel ran on the DEVICE while the native
                    # kernel ran on the host — this fence measures only
                    # the residual wait, so the embed attribution here is
                    # a floor on device time (rates read high; the MFU
                    # cap keeps the headline honest, and the jitted-path
                    # attribution above is the one benches measure)
                    t_emb = time.perf_counter() if cm is not None else 0.0
                    emb_host = (np.asarray(emb[0]), np.asarray(emb[1]), emb[2])
                    if cm is not None:
                        cm.observe_kernel(
                            "embed_topk",
                            time.perf_counter() - t_emb,
                            b=self._bucket_batch(max(len(seed_sets), 1)),
                            l=self._bucket_len(
                                max((len(s) for s in seed_sets), default=1)
                            ),
                            v=len(bundle.emb_vocab or ()),
                            r=int(bundle.emb_factors.shape[1]),
                            k_best=self.cfg.k_best_tracks,
                        )
                out: list[tuple[list[str], str]] = []
                for r, seeds in enumerate(seed_sets):
                    emb_row = None if emb_host is None else (
                        emb_host[0][r], emb_host[1][r], emb_host[2][r]
                    )
                    out.append(self._compose_answer(
                        bundle, seeds, bool(known_rows[r]),
                        host_ids[r], host_confs[r], emb_row,
                    ))
                return out

            return finish_native

        length = self._bucket_len(
            max((len(s) for s in seed_sets), default=1)
        )
        # pad the batch dimension UP to the nearest power-of-two bucket: a
        # varying batch dimension would compile a fresh kernel per distinct
        # size, and padding every batch to the 32-wide cap (the old scheme)
        # made a batch of 3 pay a 32-row kernel — ~8x the work on the
        # scatter/top-k. Every bucket is pre-warmed at bundle publish.
        n_rows = self._bucket_batch(max(len(seed_sets), 1))
        seeds_dev, known_rows = self._stage_seeds(
            bundle, seed_sets, n_rows, length
        )
        # sharded layout dispatches the vocab-sharded lookup (per-shard
        # gather/top-k + cross-device max-merge) resolved at publication;
        # replicated keeps the per-replica kernel
        cm = self.cost_model
        t_kernel = time.perf_counter() if cm is not None else 0.0
        top_ids, top_confs = (bundle.shard_kernel or self._resolve_kernel())(
            bundle.rule_ids, bundle.rule_confs, seeds_dev
        )
        # second model family: the embedding lookup dispatches alongside
        # the rule kernel onto the same replica device — both async, both
        # consumed together in finish()
        emb = self._dispatch_embed(bundle, seed_sets, n_rows, length)
        self._note_dispatch(idx)

        def finish() -> list[tuple[list[str], str]]:
            # chaos hook on the completion path (see finish_native)
            faults.fire("replica.kernel", replica=idx)
            host_ids = np.asarray(top_ids)  # blocks on the device transfer
            host_confs = np.asarray(top_confs)
            if cm is not None:
                # fenced per-kernel attribution (ISSUE 12): the host
                # conversion above IS the fence for the rule kernel (the
                # device executes in order, so the embed kernel hasn't
                # started billing yet); dispatch→fence is the same
                # upper-bound-on-device-time semantics as the batcher's
                # device span, so the derived MFU is a lower bound
                t_rules = time.perf_counter()
                dims = dict(
                    b=n_rows, l=length, k_max=bundle.rule_ids.shape[1],
                    v=len(bundle.vocab), k_best=self.cfg.k_best_tracks,
                    shards=bundle.n_shards,
                )
                if bundle.shard_kernel is not None:
                    cm.observe_kernel(
                        "serve_sharded", t_rules - t_kernel, **dims
                    )
                else:
                    cm.observe_kernel(
                        "serve_rules", t_rules - t_kernel, **dims
                    )
            emb_host = None
            if emb is not None:
                emb_host = (np.asarray(emb[0]), np.asarray(emb[1]), emb[2])
                if cm is not None:
                    # incremental fence: rule kernel already fenced at
                    # t_rules, so this span bills only the embed kernel's
                    # compute + transfer (in-order device queue)
                    cm.observe_kernel(
                        "embed_topk",
                        time.perf_counter() - t_rules,
                        b=n_rows, l=length, v=len(bundle.emb_vocab or ()),
                        r=int(bundle.emb_factors.shape[1]),
                        k_best=self.cfg.k_best_tracks,
                    )
            out: list[tuple[list[str], str]] = []
            for r, seeds in enumerate(seed_sets):
                emb_row = None if emb_host is None else (
                    emb_host[0][r], emb_host[1][r], emb_host[2][r]
                )
                out.append(self._compose_answer(
                    bundle, seeds, bool(known_rows[r]),
                    host_ids[r], host_confs[r], emb_row,
                ))
            return out

        return finish

    def _mesh_recommend_async(
        self, bundle: RuleBundle, seed_sets: list[list[str]], idx: int,
        deadline: float | None = None,
    ):
        """The pod-spanning dispatch/finish pair: fan the staged batch to
        every gang peer FIRST (socket I/O overlaps the local device
        work), dispatch this rank's slab partial, and at finish() stack
        the rank-ordered partials and run the merge — the same two
        functions the single-process shard_map kernel composes, so the
        answer is bit-identical by construction. A dead gang member
        surfaces as :class:`~.mesh.MeshShardUnavailable` out of finish():
        the app maps it to the gang-degraded signal (503 +
        ``X-KMLS-Mesh-Unavailable`` under fleet routing) and the routed
        client spills the request to the next ring peer."""
        from ..ops.serve import merge_partial_topk, shard_partial_topk

        length = self._bucket_len(
            max((len(s) for s in seed_sets), default=1)
        )
        n_rows = self._bucket_batch(max(len(seed_sets), 1))
        shape = (n_rows, length)
        # exact-built host staging (not the reusable buffers): the batch
        # must survive into the peer fan-out — fetch_partials snapshots
        # it before the pool threads serialize it to sockets
        arr = np.full(shape, -1, dtype=np.int32)
        known_rows = self._fill_seed_rows(bundle, seed_sets, arr, length)
        if bundle.shard_size > 0:
            hit = arr[arr >= 0]
            if hit.size:
                self._note_shard_dispatch(np.bincount(
                    hit // bundle.shard_size, minlength=bundle.n_shards
                ))
        # deadline propagation: stamp the REMAINING budget on the peer
        # frames (computed now — staging time already spent), so a
        # backed-up worker sheds expired partials instead of computing
        # results nobody will wait for
        budget_ms = None
        if deadline is not None:
            budget_ms = max(0.0, (deadline - time.perf_counter()) * 1e3)
        finish_remote = self.mesh_coordinator.fetch_partials(
            arr, bundle.model_token or "", budget_ms=budget_ms
        )
        if shape not in bundle.warmed_shapes:
            self.unwarmed_dispatches += 1
            logger.warning(
                "unwarmed seed shape %s dispatched (compile on the "
                "serving path); warmed buckets: batches %s x lengths %s",
                shape, self._batch_buckets(), self._len_buckets(),
            )
        seeds_dev = jax.device_put(arr)
        kb = self.cfg.k_best_tracks
        cm = self.cost_model
        t_kernel = time.perf_counter() if cm is not None else 0.0
        part_ids, part_confs = shard_partial_topk(
            bundle.rule_ids, bundle.rule_confs, seeds_dev, bundle.mesh_lo,
            v=bundle.mesh_v, k_best=kb,
        )
        emb = self._dispatch_embed(bundle, seed_sets, n_rows, length)
        self._note_dispatch(idx)

        def finish() -> list[tuple[list[str], str]]:
            # chaos hook on the completion path (see finish_native)
            faults.fire("replica.kernel", replica=idx)
            local_ids = np.asarray(part_ids)  # blocks on the device
            local_confs = np.asarray(part_confs)
            # blocks on the slowest peer; raises MeshShardUnavailable
            # for the first rank the gang cannot serve through
            parts = finish_remote()
            stack_ids = np.empty(
                (bundle.n_shards,) + local_ids.shape, dtype=np.int32
            )
            stack_confs = np.empty(
                (bundle.n_shards,) + local_confs.shape, dtype=np.float32
            )
            stack_ids[bundle.gang_rank] = local_ids
            stack_confs[bundle.gang_rank] = local_confs
            for rank, (ids_r, confs_r) in parts.items():
                stack_ids[rank] = ids_r
                stack_confs[rank] = confs_r
            # hedged straggler-drop / deadline-shed (ISSUE 18): ranks the
            # coordinator dropped contribute NOTHING to the merge — their
            # slots get -inf confidences so the max-merge never selects
            # them, and every answer is marked degraded (a partial
            # catalog is a degraded answer, never a silent one)
            dropped = getattr(finish_remote, "dropped", None) or []
            for rank in dropped:
                stack_ids[rank] = 0
                stack_confs[rank] = np.float32(-np.inf)
            merged_ids, merged_confs = merge_partial_topk(
                stack_ids, stack_confs, v=bundle.mesh_v, k_best=kb
            )
            host_ids = np.asarray(merged_ids)
            host_confs = np.asarray(merged_confs)
            if cm is not None:
                cm.observe_kernel(
                    "serve_mesh", time.perf_counter() - t_kernel,
                    b=n_rows, l=length, k_max=bundle.rule_ids.shape[1],
                    v=len(bundle.vocab), k_best=kb,
                    shards=bundle.n_shards,
                )
            emb_host = None
            if emb is not None:
                emb_host = (np.asarray(emb[0]), np.asarray(emb[1]), emb[2])
            out: list[tuple[list[str], str]] = []
            for r, seeds in enumerate(seed_sets):
                emb_row = None if emb_host is None else (
                    emb_host[0][r], emb_host[1][r], emb_host[2][r]
                )
                out.append(self._compose_answer(
                    bundle, seeds, bool(known_rows[r]),
                    host_ids[r], host_confs[r], emb_row,
                ))
            if dropped:
                # the degraded source string is the per-request side
                # channel: the app maps it to X-KMLS-Degraded (never a
                # 5xx) and the answer cache refuses to store it, so a
                # recovered gang never serves a stale partial-catalog
                # answer from cache
                self.mesh_straggler_degraded += len(out)
                out = [
                    (songs, "degraded:mesh-straggler") for songs, _src in out
                ]
            finish._kmls_hedge = getattr(finish_remote, "hedge_outcome", None)
            return out

        return finish

    def recommend_many(
        self, seed_sets: list[list[str]]
    ) -> list[tuple[list[str], str]]:
        """Batched device call over aggregated concurrent requests (the QPS
        path): ONE kernel invocation serves the whole batch."""
        return self.recommend_many_async(seed_sets)()

    def static_recommendation(
        self, seed_tracks: list[str], deadline: float | None = None
    ) -> list[str]:
        """Deterministic popular-tracks sample (reference:
        rest_api/app/main.py:205-222), keyed by a stable hash of the seeds.

        ``deadline`` (perf_counter seconds) latency-budgets the fallback
        itself: a request that arrives here with its budget already spent
        gets the cheapest legitimate answer — the head of the popularity
        ranking, no hashing or sampling — so the degraded path can never
        be the thing that blows the deadline further."""
        best = self.best_tracks
        if not best:
            return []
        names = [b["track_name"] for b in best]
        k = min(self.cfg.k_best_tracks, len(names))
        if deadline is not None and time.perf_counter() >= deadline:
            return names[:k]
        rng = random.Random(stable_seed(seed_tracks))
        return rng.sample(names, k)

    # ---------- background polling ----------

    def start_polling(self) -> threading.Thread:
        """First load + periodic staleness re-check, like the reference's
        lifespan + @repeat_every timer (rest_api/app/main.py:100-108)."""

        def loop() -> None:
            interval = max(self.cfg.polling_wait_in_minutes * 60.0, 0.05)
            while True:  # first load included: a crash must not kill the poller
                try:
                    self.reload_if_required()
                except Exception:
                    logger.exception("reload failed; will retry next poll")
                time.sleep(interval)

        thread = threading.Thread(target=loop, daemon=True, name="kmls-reload-poller")
        thread.start()
        return thread
