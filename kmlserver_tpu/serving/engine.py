"""The online recommendation engine: HBM-resident rule tensors, a jitted
lookup kernel, and a double-buffered hot swap driven by the reference's
polling protocol.

Reference behaviors replicated (rest_api/app/main.py):

- artifact loading (:52-80): ``best_tracks.pickle`` is required — but where
  the reference raises and crash-loops on a fresh/empty PVC (its report lists
  this as risk #2), this engine fails SOFT: ``load()`` returns False and the
  readiness endpoint gates traffic until the first mining run lands.
- staleness detection (:82-97): compare the cached token against
  ``last_execution.txt`` content; missing file counts as stale; the cached
  value doubles as the response's ``model_date``.
- reload loop (:100-122): first load at startup + periodic re-check; a
  reload builds a complete new :class:`RuleBundle` and swaps ONE reference —
  in-flight requests keep the old bundle (the double-buffer makes the
  reference's acknowledged read-mid-swap race structurally impossible).
- lookup (:224-254): seeds filtered by rule-key membership (frequent
  singletons with empty rows ARE members); no known seed → deterministic
  static fallback (:205-222); otherwise the batched device kernel
  (ops/serve.py) does the max-merge + top-k.
- the static fallback's determinism (:214): the reference seeds ``random``
  with ``hash(tuple(sorted(seeds)))``, which is process-salted in modern
  Python (deterministic only within one process); here the seed is a stable
  blake2 digest so all replicas agree — a documented deliberate fix.

The engine prefers the tensor-native npz artifact (straight ``device_put``)
and falls back to the reference-format pickle, so it can serve a PVC
populated by either the rebuild's or the reference's mining job.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import random
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ServingConfig
from ..io import artifacts, registry
from ..ops.serve import recommend_batch

logger = logging.getLogger("kmlserver_tpu.serving")


def stable_seed(seed_tracks: list[str]) -> int:
    """Process-independent replacement for the reference's salted
    ``hash(tuple(sorted(seed_tracks)))`` (rest_api/app/main.py:214)."""
    digest = hashlib.blake2b(
        "\x1f".join(sorted(seed_tracks)).encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


@dataclasses.dataclass
class RuleBundle:
    """One immutable generation of serving state. Swapped atomically."""

    vocab: list[str]
    index: dict[str, int]
    rule_ids: jax.Array  # device, int32 (V, K)
    rule_confs: jax.Array  # device, float32 (V, K)
    known_mask: np.ndarray  # host, bool (V,) — rule-dict key membership
    model_token: str  # token value when loaded


class RecommendEngine:
    """Holds serving state and executes lookups. Thread-safe: the bundle and
    best-tracks references are replaced atomically; readers never block."""

    def __init__(self, cfg: ServingConfig):
        self.cfg = cfg
        self.bundle: RuleBundle | None = None
        self.best_tracks: list[dict] | None = None
        self.cache_value: str | None = None  # the reference's app.cache_value
        self.finished_loading = False
        self.reload_counter = 0
        self._reload_lock = threading.Lock()
        self._kernel = partial(recommend_batch, k_best=cfg.k_best_tracks)

    # ---------- artifact loading / hot swap ----------

    def _token_path(self) -> str:
        return registry.token_path_for(self.cfg.base_dir, self.cfg.data_invalidation_file)

    def _read_token(self) -> str | None:
        try:
            return artifacts.read_text(self._token_path())
        except FileNotFoundError:
            return None

    def is_data_stale(self) -> bool:
        """Token-comparison staleness (reference: rest_api/app/main.py:82-97);
        missing token file counts as stale.

        Deliberate divergence: the reference's check UPDATES its cached token
        as a side effect, so (a) a failed reload permanently swallows the
        staleness signal and (b) ``model_date`` advertises data that isn't
        being served yet. Here the check is pure — ``cache_value`` moves only
        when a new bundle actually loads, so ``model_date`` always describes
        the rules answering the request."""
        token = self._read_token()
        if token is None:
            logger.warning("invalidation token %s missing", self._token_path())
            return True
        if token != self.cache_value:
            logger.info("data stale: token changed %r -> %r", self.cache_value, token)
            return True
        return False

    def load(self) -> bool:
        """Build a fresh bundle from the PVC; atomic swap on success.
        Returns False (fail-soft) when artifacts aren't there yet."""
        with self._reload_lock:
            # re-check under the lock: concurrent "nudge" threads that queued
            # behind an in-flight load must not repeat it (their staleness
            # decision predates the load that just completed)
            if self.finished_loading and not self.is_data_stale():
                return True
            cfg = self.cfg
            best_path = os.path.join(cfg.pickles_dir, cfg.best_tracks_file)
            rec_path = os.path.join(cfg.pickles_dir, cfg.recommendations_file)
            npz_path = artifacts.tensor_artifact_path(rec_path)
            try:
                best = artifacts.load_pickle(best_path)
                bundle = self._build_bundle(rec_path, npz_path)
                # warm the serving kernel for every seed-bucket shape BEFORE
                # publishing: the first jit compile costs seconds on TPU and
                # must not land inside a request (readiness implies warmed).
                # Reloads with unchanged tensor shapes hit the jit cache and
                # skip this. Inside the try: tensors that np.load accepts
                # but the kernel rejects must fail-soft too.
                self._warmup(bundle)
            except FileNotFoundError as exc:
                logger.warning("artifacts not ready: %s", exc)
                return False
            except Exception:
                # corrupt/torn artifact (the REFERENCE mining job writes
                # non-atomically — its report acknowledges the race; this
                # engine must serve either side's PVC): keep the current
                # bundle, retry on the next poll
                logger.exception("artifact load failed; keeping current bundle")
                return False
            # atomic publication: single reference assignments
            self.best_tracks = best
            self.bundle = bundle
            self.cache_value = bundle.model_token or self.cache_value
            self.finished_loading = True
            self.reload_counter += 1
            logger.info(
                "reload #%d complete: %d tracks, %d rule keys, token %r",
                self.reload_counter, len(bundle.vocab),
                int(bundle.known_mask.sum()), bundle.model_token,
            )
            return True

    def _build_bundle(self, rec_path: str, npz_path: str) -> RuleBundle:
        token = self._read_token() or ""
        loaded = None
        if self.cfg.prefer_tensor_artifact and os.path.exists(npz_path):
            try:
                loaded = artifacts.load_rule_tensors(npz_path)
            except Exception:
                # torn/corrupt npz next to a possibly-intact pickle of the
                # same generation: fall through to the pickle rather than
                # abandoning the whole reload
                logger.exception(
                    "tensor artifact %s unreadable; trying the pickle", npz_path
                )
        if loaded is not None:
            vocab = loaded["vocab"]
            rule_ids = loaded["rule_ids"]
            rule_confs = loaded["rule_confs"]
            from ..ops.support import min_count_for

            known = loaded["item_counts"] >= min_count_for(
                loaded["min_support"], loaded["n_playlists"]
            )
        else:
            rules_dict = artifacts.load_pickle(rec_path)
            vocab = sorted(
                set(rules_dict)
                | {o for row in rules_dict.values() for o in row}
            )
            rule_ids, rule_confs, known = artifacts.tensors_from_rules_dict(
                rules_dict, vocab, k_max=max(
                    (len(r) for r in rules_dict.values()), default=1
                ),
            )
        return RuleBundle(
            vocab=vocab,
            index={n: i for i, n in enumerate(vocab)},
            rule_ids=jax.device_put(jnp.asarray(rule_ids)),
            rule_confs=jax.device_put(jnp.asarray(rule_confs)),
            known_mask=np.asarray(known),
            model_token=token,
        )

    def _warmup(self, bundle: RuleBundle) -> None:
        for length in self._len_buckets():
            for batch in (1, self.cfg.batch_max_size):
                seeds = jnp.zeros((batch, length), dtype=jnp.int32)
                jax.block_until_ready(
                    self._kernel(bundle.rule_ids, bundle.rule_confs, seeds)
                )

    def reload_if_required(self) -> None:
        """Reference: reload when stale or never fully loaded
        (rest_api/app/main.py:110-114)."""
        if self.is_data_stale() or not self.finished_loading:
            self.load()

    # ---------- lookups ----------

    def _len_buckets(self) -> list[int]:
        """Coarse seed-length buckets: every (batch, length) shape a request
        can produce is warmed at load time, so no request ever pays a
        compile. The cap itself is always a member — a >128-seed bucket must
        be warmable too."""
        cap = self.cfg.max_seed_tracks
        return sorted({min(b, cap) for b in (1, 8, 32, 128)} | {cap})

    def _bucket_len(self, n: int) -> int:
        buckets = self._len_buckets()
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    def recommend(self, seed_tracks: list[str]) -> tuple[list[str], str]:
        """→ (songs, source) where source ∈ {"rules", "fallback", "empty"}.

        Mirrors rest_api/app/main.py:224-254, including: degraded fallback
        while rules are loading (:225-228), membership filter (:235),
        fallback only when NO seed is known (:236-238), and results that may
        legitimately be empty when all known seeds have empty rows.
        """
        bundle = self.bundle
        if bundle is None:
            # degrade + nudge a reload, like the reference's late-load path
            threading.Thread(target=self.reload_if_required, daemon=True).start()
            return self.static_recommendation(seed_tracks), "fallback"
        known_ids = [
            bundle.index[s]
            for s in seed_tracks
            if s in bundle.index and bundle.known_mask[bundle.index[s]]
        ]
        if not known_ids:
            logger.info("no seed of %d known; static fallback", len(seed_tracks))
            return self.static_recommendation(seed_tracks), "fallback"
        known_ids = known_ids[: self.cfg.max_seed_tracks]
        length = self._bucket_len(len(known_ids))
        seed_arr = np.full((1, length), -1, dtype=np.int32)
        seed_arr[0, : len(known_ids)] = known_ids
        top_ids, top_confs = self._kernel(
            bundle.rule_ids, bundle.rule_confs, jnp.asarray(seed_arr)
        )
        ids = np.asarray(top_ids[0])
        songs = [bundle.vocab[int(i)] for i in ids if i >= 0]
        return songs, ("rules" if songs else "empty")

    def recommend_many_async(self, seed_sets: list[list[str]]):
        """Batched lookup split into DISPATCH (device call enqueued, returns
        immediately — jax dispatch is asynchronous) and FINISH (a zero-arg
        callable that blocks on the result and builds the responses).

        The split lets the micro-batcher pipeline device calls: with a
        high-latency host<->device link (this environment's remote-TPU
        tunnel adds ~65 ms per blocked call) a dispatch-block-respond loop
        caps throughput at batch_size/RTT; overlapping the next dispatch
        with the previous transfer removes that ceiling. Per-request
        semantics identical to :meth:`recommend`."""
        bundle = self.bundle
        if bundle is None:
            # same late-load nudge as the single-request path
            threading.Thread(target=self.reload_if_required, daemon=True).start()

            def finish_fallback() -> list[tuple[list[str], str]]:
                return [
                    (self.static_recommendation(s), "fallback")
                    for s in seed_sets
                ]

            return finish_fallback
        length = self._bucket_len(
            max((len(s) for s in seed_sets), default=1)
        )
        # pad the batch dimension to a multiple of the canonical size: a
        # varying batch dimension would compile a fresh kernel per distinct
        # size (oversized batches round UP, keeping the shape set bounded)
        step = self.cfg.batch_max_size
        n_rows = ((max(len(seed_sets), 1) + step - 1) // step) * step
        arr = np.full((n_rows, length), -1, dtype=np.int32)
        for r, seeds in enumerate(seed_sets):
            ids = [
                bundle.index[s]
                for s in seeds
                if s in bundle.index and bundle.known_mask[bundle.index[s]]
            ][:length]
            arr[r, : len(ids)] = ids
        top_ids, _ = self._kernel(bundle.rule_ids, bundle.rule_confs, jnp.asarray(arr))

        def finish() -> list[tuple[list[str], str]]:
            host_ids = np.asarray(top_ids)  # blocks on the device transfer
            out: list[tuple[list[str], str]] = []
            for r, seeds in enumerate(seed_sets):
                if (arr[r] >= 0).any():
                    songs = [bundle.vocab[int(i)] for i in host_ids[r] if i >= 0]
                    out.append((songs, "rules" if songs else "empty"))
                else:
                    out.append((self.static_recommendation(seeds), "fallback"))
            return out

        return finish

    def recommend_many(
        self, seed_sets: list[list[str]]
    ) -> list[tuple[list[str], str]]:
        """Batched device call over aggregated concurrent requests (the QPS
        path): ONE kernel invocation serves the whole batch."""
        return self.recommend_many_async(seed_sets)()

    def static_recommendation(self, seed_tracks: list[str]) -> list[str]:
        """Deterministic popular-tracks sample (reference:
        rest_api/app/main.py:205-222), keyed by a stable hash of the seeds."""
        best = self.best_tracks
        if not best:
            return []
        names = [b["track_name"] for b in best]
        rng = random.Random(stable_seed(seed_tracks))
        k = min(self.cfg.k_best_tracks, len(names))
        return rng.sample(names, k)

    # ---------- background polling ----------

    def start_polling(self) -> threading.Thread:
        """First load + periodic staleness re-check, like the reference's
        lifespan + @repeat_every timer (rest_api/app/main.py:100-108)."""

        def loop() -> None:
            interval = max(self.cfg.polling_wait_in_minutes * 60.0, 0.05)
            while True:  # first load included: a crash must not kill the poller
                try:
                    self.reload_if_required()
                except Exception:
                    logger.exception("reload failed; will retry next poll")
                time.sleep(interval)

        thread = threading.Thread(target=loop, daemon=True, name="kmls-reload-poller")
        thread.start()
        return thread
