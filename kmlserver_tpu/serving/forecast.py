"""Online traffic forecasting — learn the ramp before the queue does.

Every overload mechanism in the serving stack is reactive: the admission
ladder (PR 8) escalates only after measured queue waits rise,
``kmls_utilization`` tells the HPA about a burst only once it has
landed, and the fleet cache warms a hot seed only after its first miss.
Ramp and flash-crowd shapes therefore pay a p99/shed penalty in exactly
the onset window the bench's ``loadshape`` bracket measures — the
queue has to GROW before anything widens, scales, or warms.

:class:`TrafficForecaster` closes that gap with the cheapest model that
can see a ramp coming: Holt double-exponential smoothing (level +
trend) over fixed arrival-count windows, plus a decayed per-seed-set
frequency table for the request MIX. Fed one ``observe()`` per admitted
request from the batcher's submit path, it answers three questions:

- ``predicted_rate()`` — arrivals/s a short horizon
  (``KMLS_FORECAST_HORIZON_S``) ahead: level + trend·horizon, floored
  at zero. Predictions roll the window clock forward, so the forecast
  DECAYS in real time after a burst ends instead of freezing at the
  burst's last slope.
- ``growth_ratio()`` — predicted over current rate, the dimensionless
  "is a ramp coming" signal the actuators key on (1.0 = steady state).
- ``hot_seed_sets()`` — the top-N seed sets by decayed frequency, the
  pre-fetch candidates for the owner-targeted cache re-materialization
  after a delta apply.

The three actuators and their safety contract (ISSUE 17): (a) the
batcher sizes its adaptive collection window from the PREDICTED arrival
gap when a ramp is forecast, and pre-touches the engine's largest shape
bucket once per ramp episode; (b) ``batcher.utilization()`` gains
:meth:`utilization_lead` — the reactive value scaled by the growth
ratio, clamped to ``[reactive, util_cap]`` so the forecast can raise
the HPA signal but NEVER lower it and never exceed the cap; (c) the app
re-materializes predicted-hot, ring-owned seed sets through the normal
singleflight path after a selective invalidation. A wrong forecast can
only over-provision (earlier scale-out, a wasted pre-touch, a wasted
pre-fetch) — the admission ladder's shed/degrade decisions never read
the forecast, so shedding can never start EARLIER than reactive.

Zero-cost proof (the PR 11 cost-model pattern): with ``KMLS_FORECAST=0``
the app leaves the forecaster hook ``None`` and every call site is one
is-None check, so the module-level ``OBSERVATIONS_TOTAL`` counter below
must stay 0 under any traffic — tests pin it the way the cost model's
observation counter is pinned.

The clock is injectable (``clock=time.monotonic``, the FleetRouter
precedent) so tests drive ramp/sine schedules deterministically.
"""

from __future__ import annotations

import threading
import time

# the zero-cost proof counter: incremented by every observe() in the
# process. The forecaster is only ever reached through a
# `forecaster is not None` check, so with KMLS_FORECAST=0 this must
# never move — a moved counter means a call site dodged the gate.
OBSERVATIONS_TOTAL = 0

# per-window decay applied to the request-mix frequency table: ~0.9 per
# window keeps a seed set "hot" for a few dozen windows after its last
# appearance — long enough to survive a delta apply, short enough that
# yesterday's flash crowd doesn't get pre-fetched today
_MIX_DECAY = 0.9
_MIX_FLOOR = 0.05


class TrafficForecaster:
    """Per-window arrival-rate + request-mix EWMAs with a trend term.

    Holt's linear (double-exponential) smoothing over windows of
    ``window_s`` seconds: when a window closes, its arrival count
    becomes a rate sample ``y``; ``level`` tracks the smoothed rate and
    ``trend`` its slope (arrivals/s per second). Windows with no
    arrivals still close — silence folds in as zero-rate samples when
    the next observation or prediction rolls the clock, so the model
    decays toward reality instead of freezing.

    Thread-safe: ``observe()`` runs on request threads under the
    threaded batcher and on the event loop under the async one; all
    state mutates under one short lock (the roll is O(1) amortized, the
    mix decay O(table) once per window).
    """

    def __init__(
        self,
        *,
        horizon_s: float = 2.0,
        window_s: float = 0.5,
        alpha: float = 0.35,
        trend_alpha: float = 0.3,
        util_cap: float = 1.0,
        ramp_ratio: float = 1.2,
        hot_top_n: int = 8,
        mix_capacity: int = 512,
        clock=time.monotonic,
    ):
        self.horizon_s = max(0.0, float(horizon_s))
        self.window_s = max(1e-3, float(window_s))
        self.alpha = min(1.0, max(0.0, float(alpha)))
        self.trend_alpha = min(1.0, max(0.0, float(trend_alpha)))
        self.util_cap = max(0.0, float(util_cap))
        self.ramp_ratio = max(1.0, float(ramp_ratio))
        self.hot_top_n = max(1, int(hot_top_n))
        self.mix_capacity = max(1, int(mix_capacity))
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0.0   # smoothed arrivals/s
        self._trend = 0.0   # arrivals/s per second
        self._windows = 0   # closed windows folded into the model
        self._win_start: float | None = None
        self._win_count = 0
        # canonical seed key -> [decayed weight, seed list]; bounded by
        # mix_capacity (lowest weights evicted on overflow)
        self._mix: dict[str, list] = {}
        self.observations = 0

    # ---------- feeding ----------

    def observe(self, seeds: list[str] | None = None) -> None:
        """Record one admitted request (and optionally its seed set).
        Called from the batcher's submit path behind the is-None gate —
        this is the ONLY entry point that counts toward the zero-cost
        proof counter."""
        global OBSERVATIONS_TOTAL
        OBSERVATIONS_TOTAL += 1
        now = self._clock()
        with self._lock:
            self.observations += 1
            if self._win_start is None:
                self._win_start = now
            else:
                self._roll_locked(now)
            self._win_count += 1
            if seeds:
                key = "\x1f".join(sorted(seeds))
                entry = self._mix.get(key)
                if entry is None:
                    if len(self._mix) >= self.mix_capacity:
                        coldest = min(
                            self._mix, key=lambda k: self._mix[k][0]
                        )
                        del self._mix[coldest]
                    self._mix[key] = [1.0, list(seeds)]
                else:
                    entry[0] += 1.0

    # ---------- model ----------

    def _roll_locked(self, now: float) -> None:
        """Fold every window that has fully elapsed into level/trend.
        The first closed window carries the counted arrivals; any
        further elapsed windows were silent and fold in as zero-rate
        samples, which is what makes the forecast decay after a burst."""
        if self._win_start is None:
            return
        elapsed = int((now - self._win_start) / self.window_s)
        if elapsed <= 0:
            return
        for i in range(elapsed):
            rate = (self._win_count if i == 0 else 0) / self.window_s
            if self._windows == 0:
                self._level = rate
                self._trend = 0.0
            else:
                prev = self._level
                self._level = self.alpha * rate + (1.0 - self.alpha) * (
                    self._level + self._trend * self.window_s
                )
                # rates are non-negative: without this floor a string of
                # silent windows drives the level negative and the
                # -alpha·level term then flips the trend positive — a
                # damped oscillation around zero that makes a DEAD burst
                # forecast a comeback
                if self._level < 0.0:
                    self._level = 0.0
                self._trend = (
                    self.trend_alpha * (self._level - prev) / self.window_s
                    + (1.0 - self.trend_alpha) * self._trend
                )
            self._windows += 1
            if self._mix:
                dead = []
                for key, entry in self._mix.items():
                    entry[0] *= _MIX_DECAY
                    if entry[0] < _MIX_FLOOR:
                        dead.append(key)
                for key in dead:
                    del self._mix[key]
        self._win_start += elapsed * self.window_s
        self._win_count = 0

    # ---------- predictions ----------

    def current_rate(self, now: float | None = None) -> float:
        """The smoothed CURRENT arrival rate (arrivals/s), after rolling
        the window clock to ``now``."""
        if now is None:
            now = self._clock()
        with self._lock:
            self._roll_locked(now)
            return max(0.0, self._level)

    def predicted_rate(self, now: float | None = None) -> float:
        """Arrivals/s forecast ``horizon_s`` ahead: level +
        trend·horizon, floored at zero (a decaying burst can predict
        below current, never below nothing)."""
        if now is None:
            now = self._clock()
        with self._lock:
            self._roll_locked(now)
            return max(0.0, self._level + self._trend * self.horizon_s)

    def growth_ratio(self, now: float | None = None) -> float:
        """predicted_rate / current_rate — 1.0 at steady state (or with
        no signal yet), >1 when a ramp is forecast, <1 when decay is."""
        if now is None:
            now = self._clock()
        with self._lock:
            self._roll_locked(now)
            if self._level <= 1e-9 or self._windows < 2:
                return 1.0
            predicted = max(
                0.0, self._level + self._trend * self.horizon_s
            )
            return predicted / self._level

    def ramp_predicted(self, now: float | None = None) -> bool:
        """True when the forecast growth ratio clears ``ramp_ratio`` —
        the arm signal for the pre-warm/pre-widen actuators."""
        return self.growth_ratio(now) >= self.ramp_ratio

    def expected_gap_s(self, now: float | None = None) -> float:
        """Mean inter-arrival gap implied by the horizon forecast — what
        the batcher sizes its collection window from when a ramp is
        predicted (the trailing measured gap lags the ramp by
        construction)."""
        rate = self.predicted_rate(now)
        return (1.0 / rate) if rate > 1e-9 else float("inf")

    def utilization_lead(
        self, reactive: float, now: float | None = None
    ) -> float:
        """The bounded HPA-lead term (actuator b): the reactive
        utilization scaled by the forecast growth ratio, clamped to
        ``[reactive, max(reactive, util_cap)]``. Monotone contract: the
        returned value is NEVER below ``reactive`` (the forecast can
        only add lead, never mask measured load) and the forecast
        contribution alone never exceeds ``util_cap`` (only measured
        overload may report past the cap)."""
        ratio = self.growth_ratio(now)
        if ratio <= 1.0:
            return reactive
        return max(reactive, min(self.util_cap, reactive * ratio))

    def hot_seed_sets(self, top_n: int | None = None) -> list[list[str]]:
        """The predicted-hot seed sets, hottest first — the candidate
        list for the owner-targeted post-delta cache pre-fetch
        (actuator c)."""
        n = self.hot_top_n if top_n is None else max(0, int(top_n))
        with self._lock:
            ranked = sorted(
                self._mix.values(), key=lambda e: e[0], reverse=True
            )
            return [list(entry[1]) for entry in ranked[:n]]

    def snapshot(self, now: float | None = None) -> dict:
        """One consistent read of the exposition values (rate,
        prediction, ratio, observation count) for /metrics rendering."""
        if now is None:
            now = self._clock()
        with self._lock:
            self._roll_locked(now)
            level = max(0.0, self._level)
            predicted = max(
                0.0, self._level + self._trend * self.horizon_s
            )
            if self._level <= 1e-9 or self._windows < 2:
                ratio = 1.0
            else:
                ratio = predicted / self._level
            return {
                "rate": level,
                "predicted_rate": predicted,
                "ratio": ratio,
                "observations": self.observations,
            }
