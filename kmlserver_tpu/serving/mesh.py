"""Pod-spanning serve mesh (ISSUE 16) — the gang transport.

PR 7 sharded the rule tensors across one process's local devices; this
module lets the SAME vocab axis span processes/pods, so the servable
catalog scales with the gang instead of capping at one host. A gang of
``KMLS_SERVE_GANG_SIZE`` members (kubernetes/serve-gang.yaml: one
indexed StatefulSet, ordinal → rank) each holds only its own vocab slab
— rows ``[rank·slab, (rank+1)·slab)`` of the padded rule tensors — yet
presents ONE logical replica to the dispatcher and ONE ring member to
the PR 15 ``FleetRouter``.

Two transports, one math:

- **Real collectives** (TPU pods over DCN): the gang joins one JAX
  world via ``parallel.distributed.maybe_initialize_serve_gang`` (the
  mining job's coordinator recipe, reused) and the PR 7 shard_map
  kernel runs globally — pjit/GSPMD places the all_gather on DCN. This
  sandbox has no multi-process GSPMD, so that path is wired but
  exercised only in the standing TPU-window item.
- **Simulation transport** (CPU-testable end to end, this module): each
  "pod" is a real local process owning a slab. Every member runs a
  :class:`MeshWorkerServer` (a tiny length-prefixed TCP protocol — raw
  numpy bytes + a JSON header, no pickle) serving its per-slab top-k
  partial, and a :class:`MeshCoordinator` that fans a request's seed
  batch to its peers, stacks the (rank-ordered) partials, and merges.
  Partial and merge are the EXACT functions the shard_map kernel
  composes (``ops.serve.shard_partial_topk`` / ``merge_partial_topk``
  — the all_gather + max-merge of PR 7, factored out), so gang answers
  are bit-identical to the single-process sharded kernel by
  construction (pinned in tests/test_mesh.py).

Failure model: a dead gang member makes the whole gang degrade exactly
like a dead replica — the engine raises :class:`MeshShardUnavailable`,
the app answers 503 with ``X-KMLS-Mesh-Unavailable: <rank>`` when fleet
routing is armed (the routed client treats it as a transport failure:
circuit-breaker ejection of the WHOLE gang, spill to the next ring
peer, half-open re-admission when the gang re-forms), or falls back to
the degraded popularity answer standalone; ``/readyz`` names the
missing shard (``serve_mesh_shard_missing:<rank>``).
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass

import numpy as np

logger = logging.getLogger("kmlserver_tpu.mesh")

_LEN = struct.Struct("!I")
_MAX_FRAME = 1 << 28  # 256 MiB: no sane seed batch or partial is larger


class MeshShardUnavailable(RuntimeError):
    """A gang member's slab partial could not be obtained — the mesh is
    missing a shard, so a full-catalog answer is impossible. Carries the
    rank so /readyz and the 503 signal can name it."""

    def __init__(self, rank: int, reason: str):
        super().__init__(f"serve mesh shard {rank} unavailable: {reason}")
        self.rank = rank
        self.reason = reason


@dataclass(frozen=True)
class GangConfig:
    """One gang member's identity + addressing.

    ``coordinator`` is rank 0's partial-fetch address (``host:port``).
    Peer addressing derives from it: a hostname carrying the ``-0``
    ordinal (the headless-Service pod DNS recipe —
    ``serve-gang-0.serve-mesh:8477``) maps rank r to the ``-r`` name on
    the SAME port; a bare host (the CPU simulation's ``127.0.0.1``)
    maps rank r to port ``base_port + r`` on the same host."""

    coordinator: str
    size: int
    rank: int

    def peer_address(self, rank: int) -> tuple[str, int]:
        host, _, port_s = self.coordinator.rpartition(":")
        if not host or not port_s.isdigit():
            raise ValueError(
                f"serve gang coordinator must be host:port, got "
                f"{self.coordinator!r}"
            )
        port = int(port_s)
        if "-0." in host:
            return host.replace("-0.", f"-{rank}.", 1), port
        if host.endswith("-0"):
            return f"{host[:-2]}-{rank}", port
        return host, port + rank

    @property
    def my_address(self) -> tuple[str, int]:
        return self.peer_address(self.rank)


def gang_from_config(cfg) -> GangConfig | None:
    """→ this process's :class:`GangConfig`, or None when no gang is
    armed. Same fail-fast contract as the mining bootstrap: a rank
    outside the declared size is a boot-time config error, never a
    hang (parallel/distributed.py:distributed_env)."""
    size = int(getattr(cfg, "serve_gang_size", 1) or 1)
    coordinator = getattr(cfg, "serve_gang_coordinator", "") or ""
    if size <= 1 or not coordinator:
        return None
    rank = int(getattr(cfg, "serve_gang_rank", 0) or 0)
    if rank >= size:
        raise ValueError(
            f"serve gang rank {rank} >= gang size {size}: set "
            "KMLS_SERVE_GANG_SIZE to the StatefulSet's replica count"
        )
    return GangConfig(coordinator=coordinator, size=size, rank=rank)


# ---------------------------------------------------------------------------
# wire protocol: !I header length + JSON header + raw payload bytes.
# Arrays travel as C-order bytes with shape/dtype in the header — no
# pickle anywhere (an artifact server must never eval peer bytes).
# ---------------------------------------------------------------------------


def _send_frame(sock: socket.socket, header: dict, payload: bytes = b""):
    head = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(_LEN.pack(len(head)) + head + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> tuple[dict, bytes]:
    (head_len,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if head_len > _MAX_FRAME:
        raise ConnectionError(f"oversized header ({head_len} bytes)")
    header = json.loads(_recv_exact(sock, head_len))
    n = int(header.get("payload_bytes", 0))
    if not 0 <= n <= _MAX_FRAME:
        raise ConnectionError(f"oversized payload ({n} bytes)")
    return header, _recv_exact(sock, n) if n else b""


class MeshWorkerServer:
    """Every gang member's partial-protocol endpoint: serves this slab's
    (B, k_best) top-k partials to whichever member coordinates a
    request (the design is symmetric — any member can front the gang;
    under the k8s recipe the ring lists the gang Service, so traffic
    lands on whichever pod DNS round-robins to).

    ``serve_partial(seeds) -> (ids, confs, token)`` and
    ``status() -> dict`` come from the engine; this class owns only the
    sockets. Threads are daemonic and connections persistent (one
    framed request/response at a time per connection — the coordinator
    serializes per-peer calls)."""

    def __init__(self, serve_partial, status, host: str = "", port: int = 0):
        self._serve_partial = serve_partial
        self._status = status
        # short bind-retry: a re-forming gang member reuses its rank's
        # port, and the dead incarnation's sockets may still be mid-FIN
        # (SO_REUSEADDR — create_server sets it — already covers the
        # TIME_WAIT case; the retry covers the close race)
        deadline = time.monotonic() + 5.0
        while True:
            try:
                self._sock = socket.create_server((host or "0.0.0.0", port))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.1)
        self.port = self._sock.getsockname()[1]
        # partial frames shed because their deadline budget was already
        # spent when they arrived (wasted-work, not slow-compute)
        self.expired_on_arrival = 0
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="kmls-mesh-worker", daemon=True
        )

    def start(self) -> "MeshWorkerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        try:
            # shutdown BEFORE close: close alone only drops the fd — the
            # accept thread blocked in the syscall keeps the kernel
            # socket (and the port) alive; shutdown aborts the accept
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listening socket closed
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="kmls-mesh-conn", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._stopped.is_set():
                try:
                    header, payload = _recv_frame(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                if self._stopped.is_set():
                    # stop() landed while blocked in recv: drop the
                    # request unanswered — the peer reads the close as
                    # this shard going missing (the test/chaos stand-in
                    # for a SIGKILLed pod, where every socket dies)
                    return
                try:
                    self._handle(conn, header, payload)
                except (BrokenPipeError, ConnectionError, OSError):
                    return

    def _handle(self, conn, header: dict, payload: bytes) -> None:
        op = header.get("op")
        if op == "ready":
            _send_frame(conn, {"ok": True, **self._status()})
            return
        if op != "partial":
            _send_frame(conn, {"ok": False, "error": f"unknown op {op!r}"})
            return
        budget = header.get("budget_ms")
        if budget is not None and float(budget) <= 0.0:
            # deadline propagation (ISSUE 18): the request's remaining
            # budget died in transit — shed instead of computing a
            # partial nobody will wait for. The counter distinguishes
            # wasted-work (expired on ARRIVAL) from slow-compute.
            self.expired_on_arrival += 1
            _send_frame(conn, {"ok": False, "error": "deadline-expired"})
            return
        try:
            b, length = (int(x) for x in header["shape"])
            seeds = np.frombuffer(payload, dtype=np.int32).reshape(b, length)
            ids, confs, token = self._serve_partial(seeds)
        except Exception as exc:  # surfaced to the coordinator, not eaten
            logger.warning("mesh partial failed: %s", exc)
            _send_frame(conn, {"ok": False, "error": str(exc)})
            return
        ids = np.ascontiguousarray(ids, dtype=np.int32)
        confs = np.ascontiguousarray(confs, dtype=np.float32)
        body = ids.tobytes() + confs.tobytes()
        _send_frame(conn, {
            "ok": True, "token": token, "shape": list(ids.shape),
            "payload_bytes": len(body),
        }, body)


class MeshPeerClient:
    """One persistent connection to one gang member's worker endpoint.
    Any transport fault — refused connect, timeout, mid-frame close, a
    peer-side error, a model-token mismatch — closes the socket and
    raises :class:`MeshShardUnavailable` for that rank."""

    def __init__(
        self, rank: int, address: tuple[str, int],
        connect_timeout_s: float = 2.0, request_timeout_s: float = 30.0,
    ):
        self.rank = rank
        self.address = address
        self.connect_timeout_s = connect_timeout_s
        self.request_timeout_s = request_timeout_s
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _request(self, header: dict, payload: bytes) -> tuple[dict, bytes]:
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        self.address, timeout=self.connect_timeout_s
                    )
                    self._sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                self._sock.settimeout(self.request_timeout_s)
                _send_frame(self._sock, header, payload)
                resp, body = _recv_frame(self._sock)
            except (OSError, ConnectionError, ValueError) as exc:
                self._close_locked()
                raise MeshShardUnavailable(
                    self.rank, f"{type(exc).__name__}: {exc}"
                ) from exc
        if not resp.get("ok"):
            raise MeshShardUnavailable(
                self.rank, str(resp.get("error", "peer error"))
            )
        return resp, body

    def partial(
        self, seeds: np.ndarray, token: str,
        budget_ms: float | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """→ this peer slab's (B, k_best) partial for ``seeds``. The
        model token travels both ways: a peer serving a DIFFERENT
        publication (mid-rollout generation skew) must read as a
        missing shard — merging partials across epochs would be silent
        corruption, spilling to a ring peer is a clean answer.
        ``budget_ms`` rides the frame header (deadline propagation): a
        worker receiving an already-expired budget sheds the partial
        instead of computing it."""
        seeds = np.ascontiguousarray(seeds, dtype=np.int32)
        header = {
            "op": "partial", "token": token,
            "shape": list(seeds.shape), "payload_bytes": seeds.nbytes,
        }
        if budget_ms is not None:
            header["budget_ms"] = round(float(budget_ms), 3)
        resp, body = self._request(header, seeds.tobytes())
        if resp.get("token") != token:
            raise MeshShardUnavailable(
                self.rank,
                f"model token mismatch (peer {resp.get('token')!r})",
            )
        b, k = (int(x) for x in resp["shape"])
        n = b * k * 4
        if len(body) != 2 * n:
            raise MeshShardUnavailable(
                self.rank, f"short partial payload ({len(body)} bytes)"
            )
        ids = np.frombuffer(body[:n], dtype=np.int32).reshape(b, k)
        confs = np.frombuffer(body[n:], dtype=np.float32).reshape(b, k)
        return ids, confs

    def ready(self) -> dict:
        resp, _ = self._request({"op": "ready"}, b"")
        return resp


class MeshCoordinator:
    """The request-side fan-out/merge state for one gang member:
    persistent peer clients, a small fetch pool, and the missing-shard
    health record that /readyz, the gauge, and the request short-circuit
    read.

    Recovery needs no background thread: a missing rank is re-probed
    (cheap ``ready`` op) at most every ``probe_min_interval_s``, from
    whatever touches the state first — a request arriving while the
    gang is degraded, or a periodic /readyz. The FleetRouter's own
    half-open probe request therefore finds a re-formed gang within one
    probe interval."""

    def __init__(
        self, gang: GangConfig, *,
        connect_timeout_s: float = 2.0, request_timeout_s: float = 30.0,
        probe_min_interval_s: float = 1.0, clock=time.monotonic,
        hedge: bool = False, hedge_delay_ms: float = 30.0,
        hedge_max_frac: float = 0.05, peer_slow_ratio: float = 0.0,
    ):
        self.gang = gang
        self.request_timeout_s = request_timeout_s
        self.clients = {
            r: MeshPeerClient(
                r, gang.peer_address(r),
                connect_timeout_s=connect_timeout_s,
                request_timeout_s=request_timeout_s,
            )
            for r in range(gang.size) if r != gang.rank
        }
        self._missing: dict[int, str] = {}
        self._lock = threading.Lock()
        self._clock = clock
        self._probe_min_interval_s = probe_min_interval_s
        self._next_probe_at = 0.0
        # gray-failure spine (ISSUE 18): per-rank latency tracking feeds
        # an adaptive straggler bound — when ``hedge`` is on, a rank
        # that hasn't answered within ~its own p95 (floored at
        # hedge_delay_ms) is DROPPED from the merge under the
        # deadline-degrade contract (no slab replica exists to re-issue
        # to in the simulation transport), budget-capped by a token
        # bucket so degrade amplification is structurally bounded.
        # hedge=False allocates no decisions: the counters stay 0.
        self.hedge = bool(hedge)
        self.hedge_delay_ms = hedge_delay_ms
        self.hedge_max_frac = hedge_max_frac
        self._hedge_cap = max(1.0, 16.0 * hedge_max_frac)
        self._hedge_tokens = self._hedge_cap
        self._rank_recent: dict[int, deque] = {
            r: deque(maxlen=64) for r in self.clients
        }
        self.hedge_wins = 0        # straggler dropped, merged without it
        self.hedge_losses = 0      # straggler finished in the grace check
        self.hedge_cancelled = 0   # budget exhausted → plain full wait
        # slow-outlier ladder (the FleetRouter's ladder, mesh-side): a
        # rank whose EWMA latency exceeds peer_slow_ratio × the healthy
        # median is marked SLOW — its straggler bound collapses to the
        # floor (hedge immediately, don't re-learn its p95 every
        # request) until its EWMA, fed by the grace-landing and
        # full-wait samples that double as probes, recovers under the
        # same ratio. 0.0 (the default) disables the ladder entirely.
        self.peer_slow_ratio = max(0.0, peer_slow_ratio)
        self._rank_ewma: dict[int, float] = {}
        self._rank_slow: set[int] = set()
        self.slow_ejections = 0
        self.slow_readmissions = 0
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, gang.size - 1),
            thread_name_prefix="kmls-mesh-fetch",
        )

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        for client in self.clients.values():
            client.close()

    # -- health record ----------------------------------------------------

    def _note_missing(self, rank: int, reason: str) -> None:
        with self._lock:
            fresh = rank not in self._missing
            self._missing[rank] = reason
        if fresh:
            logger.warning("serve mesh shard %d missing: %s", rank, reason)

    def _note_serving(self, rank: int) -> None:
        with self._lock:
            back = self._missing.pop(rank, None) is not None
        if back:
            logger.info("serve mesh shard %d re-formed", rank)

    def missing_shards(self, probe: bool = False) -> list[int]:
        """Currently-missing ranks (sorted). ``probe=True`` re-auditions
        them first (rate-limited), so a re-formed gang recovers from
        the readyz/request path without waiting for traffic to fail."""
        with self._lock:
            missing = sorted(self._missing)
        if not (probe and missing):
            return missing
        now = self._clock()
        with self._lock:
            if now < self._next_probe_at:
                return missing
            self._next_probe_at = now + self._probe_min_interval_s
        for rank in missing:
            try:
                self.clients[rank].ready()
            except MeshShardUnavailable as exc:
                self._note_missing(rank, exc.reason)
            else:
                self._note_serving(rank)
        with self._lock:
            return sorted(self._missing)

    # -- the request fan-out ----------------------------------------------

    def _rank_straggler_bound_s(self, rank: int) -> float:
        """Per-rank adaptive straggler bound: ~p95 of its recent fetch
        latencies, floored at ``hedge_delay_ms`` (a cold coordinator
        must not drop ranks on noise)."""
        floor = self.hedge_delay_ms / 1e3
        with self._lock:
            if rank in self._rank_slow:
                # a slow-marked rank hedges at the floor: its own p95 IS
                # the stall being routed around
                return floor
            recent = self._rank_recent.get(rank)
            if not recent or len(recent) < 8:
                return floor
            ordered = sorted(recent)
            q = ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]
        return max(floor, q)

    def _mark_rank_latency(self, rank: int, seconds: float) -> None:
        seconds = max(0.0, float(seconds))
        with self._lock:
            recent = self._rank_recent.get(rank)
            if recent is None:
                return
            recent.append(seconds)
            prev = self._rank_ewma.get(rank)
            ewma = seconds if prev is None else 0.2 * seconds + 0.8 * prev
            self._rank_ewma[rank] = ewma
            if self.peer_slow_ratio <= 0.0 or len(recent) < 8:
                return
            peers = [
                e for r, e in self._rank_ewma.items()
                if r != rank and len(self._rank_recent[r]) >= 8
                and r not in self._rank_slow
            ]
            if not peers:
                return
            peers.sort()
            median = peers[len(peers) // 2]
            bound = self.peer_slow_ratio * median
            if rank not in self._rank_slow and ewma > bound:
                self._rank_slow.add(rank)
                self.slow_ejections += 1
            elif rank in self._rank_slow and ewma <= bound:
                self._rank_slow.discard(rank)
                self.slow_readmissions += 1

    def slow_ranks(self) -> list[int]:
        """Ranks the slow-outlier ladder currently marks slow (sorted;
        empty with KMLS_PEER_SLOW_RATIO=0)."""
        with self._lock:
            return sorted(self._rank_slow)

    def fetch_partials(
        self, seeds: np.ndarray, token: str,
        budget_ms: float | None = None,
    ):
        """Submit every peer's partial fetch NOW (concurrent with the
        local slab's device dispatch); the returned ``finish()`` blocks
        and yields ``{rank: (ids, confs)}`` or raises
        :class:`MeshShardUnavailable` for the first dead rank. The
        seeds array is serialized up front — the engine's staging
        buffer may be reused by the next batch before the pool thread
        runs.

        ``budget_ms`` (deadline propagation) rides each partial frame so
        a backed-up worker sheds expired work instead of computing it; a
        shed rank lands in ``finish.dropped`` whether or not hedging is
        armed — the merge degrades, the shard is never blamed missing.

        With ``hedge`` armed, a rank that hasn't answered within its
        adaptive straggler bound is dropped from the merge (one token
        from the hedge budget): ``finish.dropped`` lists the dropped
        ranks — the engine merges without them and marks the answers
        degraded — and ``finish.hedge_outcome`` carries
        ``won``/``lost``/``cancelled`` for the trace span. A dropped
        rank is NOT blamed as missing: it is alive, just late."""
        payload = np.ascontiguousarray(seeds, dtype=np.int32).copy()
        if self.hedge:
            # the bucket EARNS hedge_max_frac per dispatch (the replay
            # client's accounting, coordinator-side): straggler drops
            # are bounded at ~hedge_max_frac of traffic, not a one-time
            # allowance that exhausts for the process lifetime
            with self._lock:
                self._hedge_tokens = min(
                    self._hedge_tokens + self.hedge_max_frac,
                    self._hedge_cap,
                )
        t_submit = time.monotonic()
        futures = {
            rank: self._pool.submit(
                client.partial, payload, token, budget_ms
            )
            for rank, client in self.clients.items()
        }

        def finish() -> dict[int, tuple[np.ndarray, np.ndarray]]:
            out: dict[int, tuple[np.ndarray, np.ndarray]] = {}
            failed: MeshShardUnavailable | None = None
            for rank, future in sorted(futures.items()):
                timeout = self.request_timeout_s + 5.0
                if self.hedge:
                    bound = self._rank_straggler_bound_s(rank)
                    remaining = (t_submit + bound) - time.monotonic()
                    try:
                        out[rank] = future.result(
                            timeout=max(0.0, remaining)
                        )
                        self._mark_rank_latency(
                            rank, time.monotonic() - t_submit
                        )
                        self._note_serving(rank)
                        continue

                    except FutureTimeoutError:
                        with self._lock:
                            has_token = self._hedge_tokens >= 1.0
                            if has_token:
                                self._hedge_tokens -= 1.0
                        if has_token:
                            # the "re-issue" equivalent when no slab
                            # replica exists: one short grace (the cost
                            # a hedge copy would have paid), then merge
                            # WITHOUT the straggler — it is alive, just
                            # late, so degrade, don't blame
                            grace = min(
                                0.25 * max(bound, 1e-3), 0.025
                            )
                            try:
                                out[rank] = future.result(timeout=grace)
                            except FutureTimeoutError:
                                finish.dropped.append(rank)
                                self.hedge_wins += 1
                                finish.hedge_outcome = "won"
                                continue
                            except MeshShardUnavailable as exc:
                                if exc.reason == "deadline-expired":
                                    finish.dropped.append(rank)
                                    continue
                                self._note_missing(rank, exc.reason)
                                failed = failed or exc
                                continue
                            # the straggler slipped in under the grace:
                            # its answer is used, the token refunded
                            with self._lock:
                                self._hedge_tokens = min(
                                    self._hedge_tokens + 1.0,
                                    self._hedge_cap,
                                )
                            self.hedge_losses += 1
                            finish.hedge_outcome = "lost"
                            self._mark_rank_latency(
                                rank, time.monotonic() - t_submit
                            )
                            self._note_serving(rank)
                            continue
                        # budget exhausted: plain waiting, the
                        # pre-hedge behavior exactly
                        self.hedge_cancelled += 1
                        finish.hedge_outcome = "cancelled"
                    except MeshShardUnavailable as exc:
                        if exc.reason == "deadline-expired":
                            # the worker shed expired work — that is
                            # deadline propagation doing its job, not a
                            # sick shard
                            finish.dropped.append(rank)
                            continue
                        self._note_missing(rank, exc.reason)
                        failed = failed or exc
                        continue
                    except Exception as exc:
                        wrapped = MeshShardUnavailable(
                            rank, f"{type(exc).__name__}: {exc}"
                        )
                        self._note_missing(rank, wrapped.reason)
                        failed = failed or wrapped
                        continue
                try:
                    out[rank] = future.result(timeout=timeout)
                    if self.hedge:
                        # the cancelled fall-through: the straggler was
                        # waited out plain-style (budget exhausted)
                        self._mark_rank_latency(
                            rank, time.monotonic() - t_submit
                        )
                    self._note_serving(rank)
                except MeshShardUnavailable as exc:
                    if exc.reason == "deadline-expired":
                        # the worker shed expired work — deadline
                        # propagation doing its job whether or not
                        # hedging is armed: degrade, don't blame a
                        # live shard (and don't fail the batch)
                        finish.dropped.append(rank)
                        continue
                    self._note_missing(rank, exc.reason)
                    failed = failed or exc
                except Exception as exc:  # pool/timeout faults
                    wrapped = MeshShardUnavailable(
                        rank, f"{type(exc).__name__}: {exc}"
                    )
                    self._note_missing(rank, wrapped.reason)
                    failed = failed or wrapped
            if failed is not None:
                raise failed
            return out

        finish.dropped = []
        finish.hedge_outcome = None
        return finish
