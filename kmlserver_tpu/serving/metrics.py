"""Serving observability — the counters the reference lacks.

The reference's only observability is log lines and one in-memory
``reload_counter`` (rest_api/app/main.py:18-29,120,143; SURVEY.md §5 calls
out the absence of a metrics endpoint). This adds latency/QPS counters with a
bounded reservoir so the p50-at-QPS target is measurable, exposed in
Prometheus text format at ``GET /metrics``.
"""

from __future__ import annotations

import threading
import time


class LatencyReservoir:
    """Fixed-size ring of recent latencies; cheap percentile reads."""

    def __init__(self, size: int = 4096):
        self._buf = [0.0] * size
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = seconds
            self._n += 1

    def percentiles(self, *qs: float) -> list[float]:
        with self._lock:
            live = sorted(self._buf[: min(self._n, len(self._buf))])
        if not live:
            return [0.0 for _ in qs]
        return [live[min(int(q * len(live)), len(live) - 1)] for q in qs]

    def reset(self) -> int:
        """Empty the ring → number of observations discarded."""
        with self._lock:
            n = self._n
            self._n = 0
        return n


class ServingMetrics:
    def __init__(self):
        self.started_at = time.time()
        self.requests_total = 0
        self.requests_by_source = {"rules": 0, "fallback": 0, "empty": 0}
        self.errors_total = 0
        self.latency = LatencyReservoir()
        self._lock = threading.Lock()

    def record(self, source: str, seconds: float) -> None:
        with self._lock:
            self.requests_total += 1
            self.requests_by_source[source] = self.requests_by_source.get(source, 0) + 1
        self.latency.observe(seconds)

    def record_error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def reset_latency(self) -> int:
        """Clear ONLY the latency reservoir (→ observations discarded).

        Lets a measurement harness window the percentiles to one replay
        run (VERDICT r4 #7). The Prometheus counters stay cumulative —
        resetting counters would break scrape-delta semantics."""
        return self.latency.reset()

    def render(self, reload_counter: int, finished_loading: bool) -> str:
        p50, p95, p99 = self.latency.percentiles(0.50, 0.95, 0.99)
        uptime = time.time() - self.started_at
        lines = [
            "# TYPE kmls_requests_total counter",
            f"kmls_requests_total {self.requests_total}",
            "# TYPE kmls_request_errors_total counter",
            f"kmls_request_errors_total {self.errors_total}",
            "# TYPE kmls_requests_by_source counter",
        ]
        for source, count in sorted(self.requests_by_source.items()):
            lines.append(f'kmls_requests_by_source{{source="{source}"}} {count}')
        lines += [
            "# TYPE kmls_request_latency_seconds summary",
            f'kmls_request_latency_seconds{{quantile="0.5"}} {p50:.6f}',
            f'kmls_request_latency_seconds{{quantile="0.95"}} {p95:.6f}',
            f'kmls_request_latency_seconds{{quantile="0.99"}} {p99:.6f}',
            "# TYPE kmls_reloads_total counter",
            f"kmls_reloads_total {reload_counter}",
            "# TYPE kmls_finished_loading gauge",
            f"kmls_finished_loading {int(finished_loading)}",
            "# TYPE kmls_uptime_seconds gauge",
            f"kmls_uptime_seconds {uptime:.1f}",
        ]
        return "\n".join(lines) + "\n"
