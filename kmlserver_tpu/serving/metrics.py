"""Serving observability — the counters the reference lacks.

The reference's only observability is log lines and one in-memory
``reload_counter`` (rest_api/app/main.py:18-29,120,143; SURVEY.md §5 calls
out the absence of a metrics endpoint). This adds latency/QPS counters with
bounded reservoirs so the p50-at-QPS target is measurable, exposed in
Prometheus text format at ``GET /metrics`` — including the queue-vs-device
latency attribution the micro-batcher threads through
(``kmls_queue_wait_ms`` / ``kmls_device_ms`` / ``kmls_e2e_ms``, quantiles
up to p999), which is what lets a replay harness say WHERE a tail lives
instead of only that one exists.
"""

from __future__ import annotations

import bisect
import threading
import time

# every summary rendered below carries these quantiles; p999 needs the
# larger reservoir to mean anything (16384 samples → ~16 above p999)
_QUANTILES = (0.50, 0.95, 0.99, 0.999)

# ---------------------------------------------------------------------------
# The metric-series registry — THE declaration point for every exported
# Prometheus series, serving (`GET /metrics`) and mining (the
# `pickles/job_metrics.prom` textfile) alike. Values are "<type>:<scope>"
# with type ∈ counter/gauge/summary/histogram and scope ∈ serving/mining.
#
# kmls-verify's `metrics` checker (kmlserver_tpu/analysis/metricsreg.py)
# enforces, in CI: every series name spelled in the exposition modules
# (this file, observability/jobmetrics.py, and the app's dynamically
# rendered robustness keys) is declared here with a valid type+scope and
# has a README row; and the inverse — a registry entry nothing renders is
# an orphan. The mining textfile writer additionally looks its names up
# HERE at render time, so the two exposition surfaces can never drift
# from one declaration the way KNOB_REGISTRY keeps env knobs honest.
# Adding a series = render it, add an entry here, and a README table row
# — or CI's verify job rejects the diff, naming exactly what is missing.
# ---------------------------------------------------------------------------
METRIC_REGISTRY: dict[str, str] = {
    # --- serving: request counters ---
    "kmls_requests_total": "counter:serving",
    "kmls_request_errors_total": "counter:serving",
    "kmls_requests_shed_total": "counter:serving",
    "kmls_requests_by_source": "counter:serving",
    # --- serving: latency (reservoir summaries for bench windowing,
    # fixed-bucket histograms for fleet aggregation — see ISSUE 9) ---
    "kmls_request_latency_seconds": "summary:serving",
    "kmls_queue_wait_ms": "summary:serving",
    "kmls_device_ms": "summary:serving",
    "kmls_e2e_ms": "summary:serving",
    "kmls_queue_wait_seconds": "histogram:serving",
    "kmls_device_seconds": "histogram:serving",
    "kmls_e2e_seconds": "histogram:serving",
    # --- serving: recommendation cache ---
    "kmls_cache_hits_total": "counter:serving",
    "kmls_cache_misses_total": "counter:serving",
    "kmls_cache_evictions_total": "counter:serving",
    "kmls_cache_singleflight_joins_total": "counter:serving",
    "kmls_cache_entries": "gauge:serving",
    "kmls_cache_hit_ratio": "gauge:serving",
    # selective invalidation (continuous freshness, ISSUE 10): delta
    # applies bump per-seed-name generations instead of the epoch —
    # invalidation events and the entries each walk deleted
    "kmls_cache_selective_invalidations_total": "counter:serving",
    "kmls_cache_invalidated_keys_total": "counter:serving",
    # fleet cache affinity (freshness/ring.py): would a rendezvous-hash
    # router have kept this request on THIS replica? The decision data
    # for affinity routing vs a shared external cache tier.
    "kmls_cache_affinity_local_total": "counter:serving",
    "kmls_cache_affinity_remote_total": "counter:serving",
    # fleet cache routing (ISSUE 15): with KMLS_FLEET_PEERS armed, a
    # non-owned miss answered locally is routing DRIFT at the ingress/
    # client — the counter a dashboard alerts on when the consistent-
    # hash tier stops keeping keys on their owners — plus the configured
    # routing-ring size (0 = tier unarmed)
    "kmls_cache_misrouted_total": "counter:serving",
    "kmls_fleet_peers": "gauge:serving",
    # --- serving: dispatch / layout ---
    "kmls_device_dispatch_total": "counter:serving",
    "kmls_shard_dispatch_total": "counter:serving",
    "kmls_model_shards": "gauge:serving",
    # pod-spanning serve mesh (ISSUE 16): gang shard health by state
    # (serving/missing) — rendered only on gang members, so the series
    # existing at all says "this pod is a mesh member", and
    # {state="missing"} > 0 is the alert that a vocab slab is dark
    # (the same condition /readyz names as serve_mesh_shard_missing:<r>)
    "kmls_serve_mesh_shards": "gauge:serving",
    # --- serving: fault tolerance / overload ---
    "kmls_degraded_total": "counter:serving",
    "kmls_degraded_by_reason": "counter:serving",
    "kmls_replica_ejections_total": "counter:serving",
    "kmls_replica_readmissions_total": "counter:serving",
    "kmls_redispatch_total": "counter:serving",
    "kmls_artifact_quarantines_total": "counter:serving",
    "kmls_reload_failures_total": "counter:serving",
    "kmls_reload_consecutive_failures": "gauge:serving",
    "kmls_embedding_active": "gauge:serving",
    "kmls_embedding_load_failures_total": "counter:serving",
    "kmls_replicas_ejected": "gauge:serving",
    "kmls_utilization": "gauge:serving",
    "kmls_admission_degrade_total": "counter:serving",
    # --- serving: gray-failure spine (ISSUE 18) ---
    # deadline propagation: requests whose forwarded
    # X-KMLS-Deadline-Budget arrived already spent (answered degraded,
    # counted as wasted-work — distinct from slow-compute "deadline"
    # degrades), and the mesh-worker twin (partial frames shed before
    # compute because their budget field was ≤ 0 on arrival)
    "kmls_deadline_expired_total": "counter:serving",
    "kmls_mesh_expired_on_arrival_total": "counter:serving",
    # hedged mesh dispatch (KMLS_HEDGE): straggler outcomes — won
    # (merged without the late rank), lost (it landed in the grace
    # re-check; token refunded), cancelled (hedge budget exhausted →
    # plain waiting). All pinned 0 with the knob off (zero-cost proof).
    "kmls_hedge_wins_total": "counter:serving",
    "kmls_hedge_losses_total": "counter:serving",
    "kmls_hedge_cancelled_total": "counter:serving",
    # slow-outlier ladder (KMLS_PEER_SLOW_RATIO): gang ranks ejected
    # for EWMA latency over ratio×healthy-median, re-admissions after
    # recovery, and how many ranks are slow-marked right now
    "kmls_peer_slow_ejections_total": "counter:serving",
    "kmls_peer_slow_readmissions_total": "counter:serving",
    "kmls_peer_slow": "gauge:serving",
    # merges answered without a straggler slab's candidates (each one
    # also counts kmls_degraded_total{reason="mesh-straggler"})
    "kmls_mesh_straggler_degraded_total": "counter:serving",
    # --- serving: storage gray-failure spine (ISSUE 19) ---
    # artifact-plane IO health (io/iohealth.py, fed by io/artifacts.py):
    # per-operation latency EWMA {op ∈ token_poll/read/write/fsync},
    # errors by (op, errno), transient-EIO retries, free bytes on the
    # artifact volume, and the storage-slow conviction behind the
    # /readyz "storage-slow" degraded reason
    "kmls_io_latency_seconds": "gauge:serving",
    "kmls_io_errors_total": "counter:serving",
    "kmls_io_retries_total": "counter:serving",
    "kmls_disk_free_bytes": "gauge:serving",
    "kmls_storage_slow": "gauge:serving",
    # --- serving: continuous freshness (ISSUE 10) ---
    # delta bundles applied in place vs rejected (torn/wrong-base/
    # injected), the chain position serving ((base, delta_seq) epoch
    # pair), and the age of the newest APPLIED generation — the
    # freshness-lag number the delta path exists to shrink
    "kmls_delta_applied_total": "counter:serving",
    "kmls_delta_rejected_total": "counter:serving",
    "kmls_delta_seq": "gauge:serving",
    "kmls_freshness_lag_seconds": "gauge:serving",
    # --- serving: quality loop (ISSUE 14) ---
    # published delta-chain length for the serving generation — the
    # compaction trigger (KMLS_DELTA_COMPACT_AFTER), observable before
    # the compactor acts on it
    "kmls_delta_chain_length": "gauge:serving",
    # the EFFECTIVE hybrid blend weight: the measured optimum when
    # KMLS_HYBRID_BLEND_WEIGHT=measured published one, else the knob —
    # dashboards see which weight actually ranks answers
    "kmls_hybrid_blend_weight": "gauge:serving",
    # per-artifact staleness flag: 1 when the artifact's age exceeds
    # KMLS_ARTIFACT_MAX_AGE_S (always 0 with the bound disabled) — the
    # alertable twin of kmls_artifact_age_seconds
    "kmls_artifact_stale": "gauge:serving",
    # --- serving: observability (ISSUE 9) ---
    # peak-hold event-loop/scheduler stall estimate, decayed — the
    # runtime-health signal the admission ladder also folds in
    "kmls_loop_lag_ms": "gauge:serving",
    "kmls_traces_began_total": "counter:serving",
    "kmls_traces_retained_total": "counter:serving",
    "kmls_trace_buffer_entries": "gauge:serving",
    # --- serving: device-truth cost attribution (ISSUE 12) ---
    # per-kernel fenced device time + analytic FLOPs/bytes → achieved
    # rates, MFU vs the backend peak table, and the roofline class
    # (1 = compute-bound); rendered by observability/costmodel.py
    "kmls_kernel_device_seconds": "counter:serving",
    "kmls_kernel_dispatches_total": "counter:serving",
    "kmls_kernel_flops_per_second": "gauge:serving",
    "kmls_kernel_bytes_per_second": "gauge:serving",
    "kmls_mfu": "gauge:serving",
    "kmls_kernel_compute_bound": "gauge:serving",
    # jit-cache growth after publication — the LIVE form of the
    # zero-compiles-post-publish invariant (was test-only before)
    "kmls_compiles_total": "counter:serving",
    # cost-model bookkeeping: total observations (the zero-cost proof
    # counter — 0 with KMLS_COSTMODEL=0) and dispatches naming a kernel
    # with no registered spec (the costspec checker's runtime shadow)
    "kmls_costmodel_observations_total": "counter:serving",
    "kmls_costmodel_unspecced_total": "counter:serving",
    # memory telemetry: live memory_stats() gauges where the backend
    # provides them, plus the analytic per-artifact tensor residency
    # the layout.py auto decision measures — budget, headroom, and the
    # publish-time bytes-in-use watermark
    "kmls_device_bytes_in_use": "gauge:serving",
    "kmls_device_bytes_limit": "gauge:serving",
    "kmls_model_tensor_bytes": "gauge:serving",
    "kmls_device_budget_bytes": "gauge:serving",
    "kmls_device_headroom_bytes": "gauge:serving",
    "kmls_publish_watermark_bytes": "gauge:serving",
    # --- serving: predictive serving (ISSUE 17, serving/forecast.py) ---
    # online traffic forecaster: smoothed current arrival rate, the
    # horizon prediction, their ratio (the ramp signal), the zero-cost
    # proof counter (0 with KMLS_FORECAST=0 — test-pinned, costmodel
    # style), the actuator counters (owner-targeted cache pre-fetches
    # led, shape-bucket pre-touches dispatched), and the bounded
    # forecast term actually folded into kmls_utilization — rendered
    # through the robustness dict only while the forecaster is armed
    "kmls_forecast_rate": "gauge:serving",
    "kmls_forecast_predicted_rate": "gauge:serving",
    "kmls_forecast_ratio": "gauge:serving",
    "kmls_forecast_observations_total": "counter:serving",
    "kmls_forecast_prefetch_total": "counter:serving",
    "kmls_forecast_prewarm_total": "counter:serving",
    "kmls_utilization_forecast": "gauge:serving",
    # --- serving: SLO burn rates (ISSUE 12, observability/slo.py) ---
    # multi-window budget-consumption rates (slo ∈ latency_p99/
    # availability/quality, window ∈ fast/slow); observability only —
    # the admission ladder stays the actuator
    "kmls_slo_burn_rate": "gauge:serving",
    # per-artifact freshness age (ISSUE 12 satellite): seconds since
    # each served artifact's publication (rules/delta-chain/embeddings/
    # popularity) — the staleness bound /readyz also reports
    "kmls_artifact_age_seconds": "gauge:serving",
    # --- serving: lifecycle ---
    "kmls_reloads_total": "counter:serving",
    "kmls_finished_loading": "gauge:serving",
    "kmls_uptime_seconds": "gauge:serving",
    # --- mining: the job_metrics.prom textfile (observability/
    # jobmetrics.py — node-exporter textfile-collector format; gauges
    # because a batch job's file restarts from scratch every run, so
    # counter delta semantics would lie across runs) ---
    "kmls_job_phase_duration_seconds": "gauge:mining",
    "kmls_job_phase_resumed": "gauge:mining",
    "kmls_job_rows": "gauge:mining",
    "kmls_job_playlists": "gauge:mining",
    "kmls_job_tracks": "gauge:mining",
    "kmls_job_artifact_bytes": "gauge:mining",
    "kmls_job_rule_generation_seconds": "gauge:mining",
    "kmls_job_fencing_token": "gauge:mining",
    "kmls_job_duration_seconds": "gauge:mining",
    "kmls_job_success": "gauge:mining",
    "kmls_job_last_success_timestamp_seconds": "gauge:mining",
    # per-phase analytic cost attribution (ISSUE 12): the same
    # costmodel.phase_cost formulas the serving side uses, evaluated on
    # the mined shape — what the phase's dominant kernel moved/computed
    "kmls_job_phase_flops": "gauge:mining",
    "kmls_job_phase_bytes_moved": "gauge:mining",
    # sparsity-adaptive dispatch (ISSUE 13): which pair-count family the
    # measured dispatcher chose for this generation, labeled
    # {path, source} — value is always 1 (an info-style gauge)
    "kmls_job_count_path": "gauge:mining",
}

# The autoscaling signal (ISSUE 8): the gauge kubernetes/hpa.yaml scales
# the API fleet on, derived by the batcher from its queue/device latency
# attribution (max of pipeline occupancy and admission queue pressure;
# 1.0 = at capacity, shedding begins above it). With KMLS_FORECAST=1 a
# bounded predictive lead term joins the max (ISSUE 17): the reactive
# value scaled by the forecast growth ratio, clamped so it can raise
# the signal ahead of a ramp but never lower it and never exceed
# KMLS_FORECAST_UTIL_CAP on prediction alone. The app exposes it
# through the robustness-state dict (serving/app.py _robustness_state,
# key "utilization" → rendered with the kmls_ prefix below);
# tests/test_deploy.py pins the HPA manifest to THIS name so the metric
# the adapter queries can never drift from the one the server exports.
UTILIZATION_SERIES = "kmls_utilization"


class LatencyReservoir:
    """Fixed-size ring of recent latencies; cheap percentile reads."""

    def __init__(self, size: int = 16384):
        self._buf = [0.0] * size
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = seconds
            self._n += 1

    def percentiles(self, *qs: float) -> list[float]:
        # COPY under the lock, sort OUTSIDE it (ISSUE 9 satellite): the
        # sort is O(n log n) over up to 16384 floats — holding the observe
        # lock through it would stall every request thread mid-record on
        # each scrape. The slice is a snapshot; a concurrent observe
        # racing the copy costs at most one sample's visibility.
        with self._lock:
            live = self._buf[: min(self._n, len(self._buf))]
        if not live:
            return [0.0 for _ in qs]
        live.sort()
        return [live[min(int(q * len(live)), len(live) - 1)] for q in qs]

    def reset(self) -> int:
        """Empty the ring → number of observations discarded."""
        with self._lock:
            n = self._n
            self._n = 0
        return n


# default latency buckets (seconds): sub-ms resolution where the serving
# p50 lives (0.4–5 ms on the CPU replay record), decade coverage out to
# the deadline/backoff regime. Shared across every replica of a fleet —
# fixed buckets are the whole point: per-pod `_bucket` counters SUM
# across replicas, which per-pod reservoir quantiles never can.
LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class LatencyHistogram:
    """Fixed-bucket Prometheus histogram (`_bucket`/`_sum`/`_count`).

    The reservoirs above answer "what is THIS pod's p99 right now"
    (bench windowing — they reset per run); this histogram answers the
    fleet question: bucket counters are cumulative and additive across
    replicas, so `histogram_quantile(0.99, sum(rate(..._bucket[5m])) by
    (le))` is the aggregation the ROADMAP's millions-of-users fleet
    needs and reservoir quantiles mathematically cannot provide.
    Deliberately NOT reset by the bench's `/metrics/reset` — counters
    keep scrape-delta semantics."""

    def __init__(self, buckets: tuple[float, ...] = LATENCY_BUCKETS_S):
        self.buckets = tuple(buckets)
        # counts[i] = observations <= buckets[i]; counts[-1] = +Inf band
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        idx = bisect.bisect_left(self.buckets, seconds)
        with self._lock:
            self._counts[idx] += 1
            self._sum += seconds
            self._count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count

    def quantile(self, q: float) -> float:
        """Bucket-derived quantile (histogram_quantile semantics: linear
        interpolation inside the winning bucket; the +Inf band answers
        its finite lower edge). Used by the test pinning histogram
        quantiles against reservoir quantiles — and by nothing on any
        hot path."""
        counts, _total_sum, n = self.snapshot()
        if n == 0:
            return 0.0
        target = q * n
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                cum += c
                continue
            if cum + c >= target:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i] if i < len(self.buckets) else lo
                frac = (target - cum) / c
                return lo + frac * (hi - lo)
            cum += c
        return self.buckets[-1]

    def render(self, name: str) -> list[str]:
        counts, total_sum, n = self.snapshot()
        lines = [f"# TYPE {name} histogram"]
        cum = 0
        for bound, count in zip(self.buckets, counts):
            cum += count
            lines.append(f'{name}_bucket{{le="{bound:g}"}} {cum}')
        lines += [
            f'{name}_bucket{{le="+Inf"}} {n}',
            f"{name}_sum {total_sum:.6f}",
            f"{name}_count {n}",
        ]
        return lines


class ServingMetrics:
    def __init__(self):
        self.started_at = time.time()
        self.requests_total = 0
        # "embed"/"hybrid" are the second model family's sources — present
        # from the start so dashboards can rely on the series existing
        self.requests_by_source = {
            "rules": 0, "embed": 0, "hybrid": 0, "fallback": 0, "empty": 0,
        }
        self.errors_total = 0
        self.shed_total = 0
        # fault-tolerance counters: degraded answers by reason (deadline
        # exhaustion vs total replica loss), plus the batcher's circuit-
        # breaker events — every recovery event is visible, not just logged
        self.degraded_by_reason: dict[str, int] = {}
        self.replica_ejections_total = 0
        self.replica_readmissions_total = 0
        self.redispatch_total = 0
        self.latency = LatencyReservoir()
        # per-request latency attribution from the micro-batcher:
        # queue_wait = enqueue→dispatch, device = dispatch→result-on-host
        # (device compute + transfer + in-order queue), e2e = enqueue→done
        self.queue_wait = LatencyReservoir()
        self.device = LatencyReservoir()
        self.e2e = LatencyReservoir()
        # the same attributions as fixed-bucket histograms: reservoirs
        # window per-pod bench runs, histograms aggregate across a fleet
        self.queue_wait_hist = LatencyHistogram()
        self.device_hist = LatencyHistogram()
        self.e2e_hist = LatencyHistogram()
        self._lock = threading.Lock()

    def record(self, source: str, seconds: float) -> None:
        with self._lock:
            self.requests_total += 1
            self.requests_by_source[source] = self.requests_by_source.get(source, 0) + 1
        self.latency.observe(seconds)

    def record_error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def record_degraded(self, reason: str) -> None:
        """A request answered from the popularity fallback with an
        X-KMLS-Degraded header instead of an error."""
        with self._lock:
            self.degraded_by_reason[reason] = (
                self.degraded_by_reason.get(reason, 0) + 1
            )

    def record_replica_ejected(self) -> None:
        with self._lock:
            self.replica_ejections_total += 1

    def record_replica_readmitted(self) -> None:
        with self._lock:
            self.replica_readmissions_total += 1

    def record_redispatch(self, n: int = 1) -> None:
        with self._lock:
            self.redispatch_total += n

    def record_attribution(
        self, queue_wait_s: float, device_s: float, e2e_s: float
    ) -> None:
        self.queue_wait.observe(queue_wait_s)
        self.device.observe(device_s)
        self.e2e.observe(e2e_s)
        self.queue_wait_hist.observe(queue_wait_s)
        self.device_hist.observe(device_s)
        self.e2e_hist.observe(e2e_s)

    def reset_latency(self) -> int:
        """Clear the latency + attribution reservoirs (→ request-latency
        observations discarded).

        Lets a measurement harness window the percentiles to one replay
        run (VERDICT r4 #7). The Prometheus counters stay cumulative —
        resetting counters would break scrape-delta semantics — and the
        attribution HISTOGRAMS stay with the counters: their buckets ARE
        counters (fleet aggregation depends on scrape deltas), so only
        the reservoirs window."""
        n = self.latency.reset()
        self.queue_wait.reset()
        self.device.reset()
        self.e2e.reset()
        return n

    @staticmethod
    def _summary_ms(name: str, reservoir: LatencyReservoir) -> list[str]:
        values = reservoir.percentiles(*_QUANTILES)
        lines = [f"# TYPE {name} summary"]
        for q, val in zip(_QUANTILES, values):
            label = f"{q:g}"
            lines.append(f'{name}{{quantile="{label}"}} {val * 1e3:.4f}')
        return lines

    def render(
        self, reload_counter: int, finished_loading: bool,
        cache=None, dispatch_counts=None, robustness=None,
        shard_counts=None, cost=None, slo=None, artifact_ages=None,
        artifact_stale=None, mesh_shards=None, io=None,
    ) -> str:
        """Prometheus text. ``cache`` (a serving.cache.RecommendCache),
        ``dispatch_counts`` (the engine's per-replica dispatch counters),
        ``robustness`` (a flat dict of engine/batcher recovery-state
        values — names ending in ``_total`` render as counters, the rest
        as gauges, all under a ``kmls_`` prefix), ``shard_counts``
        (per-vocab-shard seed-hit counters, present only under the
        sharded model layout), ``cost`` (an observability.costmodel
        .CostModel — per-kernel MFU/roofline + memory/compile
        telemetry), ``slo`` (an observability.slo.SloTracker) and
        ``artifact_ages`` (artifact name → seconds since publication)
        are optional — deployments without them render exactly the old
        exposition."""
        p50, p95, p99 = self.latency.percentiles(0.50, 0.95, 0.99)
        uptime = time.time() - self.started_at
        lines = [
            "# TYPE kmls_requests_total counter",
            f"kmls_requests_total {self.requests_total}",
            "# TYPE kmls_request_errors_total counter",
            f"kmls_request_errors_total {self.errors_total}",
            "# TYPE kmls_requests_shed_total counter",
            f"kmls_requests_shed_total {self.shed_total}",
            "# TYPE kmls_requests_by_source counter",
        ]
        for source, count in sorted(self.requests_by_source.items()):
            lines.append(f'kmls_requests_by_source{{source="{source}"}} {count}')
        lines += [
            "# TYPE kmls_request_latency_seconds summary",
            f'kmls_request_latency_seconds{{quantile="0.5"}} {p50:.6f}',
            f'kmls_request_latency_seconds{{quantile="0.95"}} {p95:.6f}',
            f'kmls_request_latency_seconds{{quantile="0.99"}} {p99:.6f}',
        ]
        # batcher attribution summaries, milliseconds (absent→all-zero is
        # fine: an unbatched deployment simply never observes into them)
        lines += self._summary_ms("kmls_queue_wait_ms", self.queue_wait)
        lines += self._summary_ms("kmls_device_ms", self.device)
        lines += self._summary_ms("kmls_e2e_ms", self.e2e)
        # the same attributions as fixed-bucket histograms (seconds):
        # `_bucket` counters sum across replicas, so the fleet's
        # histogram_quantile works where per-pod reservoir quantiles
        # cannot aggregate (ISSUE 9)
        lines += self.queue_wait_hist.render("kmls_queue_wait_seconds")
        lines += self.device_hist.render("kmls_device_seconds")
        lines += self.e2e_hist.render("kmls_e2e_seconds")
        if cache is not None:
            # epoch-keyed recommendation cache: hit/miss/evict counters +
            # the hit-ratio gauge the 10k-QPS claim is judged on
            lines += [
                "# TYPE kmls_cache_hits_total counter",
                f"kmls_cache_hits_total {cache.hits}",
                "# TYPE kmls_cache_misses_total counter",
                f"kmls_cache_misses_total {cache.misses}",
                "# TYPE kmls_cache_evictions_total counter",
                f"kmls_cache_evictions_total {cache.evictions}",
                "# TYPE kmls_cache_singleflight_joins_total counter",
                f"kmls_cache_singleflight_joins_total {cache.singleflight_joins}",
                "# TYPE kmls_cache_entries gauge",
                f"kmls_cache_entries {len(cache)}",
                "# TYPE kmls_cache_hit_ratio gauge",
                f"kmls_cache_hit_ratio {cache.hit_ratio():.4f}",
                # selective invalidation (continuous freshness): delta
                # applies invalidate only touched seed keys — events and
                # entries deleted, vs the for-free wholesale epoch bump
                "# TYPE kmls_cache_selective_invalidations_total counter",
                "kmls_cache_selective_invalidations_total "
                f"{getattr(cache, 'selective_invalidations', 0)}",
                "# TYPE kmls_cache_invalidated_keys_total counter",
                "kmls_cache_invalidated_keys_total "
                f"{getattr(cache, 'invalidated_keys', 0)}",
            ]
        if dispatch_counts:
            # per-replica device dispatch counters: the evidence that the
            # data-parallel dispatcher actually spreads work
            lines.append("# TYPE kmls_device_dispatch_total counter")
            lines += [
                f'kmls_device_dispatch_total{{device="{i}"}} {count}'
                for i, count in enumerate(dispatch_counts)
            ]
        if shard_counts:
            # sharded model layout: seed ids dispatched per vocab shard —
            # the load-balance evidence for the model-parallel lookup
            # (which shard's rule rows the traffic actually hits)
            lines.append("# TYPE kmls_shard_dispatch_total counter")
            lines += [
                f'kmls_shard_dispatch_total{{shard="{i}"}} {count}'
                for i, count in enumerate(shard_counts)
            ]
        if mesh_shards:
            # pod-spanning serve mesh (ISSUE 16): shard health by state —
            # {state="serving"} + {state="missing"} always sums to the
            # gang size, so either series alone places this pod's gang
            # health; rendered only when the app passes a gang snapshot
            # (non-mesh deployments keep the exact old exposition)
            lines.append("# TYPE kmls_serve_mesh_shards gauge")
            lines += [
                f'kmls_serve_mesh_shards{{state="{state}"}} {count}'
                for state, count in sorted(mesh_shards.items())
            ]
        # fault-tolerance exposition: degraded answers by reason + the
        # circuit breaker's eject/readmit/redispatch counters — always
        # present (zero-valued when nothing ever degraded), so dashboards
        # and the chaos bench can rely on the series existing
        with self._lock:
            degraded = dict(self.degraded_by_reason)
            ejections = self.replica_ejections_total
            readmissions = self.replica_readmissions_total
            redispatches = self.redispatch_total
        lines += [
            "# TYPE kmls_degraded_total counter",
            f"kmls_degraded_total {sum(degraded.values())}",
            "# TYPE kmls_degraded_by_reason counter",
        ]
        lines += [
            f'kmls_degraded_by_reason{{reason="{reason}"}} {count}'
            for reason, count in sorted(degraded.items())
        ]
        lines += [
            "# TYPE kmls_replica_ejections_total counter",
            f"kmls_replica_ejections_total {ejections}",
            "# TYPE kmls_replica_readmissions_total counter",
            f"kmls_replica_readmissions_total {readmissions}",
            "# TYPE kmls_redispatch_total counter",
            f"kmls_redispatch_total {redispatches}",
        ]
        lines += [
            "# TYPE kmls_reloads_total counter",
            f"kmls_reloads_total {reload_counter}",
            "# TYPE kmls_finished_loading gauge",
            f"kmls_finished_loading {int(finished_loading)}",
            "# TYPE kmls_uptime_seconds gauge",
            f"kmls_uptime_seconds {uptime:.1f}",
        ]
        if cost is not None:
            # device-truth cost attribution (ISSUE 12): per-kernel
            # device seconds / achieved rates / MFU / roofline class,
            # the live compile counter, and the memory accounting —
            # rendered by the cost model itself (one exposition site)
            lines += cost.render_lines()
        if slo is not None:
            # multi-window SLO burn rates (observability only — the
            # admission ladder stays the actuator)
            lines += slo.render_lines()
        if artifact_ages:
            # per-artifact freshness age: seconds since each served
            # artifact's publication (the /readyz staleness bound)
            lines.append("# TYPE kmls_artifact_age_seconds gauge")
            lines += [
                f'kmls_artifact_age_seconds{{artifact="{name}"}} '
                f"{artifact_ages[name]:.3f}"
                for name in sorted(artifact_ages)
            ]
        if artifact_stale:
            # the alertable staleness flag (ISSUE 14): 1 = the artifact
            # is over KMLS_ARTIFACT_MAX_AGE_S (and /readyz says so too);
            # rendered wherever ages are, all-0 with the bound disabled
            lines.append("# TYPE kmls_artifact_stale gauge")
            lines += [
                f'kmls_artifact_stale{{artifact="{name}"}} '
                f"{int(artifact_stale[name])}"
                for name in sorted(artifact_stale)
            ]
        if io is not None:
            # storage gray-failure spine (ISSUE 19): the IO-health
            # monitor's snapshot (io/iohealth.py). Latency EWMAs are
            # gauges (not summaries — they carry the conviction math's
            # exact inputs), errors are labeled by the errno a real bad
            # mount would return, and kmls_storage_slow is the 0/1
            # conviction behind /readyz's "storage-slow" reason.
            lines.append("# TYPE kmls_io_latency_seconds gauge")
            lines += [
                f'kmls_io_latency_seconds{{op="{op}"}} {ewma:.6f}'
                for op, ewma in sorted(io.get("latency_s", {}).items())
            ]
            lines.append("# TYPE kmls_io_errors_total counter")
            lines += [
                f'kmls_io_errors_total{{op="{op}",errno="{err}"}} {count}'
                for (op, err), count in sorted(io.get("errors", {}).items())
            ]
            lines += [
                "# TYPE kmls_io_retries_total counter",
                f"kmls_io_retries_total {int(io.get('retries', 0))}",
                "# TYPE kmls_storage_slow gauge",
                f"kmls_storage_slow {int(bool(io.get('storage_slow')))}",
            ]
            free = io.get("disk_free_bytes")
            if free is not None:
                lines += [
                    "# TYPE kmls_disk_free_bytes gauge",
                    f"kmls_disk_free_bytes {int(free)}",
                ]
        if robustness:
            # dedupe by series name (ISSUE 9 satellite): a robustness key
            # colliding with a statically rendered series (e.g. a
            # `degraded_total` entry vs kmls_degraded_total above) must
            # not emit a second `# TYPE` line — duplicate TYPE for one
            # name is invalid exposition and breaks strict scrapers. The
            # static rendering wins; the colliding dynamic entry is
            # dropped whole (its VALUE would be a second unlabeled sample
            # of the same series, equally invalid). The dynamic block
            # renders LAST so this set covers every static series.
            typed = {
                line.split(" ", 3)[2]
                for line in lines
                if line.startswith("# TYPE ")
            }
            for name, value in robustness.items():
                full = f"kmls_{name}"
                if full in typed:
                    continue
                typed.add(full)
                mtype = "counter" if name.endswith("_total") else "gauge"
                lines += [
                    f"# TYPE {full} {mtype}",
                    f"{full} {value}",
                ]
        return "\n".join(lines) + "\n"
