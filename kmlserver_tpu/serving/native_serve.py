"""ctypes binding for the native CPU serving kernel
(native/kmls_serve.cpp) — the serving twin of the mining fallback in
ops/cpu_popcount.py.

XLA:CPU lowers recommend_batch's (B, L, K) → (B, V) scatter-max to ~190 ns
per update (measured: 12.6 ms for a 32-row ds2 batch this round — 99% of
the kernel), which makes the scatter the entire serving tail on a CPU pod.
The native kernel does the identical updates at ~2 ns each and reproduces
``jax.lax.top_k``'s exact tie order, so results are bit-identical to the
device path. Accelerator backends keep the jitted kernel — their scatter
is not the bottleneck and the rule tensors live in HBM.

Build/load follows the established pattern (``utils.nativelib``): ``make
-C native`` on demand, graceful fallback when the toolchain or .so is
absent, ``KMLS_NATIVE=0`` kills every native path.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..utils import nativelib

# must match kAbiVersion in native/kmls_serve.cpp
_ABI_VERSION = 1


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.kmls_serve_abi_version.restype = ctypes.c_int32
    lib.kmls_serve_abi_version.argtypes = []
    got = lib.kmls_serve_abi_version()
    if got != _ABI_VERSION:
        raise OSError(
            f"native serve ABI {got} != expected {_ABI_VERSION} "
            f"(stale build: run make -C native)"
        )
    lib.kmls_serve_topk.restype = None
    lib.kmls_serve_topk.argtypes = [
        ctypes.POINTER(ctypes.c_int32),   # rule_ids (V, K)
        ctypes.POINTER(ctypes.c_float),   # rule_confs (V, K)
        ctypes.POINTER(ctypes.c_int32),   # seed_ids (B, L)
        ctypes.c_int32,                   # v
        ctypes.c_int32,                   # kmax
        ctypes.c_int32,                   # b
        ctypes.c_int32,                   # l
        ctypes.c_int32,                   # k_best
        ctypes.POINTER(ctypes.c_int32),   # out_ids (B, k_best)
        ctypes.POINTER(ctypes.c_float),   # out_confs (B, k_best)
    ]
    return lib


_LIB = nativelib.NativeLib("libkmls_serve.so", _bind)


def available() -> bool:
    return _LIB.available()


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def serve_topk(
    rule_ids: np.ndarray,   # (V, K) int32, -1 padded (trailing)
    rule_confs: np.ndarray,  # (V, K) float32
    seed_ids: np.ndarray,   # (B, L) int32, -1 padded
    k_best: int,
) -> tuple[np.ndarray, np.ndarray]:
    """→ ``(top_ids (B, k_best) int32 with -1 padding, top_confs f32)`` —
    same contract as :func:`~..ops.serve.recommend_batch`, host arrays.
    The ctypes call releases the GIL for the whole batch."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native serve kernel unavailable")
    v, kmax = rule_ids.shape
    b, l = seed_ids.shape
    out_ids = np.empty((b, k_best), dtype=np.int32)
    out_confs = np.empty((b, k_best), dtype=np.float32)
    lib.kmls_serve_topk(
        _ptr(rule_ids, ctypes.c_int32),
        _ptr(rule_confs, ctypes.c_float),
        _ptr(seed_ids, ctypes.c_int32),
        v, kmax, b, l, int(k_best),
        _ptr(out_ids, ctypes.c_int32),
        _ptr(out_confs, ctypes.c_float),
    )
    return out_ids, out_confs
