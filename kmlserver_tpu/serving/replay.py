"""QPS replay harness — the offline load test the reference never had.

SURVEY.md §4 prescribes "a replay harness for the 1k-QPS batch-32 serving
config" (BASELINE.json config 5: `/api/recommend/` p50 < 10 ms at 1k QPS,
batch 32). This module drives a serving target at a fixed request rate with
open-loop (Poisson-paced) arrivals — closed-loop clients understate tail
latency because a slow server throttles its own load — and reports achieved
QPS plus latency percentiles per response source.

Two targets:

- in-process: a :class:`MicroBatcher` over a loaded
  :class:`RecommendEngine` (measures the engine + batching, no HTTP) —
  what the tests and ``python -m kmlserver_tpu.serving.replay`` use;
- HTTP: any running server URL (measures the full stack), via
  ``--url http://host:port``.

Seed sets are sampled from the engine's vocabulary (mixing known and
unknown seeds exercises both the rules path and the static fallback, like
the reference's three canned Swagger examples at rest_api/app/main.py:158-174).
``--zipf-s`` switches the mix to a Zipf-skewed repetition of a payload
pool — the head-heavy shape real playlist-seed traffic has, which is what
the epoch-keyed answer cache feeds on (default off, preserving the
all-distinct legacy mix bit for bit). Targets that report cache outcomes
(the in-process app path, or an HTTP server's ``X-KMLS-Cache`` header)
get cached/uncached latency split out in the report.

**Traffic shapes** (ISSUE 8): constant-rate Poisson is the only shape
production traffic never has. :func:`shaped_arrivals` generates the
arrival schedule for composable load shapes — ``constant`` (the legacy
Poisson process, bit-identical), ``burst`` (trains of
``burst_factor``× the base rate), ``ramp`` (linear rate ramp),
``sine`` (one or more diurnal cycles) — selected by ``--shape`` /
``KMLS_REPLAY_SHAPE`` and accepted by every replay driver via the
``arrivals=`` parameter. Two shapes act on the *request mix* instead of
(or as well as) the rate: :func:`flash_crowd_payloads` collapses a
mid-run window of the payload list onto a tiny hot seed pool (all
traffic lands on a handful of cache keys — the singleflight/shed
interaction case), and the **epoch-flip** scenario keeps a hot Zipf mix
but fires an ``events`` callback mid-run (``replay``/``replay_pooled``
``events=[(index, fn)]``) that the harness points at a real bundle
republication — every hot cache key invalidates at once mid-burst, the
cache-invalidation worst case the epoch-keyed design must absorb
without stampeding the batcher.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import threading
import time

import numpy as np

# Hedged-dispatch zero-cost pin (ISSUE 18): every hedge copy
# replay_fleet_http actually issues increments this module counter, and
# NOTHING else touches it — so ``hedge=False`` (the default, mirroring
# KMLS_HEDGE=0) is proven zero-cost the same way the SpanRecorder's
# ``began`` counter proves tracing-off allocates nothing: tests pin it
# at 0 across a full no-hedge replay, and the bench control leg asserts
# it stayed 0 under real traffic.
HEDGES_ISSUED = 0


@dataclasses.dataclass
class ReplayReport:
    target_qps: float
    # offered = arrival rate actually generated (includes drops + errors);
    # achieved = COMPLETED requests only — a saturated target that drops
    # most arrivals must show a low achieved_qps, not echo the target rate
    offered_qps: float
    achieved_qps: float
    duration_s: float
    n_requests: int
    n_errors: int
    p50_ms: float
    p95_ms: float
    p99_ms: float
    by_source: dict[str, int]
    # queue-vs-device latency attribution (from the batcher's metrics when
    # the in-process target is driven; None for targets that don't expose
    # it — the HTTP path scrapes the same split from /metrics instead)
    queue_wait_p50_ms: float | None = None
    queue_wait_p99_ms: float | None = None
    device_p50_ms: float | None = None
    device_p99_ms: float | None = None
    e2e_p999_ms: float | None = None
    # cache split, present when the target reports per-response cache
    # outcomes (a send() returning (source, cached), or the HTTP server's
    # X-KMLS-Cache header): cached answers are dictionary lookups and
    # computed answers pay the device — reporting them pooled would let a
    # high hit ratio mask a computed-path regression
    cache_hit_ratio: float | None = None
    cached_p50_ms: float | None = None
    cached_p99_ms: float | None = None
    uncached_p50_ms: float | None = None
    uncached_p99_ms: float | None = None
    # per-replica device dispatch counters (in-process target only): the
    # evidence the data-parallel dispatcher spread work across devices
    per_device_dispatch: list[int] | None = None
    # onset/steady split (ISSUE 17): p99 over requests that ARRIVED in
    # the schedule's first 40% vs its last 40%. On ramp/sine shapes the
    # onset window is where every reactive mechanism is still measuring
    # its way up the rate curve — exactly where predictive serving can
    # help and where a pooled p99 averages the difference away. Pooled
    # drivers that don't keep arrival-indexed latencies leave these None.
    onset_p99_ms: float | None = None
    steady_p99_ms: float | None = None

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


class ClientTraceLog:
    """Client-side half of the trace JOIN (ISSUE 9 remainder, landed with
    ISSUE 10): one record per request whose response echoed an
    ``X-KMLS-Trace`` id — the send/recv wall-clock timestamps the server's
    retained spans (``GET /debug/traces``) cannot know. Bounded, thread-
    safe, JSONL on disk; ``scripts/kmls_tracejoin.py`` merges the two
    halves into one per-request timeline keyed by trace id."""

    def __init__(self, capacity: int = 100_000):
        self.capacity = max(1, capacity)
        self._entries: list[dict] = []
        self._lock = threading.Lock()
        self.dropped = 0

    def record(
        self,
        trace_id: str,
        send_unix: float,
        recv_unix: float,
        status: int = 200,
    ) -> None:
        if not trace_id:
            return
        entry = {
            "trace_id": trace_id,
            "client_send_unix": round(send_unix, 6),
            "client_recv_unix": round(recv_unix, 6),
            "client_rtt_ms": round((recv_unix - send_unix) * 1e3, 4),
            "status": int(status),
        }
        with self._lock:
            if len(self._entries) >= self.capacity:
                self.dropped += 1
                return
            self._entries.append(entry)

    def entries(self) -> list[dict]:
        with self._lock:
            return list(self._entries)

    def write_jsonl(self, path: str) -> int:
        """Dump the log → records written. Plain open(): a loadgen-side
        scratch file, not a PVC artifact (no atomicity contract)."""
        entries = self.entries()
        # kmls-verify: allow[atomic-write] — loadgen-side scratch JSONL on
        # the client host, not a PVC artifact; no reader races it
        with open(path, "w", encoding="utf-8") as fh:
            for e in entries:
                fh.write(json.dumps(e) + "\n")
        return len(entries)


def _unpack_send_result(result) -> tuple[str, bool | None]:
    """send() contract: a bare source tag (legacy), or (source, cached)."""
    if isinstance(result, tuple):
        return result[0], bool(result[1])
    return result, None


def _cache_split_fields(
    lat_cached: list[float], lat_uncached: list[float], n_ok: int
) -> dict:
    """→ the ReplayReport cache-split kwargs (empty when the target never
    reported a cache outcome)."""
    if not lat_cached and not lat_uncached:
        return {}
    cached_sorted = sorted(lat_cached)
    uncached_sorted = sorted(lat_uncached)
    out = {
        "cache_hit_ratio": len(cached_sorted) / n_ok if n_ok else 0.0,
    }
    if cached_sorted:
        out["cached_p50_ms"] = _percentile(cached_sorted, 0.50)
        out["cached_p99_ms"] = _percentile(cached_sorted, 0.99)
    if uncached_sorted:
        out["uncached_p50_ms"] = _percentile(uncached_sorted, 0.50)
        out["uncached_p99_ms"] = _percentile(uncached_sorted, 0.99)
    return out


def attach_attribution(report: "ReplayReport", metrics) -> "ReplayReport":
    """Fold a :class:`~.metrics.ServingMetrics` queue/device split into the
    report (milliseconds) — the keys that tell the next round WHERE the
    tail lives instead of only that one exists."""
    qw50, qw99 = metrics.queue_wait.percentiles(0.50, 0.99)
    dv50, dv99 = metrics.device.percentiles(0.50, 0.99)
    (e2e999,) = metrics.e2e.percentiles(0.999)
    report.queue_wait_p50_ms = qw50 * 1e3
    report.queue_wait_p99_ms = qw99 * 1e3
    report.device_p50_ms = dv50 * 1e3
    report.device_p99_ms = dv99 * 1e3
    report.e2e_p999_ms = e2e999 * 1e3
    return report


def onset_steady_p99(
    points: list[tuple[float, float]],
    span_s: float,
    *,
    onset_frac: float = 0.4,
    steady_frac: float = 0.6,
) -> tuple[float | None, float | None]:
    """Split ``(relative_arrival_s, latency_ms)`` completion points by
    ARRIVAL time into the schedule's onset window (first ``onset_frac``
    of ``span_s``) and steady window (last ``1 - steady_frac``) and
    return each window's p99 (None for an empty window). Splitting by
    arrival — not completion — keeps a request that arrived at the cliff
    but finished late attributed to the cliff."""
    if not points or span_s <= 0:
        return None, None
    onset = sorted(d for t, d in points if t <= onset_frac * span_s)
    steady = sorted(d for t, d in points if t >= steady_frac * span_s)
    return (
        _percentile(onset, 0.99) if onset else None,
        _percentile(steady, 0.99) if steady else None,
    )


def _percentile(sorted_ms: list[float], q: float) -> float:
    if not sorted_ms:
        return float("nan")
    idx = min(int(q * len(sorted_ms)), len(sorted_ms) - 1)
    return sorted_ms[idx]


def sample_seed_sets(
    vocab: list[str],
    n: int,
    *,
    seeds_per_request: int = 3,
    unknown_fraction: float = 0.1,
    rng_seed: int = 0,
    zipf_s: float = 0.0,
    zipf_pool: int = 512,
) -> list[list[str]]:
    """n request payloads: mostly known tracks, a slice of unknown ones.

    ``zipf_s > 0`` switches to a Zipf-distributed query mix: a pool of
    ``zipf_pool`` distinct payloads is drawn exactly as before, and each of
    the n requests picks pool entry k with probability ∝ 1/k^s — the
    skewed head real playlist-seed traffic has, and what an epoch-keyed
    answer cache feeds on. Default OFF (0.0) so every existing bench/replay
    number keeps its all-distinct request mix, bit for bit."""
    rng = random.Random(rng_seed)

    def _draw(i: int) -> list[str]:
        if vocab and rng.random() >= unknown_fraction:
            k = min(seeds_per_request, len(vocab))
            return rng.sample(vocab, k)
        return [f"__replay_unknown_{i}__"]

    if zipf_s <= 0.0:
        return [_draw(i) for i in range(n)]
    pool = [_draw(i) for i in range(max(1, min(zipf_pool, max(n, 1))))]
    ranks = np.arange(1, len(pool) + 1, dtype=np.float64)
    p = ranks ** -zipf_s
    p /= p.sum()
    picks = np.random.default_rng(rng_seed).choice(len(pool), size=n, p=p)
    return [pool[int(i)] for i in picks]


REPLAY_SHAPES = ("constant", "burst", "ramp", "sine")


def shaped_arrivals(
    n: int,
    qps: float,
    shape: str = "constant",
    *,
    rng_seed: int = 12345,
    burst_factor: float = 10.0,
    burst_fraction: float = 0.15,
    n_bursts: int = 4,
    ramp_start_factor: float = 0.1,
    ramp_stop_factor: float = 2.0,
    sine_amplitude: float = 0.75,
    sine_cycles: float = 2.0,
) -> np.ndarray:
    """Arrival times (seconds from start) for ``n`` requests under a
    non-homogeneous Poisson process whose rate follows ``shape``:

    - ``constant``: rate ``qps`` throughout — BIT-identical to the
      internal schedule every replay driver used before shapes existed
      (same rng seed, same exponential stream), so un-shaped runs stay
      comparable across rounds;
    - ``burst``: ``n_bursts`` burst trains — ``burst_fraction`` of each
      period at ``burst_factor × qps``, the rest at the base rate (a
      10× burst is the overload-robustness acceptance shape);
    - ``ramp``: rate climbs linearly ``ramp_start_factor × qps`` →
      ``ramp_stop_factor × qps`` (the autoscaler's approach ramp);
    - ``sine``: ``sine_cycles`` diurnal cycles of
      ``qps·(1 ± sine_amplitude)``.

    Thinning-free construction: unit-rate exponential gaps are divided
    by the instantaneous rate at the current arrival time, so every
    shape emits exactly ``n`` requests and an unknown shape never drops
    traffic silently — it raises."""
    if shape not in REPLAY_SHAPES:
        raise ValueError(
            f"unknown replay shape {shape!r}; expected one of "
            f"{'/'.join(REPLAY_SHAPES)}"
        )
    rng = np.random.default_rng(rng_seed)
    if shape == "constant":
        # EXACTLY the legacy drivers' draw — scale passed to exponential(),
        # not divided out afterwards: numpy computes scale·standard_exp, and
        # gaps/qps differs from that in the last float bit at most rates,
        # which would silently break the bit-identity (comparability)
        # contract this branch exists for
        return np.cumsum(rng.exponential(1.0 / qps, size=n))
    unit_gaps = rng.exponential(1.0, size=n)
    # nominal run length at the shape's MEAN rate — the rate functions
    # are phased against it, so "4 bursts" means 4 bursts over the run
    # regardless of n
    if shape == "burst":
        mean = qps * (1.0 + burst_fraction * (burst_factor - 1.0))
    elif shape == "ramp":
        mean = qps * (ramp_start_factor + ramp_stop_factor) / 2.0
    else:  # sine
        mean = qps
    nominal_s = n / mean

    def rate(t: float) -> float:
        # past the nominal window (a slow target stretches real time)
        # the shape holds its final value instead of wrapping
        phase = min(t / nominal_s, 1.0) if nominal_s > 0 else 1.0
        if shape == "burst":
            if phase >= 1.0:
                # each period ENDS at the base rate, but 1.0 % period == 0
                # reads as burst onset — hold the base rate explicitly so
                # the tail past the nominal window doesn't grow a fifth,
                # undocumented burst
                return qps
            period = 1.0 / max(n_bursts, 1)
            in_burst = (phase % period) < burst_fraction * period
            return qps * burst_factor if in_burst else qps
        if shape == "ramp":
            return qps * (
                ramp_start_factor
                + (ramp_stop_factor - ramp_start_factor) * phase
            )
        # sine, floored at 5% of base so the process always advances
        import math

        return max(
            qps * (1.0 + sine_amplitude
                   * math.sin(2.0 * math.pi * sine_cycles * phase)),
            0.05 * qps,
        )

    out = np.empty(n, dtype=np.float64)
    t = 0.0
    for i in range(n):
        t += unit_gaps[i] / rate(t)
        out[i] = t
    return out


def flash_crowd_payloads(
    payloads: list[list[str]],
    *,
    window: tuple[float, float] = (0.4, 0.7),
    hot_pool: int = 4,
) -> list[list[str]]:
    """The flash-crowd request mix: inside ``window`` (fractions of the
    request stream) EVERY request collapses onto a ``hot_pool``-sized
    set of seed payloads drawn from the window's own head — all traffic
    lands on a handful of cache keys at once, which is exactly where
    singleflight, the answer cache, and admission control interact.
    Outside the window the mix is untouched. The hot pool comes from
    INSIDE the window so the crowd's keys are cold at onset (never
    pre-warmed by the preceding traffic) — the worst case."""
    n = len(payloads)
    lo, hi = int(window[0] * n), int(window[1] * n)
    if hi <= lo:
        return list(payloads)
    # distinct pool entries (a Zipf mix repeats payloads): first
    # hot_pool DISTINCT seed sets from the window's own slice
    seen: dict[tuple, None] = {}
    for p in payloads[lo:hi]:
        seen.setdefault(tuple(p), None)
        if len(seen) >= hot_pool:
            break
    pool = [list(p) for p in seen]
    return [
        list(pool[i % len(pool)]) if lo <= i < hi else list(payloads[i])
        for i in range(n)
    ]


def _fire_events(events, i: int, fired: set) -> None:
    """Run every not-yet-fired event whose trigger index <= i (pacing
    thread only; an event that raises is the harness's bug, not a
    request error — let it propagate)."""
    if not events:
        return
    for j, (at_index, fn) in enumerate(events):
        if j not in fired and i >= at_index:
            fired.add(j)
            fn()


def replay(
    send,  # callable(list[str]) -> str (response source tag)
    payloads: list[list[str]],
    *,
    qps: float,
    max_concurrency: int = 256,
    arrivals: np.ndarray | None = None,
    events: list | None = None,
) -> ReplayReport:
    """Open-loop replay: request i is DISPATCHED at its Poisson arrival time
    regardless of whether earlier requests completed (up to
    ``max_concurrency`` in flight, beyond which arrivals count as errors —
    an overloaded server must show up as drops/latency, not reduced load).
    ``arrivals`` overrides the internal constant-rate schedule with a
    :func:`shaped_arrivals` one; ``events`` is ``[(index, fn)]`` — each
    ``fn`` runs once on the pacing thread when dispatch reaches its index
    (the epoch-flip harness hook)."""
    arrival = (
        arrivals if arrivals is not None
        else np.cumsum(
            np.random.default_rng(12345).exponential(
                1.0 / qps, size=len(payloads)
            )
        )
    )

    lat_ms: list[float] = []
    lat_cached: list[float] = []
    lat_uncached: list[float] = []
    by_source: dict[str, int] = {}
    errors = 0
    lock = threading.Lock()
    inflight = threading.Semaphore(max_concurrency)
    threads: list[threading.Thread] = []

    def worker(seeds: list[str]) -> None:
        t0 = time.perf_counter()
        try:
            source, cached = _unpack_send_result(send(seeds))
            dt_ms = (time.perf_counter() - t0) * 1e3
            with lock:
                lat_ms.append(dt_ms)
                if cached is not None:
                    (lat_cached if cached else lat_uncached).append(dt_ms)
                by_source[source] = by_source.get(source, 0) + 1
        except Exception:
            nonlocal errors
            with lock:
                errors += 1
        finally:
            inflight.release()

    fired: set = set()
    start = time.perf_counter()
    for i, seeds in enumerate(payloads):
        now = time.perf_counter() - start
        wait = arrival[i] - now
        if wait > 0:
            time.sleep(wait)
        _fire_events(events, i, fired)
        if not inflight.acquire(blocking=False):
            with lock:
                errors += 1
            continue
        t = threading.Thread(target=worker, args=(seeds,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=60.0)
    duration = time.perf_counter() - start

    # snapshot under the lock: a straggler worker past its join deadline may
    # still complete and append concurrently — its write either lands before
    # this snapshot (counted) or is excluded, never racing the sort
    with lock:
        lat_sorted = sorted(lat_ms)
        sources = dict(by_source)
        n_errors = errors
        split = _cache_split_fields(lat_cached, lat_uncached, len(lat_ms))
    n_ok = len(lat_sorted)
    return ReplayReport(
        target_qps=qps,
        offered_qps=(n_ok + n_errors) / duration if duration > 0 else 0.0,
        achieved_qps=n_ok / duration if duration > 0 else 0.0,
        duration_s=duration,
        n_requests=len(payloads),
        n_errors=n_errors,
        p50_ms=_percentile(lat_sorted, 0.50),
        p95_ms=_percentile(lat_sorted, 0.95),
        p99_ms=_percentile(lat_sorted, 0.99),
        by_source=sources,
        **split,
    )


def replay_pooled(
    make_send,  # () -> callable(list[str]) -> str; one per worker
    payloads: list[list[str]],
    *,
    qps: float,
    n_workers: int = 64,
    max_queue: int = 512,
    arrivals: np.ndarray | None = None,
    events: list | None = None,
) -> ReplayReport:
    """Open-loop replay with a fixed worker pool and one persistent sender
    per worker (wrk-style). The thread-per-request :func:`replay` melts at
    ~1k QPS on its own overhead (thread spawn + TCP handshake per request),
    which measures the load generator, not the server; here arrivals are
    Poisson-paced into a bounded queue and latency runs from the scheduled
    ARRIVAL to completion — queue wait included — so an overloaded server
    shows up as latency and drops, never as reduced offered load.
    ``arrivals``/``events`` as in :func:`replay`: a shaped arrival
    schedule, and ``[(index, fn)]`` hooks fired on the pacing thread."""
    arrival = (
        arrivals if arrivals is not None
        else np.cumsum(
            np.random.default_rng(12345).exponential(
                1.0 / qps, size=len(payloads)
            )
        )
    )

    import queue as queue_mod

    q: "queue_mod.Queue" = queue_mod.Queue(maxsize=max_queue)
    lat_ms: list[float] = []
    # (relative arrival s, latency ms) per completion — the onset/steady
    # split's input (ISSUE 17); arrival_abs is start-anchored below
    lat_points: list[tuple[float, float]] = []
    lat_cached: list[float] = []
    lat_uncached: list[float] = []
    by_source: dict[str, int] = {}
    errors = 0
    lock = threading.Lock()

    def worker() -> None:
        nonlocal errors
        send = make_send()
        while True:
            item = q.get()
            if item is None:
                return
            # drain a burst behind the blocking get: at 10k-QPS pacing,
            # one futex wake per item IS the loadgen ceiling on a small
            # host (~8k/s measured on a 2-core sandbox); a woken worker
            # that sweeps everything already queued amortizes the wakeup
            # the same way the async HTTP client amortizes syscalls.
            # Low-rate behavior is unchanged — an empty queue yields a
            # burst of one.
            burst = [item]
            while len(burst) < 64:
                try:
                    extra = q.get_nowait()
                except queue_mod.Empty:
                    break
                if extra is None:
                    q.put_nowait(None)  # keep the sentinel for the pool
                    break
                burst.append(extra)
            for arrival_abs, seeds in burst:
                try:
                    source, cached = _unpack_send_result(send(seeds))
                    dt_ms = (time.perf_counter() - arrival_abs) * 1e3
                    with lock:
                        lat_ms.append(dt_ms)
                        # `start` is bound before any item is enqueued,
                        # so the dereference here can never race it
                        lat_points.append((arrival_abs - start, dt_ms))
                        if cached is not None:
                            (lat_cached if cached else lat_uncached).append(
                                dt_ms
                            )
                        by_source[source] = by_source.get(source, 0) + 1
                except Exception:
                    with lock:
                        errors += 1

    workers = [
        threading.Thread(target=worker, daemon=True) for _ in range(n_workers)
    ]
    for w in workers:
        w.start()

    fired: set = set()
    start = time.perf_counter()
    for i, seeds in enumerate(payloads):
        wait = arrival[i] - (time.perf_counter() - start)
        if wait > 0:
            time.sleep(wait)
        _fire_events(events, i, fired)
        try:
            q.put_nowait((start + arrival[i], seeds))
        except queue_mod.Full:
            with lock:
                errors += 1  # server (or pool) saturated: an honest drop
    for _ in workers:
        q.put(None)
    for w in workers:
        w.join(timeout=120.0)
    duration = time.perf_counter() - start

    with lock:
        lat_sorted = sorted(lat_ms)
        sources = dict(by_source)
        n_errors = errors
        split = _cache_split_fields(lat_cached, lat_uncached, len(lat_ms))
        points = list(lat_points)
    n_ok = len(lat_sorted)
    onset_p99, steady_p99 = onset_steady_p99(
        points, float(arrival[-1]) if len(arrival) else 0.0
    )
    return ReplayReport(
        target_qps=qps,
        offered_qps=(n_ok + n_errors) / duration if duration > 0 else 0.0,
        achieved_qps=n_ok / duration if duration > 0 else 0.0,
        duration_s=duration,
        n_requests=len(payloads),
        n_errors=n_errors,
        p50_ms=_percentile(lat_sorted, 0.50),
        p95_ms=_percentile(lat_sorted, 0.95),
        p99_ms=_percentile(lat_sorted, 0.99),
        by_source=sources,
        onset_p99_ms=onset_p99,
        steady_p99_ms=steady_p99,
        **split,
    )


def _parse_http_head(head: bytes) -> tuple[int, int, bytes]:
    """One copy of the pipelined clients' response-head parse →
    ``(status, content_length, lowercased head)`` — shared by
    :func:`replay_async_http` and :func:`replay_fleet_http` so the two
    drivers can never diverge in what they count as an answer."""
    head_lower = head.lower()
    clen = 0
    for line in head_lower.split(b"\r\n"):
        if line.startswith(b"content-length"):
            clen = int(line.split(b":", 1)[1])
    return int(head.split(b" ", 2)[1]), clen, head_lower


async def _open_http_conn(host: str, port: int):
    """Persistent loadgen connection with TCP_NODELAY (the header and
    body go out as separate-enough writes that Nagle would serialize
    them behind delayed ACKs)."""
    import asyncio
    import socket as socket_mod

    reader, writer = await asyncio.open_connection(host, port)
    sock = writer.get_extra_info("socket")
    if sock is not None:
        sock.setsockopt(socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1)
    return reader, writer


def replay_async_http(
    url: str,
    payloads: list[list[str]],
    *,
    qps: float,
    n_conns: int = 32,
    pipeline: int = 16,
    max_queue: int = 4096,
    trace_log: ClientTraceLog | None = None,
) -> ReplayReport:
    """Open-loop HTTP replay on ONE event loop with request pipelining —
    the load generator the 1k-QPS acceptance needs on a syscall-taxed
    sandbox. Thread-pool clients (``replay_pooled`` +
    ``pooled_http_sender_factory``) melt first on this class of host:
    64 Python threads convoy on the GIL, and every request pays ~2
    traps (~0.5 ms each here) for its send/recv. Here arrivals are
    Poisson-paced into a queue, each of ``n_conns`` persistent
    connections writes bursts of up to ``pipeline`` queued requests as
    one send and reads the responses back to back, and latency runs
    from the SCHEDULED arrival to response completion — queue wait and
    burst wait included, so an overloaded server (or client) shows up
    as latency/drops, never as reduced offered load."""
    import asyncio
    import urllib.parse

    u = urllib.parse.urlsplit(url)
    host, port = u.hostname or "127.0.0.1", u.port or 80
    # pre-encode every request: the loadgen's job is pacing, not cooking
    reqs: list[bytes] = []
    for seeds in payloads:
        body = json.dumps({"songs": seeds}).encode()
        reqs.append(
            b"POST /api/recommend/ HTTP/1.1\r\nHost: replay\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
    rng = np.random.default_rng(12345)
    arrival = np.cumsum(rng.exponential(1.0 / qps, size=len(payloads)))

    lat_ms: list[float] = []
    lat_cached: list[float] = []
    lat_uncached: list[float] = []
    by_source: dict[str, int] = {}
    errors = 0
    # perf_counter → unix offset, captured once: trace-log records carry
    # wall-clock endpoints so kmls_tracejoin can line them up with the
    # server spans' start_unix
    wall_off = time.time() - time.perf_counter()

    async def _run() -> None:
        nonlocal errors
        queue: "asyncio.Queue" = asyncio.Queue(maxsize=max_queue)

        async def connect():
            return await _open_http_conn(host, port)

        async def worker() -> None:
            nonlocal errors
            reader, writer = await connect()
            dead = False  # reconnect failed: drain the queue as errors
            while True:
                item = await queue.get()
                if item is None:
                    if writer is not None:
                        writer.close()
                    return
                burst = [item]
                while len(burst) < pipeline:
                    try:
                        extra = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if extra is None:
                        # keep the sentinel for after this burst
                        queue.put_nowait(None)
                        break
                    burst.append(extra)
                if dead:
                    errors += len(burst)
                    continue
                done = 0  # responses already accounted (ok OR non-200)
                try:
                    writer.write(b"".join(reqs[i] for _, i in burst))
                    for t_arr, _i in burst:
                        head = await reader.readuntil(b"\r\n\r\n")
                        status, clen, head_lower = _parse_http_head(head)
                        body = await reader.readexactly(clen)
                        done += 1
                        t_done = time.perf_counter()
                        if trace_log is not None:
                            # echoed trace id (present when the server's
                            # recorder is armed) → the client half of the
                            # tracejoin timeline
                            for line in head_lower.split(b"\r\n"):
                                if line.startswith(b"x-kmls-trace:"):
                                    trace_log.record(
                                        line.split(b":", 1)[1]
                                        .strip().decode("ascii", "replace"),
                                        wall_off + t_arr,
                                        wall_off + t_done,
                                        status,
                                    )
                                    break
                        if status != 200:
                            errors += 1
                            continue
                        dt_ms = (t_done - t_arr) * 1e3
                        lat_ms.append(dt_ms)
                        # the server marks answer-cache hits with an
                        # X-KMLS-Cache header (serving/app.py) — the only
                        # way a black-box client can split cached latency
                        if b"x-kmls-cache: hit" in head_lower:
                            lat_cached.append(dt_ms)
                        else:
                            lat_uncached.append(dt_ms)
                        source = (
                            "empty" if b'"songs": []' in body else "nonempty"
                        )
                        by_source[source] = by_source.get(source, 0) + 1
                except Exception:
                    # only the UNanswered tail of the burst is new errors —
                    # responses already read above were counted either way
                    errors += len(burst) - done
                    try:
                        writer.close()
                    except Exception:
                        pass
                    try:
                        reader, writer = await connect()
                    except OSError:
                        # server gone: stop sending, keep draining the
                        # queue into errors so the report still lands
                        dead = True
                        writer = None

        workers = [asyncio.create_task(worker()) for _ in range(n_conns)]
        t0 = time.perf_counter()
        for i in range(len(payloads)):
            wait = arrival[i] - (time.perf_counter() - t0)
            if wait > 0:
                await asyncio.sleep(wait)
            try:
                queue.put_nowait((t0 + arrival[i], i))
            except asyncio.QueueFull:
                errors += 1  # saturated: an honest drop
        for _ in workers:
            await queue.put(None)
        await asyncio.gather(*workers)

    start = time.perf_counter()
    asyncio.run(_run())
    duration = time.perf_counter() - start
    lat_sorted = sorted(lat_ms)
    n_ok = len(lat_sorted)
    return ReplayReport(
        target_qps=qps,
        offered_qps=(n_ok + errors) / duration if duration > 0 else 0.0,
        achieved_qps=n_ok / duration if duration > 0 else 0.0,
        duration_s=duration,
        n_requests=len(payloads),
        n_errors=errors,
        p50_ms=_percentile(lat_sorted, 0.50),
        p95_ms=_percentile(lat_sorted, 0.95),
        p99_ms=_percentile(lat_sorted, 0.99),
        by_source=by_source,
        **_cache_split_fields(lat_cached, lat_uncached, n_ok),
    )


def replay_fleet_http(
    peer_urls: dict[str, str],
    payloads: list[list[str]],
    *,
    qps: float,
    policy: str = "ring",
    n_conns: int = 4,
    pipeline: int = 16,
    max_queue: int = 8192,
    eject_threshold: int = 3,
    probe_interval_s: float = 1.0,
    redispatch_max: int = 4,
    window_end: int | None = None,
    events: list | None = None,
    hedge: bool = False,
    hedge_delay_ms: float = 30.0,
    hedge_max_frac: float = 0.05,
    slow_ratio: float = 0.0,
    deadline_ms: float = 0.0,
) -> tuple[ReplayReport, dict]:
    """Open-loop HTTP replay against an N-replica FLEET with client-side
    consistent-hash routing (ISSUE 15) — the load generator half of the
    fleet cache tier, and the local stand-in for a consistent-hash
    ingress. One event loop; per peer, ``n_conns`` persistent pipelined
    connections (the ``replay_async_http`` transport).

    ``policy``:

    - ``ring`` — each request routes to the rendezvous owner of its
      canonicalized seed-set key via :class:`~..freshness.ring
      .FleetRouter`: the SAME ring implementation ``simulate_fleet``
      scores and the serving side stamps owners with, so the simulated
      hit-ratio multiplier is a prediction this replay can falsify. A
      peer failing ``eject_threshold`` consecutive sends is ejected
      (PR 3 circuit-breaker semantics) and its keys spill to their
      next-highest rendezvous weight — the bounded remap — with a
      half-open probe every ``probe_interval_s`` for re-admission.
    - ``roundrobin`` — the independent-caches baseline: the same fleet,
      no affinity, every replica re-warms the same head.

    A send that dies mid-burst re-dispatches its UNanswered requests
    through the router (up to ``redispatch_max`` attempts each) before
    counting an error, so a replica kill mid-replay must surface as
    remap + survivor latency, never as drops. Latency always runs from
    the scheduled arrival — retries included.

    ``window_end`` additionally splits cache-outcome accounting at that
    request index (the fleet bench judges the hit-ratio multiplier on
    the pre-kill window so the kill's cold remap doesn't blur the
    routed-vs-independent comparison). → ``(ReplayReport, fleet)`` where
    ``fleet`` carries hit ratios, per-peer answer counts, 5xx/reroute/
    ejection counters, and owner-stamped (misrouted) observations.

    **Gray-failure spine** (ISSUE 18):

    - ``slow_ratio > 0`` arms the router's slow-outlier ladder: every
      primary answer feeds ``FleetRouter.mark_latency`` and a peer whose
      EWMA exceeds ``slow_ratio ×`` the healthy median is ejected like a
      failing one (``slow_ejections`` in the fleet dict).
    - ``hedge=True`` arms hedged dispatch: after a per-peer adaptive
      delay (tracked ~p95, floored at ``hedge_delay_ms``) an unanswered
      request re-issues ONCE to the next-ranked peer; first valid answer
      wins, the loser is discarded on arrival (the pipelined-HTTP form
      of cancellation), and winner/loser bodies are digest-compared —
      ``hedge_mismatch`` must stay 0 because fleet peers serve the same
      artifacts. Hedges spend a token bucket earning ``hedge_max_frac``
      per primary dispatch (amplification structurally bounded); an
      empty bucket counts ``hedges_suppressed`` and falls back to plain
      waiting. ``hedge=False`` is proven zero-cost via the module
      :data:`HEDGES_ISSUED` counter.
    - ``deadline_ms > 0`` stamps the remaining budget on every request
      as ``X-KMLS-Deadline-Budget`` (computed at WRITE time, so queue
      wait and hedge delay are already spent); servers answering
      degraded with ``deadline-expired`` are counted separately from
      slow-compute degradation (``deadline_expired``)."""
    import asyncio
    import urllib.parse

    from ..freshness.ring import FleetRouter, seeds_key

    if policy not in ("ring", "roundrobin"):
        raise ValueError(f"unknown fleet routing policy {policy!r}")
    peers = sorted(peer_urls)
    router = FleetRouter(
        peers,
        eject_threshold=eject_threshold,
        probe_interval_s=probe_interval_s,
        slow_ratio=slow_ratio,
    )
    addr: dict[str, tuple[str, int]] = {}
    for peer, url in peer_urls.items():
        u = urllib.parse.urlsplit(url)
        addr[peer] = (u.hostname or "127.0.0.1", u.port or 80)
    keys = [seeds_key(p) for p in payloads]
    # dynamic heads (deadline budget stamped at WRITE time, hedge copies
    # marked) are assembled per send; the pre-encoded fast path stays
    # byte-identical to the pre-ISSUE-18 replay whenever both are off
    dynamic_head = hedge or deadline_ms > 0
    bodies = [json.dumps({"songs": seeds}).encode() for seeds in payloads]
    reqs: list[bytes] = []
    for body in bodies:
        reqs.append(
            b"POST /api/recommend/ HTTP/1.1\r\nHost: replay\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
    arrival = np.cumsum(
        np.random.default_rng(12345).exponential(
            1.0 / qps, size=len(payloads)
        )
    )

    lat_ms: list[float] = []
    lat_cached: list[float] = []
    lat_uncached: list[float] = []
    stats = {
        "http_5xx": 0, "owner_stamped": 0, "rerouted": 0, "errors": 0,
        "win_total": 0, "win_hits": 0, "mesh_unavailable": 0,
        "hedges_issued": 0, "hedge_wins": 0, "hedge_losses": 0,
        "hedges_suppressed": 0, "hedge_mismatch": 0, "deadline_expired": 0,
    }
    answered_by = {p: 0 for p in peers}
    # per-request single-winner state (hedging races two copies):
    # answered flags gate the discard path, digests back the bit-identity
    # check, hedged marks indices whose hedge copy actually went out
    answered = bytearray(len(payloads))
    digests: dict[int, bytes] = {}
    hedged: set[int] = set()
    # token bucket: earns hedge_max_frac per primary dispatch, spends
    # 1.0 per hedge, starts full at a small burst cap — extra dispatches
    # are structurally bounded at hedge_max_frac of total (+ the cap)
    hedge_cap = max(1.0, 16.0 * hedge_max_frac)
    hedge_tokens = [hedge_cap]

    def _bdigest(payload: bytes) -> bytes:
        import hashlib

        return hashlib.blake2b(payload, digest_size=8).digest()

    def _wire(item) -> bytes:
        """Request bytes for one copy — the pre-encoded fast path, or a
        head rebuilt at write time carrying the remaining deadline
        budget (what's left NOW, queue wait already spent) and the
        hedge marker."""
        t_arr, idx, _attempts, is_hedge = item
        if not dynamic_head:
            return reqs[idx]
        extra = b""
        if deadline_ms > 0:
            remaining = deadline_ms - (time.perf_counter() - t_arr) * 1e3
            extra += (
                b"X-KMLS-Deadline-Budget: "
                + str(max(0, int(remaining))).encode() + b"\r\n"
            )
        if is_hedge:
            extra += b"X-KMLS-Hedge: 1\r\n"
        body = bodies[idx]
        return (
            b"POST /api/recommend/ HTTP/1.1\r\nHost: replay\r\n"
            b"Content-Type: application/json\r\n" + extra
            + b"Content-Length: " + str(len(body)).encode()
            + b"\r\n\r\n" + body
        )

    async def _run() -> None:
        queues = {p: asyncio.Queue(maxsize=max_queue) for p in peers}
        outstanding = [0]
        drained = asyncio.Event()
        drained.set()

        def _enter() -> None:
            outstanding[0] += 1
            drained.clear()

        def _leave() -> None:
            outstanding[0] -= 1
            if outstanding[0] <= 0:
                drained.set()

        def _redispatch(item, failed_peer: str) -> None:
            """One failed request back out (spill), or an honest error
            once its re-dispatch budget is spent. Ring policy spills
            through the router; the round-robin BASELINE must stay
            hash-free even on retries — routing its failures to
            rendezvous owners would warm owner caches exactly like the
            routed leg and inflate the baseline hit ratio the multiplier
            is judged against — so it retries on the next peer in fixed
            cyclic order instead."""
            t_arr, idx, attempts, is_hedge = item
            if hedge and answered[idx]:
                # the other copy of a hedged pair already won: this
                # copy's failure is moot — drop it, no retry, no error
                _leave()
                return
            if attempts >= redispatch_max:
                stats["errors"] += 1
                _leave()
                return
            if policy == "ring":
                target = router.route(keys[idx])
            else:
                step = 1 + (attempts % max(len(peers) - 1, 1))
                target = peers[(peers.index(failed_peer) + step) % len(peers)]
            stats["rerouted"] += 1
            try:
                queues[target].put_nowait((t_arr, idx, attempts + 1, is_hedge))
            except asyncio.QueueFull:
                stats["errors"] += 1
                _leave()

        def _account(
            peer: str, item, status: int, head_lower: bytes,
            payload: bytes = b"",
        ) -> bool:
            """Account one response → True when it was the gang-degraded
            refusal (the caller must NOT mark_success for a burst that
            carried one: transport-level success with every answer a
            mesh refusal would re-admit the gang and wipe the shard
            blame while the member is still dark)."""
            t_arr, idx, attempts, is_hedge = item
            if hedge and answered[idx]:
                # losing copy of a hedged pair: its answer is DISCARDED
                # (first valid answer already won) — but it is still a
                # real observation: a 200 body must be bit-identical to
                # the winner's, a late primary still feeds the slow
                # ladder, and a server 5xx is still a server 5xx
                if status == 200:
                    want = digests.get(idx)
                    if want is not None and _bdigest(payload) != want:
                        stats["hedge_mismatch"] += 1
                    if not is_hedge and attempts == 0:
                        router.mark_latency(
                            peer, time.perf_counter() - t_arr
                        )
                elif status >= 500 and b"x-kmls-mesh-unavailable:" not in head_lower:
                    stats["http_5xx"] += 1
                _leave()
                return False
            if status == 503 and b"x-kmls-mesh-unavailable:" in head_lower:
                # gang-degraded (ISSUE 16): the peer is a pod-gang
                # missing a member and REFUSED rather than serve a
                # partial catalog. That is a PEER failure, not a served
                # 5xx — blame the named shard on the gang's breaker
                # entry and spill the request through the router, the
                # exact path a dead-replica transport failure takes
                shard = None
                for line in head_lower.split(b"\r\n"):
                    if line.startswith(b"x-kmls-mesh-unavailable:"):
                        val = line.split(b":", 1)[1].strip()
                        if val.isdigit():
                            shard = int(val)
                stats["mesh_unavailable"] += 1
                router.mark_failure(peer, shard=shard)
                _redispatch(item, peer)
                return True
            if status >= 500:
                stats["http_5xx"] += 1
                stats["errors"] += 1
                _leave()
                return False
            if status != 200:
                stats["errors"] += 1
                _leave()
                return False
            dt_s = time.perf_counter() - t_arr
            dt_ms = dt_s * 1e3
            lat_ms.append(dt_ms)
            hit = b"x-kmls-cache: hit" in head_lower
            (lat_cached if hit else lat_uncached).append(dt_ms)
            if b"x-kmls-cache-owner:" in head_lower:
                stats["owner_stamped"] += 1
            if b"x-kmls-degraded: deadline-expired" in head_lower:
                # the deadline budget died in transit: the peer answered
                # degraded instead of computing a result nobody waits
                # for — wasted-work avoided, distinct from slow-compute
                stats["deadline_expired"] += 1
            if window_end is not None and idx < window_end:
                stats["win_total"] += 1
                stats["win_hits"] += int(hit)
            answered_by[peer] += 1
            # latency health: first-attempt primaries are clean
            # arrival→answer observations of the peer that served them
            # (retried/hedge copies would double-blame)
            if not is_hedge and attempts == 0:
                router.mark_latency(peer, dt_s)
            if hedge:
                answered[idx] = 1
                if idx in hedged:
                    digests[idx] = _bdigest(payload)
                    if is_hedge:
                        stats["hedge_wins"] += 1
                    else:
                        stats["hedge_losses"] += 1
            _leave()
            return False

        async def connect(peer: str):
            return await _open_http_conn(*addr[peer])

        async def worker(peer: str) -> None:
            queue = queues[peer]
            reader = writer = None
            while True:
                item = await queue.get()
                if item is None:
                    if writer is not None:
                        writer.close()
                    return
                burst = [item]
                while len(burst) < pipeline:
                    try:
                        extra = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if extra is None:
                        queue.put_nowait(None)
                        break
                    burst.append(extra)
                if writer is None:
                    try:
                        reader, writer = await connect(peer)
                    except OSError:
                        # peer unreachable: one failure mark per burst
                        # (the breaker counts failure EVENTS, like the
                        # batcher's per-batch accounting), spill the work
                        router.mark_failure(peer)
                        for it in burst:
                            _redispatch(it, peer)
                        continue
                done = 0
                burst_mesh_degraded = False
                try:
                    writer.write(b"".join(_wire(it) for it in burst))
                    for it in burst:
                        head = await reader.readuntil(b"\r\n\r\n")
                        status, clen, head_lower = _parse_http_head(head)
                        payload = await reader.readexactly(clen)
                        done += 1
                        burst_mesh_degraded |= _account(
                            peer, it, status, head_lower, payload
                        )
                    if not burst_mesh_degraded:
                        # gang-degraded refusals in the burst leave the
                        # breaker's failure marks standing: the gang
                        # answered at the transport level but is still
                        # missing a shard — re-admission must wait for a
                        # burst it actually SERVES (the half-open probe
                        # after the member re-forms)
                        router.mark_success(peer)
                except Exception:
                    # answered prefix already accounted; the unanswered
                    # tail spills through the router (a mid-replay kill
                    # must read as remap, not as drops)
                    router.mark_failure(peer)
                    for it in burst[done:]:
                        _redispatch(it, peer)
                    try:
                        writer.close()
                    except Exception:
                        pass
                    reader = writer = None

        async def _hedge_after(idx: int, t_arr: float, primary: str) -> None:
            """One hedge audition for request ``idx``: sleep the
            adaptive per-peer delay, then — still unanswered and budget
            permitting — issue ONE copy to the next-ranked peer. First
            valid answer wins; the loser is discarded on arrival."""
            global HEDGES_ISSUED
            delay = router.hedge_delay_s(primary, hedge_delay_ms / 1e3)
            wait = (t_arr + delay) - time.perf_counter()
            if wait > 0:
                await asyncio.sleep(wait)
            if answered[idx]:
                return
            if hedge_tokens[0] < 1.0:
                # amplification bound: no token, no hedge — the request
                # falls back to plain waiting on its primary
                stats["hedges_suppressed"] += 1
                return
            # the hedge must land on a peer the router considers
            # healthy — hedging to an ejected (or slow-ejected) peer
            # re-issues to exactly the stall being routed around and
            # wastes both the token and the hedge
            unhealthy = set(router.ejected_peers())
            if policy == "ring":
                target = next(
                    (
                        p for p in router.ring.ranked(keys[idx])
                        if p != primary and p not in unhealthy
                    ),
                    None,
                )
            else:
                start = peers.index(primary)
                target = next(
                    (
                        peers[(start + off) % len(peers)]
                        for off in range(1, len(peers))
                        if peers[(start + off) % len(peers)] not in unhealthy
                    ),
                    None,
                )
            if target is None or target == primary:
                # no healthy alternate exists: suppress, fall back to
                # plain waiting on the primary
                stats["hedges_suppressed"] += 1
                return
            hedge_tokens[0] -= 1.0
            HEDGES_ISSUED += 1
            stats["hedges_issued"] += 1
            hedged.add(idx)
            _enter()
            try:
                queues[target].put_nowait((t_arr, idx, 0, True))
            except asyncio.QueueFull:
                hedged.discard(idx)
                stats["hedges_suppressed"] += 1
                _leave()

        workers = [
            asyncio.create_task(worker(p))
            for p in peers
            for _ in range(n_conns)
        ]
        hedge_tasks: list = []
        fired: set = set()
        t0 = time.perf_counter()
        for i in range(len(payloads)):
            wait = arrival[i] - (time.perf_counter() - t0)
            if wait > 0:
                await asyncio.sleep(wait)
            if events:
                for j, (at_index, fn) in enumerate(events):
                    if j not in fired and i >= at_index:
                        fired.add(j)
                        fn()
            target = (
                router.route(keys[i])
                if policy == "ring"
                else peers[i % len(peers)]
            )
            _enter()
            try:
                queues[target].put_nowait((t0 + arrival[i], i, 0, False))
            except asyncio.QueueFull:
                stats["errors"] += 1
                _leave()
                continue
            if hedge:
                hedge_tokens[0] = min(
                    hedge_tokens[0] + hedge_max_frac, hedge_cap
                )
                hedge_tasks.append(
                    asyncio.create_task(
                        _hedge_after(i, t0 + arrival[i], target)
                    )
                )
        # every request is answered, errored, or re-dispatched before the
        # pool shuts down — re-dispatches re-enter a queue, so sentinels
        # can only go out once the in-flight count settles to zero
        try:
            await asyncio.wait_for(drained.wait(), timeout=120.0)
        except asyncio.TimeoutError:
            # wedged (a peer hung mid-response past every retry): count
            # the stuck tail honestly and tear the pool down
            stats["errors"] += max(outstanding[0], 0)
            for w in workers:
                w.cancel()
            for h in hedge_tasks:
                h.cancel()
            await asyncio.gather(
                *workers, *hedge_tasks, return_exceptions=True
            )
            return
        # drained ⇒ every logical request resolved: still-sleeping hedge
        # auditions are moot — cancel before the sentinels go out so a
        # late hedge can't race a closing queue
        for h in hedge_tasks:
            h.cancel()
        if hedge_tasks:
            await asyncio.gather(*hedge_tasks, return_exceptions=True)
        for p in peers:
            for _ in range(n_conns):
                queues[p].put_nowait(None)
        await asyncio.gather(*workers)

    start = time.perf_counter()
    asyncio.run(_run())
    duration = time.perf_counter() - start
    lat_sorted = sorted(lat_ms)
    n_ok = len(lat_sorted)
    n_errors = stats["errors"]
    report = ReplayReport(
        target_qps=qps,
        offered_qps=(n_ok + n_errors) / duration if duration > 0 else 0.0,
        achieved_qps=n_ok / duration if duration > 0 else 0.0,
        duration_s=duration,
        n_requests=len(payloads),
        n_errors=n_errors,
        p50_ms=_percentile(lat_sorted, 0.50),
        p95_ms=_percentile(lat_sorted, 0.95),
        p99_ms=_percentile(lat_sorted, 0.99),
        by_source={"fleet": n_ok},
        **_cache_split_fields(lat_cached, lat_uncached, n_ok),
    )
    fleet = {
        "policy": policy,
        "peers": peers,
        "answered_by": dict(answered_by),
        "hit_ratio": (len(lat_cached) / n_ok) if n_ok else 0.0,
        "window_hit_ratio": (
            stats["win_hits"] / stats["win_total"]
            if stats["win_total"]
            else None
        ),
        "window_requests": stats["win_total"],
        "http_5xx": stats["http_5xx"],
        "rerouted": stats["rerouted"],
        "ejections": router.ejections,
        "readmissions": router.readmissions,
        "spills": router.spills,
        "owner_stamped": stats["owner_stamped"],
        "mesh_unavailable": stats["mesh_unavailable"],
        "failed_shards": router.failed_shards(),
        "slow_ejections": router.slow_ejections,
        "hedges_issued": stats["hedges_issued"],
        "hedge_wins": stats["hedge_wins"],
        "hedge_losses": stats["hedge_losses"],
        "hedges_suppressed": stats["hedges_suppressed"],
        "hedge_mismatch": stats["hedge_mismatch"],
        "deadline_expired": stats["deadline_expired"],
    }
    return report, fleet


def pooled_http_sender_factory(url: str, trace_log: ClientTraceLog | None = None):
    """→ ``make_send`` for :func:`replay_pooled`: each worker gets its own
    keep-alive HTTP/1.1 connection (the server speaks HTTP/1.1 —
    serving/app.py Handler.protocol_version), reconnecting on error.
    ``trace_log`` records echoed ``X-KMLS-Trace`` ids with client
    send/recv wall clocks for the tracejoin tooling."""
    import http.client
    import urllib.parse

    u = urllib.parse.urlsplit(url)
    host, port = u.hostname or "127.0.0.1", u.port or 80

    def make_send():
        conn = http.client.HTTPConnection(host, port, timeout=30)

        def send(seeds: list[str]) -> str:
            body = json.dumps({"songs": seeds})
            t_send = time.time()
            try:
                conn.request(
                    "POST", "/api/recommend/", body=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                data = json.load(resp)
                if trace_log is not None:
                    tid = resp.getheader("X-KMLS-Trace")
                    if tid:
                        trace_log.record(
                            tid, t_send, time.time(), resp.status
                        )
                if resp.status != 200:
                    # a shed (429) or server error must count as an
                    # error/drop, not masquerade as an "empty" result
                    raise RuntimeError(f"HTTP {resp.status}")
            except Exception:
                conn.close()  # next request reconnects
                raise
            return "nonempty" if data.get("songs") else "empty"

        return send

    return make_send


def _local_vocab() -> list[str]:
    """Best-effort seed vocabulary for --url runs: the local artifacts, when
    BASE_DIR points at the same PVC the server reads. Empty when absent —
    then every request is an unknown seed and only exercises the static
    fallback, which the report will show as such."""
    try:
        from ..config import ServingConfig
        from .engine import RecommendEngine

        engine = RecommendEngine(ServingConfig.from_env())
        if engine.load():
            return engine.bundle.vocab
    except Exception:
        pass
    return []


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--qps", type=float, default=1000.0)
    parser.add_argument("--requests", type=int, default=2000)
    parser.add_argument("--url", default=None, help="HTTP target; default: in-process engine")
    parser.add_argument("--batch-max-size", type=int, default=32)
    parser.add_argument("--batch-window-ms", type=float, default=2.0)
    parser.add_argument("--workers", type=int, default=64)
    parser.add_argument(
        "--client", choices=("async", "pooled"), default="async",
        help="HTTP loadgen: single-loop pipelined (default) or thread pool",
    )
    parser.add_argument(
        "--zipf-s", type=float, default=0.0,
        help="Zipf exponent for a skewed query mix over a pool of distinct "
             "payloads (0 = off, the all-distinct legacy mix; 1.1 models "
             "real playlist-seed traffic and feeds the answer cache)",
    )
    parser.add_argument(
        "--shape",
        choices=REPLAY_SHAPES + ("flashcrowd",),
        default=os.environ.get("KMLS_REPLAY_SHAPE") or "constant",
        help="traffic shape: constant (legacy Poisson), burst "
             "(--burst-factor trains), ramp, sine, or flashcrowd "
             "(constant rate, mid-run payload collapse onto a hot seed "
             "pool); default from KMLS_REPLAY_SHAPE. The epoch-flip "
             "scenario needs a publication harness and lives in bench.py "
             "and the chaos tests, not this CLI",
    )
    parser.add_argument(
        "--burst-factor", type=float, default=10.0,
        help="burst-shape rate multiplier over --qps",
    )
    parser.add_argument(
        "--fleet", default=None, metavar="PEER=URL,...",
        help="replay against an N-replica fleet with client-side "
             "consistent-hash routing (freshness/ring.py): comma-"
             "separated peer=url pairs whose peer names match each "
             "server's KMLS_FLEET_SELF. Overrides --url.",
    )
    parser.add_argument(
        "--fleet-policy", choices=("ring", "roundrobin"), default="ring",
        help="fleet routing policy: ring (rendezvous owner, the cache "
             "tier) or roundrobin (the independent-caches baseline)",
    )
    parser.add_argument(
        "--trace-log", default=None, metavar="PATH",
        help="write echoed X-KMLS-Trace ids + client send/recv wall "
             "clocks as JSONL (HTTP targets only; requires the server's "
             "KMLS_TRACE_SAMPLE > 0). Join with the server's "
             "/debug/traces via scripts/kmls_tracejoin.py",
    )
    args = parser.parse_args()
    if args.shape == "flashcrowd":
        arrivals_for = lambda n: shaped_arrivals(n, args.qps)  # noqa: E731
        reshape = flash_crowd_payloads
    else:
        arrivals_for = lambda n: shaped_arrivals(  # noqa: E731
            n, args.qps, args.shape, burst_factor=args.burst_factor
        )
        reshape = lambda p: p  # noqa: E731

    if args.fleet:
        peer_urls = dict(
            pair.split("=", 1)
            for pair in args.fleet.split(",")
            if "=" in pair
        )
        if not peer_urls:
            print("--fleet needs at least one peer=url pair")
            return 1
        if args.shape != "constant" or args.trace_log:
            # refuse rather than silently pace a constant stream under a
            # burst/trace label — the operator would read un-shaped
            # numbers as shaped evidence
            print(
                "--fleet supports constant arrivals only (no --shape/"
                "--trace-log yet); drop the unsupported flag"
            )
            return 1
        vocab = _local_vocab()
        payloads = reshape(
            sample_seed_sets(vocab, args.requests, zipf_s=args.zipf_s)
        )
        report, fleet = replay_fleet_http(
            peer_urls, payloads, qps=args.qps, policy=args.fleet_policy,
        )
        out = json.loads(report.to_json())
        out["fleet"] = fleet
        print(json.dumps(out))
        return 0
    if args.url:
        vocab = _local_vocab()
        if not vocab:
            print(
                "NOTE: no local artifacts found (BASE_DIR); all seeds are "
                "unknown — this measures the static-fallback path only",
            )
        payloads = reshape(
            sample_seed_sets(vocab, args.requests, zipf_s=args.zipf_s)
        )
        trace_log = ClientTraceLog() if args.trace_log else None
        if args.client == "async" and args.shape in ("constant", "flashcrowd"):
            # the pipelined client paces its own constant schedule; shaped
            # RATES need the pooled driver's arrivals= parameter
            report = replay_async_http(
                args.url, payloads, qps=args.qps,
                n_conns=min(args.workers, 128),
                trace_log=trace_log,
            )
        else:
            report = replay_pooled(
                pooled_http_sender_factory(args.url, trace_log), payloads,
                qps=args.qps, n_workers=args.workers,
                arrivals=arrivals_for(len(payloads)),
            )
        if trace_log is not None:
            n_traced = trace_log.write_jsonl(args.trace_log)
            print(
                f"trace log: {n_traced} client records -> {args.trace_log}"
            )
        print(report.to_json())
        return 0
    else:
        import dataclasses as dataclasses_mod

        from ..config import ServingConfig
        from .app import RecommendApp

        # the app core, not a bare batcher: the in-process target then
        # measures the same cache → batcher → engine path the HTTP front
        # ends serve, and reports the cache split + per-replica dispatch
        cfg = dataclasses_mod.replace(
            ServingConfig.from_env(),
            batch_max_size=args.batch_max_size,
            batch_window_ms=args.batch_window_ms,
        )
        app = RecommendApp(cfg)
        if not app.engine.load():
            print("artifacts not found; run the mining job first")
            return 1
        metrics = app.metrics

        def send(seeds: list[str]) -> tuple[str, bool]:
            recs, source, cached = app.recommend_direct(seeds)
            return source, cached

        payloads = reshape(sample_seed_sets(
            app.engine.bundle.vocab, args.requests, zipf_s=args.zipf_s
        ))

    report = replay(
        send, payloads, qps=args.qps, arrivals=arrivals_for(len(payloads))
    )
    attach_attribution(report, metrics)
    if app.cache is not None:
        report.cache_hit_ratio = app.cache.hit_ratio()
    report.per_device_dispatch = list(app.engine.dispatch_counts)
    print(report.to_json())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
