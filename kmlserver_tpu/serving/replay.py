"""QPS replay harness — the offline load test the reference never had.

SURVEY.md §4 prescribes "a replay harness for the 1k-QPS batch-32 serving
config" (BASELINE.json config 5: `/api/recommend/` p50 < 10 ms at 1k QPS,
batch 32). This module drives a serving target at a fixed request rate with
open-loop (Poisson-paced) arrivals — closed-loop clients understate tail
latency because a slow server throttles its own load — and reports achieved
QPS plus latency percentiles per response source.

Two targets:

- in-process: a :class:`MicroBatcher` over a loaded
  :class:`RecommendEngine` (measures the engine + batching, no HTTP) —
  what the tests and ``python -m kmlserver_tpu.serving.replay`` use;
- HTTP: any running server URL (measures the full stack), via
  ``--url http://host:port``.

Seed sets are sampled from the engine's vocabulary (mixing known and
unknown seeds exercises both the rules path and the static fallback, like
the reference's three canned Swagger examples at rest_api/app/main.py:158-174).
``--zipf-s`` switches the mix to a Zipf-skewed repetition of a payload
pool — the head-heavy shape real playlist-seed traffic has, which is what
the epoch-keyed answer cache feeds on (default off, preserving the
all-distinct legacy mix bit for bit). Targets that report cache outcomes
(the in-process app path, or an HTTP server's ``X-KMLS-Cache`` header)
get cached/uncached latency split out in the report.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import random
import threading
import time

import numpy as np


@dataclasses.dataclass
class ReplayReport:
    target_qps: float
    # offered = arrival rate actually generated (includes drops + errors);
    # achieved = COMPLETED requests only — a saturated target that drops
    # most arrivals must show a low achieved_qps, not echo the target rate
    offered_qps: float
    achieved_qps: float
    duration_s: float
    n_requests: int
    n_errors: int
    p50_ms: float
    p95_ms: float
    p99_ms: float
    by_source: dict[str, int]
    # queue-vs-device latency attribution (from the batcher's metrics when
    # the in-process target is driven; None for targets that don't expose
    # it — the HTTP path scrapes the same split from /metrics instead)
    queue_wait_p50_ms: float | None = None
    queue_wait_p99_ms: float | None = None
    device_p50_ms: float | None = None
    device_p99_ms: float | None = None
    e2e_p999_ms: float | None = None
    # cache split, present when the target reports per-response cache
    # outcomes (a send() returning (source, cached), or the HTTP server's
    # X-KMLS-Cache header): cached answers are dictionary lookups and
    # computed answers pay the device — reporting them pooled would let a
    # high hit ratio mask a computed-path regression
    cache_hit_ratio: float | None = None
    cached_p50_ms: float | None = None
    cached_p99_ms: float | None = None
    uncached_p50_ms: float | None = None
    uncached_p99_ms: float | None = None
    # per-replica device dispatch counters (in-process target only): the
    # evidence the data-parallel dispatcher spread work across devices
    per_device_dispatch: list[int] | None = None

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def _unpack_send_result(result) -> tuple[str, bool | None]:
    """send() contract: a bare source tag (legacy), or (source, cached)."""
    if isinstance(result, tuple):
        return result[0], bool(result[1])
    return result, None


def _cache_split_fields(
    lat_cached: list[float], lat_uncached: list[float], n_ok: int
) -> dict:
    """→ the ReplayReport cache-split kwargs (empty when the target never
    reported a cache outcome)."""
    if not lat_cached and not lat_uncached:
        return {}
    cached_sorted = sorted(lat_cached)
    uncached_sorted = sorted(lat_uncached)
    out = {
        "cache_hit_ratio": len(cached_sorted) / n_ok if n_ok else 0.0,
    }
    if cached_sorted:
        out["cached_p50_ms"] = _percentile(cached_sorted, 0.50)
        out["cached_p99_ms"] = _percentile(cached_sorted, 0.99)
    if uncached_sorted:
        out["uncached_p50_ms"] = _percentile(uncached_sorted, 0.50)
        out["uncached_p99_ms"] = _percentile(uncached_sorted, 0.99)
    return out


def attach_attribution(report: "ReplayReport", metrics) -> "ReplayReport":
    """Fold a :class:`~.metrics.ServingMetrics` queue/device split into the
    report (milliseconds) — the keys that tell the next round WHERE the
    tail lives instead of only that one exists."""
    qw50, qw99 = metrics.queue_wait.percentiles(0.50, 0.99)
    dv50, dv99 = metrics.device.percentiles(0.50, 0.99)
    (e2e999,) = metrics.e2e.percentiles(0.999)
    report.queue_wait_p50_ms = qw50 * 1e3
    report.queue_wait_p99_ms = qw99 * 1e3
    report.device_p50_ms = dv50 * 1e3
    report.device_p99_ms = dv99 * 1e3
    report.e2e_p999_ms = e2e999 * 1e3
    return report


def _percentile(sorted_ms: list[float], q: float) -> float:
    if not sorted_ms:
        return float("nan")
    idx = min(int(q * len(sorted_ms)), len(sorted_ms) - 1)
    return sorted_ms[idx]


def sample_seed_sets(
    vocab: list[str],
    n: int,
    *,
    seeds_per_request: int = 3,
    unknown_fraction: float = 0.1,
    rng_seed: int = 0,
    zipf_s: float = 0.0,
    zipf_pool: int = 512,
) -> list[list[str]]:
    """n request payloads: mostly known tracks, a slice of unknown ones.

    ``zipf_s > 0`` switches to a Zipf-distributed query mix: a pool of
    ``zipf_pool`` distinct payloads is drawn exactly as before, and each of
    the n requests picks pool entry k with probability ∝ 1/k^s — the
    skewed head real playlist-seed traffic has, and what an epoch-keyed
    answer cache feeds on. Default OFF (0.0) so every existing bench/replay
    number keeps its all-distinct request mix, bit for bit."""
    rng = random.Random(rng_seed)

    def _draw(i: int) -> list[str]:
        if vocab and rng.random() >= unknown_fraction:
            k = min(seeds_per_request, len(vocab))
            return rng.sample(vocab, k)
        return [f"__replay_unknown_{i}__"]

    if zipf_s <= 0.0:
        return [_draw(i) for i in range(n)]
    pool = [_draw(i) for i in range(max(1, min(zipf_pool, max(n, 1))))]
    ranks = np.arange(1, len(pool) + 1, dtype=np.float64)
    p = ranks ** -zipf_s
    p /= p.sum()
    picks = np.random.default_rng(rng_seed).choice(len(pool), size=n, p=p)
    return [pool[int(i)] for i in picks]


def replay(
    send,  # callable(list[str]) -> str (response source tag)
    payloads: list[list[str]],
    *,
    qps: float,
    max_concurrency: int = 256,
) -> ReplayReport:
    """Open-loop replay: request i is DISPATCHED at its Poisson arrival time
    regardless of whether earlier requests completed (up to
    ``max_concurrency`` in flight, beyond which arrivals count as errors —
    an overloaded server must show up as drops/latency, not reduced load)."""
    rng = np.random.default_rng(12345)
    gaps = rng.exponential(1.0 / qps, size=len(payloads))
    arrival = np.cumsum(gaps)

    lat_ms: list[float] = []
    lat_cached: list[float] = []
    lat_uncached: list[float] = []
    by_source: dict[str, int] = {}
    errors = 0
    lock = threading.Lock()
    inflight = threading.Semaphore(max_concurrency)
    threads: list[threading.Thread] = []

    def worker(seeds: list[str]) -> None:
        t0 = time.perf_counter()
        try:
            source, cached = _unpack_send_result(send(seeds))
            dt_ms = (time.perf_counter() - t0) * 1e3
            with lock:
                lat_ms.append(dt_ms)
                if cached is not None:
                    (lat_cached if cached else lat_uncached).append(dt_ms)
                by_source[source] = by_source.get(source, 0) + 1
        except Exception:
            nonlocal errors
            with lock:
                errors += 1
        finally:
            inflight.release()

    start = time.perf_counter()
    for i, seeds in enumerate(payloads):
        now = time.perf_counter() - start
        wait = arrival[i] - now
        if wait > 0:
            time.sleep(wait)
        if not inflight.acquire(blocking=False):
            with lock:
                errors += 1
            continue
        t = threading.Thread(target=worker, args=(seeds,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=60.0)
    duration = time.perf_counter() - start

    # snapshot under the lock: a straggler worker past its join deadline may
    # still complete and append concurrently — its write either lands before
    # this snapshot (counted) or is excluded, never racing the sort
    with lock:
        lat_sorted = sorted(lat_ms)
        sources = dict(by_source)
        n_errors = errors
        split = _cache_split_fields(lat_cached, lat_uncached, len(lat_ms))
    n_ok = len(lat_sorted)
    return ReplayReport(
        target_qps=qps,
        offered_qps=(n_ok + n_errors) / duration if duration > 0 else 0.0,
        achieved_qps=n_ok / duration if duration > 0 else 0.0,
        duration_s=duration,
        n_requests=len(payloads),
        n_errors=n_errors,
        p50_ms=_percentile(lat_sorted, 0.50),
        p95_ms=_percentile(lat_sorted, 0.95),
        p99_ms=_percentile(lat_sorted, 0.99),
        by_source=sources,
        **split,
    )


def replay_pooled(
    make_send,  # () -> callable(list[str]) -> str; one per worker
    payloads: list[list[str]],
    *,
    qps: float,
    n_workers: int = 64,
    max_queue: int = 512,
) -> ReplayReport:
    """Open-loop replay with a fixed worker pool and one persistent sender
    per worker (wrk-style). The thread-per-request :func:`replay` melts at
    ~1k QPS on its own overhead (thread spawn + TCP handshake per request),
    which measures the load generator, not the server; here arrivals are
    Poisson-paced into a bounded queue and latency runs from the scheduled
    ARRIVAL to completion — queue wait included — so an overloaded server
    shows up as latency and drops, never as reduced offered load."""
    rng = np.random.default_rng(12345)
    arrival = np.cumsum(rng.exponential(1.0 / qps, size=len(payloads)))

    import queue as queue_mod

    q: "queue_mod.Queue" = queue_mod.Queue(maxsize=max_queue)
    lat_ms: list[float] = []
    lat_cached: list[float] = []
    lat_uncached: list[float] = []
    by_source: dict[str, int] = {}
    errors = 0
    lock = threading.Lock()

    def worker() -> None:
        nonlocal errors
        send = make_send()
        while True:
            item = q.get()
            if item is None:
                return
            # drain a burst behind the blocking get: at 10k-QPS pacing,
            # one futex wake per item IS the loadgen ceiling on a small
            # host (~8k/s measured on a 2-core sandbox); a woken worker
            # that sweeps everything already queued amortizes the wakeup
            # the same way the async HTTP client amortizes syscalls.
            # Low-rate behavior is unchanged — an empty queue yields a
            # burst of one.
            burst = [item]
            while len(burst) < 64:
                try:
                    extra = q.get_nowait()
                except queue_mod.Empty:
                    break
                if extra is None:
                    q.put_nowait(None)  # keep the sentinel for the pool
                    break
                burst.append(extra)
            for arrival_abs, seeds in burst:
                try:
                    source, cached = _unpack_send_result(send(seeds))
                    dt_ms = (time.perf_counter() - arrival_abs) * 1e3
                    with lock:
                        lat_ms.append(dt_ms)
                        if cached is not None:
                            (lat_cached if cached else lat_uncached).append(
                                dt_ms
                            )
                        by_source[source] = by_source.get(source, 0) + 1
                except Exception:
                    with lock:
                        errors += 1

    workers = [
        threading.Thread(target=worker, daemon=True) for _ in range(n_workers)
    ]
    for w in workers:
        w.start()

    start = time.perf_counter()
    for i, seeds in enumerate(payloads):
        wait = arrival[i] - (time.perf_counter() - start)
        if wait > 0:
            time.sleep(wait)
        try:
            q.put_nowait((start + arrival[i], seeds))
        except queue_mod.Full:
            with lock:
                errors += 1  # server (or pool) saturated: an honest drop
    for _ in workers:
        q.put(None)
    for w in workers:
        w.join(timeout=120.0)
    duration = time.perf_counter() - start

    with lock:
        lat_sorted = sorted(lat_ms)
        sources = dict(by_source)
        n_errors = errors
        split = _cache_split_fields(lat_cached, lat_uncached, len(lat_ms))
    n_ok = len(lat_sorted)
    return ReplayReport(
        target_qps=qps,
        offered_qps=(n_ok + n_errors) / duration if duration > 0 else 0.0,
        achieved_qps=n_ok / duration if duration > 0 else 0.0,
        duration_s=duration,
        n_requests=len(payloads),
        n_errors=n_errors,
        p50_ms=_percentile(lat_sorted, 0.50),
        p95_ms=_percentile(lat_sorted, 0.95),
        p99_ms=_percentile(lat_sorted, 0.99),
        by_source=sources,
        **split,
    )


def replay_async_http(
    url: str,
    payloads: list[list[str]],
    *,
    qps: float,
    n_conns: int = 32,
    pipeline: int = 16,
    max_queue: int = 4096,
) -> ReplayReport:
    """Open-loop HTTP replay on ONE event loop with request pipelining —
    the load generator the 1k-QPS acceptance needs on a syscall-taxed
    sandbox. Thread-pool clients (``replay_pooled`` +
    ``pooled_http_sender_factory``) melt first on this class of host:
    64 Python threads convoy on the GIL, and every request pays ~2
    traps (~0.5 ms each here) for its send/recv. Here arrivals are
    Poisson-paced into a queue, each of ``n_conns`` persistent
    connections writes bursts of up to ``pipeline`` queued requests as
    one send and reads the responses back to back, and latency runs
    from the SCHEDULED arrival to response completion — queue wait and
    burst wait included, so an overloaded server (or client) shows up
    as latency/drops, never as reduced offered load."""
    import asyncio
    import socket as socket_mod
    import urllib.parse

    u = urllib.parse.urlsplit(url)
    host, port = u.hostname or "127.0.0.1", u.port or 80
    # pre-encode every request: the loadgen's job is pacing, not cooking
    reqs: list[bytes] = []
    for seeds in payloads:
        body = json.dumps({"songs": seeds}).encode()
        reqs.append(
            b"POST /api/recommend/ HTTP/1.1\r\nHost: replay\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
    rng = np.random.default_rng(12345)
    arrival = np.cumsum(rng.exponential(1.0 / qps, size=len(payloads)))

    lat_ms: list[float] = []
    lat_cached: list[float] = []
    lat_uncached: list[float] = []
    by_source: dict[str, int] = {}
    errors = 0

    async def _run() -> None:
        nonlocal errors
        queue: "asyncio.Queue" = asyncio.Queue(maxsize=max_queue)

        async def connect():
            reader, writer = await asyncio.open_connection(host, port)
            sock = writer.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(
                    socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1
                )
            return reader, writer

        async def worker() -> None:
            nonlocal errors
            reader, writer = await connect()
            dead = False  # reconnect failed: drain the queue as errors
            while True:
                item = await queue.get()
                if item is None:
                    if writer is not None:
                        writer.close()
                    return
                burst = [item]
                while len(burst) < pipeline:
                    try:
                        extra = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if extra is None:
                        # keep the sentinel for after this burst
                        queue.put_nowait(None)
                        break
                    burst.append(extra)
                if dead:
                    errors += len(burst)
                    continue
                done = 0  # responses already accounted (ok OR non-200)
                try:
                    writer.write(b"".join(reqs[i] for _, i in burst))
                    for t_arr, _i in burst:
                        head = await reader.readuntil(b"\r\n\r\n")
                        clen = 0
                        head_lower = head.lower()
                        for line in head_lower.split(b"\r\n"):
                            if line.startswith(b"content-length"):
                                clen = int(line.split(b":", 1)[1])
                        body = await reader.readexactly(clen)
                        status = int(head.split(b" ", 2)[1])
                        done += 1
                        if status != 200:
                            errors += 1
                            continue
                        dt_ms = (time.perf_counter() - t_arr) * 1e3
                        lat_ms.append(dt_ms)
                        # the server marks answer-cache hits with an
                        # X-KMLS-Cache header (serving/app.py) — the only
                        # way a black-box client can split cached latency
                        if b"x-kmls-cache: hit" in head_lower:
                            lat_cached.append(dt_ms)
                        else:
                            lat_uncached.append(dt_ms)
                        source = (
                            "empty" if b'"songs": []' in body else "nonempty"
                        )
                        by_source[source] = by_source.get(source, 0) + 1
                except Exception:
                    # only the UNanswered tail of the burst is new errors —
                    # responses already read above were counted either way
                    errors += len(burst) - done
                    try:
                        writer.close()
                    except Exception:
                        pass
                    try:
                        reader, writer = await connect()
                    except OSError:
                        # server gone: stop sending, keep draining the
                        # queue into errors so the report still lands
                        dead = True
                        writer = None

        workers = [asyncio.create_task(worker()) for _ in range(n_conns)]
        t0 = time.perf_counter()
        for i in range(len(payloads)):
            wait = arrival[i] - (time.perf_counter() - t0)
            if wait > 0:
                await asyncio.sleep(wait)
            try:
                queue.put_nowait((t0 + arrival[i], i))
            except asyncio.QueueFull:
                errors += 1  # saturated: an honest drop
        for _ in workers:
            await queue.put(None)
        await asyncio.gather(*workers)

    start = time.perf_counter()
    asyncio.run(_run())
    duration = time.perf_counter() - start
    lat_sorted = sorted(lat_ms)
    n_ok = len(lat_sorted)
    return ReplayReport(
        target_qps=qps,
        offered_qps=(n_ok + errors) / duration if duration > 0 else 0.0,
        achieved_qps=n_ok / duration if duration > 0 else 0.0,
        duration_s=duration,
        n_requests=len(payloads),
        n_errors=errors,
        p50_ms=_percentile(lat_sorted, 0.50),
        p95_ms=_percentile(lat_sorted, 0.95),
        p99_ms=_percentile(lat_sorted, 0.99),
        by_source=by_source,
        **_cache_split_fields(lat_cached, lat_uncached, n_ok),
    )


def pooled_http_sender_factory(url: str):
    """→ ``make_send`` for :func:`replay_pooled`: each worker gets its own
    keep-alive HTTP/1.1 connection (the server speaks HTTP/1.1 —
    serving/app.py Handler.protocol_version), reconnecting on error."""
    import http.client
    import urllib.parse

    u = urllib.parse.urlsplit(url)
    host, port = u.hostname or "127.0.0.1", u.port or 80

    def make_send():
        conn = http.client.HTTPConnection(host, port, timeout=30)

        def send(seeds: list[str]) -> str:
            body = json.dumps({"songs": seeds})
            try:
                conn.request(
                    "POST", "/api/recommend/", body=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                data = json.load(resp)
                if resp.status != 200:
                    # a shed (429) or server error must count as an
                    # error/drop, not masquerade as an "empty" result
                    raise RuntimeError(f"HTTP {resp.status}")
            except Exception:
                conn.close()  # next request reconnects
                raise
            return "nonempty" if data.get("songs") else "empty"

        return send

    return make_send


def _local_vocab() -> list[str]:
    """Best-effort seed vocabulary for --url runs: the local artifacts, when
    BASE_DIR points at the same PVC the server reads. Empty when absent —
    then every request is an unknown seed and only exercises the static
    fallback, which the report will show as such."""
    try:
        from ..config import ServingConfig
        from .engine import RecommendEngine

        engine = RecommendEngine(ServingConfig.from_env())
        if engine.load():
            return engine.bundle.vocab
    except Exception:
        pass
    return []


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--qps", type=float, default=1000.0)
    parser.add_argument("--requests", type=int, default=2000)
    parser.add_argument("--url", default=None, help="HTTP target; default: in-process engine")
    parser.add_argument("--batch-max-size", type=int, default=32)
    parser.add_argument("--batch-window-ms", type=float, default=2.0)
    parser.add_argument("--workers", type=int, default=64)
    parser.add_argument(
        "--client", choices=("async", "pooled"), default="async",
        help="HTTP loadgen: single-loop pipelined (default) or thread pool",
    )
    parser.add_argument(
        "--zipf-s", type=float, default=0.0,
        help="Zipf exponent for a skewed query mix over a pool of distinct "
             "payloads (0 = off, the all-distinct legacy mix; 1.1 models "
             "real playlist-seed traffic and feeds the answer cache)",
    )
    args = parser.parse_args()

    if args.url:
        vocab = _local_vocab()
        if not vocab:
            print(
                "NOTE: no local artifacts found (BASE_DIR); all seeds are "
                "unknown — this measures the static-fallback path only",
            )
        payloads = sample_seed_sets(vocab, args.requests, zipf_s=args.zipf_s)
        if args.client == "async":
            report = replay_async_http(
                args.url, payloads, qps=args.qps,
                n_conns=min(args.workers, 128),
            )
        else:
            report = replay_pooled(
                pooled_http_sender_factory(args.url), payloads,
                qps=args.qps, n_workers=args.workers,
            )
        print(report.to_json())
        return 0
    else:
        import dataclasses as dataclasses_mod

        from ..config import ServingConfig
        from .app import RecommendApp

        # the app core, not a bare batcher: the in-process target then
        # measures the same cache → batcher → engine path the HTTP front
        # ends serve, and reports the cache split + per-replica dispatch
        cfg = dataclasses_mod.replace(
            ServingConfig.from_env(),
            batch_max_size=args.batch_max_size,
            batch_window_ms=args.batch_window_ms,
        )
        app = RecommendApp(cfg)
        if not app.engine.load():
            print("artifacts not found; run the mining job first")
            return 1
        metrics = app.metrics

        def send(seeds: list[str]) -> tuple[str, bool]:
            recs, source, cached = app.recommend_direct(seeds)
            return source, cached

        payloads = sample_seed_sets(
            app.engine.bundle.vocab, args.requests, zipf_s=args.zipf_s
        )

    report = replay(send, payloads, qps=args.qps)
    attach_attribution(report, metrics)
    if app.cache is not None:
        report.cache_hit_ratio = app.cache.hit_ratio()
    report.per_device_dispatch = list(app.engine.dispatch_counts)
    print(report.to_json())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
