"""Container entrypoint for the online API.

Run as ``python -m kmlserver_tpu.serving.server`` — the rebuild's equivalent
of the reference API image's ``CMD fastapi run app/main.py --port 80``
(reference: rest_api/Dockerfile:28). Env-var configured
(kubernetes/deployment.yaml contract); logs to stdout with the same
timestamped format intent as the reference's logging setup
(rest_api/app/main.py:18-29).
"""

from __future__ import annotations

import logging
import signal
import sys
import threading
import time

from ..config import ServingConfig
from .app import RecommendApp, serve


def main() -> int:
    # the reference configures DEBUG-level stdout logging for ITS app
    # (rest_api/app/main.py:18-29). Scope DEBUG to this package's logger
    # only — putting the ROOT logger at DEBUG floods stdout with ~170 KB of
    # jax compile chatter per reload (and can block the process mid-warmup
    # when a log collector stops draining the pipe)
    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stdout,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    logging.getLogger("kmlserver_tpu").setLevel(logging.DEBUG)
    cfg = ServingConfig.from_env()
    # persistent XLA compilation cache (PVC-backed via KMLS_JAX_CACHE_DIR):
    # per-shape warmup on every rollout/reload hits the cache instead of
    # recompiling the same serving-bucket kernels. AFTER from_env so the
    # knob honors .env like every other KMLS_ variable; before any jit.
    from ..utils.jaxcache import enable_compilation_cache

    enable_compilation_cache()
    log = logging.getLogger("kmlserver_tpu.serving")
    # transport selection: the asyncio front end is the default (thread-
    # per-connection collapses under concurrency on small pods — see
    # serving/aioserver.py); the stdlib ThreadingHTTPServer stays as the
    # KMLS_HTTP_IMPL=threaded fallback.
    import os

    # GIL switch interval: tunable because thread-handoff latency vs
    # throughput is workload-dependent — measured here, LOWERING it from
    # the 5 ms default made a 2-core box thrash (881 → 415 QPS), so only
    # an explicit env value changes it.
    if os.environ.get("KMLS_GIL_SWITCH_S"):
        sys.setswitchinterval(float(os.environ["KMLS_GIL_SWITCH_S"]))
    use_async = (
        os.environ.get("KMLS_HTTP_IMPL", "async").strip().lower() != "threaded"
    )
    # defer_batcher under async: the transport installs its loop-native
    # AsyncMicroBatcher instead of the threaded pipeline
    app = RecommendApp(cfg, defer_batcher=use_async)
    app.engine.start_polling()
    if use_async:
        import asyncio

        from .aioserver import run_async

        return asyncio.run(run_async(app, cfg.port))
    if app.loop_lag is not None:
        # sleep-drift thread: the threaded transport's analogue of the
        # async drift tick — host-scheduling stalls (CPU starvation, GIL
        # convoy) surface as the same kmls_loop_lag_ms signal
        app.loop_lag.start_thread()
    server = serve(app)
    host, port = server.server_address[:2]
    log.info("serving on %s:%d (version %s)", host, port, cfg.version)

    # graceful drain on SIGTERM: a k8s rollout sends SIGTERM and waits
    # terminationGracePeriodSeconds before SIGKILL. The reference's uvicorn
    # drains in-flight requests on SIGTERM; the stdlib default would kill
    # them mid-response. Sequence: (1) the handler starts answering with
    # "Connection: close" so keep-alive clients migrate off the pod (k8s
    # endpoint removal only stops NEW connections — established flows keep
    # routing here); (2) shutdown() stops the accept loop and returns from
    # serve_forever (it must run OFF the serving thread or it deadlocks);
    # (3) server_close() immediately closes the LISTENING socket so racing
    # connects get an instant refusal (not a backlog-then-RST after the
    # settle); (4) a bounded settle lets in-flight responses finish —
    # handler threads are daemonic and idle keep-alive connections can
    # block forever, so joining them is not an option; instead the settle
    # polls the server's in-flight counter and exits the moment it reaches
    # zero, bounded by KMLS_DRAIN_SETTLE_S (set it to match the pod's
    # terminationGracePeriodSeconds minus a safety margin).
    draining = threading.Event()
    server.draining = draining  # handlers read this (app.make_handler)

    def _drain(signum, frame):
        log.info("SIGTERM: draining in-flight requests, then exiting")
        draining.set()
        threading.Thread(target=server.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _drain)
    except ValueError:
        pass  # not the main thread (embedded use); k8s path is main-thread
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()  # listening socket closed BEFORE the settle
        if draining.is_set():
            import os

            settle_s = float(os.getenv("KMLS_DRAIN_SETTLE_S") or 2.0)
            t_settle = time.monotonic()
            deadline = t_settle + settle_s
            # floor before the zero-exit: a connection accepted just before
            # shutdown has a handler thread that may not have reached the
            # counter increment yet — an instant first-poll zero would kill
            # it mid-parse (the floor covers accept→dispatch scheduling)
            floor = t_settle + min(0.5, settle_s)
            while time.monotonic() < deadline:
                with server.active_lock:
                    if server.active_requests == 0 and time.monotonic() >= floor:
                        break
                time.sleep(0.05)
            else:
                log.warning(
                    "drain settle expired after %.1fs with %d requests "
                    "still in flight (raise KMLS_DRAIN_SETTLE_S to match "
                    "terminationGracePeriodSeconds)",
                    settle_s, server.active_requests,
                )
    return 0


if __name__ == "__main__":
    sys.exit(main())
