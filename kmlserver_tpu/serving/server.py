"""Container entrypoint for the online API.

Run as ``python -m kmlserver_tpu.serving.server`` — the rebuild's equivalent
of the reference API image's ``CMD fastapi run app/main.py --port 80``
(reference: rest_api/Dockerfile:28). Env-var configured
(kubernetes/deployment.yaml contract); logs to stdout with the same
timestamped format intent as the reference's logging setup
(rest_api/app/main.py:18-29).
"""

from __future__ import annotations

import logging
import sys

from ..config import ServingConfig
from .app import RecommendApp, serve


def main() -> int:
    logging.basicConfig(
        level=logging.DEBUG,
        stream=sys.stdout,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    cfg = ServingConfig.from_env()
    app = RecommendApp(cfg)
    app.engine.start_polling()
    server = serve(app)
    host, port = server.server_address[:2]
    logging.getLogger("kmlserver_tpu.serving").info(
        "serving on %s:%d (version %s)", host, port, cfg.version
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
