from . import envfile, timeutil  # noqa: F401
