"""Minimal ``.env`` loader.

The reference loads local-dev defaults with python-dotenv (reference:
machine-learning/main.py:17-20, rest_api/app/main.py:31-33); that package is
not part of this image, so this is a small from-scratch parser with the same
observable behavior we rely on: ``KEY=VALUE`` lines, ``#`` comments, optional
``export`` prefix, single/double quote stripping, and *no override* of
variables already present in the process environment (dotenv's default).
"""

from __future__ import annotations

import os


def parse_env_line(line: str) -> tuple[str, str] | None:
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    if line.startswith("export "):
        line = line[len("export "):].lstrip()
    if "=" not in line:
        return None
    key, _, value = line.partition("=")
    key = key.strip()
    if not key or any(c.isspace() for c in key):
        return None
    value = value.strip()
    if value and value[0] in ("'", '"'):
        # quoted value: ends at the matching close quote; anything after
        # (e.g. an inline comment) is discarded
        close = value.find(value[0], 1)
        if close != -1:
            value = value[1:close]
    else:
        hash_pos = value.find(" #")
        if hash_pos != -1:
            value = value[:hash_pos].rstrip()
    return key, value


def load_dotenv(path: str | os.PathLike = ".env", *, override: bool = False) -> dict[str, str]:
    """Load ``path`` into ``os.environ``. Returns the parsed mapping."""
    parsed: dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for raw in fh:
                kv = parse_env_line(raw)
                if kv is None:
                    continue
                parsed[kv[0]] = kv[1]
    except FileNotFoundError:
        return parsed
    for key, value in parsed.items():
        if override or key not in os.environ:
            os.environ[key] = value
    return parsed
