"""Persistent XLA compilation cache for the deployed entrypoints.

The reference's pseudo-CronJob trick (ArgoCD TTL + Force/Replace,
kubernetes/job.yaml) re-runs the mining Job every ~20 minutes — and every
run of a JAX program in a fresh container re-pays jit/Mosaic compilation
(~11 s of the job's ~1 min, and the serving pod's per-shape warmup on every
rollout). Pointing ``KMLS_JAX_CACHE_DIR`` at a PVC path makes XLA's
persistent compilation cache survive container restarts, so only the FIRST
run after a code/shape change compiles; every subsequent Job run and pod
rollout loads the cached executables.

bench.py wires the same jax knobs itself (shared tmpdir across its phases);
this module is the production twin for the k8s manifests' env contract.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("kmlserver_tpu.jaxcache")


def enable_compilation_cache() -> str | None:
    """Apply ``KMLS_JAX_CACHE_DIR`` if set; → the cache path or None.

    Call before the first jit compile (import-time device touches are fine
    — the cache only affects compilation). Failures are non-fatal: a
    mis-mounted cache dir must never take down the job or the API."""
    path = os.environ.get("KMLS_JAX_CACHE_DIR")
    if not path:
        return None
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # default threshold (1 s) skips exactly the many small serving-
        # bucket kernels the cache exists to keep warm across rollouts
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        logger.info("persistent XLA compilation cache at %s", path)
        return path
    except Exception:
        logger.exception("compilation cache unavailable; compiling cold")
        return None
