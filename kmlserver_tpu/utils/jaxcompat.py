"""Version-bridging shims for jax APIs that moved or appeared across the
versions this repo must run on (the image pins what it pins; the code must
serve either side).

- ``shard_map``: promoted from ``jax.experimental.shard_map`` to the top
  level, and its replication-check kwarg renamed ``check_rep`` →
  ``check_vma`` along the way; the installed 0.4.x only has the
  experimental home with the old spelling. Callers here use the NEW
  spelling; the shim translates downward.
- ``pcast_varying``: ``jax.lax.pcast(..., to="varying")`` exists only where
  the device-varying type system does. Older shard_map tracing has no
  varying/invariant distinction, so the cast is correctly a no-op there —
  the accumulator carry types already match without it.
"""

from __future__ import annotations

import inspect

import jax

try:
    _shard_map_impl = jax.shard_map
except AttributeError:  # pre-promotion jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map_impl  # type: ignore

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters
)


def shard_map(f, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map_impl(f, **kwargs)


def pcast_varying(x, axes):
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axes, to="varying")
