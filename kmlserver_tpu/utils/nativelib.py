"""Shared build-and-load scaffolding for the ``native/`` shared objects.

Both native modules (the CSV loader, ``data/native.py``, and the POPCNT
pair counter, ``ops/cpu_popcount.py``) need the same lifecycle: run
``make -C native`` on demand, load the .so via ctypes, verify its ABI,
honor the ``KMLS_NATIVE=0`` kill switch on EVERY call, and degrade
gracefully when the toolchain or .so is absent. This is the one copy of
that logic — the two modules previously duplicated it verbatim, and the
duplicate missed negative caching (a host with no toolchain re-spawned a
failing ``make`` on every call).

``make`` runs at most once per process: its file dependencies make a
second invocation a no-op anyway, and per-call subprocess spawns would
land inside latency-sensitive paths (the miner consults availability when
choosing its pair-count implementation).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)

_make_lock = threading.Lock()
_make_ran = False


def run_make_once(quiet: bool = True) -> None:
    """Invoke ``make -C native`` at most once per process (all targets
    build together). Failures are swallowed — per-.so existence decides
    availability afterwards."""
    global _make_ran
    with _make_lock:
        if _make_ran:
            return
        _make_ran = True
        try:
            subprocess.run(
                ["make", "-C", NATIVE_DIR], check=True, capture_output=quiet
            )
        except (subprocess.CalledProcessError, FileNotFoundError):
            pass


class NativeLib:
    """One .so's cached loader: ``bind`` receives the raw CDLL and must
    set up prototypes + verify the ABI version (raising OSError to
    reject); both success and failure are cached, while the kill switch
    stays live (checked before the cache on every call)."""

    def __init__(self, so_name: str, bind: Callable[[ctypes.CDLL], ctypes.CDLL]):
        self.so_path = os.path.join(NATIVE_DIR, so_name)
        self._bind = bind
        self._lib: ctypes.CDLL | None = None
        self._failed = False
        self._lock = threading.Lock()

    def load(self) -> ctypes.CDLL | None:
        if os.environ.get("KMLS_NATIVE", "1") == "0":
            return None
        with self._lock:
            if self._lib is not None:
                return self._lib
            if self._failed:
                return None
            run_make_once()
            if not os.path.exists(self.so_path):
                self._failed = True
                return None
            try:
                self._lib = self._bind(ctypes.CDLL(self.so_path))
            except OSError:
                self._failed = True
                return None
            return self._lib

    def available(self) -> bool:
        return self.load() is not None
