"""Tracing / profiling — the subsystem the reference does NOT have.

The reference's entire observability for compute cost is a wall-clock bracket
around rule generation printed to stdout (reference:
machine-learning/main.py:264,306-308) plus the disabled sweep harness's
per-support durations (machine-learning/main.py:462-473). SURVEY.md §5
prescribes the TPU-native replacement: ``jax.profiler`` device traces plus
``block_until_ready``-bracketed host timers, while preserving the printed
``Time elapsed in rule generation`` line for log parity.

Two layers, both zero-cost when disabled:

- :func:`trace_session` — a ``jax.profiler`` trace of a whole region, dumped
  to ``$KMLS_PROFILE_DIR`` (TensorBoard/XProf-readable; contains XLA device
  timelines, HLO names, HBM allocations). Enabled only when the env var is
  set: profiling must be opt-in in production serving.
- :class:`PhaseTimer` — named host-side phase timings with explicit
  ``block_until_ready`` discipline (a device call isn't "done" at dispatch;
  timing without a sync fence measures nothing). Each phase is also wrapped
  in a ``jax.profiler.TraceAnnotation`` so host phases line up against the
  device timeline inside the dumped trace.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Iterator

import jax

PROFILE_DIR_ENV = "KMLS_PROFILE_DIR"


def profile_dir() -> str | None:
    """The trace dump directory, or None when profiling is disabled."""
    raw = os.getenv(PROFILE_DIR_ENV)
    return raw if raw else None


@contextlib.contextmanager
def trace_session(label: str) -> Iterator[None]:
    """``jax.profiler`` trace of the enclosed region when profiling is
    enabled (``$KMLS_PROFILE_DIR`` set), else a no-op. Safe to nest inside —
    but not around — another active trace."""
    target = profile_dir()
    if target is None:
        yield
        return
    path = os.path.join(target, label)
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield


def start_capture(label: str, seconds: float) -> "object":
    """Timed on-demand capture (ISSUE 12, the ``/debug/profile``
    endpoint): run :func:`trace_session` for ``seconds`` on a daemon
    thread → the thread (join it to wait; the endpoint doesn't). The
    trace covers whatever the process executes while the window is open
    — for a live server, the serving kernels under real traffic. A no-op
    thread when profiling is disabled (the caller gates on
    :func:`profile_dir`, this is belt-and-braces)."""
    import threading

    def run() -> None:
        with trace_session(label):
            time.sleep(max(seconds, 0.0))

    thread = threading.Thread(
        target=run, daemon=True, name="kmls-profile-capture"
    )
    thread.start()
    return thread


class PhaseTimer:
    """Named phase timings with device-sync fencing.

    >>> t = PhaseTimer()
    >>> with t.phase("pair_counts", counts):   # fences on `counts`
    ...     counts = pair_counts(x)
    """

    def __init__(self) -> None:
        self.phases: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str, *fence: Any) -> Iterator[None]:
        """Time the enclosed block under ``name``. Any ``fence`` values given
        at entry are block_until_ready'd FIRST so queued prior device work
        isn't billed to this phase; the block's own device outputs should be
        fenced by the block itself (or be host work)."""
        for f in fence:
            jax.block_until_ready(f)
        with jax.profiler.TraceAnnotation(f"kmls:{name}"):
            t0 = time.perf_counter()
            yield
            self.phases[name] = self.phases.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def report(self) -> str:
        """One log line, reference-log style."""
        return format_phases(self.phases)


def format_phases(phases: dict[str, float]) -> str:
    parts = ", ".join(f"{k} {v:.3f}s" for k, v in phases.items())
    return f"phase timings: {parts}" if parts else "phase timings: (none)"
