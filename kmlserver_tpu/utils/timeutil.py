"""Timestamps in the reference's format.

The reference prints America/Sao_Paulo wall-clock timestamps around every job
phase via pytz (reference: machine-learning/main.py:414-418). pytz is not in
this image; stdlib ``zoneinfo`` provides the same zone. A fixed UTC-3 fallback
covers environments without tzdata (Brazil abolished DST in 2019, so the
offset is constant for current dates).
"""

from __future__ import annotations

import datetime

try:
    from zoneinfo import ZoneInfo

    _SAO_PAULO: datetime.tzinfo = ZoneInfo("America/Sao_Paulo")
except Exception:  # pragma: no cover - tzdata missing
    _SAO_PAULO = datetime.timezone(datetime.timedelta(hours=-3), name="-03")

TIME_FORMAT = "%Y-%m-%d %H:%M:%S"


def now_sao_paulo() -> datetime.datetime:
    return datetime.datetime.now(_SAO_PAULO)


def get_current_time_str() -> str:
    """Equivalent of the reference's ``get_current_time_str`` (main.py:414-418)."""
    return now_sao_paulo().strftime(TIME_FORMAT)


def get_current_time_str_precise() -> str:
    """Microsecond-resolution variant used for the invalidation token: two
    mining runs inside the same wall-clock second must still produce distinct
    tokens, or the API's content-comparison staleness check
    (reference: rest_api/app/main.py:82-97) would miss the second reload."""
    return now_sao_paulo().strftime(TIME_FORMAT + ".%f")
