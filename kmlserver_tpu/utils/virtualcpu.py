"""Force JAX onto a virtual N-device CPU platform — the one shared recipe.

Multi-chip TPU hardware is never available in the build/test environment;
multi-device code is validated on XLA's host platform with N virtual CPU
devices instead. Getting there safely has one hard constraint: this image's
site hook registers a remote-TPU ("axon") backend at interpreter startup and
pins the platform selection programmatically, and merely constructing that
backend (e.g. an innocent ``jax.devices()``) hangs forever when the pool is
unreachable. So the CPU pin must happen BEFORE any device touch, via both
environment (inherited by subprocesses, honored pre-import) and
``jax.config`` (the only override the site hook respects in-process).

Used by ``tests/conftest.py`` (session-wide, permanent) and
``__graft_entry__.dryrun_multichip`` (scoped, env restored afterwards).
Keep this the ONLY copy of the recipe — round 1 lost its multichip artifact
to a second, divergent copy that probed real devices first.
"""

from __future__ import annotations

import os

_ENV_KEYS = ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS")


def force_virtual_cpu(n_devices: int = 8) -> dict[str, str | None]:
    """Pin this process to a virtual ``n_devices``-device CPU platform.

    Safe to call before or after jax has been imported (already-initialized
    backends are torn down). Returns the prior values of the environment
    variables it mutated (``None`` = was unset) so a scoped caller can
    restore them with :func:`restore_env`; the in-process ``jax.config``
    pin is deliberately left in place — un-pinning a live process back onto
    a hangable backend is never what anyone wants.
    """
    prior: dict[str, str | None] = {k: os.environ.get(k) for k in _ENV_KEYS}
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    # drop any stale device-count flag before appending ours: the in-process
    # count is pinned via jax_num_cpu_devices below, but subprocesses see
    # only the env — a leftover different count would win there
    flags = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    import jax
    from jax.extend import backend as _jeb

    _jeb.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # older jax (e.g. 0.4.37) has no jax_num_cpu_devices config option.
        # The XLA_FLAGS device-count flag set above does the same job as
        # long as it lands before the first backend build — and it does:
        # backends were just cleared, so the next device query constructs
        # the CPU client fresh and reads the env then.
        pass
    return prior


def restore_env(prior: dict[str, str | None]) -> None:
    """Undo ``force_virtual_cpu``'s environment mutations (for callers whose
    process goes on to spawn children that must see the original env)."""
    for key, value in prior.items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value
