// kmls_csv — native CSV → dictionary-encoded columnar loader.
//
// The mining pipeline consumes integer-ID tensors, not strings: playlist ids
// and interned track/artist/album ids (kmlserver_tpu/data/csv.py is the
// Python facade; the reference ingests via polars' native engine,
// machine-learning/main.py:153). This loader goes straight from the mmap'd
// file to that representation in one pass:
//
//   - RFC-4180 field scanning (quoted fields, "" escapes, embedded commas
//     and newlines, \r\n);
//   - int64 parse for `pid`;
//   - string interning for every other requested column: per column, an
//     open-addressing hash table over an append-only byte arena produces
//     int32 codes + a first-occurrence vocabulary.
//
// C ABI only (consumed via ctypes — no pybind11 in this image). All memory
// is owned by the kmls_table and freed with kmls_table_free.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Arena {
  std::vector<char> bytes;
  std::vector<uint64_t> offsets;  // offsets.size() == count+1

  Arena() { offsets.push_back(0); }

  int32_t add(const char* data, size_t len) {
    bytes.insert(bytes.end(), data, data + len);
    offsets.push_back(bytes.size());
    return static_cast<int32_t>(offsets.size() - 2);
  }
  size_t count() const { return offsets.size() - 1; }
  const char* at(size_t i, size_t* len) const {
    *len = offsets[i + 1] - offsets[i];
    return bytes.data() + offsets[i];
  }
};

// open-addressing intern table over an Arena
struct Interner {
  Arena arena;
  std::vector<int32_t> slots;  // -1 empty, else string id
  size_t mask = 0;

  Interner() { rehash(1 << 12); }

  static uint64_t hash(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ull;  // FNV-1a
    for (size_t i = 0; i < n; ++i) {
      h ^= static_cast<unsigned char>(s[i]);
      h *= 1099511628211ull;
    }
    return h;
  }

  void rehash(size_t n) {
    std::vector<int32_t> fresh(n, -1);
    for (int32_t id = 0; id < static_cast<int32_t>(arena.count()); ++id) {
      size_t len;
      const char* s = arena.at(id, &len);
      size_t slot = hash(s, len) & (n - 1);
      while (fresh[slot] != -1) slot = (slot + 1) & (n - 1);
      fresh[slot] = id;
    }
    slots.swap(fresh);
    mask = n - 1;
  }

  int32_t intern(const char* s, size_t n) {
    if (arena.count() * 2 >= slots.size()) rehash(slots.size() * 2);
    size_t slot = hash(s, n) & mask;
    while (true) {
      int32_t id = slots[slot];
      if (id == -1) {
        int32_t fresh_id = arena.add(s, n);
        slots[slot] = fresh_id;
        return fresh_id;
      }
      size_t len;
      const char* existing = arena.at(id, &len);
      if (len == n && std::memcmp(existing, s, n) == 0) return id;
      slot = (slot + 1) & mask;
    }
  }
};

struct Column {
  std::string name;
  Interner interner;
  std::vector<int32_t> codes;
};

}  // namespace

// Bumped whenever the exported C surface or parse semantics change; the
// Python binding refuses a .so whose version doesn't match, so a stale
// build from an older checkout can never silently serve the old parser.
#define KMLS_ABI_VERSION 2

extern "C" {

int32_t kmls_abi_version(void) { return KMLS_ABI_VERSION; }

struct kmls_table {
  std::vector<int64_t> pids;
  std::vector<Column> columns;
  std::string error;
};

static void parse_field(const char* p, const char* end, std::string* out,
                        const char** next) {
  out->clear();
  if (p < end && *p == '"') {
    ++p;
    while (p < end) {
      if (*p == '"') {
        if (p + 1 < end && p[1] == '"') {  // escaped quote
          out->push_back('"');
          p += 2;
        } else {
          ++p;
          break;
        }
      } else {
        out->push_back(*p++);
      }
    }
    // trailing junk until delimiter is ignored per RFC leniency
    while (p < end && *p != ',' && *p != '\n' && *p != '\r') ++p;
  } else {
    const char* start = p;
    while (p < end && *p != ',' && *p != '\n' && *p != '\r') ++p;
    out->assign(start, p - start);
  }
  *next = p;
}

// Parse `path`, interning every column except `pid` and any name in the
// comma-separated `skip_cols` list (those are scanned but neither interned
// nor returned — e.g. duration_ms, which the pipeline drops immediately).
// Returns NULL only on allocation failure; check kmls_table_error() for
// parse errors.
kmls_table* kmls_read_csv(const char* path, const char* skip_cols) {
  std::vector<std::string> skip;
  if (skip_cols != nullptr) {
    const char* s = skip_cols;
    while (*s) {
      const char* comma = std::strchr(s, ',');
      size_t len = comma ? static_cast<size_t>(comma - s) : std::strlen(s);
      if (len > 0) skip.emplace_back(s, len);
      s += len + (comma ? 1 : 0);
    }
  }
  auto* table = new kmls_table();
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    table->error = std::string("cannot open ") + path;
    return table;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    close(fd);
    table->error = std::string("empty or unreadable ") + path;
    return table;
  }
  size_t size = static_cast<size_t>(st.st_size);
  const char* data =
      static_cast<const char*>(mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0));
  close(fd);
  if (data == MAP_FAILED) {
    table->error = std::string("mmap failed for ") + path;
    return table;
  }
  const char* p = data;
  const char* end = data + size;

  // header. Per-column action: COL_PID parses int64, COL_SKIP is scanned
  // but discarded, >=0 interns into table->columns[action].
  constexpr int COL_PID = -1;
  constexpr int COL_SKIP = -2;
  std::vector<std::string> header;
  std::vector<int> action;
  std::string field;
  int pid_index = -1;
  while (p < end) {
    parse_field(p, end, &field, &p);
    header.push_back(field);
    if (p < end && *p == ',') {
      ++p;
      continue;
    }
    break;
  }
  while (p < end && (*p == '\r' || *p == '\n')) ++p;
  for (size_t i = 0; i < header.size(); ++i) {
    bool skipped = false;
    for (const std::string& s : skip) skipped = skipped || s == header[i];
    if (header[i] == "pid") {
      pid_index = static_cast<int>(i);
      action.push_back(COL_PID);
    } else if (skipped) {
      action.push_back(COL_SKIP);
    } else {
      action.push_back(static_cast<int>(table->columns.size()));
      table->columns.push_back(Column{});
      table->columns.back().name = header[i];
    }
  }
  if (pid_index < 0) {
    table->error = "missing required column 'pid'";
    munmap(const_cast<char*>(data), size);
    return table;
  }

  // rows — buffered per row so nothing is committed until the row's field
  // count and pid both validate (a malformed row must error, not corrupt).
  const int ncols = static_cast<int>(header.size());
  std::vector<std::string> fields(ncols);
  size_t row_no = 0;
  while (p < end) {
    int col = 0;
    bool row_has_data = false;
    bool trailing_comma = false;
    while (p < end && col < ncols) {
      parse_field(p, end, &fields[col], &p);
      if (!fields[col].empty()) row_has_data = true;
      ++col;
      if (p < end && *p == ',') {
        ++p;
        trailing_comma = true;
      } else {
        trailing_comma = false;
        break;
      }
    }
    // a comma consumed right before EOF carries one last EMPTY field that
    // the loop above couldn't enter for (p >= end) — same row WITH a final
    // newline parses that empty field normally, so EOF must match
    if (trailing_comma && p >= end && col < ncols) {
      fields[col].clear();
      ++col;
      trailing_comma = false;
    }
    // a well-formed row ends exactly at EOL/EOF; extra fields after the
    // ncols-th are an error, including a lone trailing empty one (the comma
    // consumed after the last field with nothing but EOL behind it)
    bool at_eol = (p >= end || *p == '\n' || *p == '\r');
    while (p < end && (*p == '\r' || *p == '\n')) ++p;
    if (!row_has_data && col <= 1) continue;  // blank trailing line
    ++row_no;
    if (col != ncols || !at_eol || trailing_comma) {
      char msg[128];
      snprintf(msg, sizeof(msg), "row %zu has %s fields, expected %d",
               row_no, col != ncols ? "too few" : "too many", ncols);
      table->error = msg;
      break;
    }
    const std::string& pid_str = fields[pid_index];
    errno = 0;
    char* endp = nullptr;
    long long pid = strtoll(pid_str.c_str(), &endp, 10);
    if (pid_str.empty() || errno == ERANGE || *endp != '\0') {
      char msg[160];
      snprintf(msg, sizeof(msg), "row %zu: invalid pid '%.64s'",
               row_no, pid_str.c_str());
      table->error = msg;
      break;
    }
    table->pids.push_back(pid);
    for (int i = 0; i < ncols; ++i) {
      int act = action[i];
      if (act >= 0) {
        Column& c = table->columns[act];
        c.codes.push_back(c.interner.intern(fields[i].data(), fields[i].size()));
      }
    }
  }
  munmap(const_cast<char*>(data), size);
  return table;
}

const char* kmls_table_error(kmls_table* t) {
  return t->error.empty() ? nullptr : t->error.c_str();
}

int64_t kmls_table_nrows(kmls_table* t) {
  return static_cast<int64_t>(t->pids.size());
}

const int64_t* kmls_table_pids(kmls_table* t) { return t->pids.data(); }

int32_t kmls_table_ncols(kmls_table* t) {
  return static_cast<int32_t>(t->columns.size());
}

const char* kmls_table_col_name(kmls_table* t, int32_t i) {
  return t->columns[i].name.c_str();
}

const int32_t* kmls_table_col_codes(kmls_table* t, int32_t i) {
  return t->columns[i].codes.data();
}

int32_t kmls_table_col_vocab_size(kmls_table* t, int32_t i) {
  return static_cast<int32_t>(t->columns[i].interner.arena.count());
}

// vocabulary as one concatenated blob + uint64 offsets (count+1 entries)
const char* kmls_table_col_vocab_blob(kmls_table* t, int32_t i, int64_t* nbytes) {
  *nbytes = static_cast<int64_t>(t->columns[i].interner.arena.bytes.size());
  return t->columns[i].interner.arena.bytes.data();
}

const uint64_t* kmls_table_col_vocab_offsets(kmls_table* t, int32_t i) {
  return t->columns[i].interner.arena.offsets.data();
}

void kmls_table_free(kmls_table* t) { delete t; }

}  // extern "C"
