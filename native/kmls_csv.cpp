// kmls_csv — native CSV → dictionary-encoded columnar loader.
//
// The mining pipeline consumes integer-ID tensors, not strings: playlist ids
// and interned track/artist/album ids (kmlserver_tpu/data/csv.py is the
// Python facade; the reference ingests via polars' native engine,
// machine-learning/main.py:153). This loader goes straight from the mmap'd
// file to that representation in one pass:
//
//   - RFC-4180 field scanning (quoted fields, "" escapes, embedded commas
//     and newlines, \r\n);
//   - int64 parse for `pid`;
//   - string interning for every other requested column: per column, an
//     open-addressing hash table over an append-only byte arena produces
//     int32 codes + a first-occurrence vocabulary.
//
// C ABI only (consumed via ctypes — no pybind11 in this image). All memory
// is owned by the kmls_table and freed with kmls_table_free.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Arena {
  std::vector<char> bytes;
  std::vector<uint64_t> offsets;  // offsets.size() == count+1

  Arena() { offsets.push_back(0); }

  int32_t add(const char* data, size_t len) {
    bytes.insert(bytes.end(), data, data + len);
    offsets.push_back(bytes.size());
    return static_cast<int32_t>(offsets.size() - 2);
  }
  size_t count() const { return offsets.size() - 1; }
  const char* at(size_t i, size_t* len) const {
    *len = offsets[i + 1] - offsets[i];
    return bytes.data() + offsets[i];
  }
};

// open-addressing intern table over an Arena
struct Interner {
  Arena arena;
  std::vector<int32_t> slots;  // -1 empty, else string id
  size_t mask = 0;

  Interner() { rehash(1 << 12); }

  static uint64_t hash(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ull;  // FNV-1a
    for (size_t i = 0; i < n; ++i) {
      h ^= static_cast<unsigned char>(s[i]);
      h *= 1099511628211ull;
    }
    return h;
  }

  void rehash(size_t n) {
    std::vector<int32_t> fresh(n, -1);
    for (int32_t id = 0; id < static_cast<int32_t>(arena.count()); ++id) {
      size_t len;
      const char* s = arena.at(id, &len);
      size_t slot = hash(s, len) & (n - 1);
      while (fresh[slot] != -1) slot = (slot + 1) & (n - 1);
      fresh[slot] = id;
    }
    slots.swap(fresh);
    mask = n - 1;
  }

  int32_t intern(const char* s, size_t n) {
    if (arena.count() * 2 >= slots.size()) rehash(slots.size() * 2);
    size_t slot = hash(s, n) & mask;
    while (true) {
      int32_t id = slots[slot];
      if (id == -1) {
        int32_t fresh_id = arena.add(s, n);
        slots[slot] = fresh_id;
        return fresh_id;
      }
      size_t len;
      const char* existing = arena.at(id, &len);
      if (len == n && std::memcmp(existing, s, n) == 0) return id;
      slot = (slot + 1) & mask;
    }
  }
};

struct Column {
  std::string name;
  Interner interner;
  std::vector<int32_t> codes;
};

}  // namespace

extern "C" {

struct kmls_table {
  std::vector<int64_t> pids;
  std::vector<Column> columns;
  std::string error;
};

static void parse_field(const char* p, const char* end, std::string* out,
                        const char** next) {
  out->clear();
  if (p < end && *p == '"') {
    ++p;
    while (p < end) {
      if (*p == '"') {
        if (p + 1 < end && p[1] == '"') {  // escaped quote
          out->push_back('"');
          p += 2;
        } else {
          ++p;
          break;
        }
      } else {
        out->push_back(*p++);
      }
    }
    // trailing junk until delimiter is ignored per RFC leniency
    while (p < end && *p != ',' && *p != '\n' && *p != '\r') ++p;
  } else {
    const char* start = p;
    while (p < end && *p != ',' && *p != '\n' && *p != '\r') ++p;
    out->assign(start, p - start);
  }
  *next = p;
}

// Parse `path`, interning every column except `pid`. Returns NULL only on
// allocation failure; check kmls_table_error() for parse errors.
kmls_table* kmls_read_csv(const char* path) {
  auto* table = new kmls_table();
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    table->error = std::string("cannot open ") + path;
    return table;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    close(fd);
    table->error = std::string("empty or unreadable ") + path;
    return table;
  }
  size_t size = static_cast<size_t>(st.st_size);
  const char* data =
      static_cast<const char*>(mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0));
  close(fd);
  if (data == MAP_FAILED) {
    table->error = std::string("mmap failed for ") + path;
    return table;
  }
  const char* p = data;
  const char* end = data + size;

  // header
  std::vector<std::string> header;
  std::string field;
  int pid_index = -1;
  while (p < end) {
    parse_field(p, end, &field, &p);
    header.push_back(field);
    if (p < end && *p == ',') {
      ++p;
      continue;
    }
    break;
  }
  while (p < end && (*p == '\r' || *p == '\n')) ++p;
  for (size_t i = 0; i < header.size(); ++i) {
    if (header[i] == "pid") {
      pid_index = static_cast<int>(i);
    } else {
      table->columns.push_back(Column{});
      table->columns.back().name = header[i];
    }
  }
  if (pid_index < 0) {
    table->error = "missing required column 'pid'";
    munmap(const_cast<char*>(data), size);
    return table;
  }

  // rows
  const int ncols = static_cast<int>(header.size());
  std::string scratch;
  while (p < end) {
    int col = 0;
    int out_col = 0;
    bool row_has_data = false;
    while (p < end && col < ncols) {
      parse_field(p, end, &scratch, &p);
      if (!scratch.empty()) row_has_data = true;
      if (col == pid_index) {
        table->pids.push_back(strtoll(scratch.c_str(), nullptr, 10));
      } else {
        Column& c = table->columns[out_col++];
        c.codes.push_back(c.interner.intern(scratch.data(), scratch.size()));
      }
      ++col;
      if (p < end && *p == ',') ++p;
      else break;
    }
    while (p < end && (*p == '\r' || *p == '\n')) ++p;
    if (!row_has_data && col <= 1) {  // blank trailing line: undo
      if (col == 1) {
        if (pid_index == 0) table->pids.pop_back();
        else {
          Column& c = table->columns[0];
          c.codes.pop_back();  // interned empty string stays in vocab; harmless
        }
      }
      continue;
    }
    if (col != ncols) {
      char msg[128];
      snprintf(msg, sizeof(msg), "row %zu has %d fields, expected %d",
               table->pids.size(), col, ncols);
      table->error = msg;
      break;
    }
  }
  munmap(const_cast<char*>(data), size);
  return table;
}

const char* kmls_table_error(kmls_table* t) {
  return t->error.empty() ? nullptr : t->error.c_str();
}

int64_t kmls_table_nrows(kmls_table* t) {
  return static_cast<int64_t>(t->pids.size());
}

const int64_t* kmls_table_pids(kmls_table* t) { return t->pids.data(); }

int32_t kmls_table_ncols(kmls_table* t) {
  return static_cast<int32_t>(t->columns.size());
}

const char* kmls_table_col_name(kmls_table* t, int32_t i) {
  return t->columns[i].name.c_str();
}

const int32_t* kmls_table_col_codes(kmls_table* t, int32_t i) {
  return t->columns[i].codes.data();
}

int32_t kmls_table_col_vocab_size(kmls_table* t, int32_t i) {
  return static_cast<int32_t>(t->columns[i].interner.arena.count());
}

// vocabulary as one concatenated blob + uint64 offsets (count+1 entries)
const char* kmls_table_col_vocab_blob(kmls_table* t, int32_t i, int64_t* nbytes) {
  *nbytes = static_cast<int64_t>(t->columns[i].interner.arena.bytes.size());
  return t->columns[i].interner.arena.bytes.data();
}

const uint64_t* kmls_table_col_vocab_offsets(kmls_table* t, int32_t i) {
  return t->columns[i].interner.arena.offsets.data();
}

void kmls_table_free(kmls_table* t) { delete t; }

}  // extern "C"
