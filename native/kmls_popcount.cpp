// kmls_popcount — native CPU pair-support counter over bit-packed baskets.
//
// The CPU-fallback analogue of the Pallas popcount kernel
// (kmlserver_tpu/ops/popcount.py): when no TPU is reachable, the mining
// bracket otherwise spends ~75% of its time in XLA:CPU's int8 one-hot
// matmul. Bit-packing the playlist axis and counting pair supports with
// the POPCNT unit does the same exact computation an order of magnitude
// faster:
//
//     C[i][j] = sum_w popcount(bt[i][w] & bt[j][w])
//
// over row-major bitsets bt (v rows, w64 uint64 words per row); C is
// symmetric with singleton supports on the diagonal, exactly the XᵀX
// matrix of ops/support.py pair_counts (int32).
//
// Threaded with a strided row partition (row i costs v-i pair loops, so
// contiguous blocks would load-imbalance). C ABI only, consumed via
// ctypes; the caller owns all buffers.

#include <cstdint>
#include <thread>
#include <vector>

namespace {

constexpr int32_t kAbiVersion = 2;

// Rows per i-block: IB rows stay L2-resident while each j-row streams
// through ONCE per block, cutting DRAM traffic from V²·row_bytes to
// (V/IB)·V·row_bytes. Untiled, a 2.7k-vocab × 1M-playlist input was
// memory-bound at ~43 s; tiled it is popcnt-bound at ~3 s.
constexpr int32_t kIBlock = 16;

// target_clones (x86 only — the names are x86 ISA levels and break the
// build elsewhere): runtime-dispatched variants so one portable .so still
// uses newer ISA where the RUNNING cpu has it (a measured ~15% on an
// avx512-family host). The baseline remains the Makefile's -mpopcnt
// (POPCNT ships on every x86-64 since 2008).
#if defined(__x86_64__) || defined(__i386__)
__attribute__((target_clones("avx2", "popcnt", "default")))
#endif
void count_blocks_strided(const uint64_t* bt, int32_t v, int64_t w64,
                          int32_t* out, int32_t start_block, int32_t stride) {
  const int32_t n_blocks = (v + kIBlock - 1) / kIBlock;
  for (int32_t b = start_block; b < n_blocks; b += stride) {
    const int32_t i0 = b * kIBlock;
    const int32_t i_hi = i0 + kIBlock < v ? i0 + kIBlock : v;
    for (int32_t j = i0; j < v; ++j) {
      const uint64_t* row_j = bt + static_cast<int64_t>(j) * w64;
      const int32_t i_end = j + 1 < i_hi ? j + 1 : i_hi;
      for (int32_t i = i0; i < i_end; ++i) {
        const uint64_t* row_i = bt + static_cast<int64_t>(i) * w64;
        int64_t acc = 0;
        for (int64_t w = 0; w < w64; ++w) {
          acc += __builtin_popcountll(row_i[w] & row_j[w]);
        }
        const int32_t c = static_cast<int32_t>(acc);
        out[static_cast<int64_t>(i) * v + j] = c;
        out[static_cast<int64_t>(j) * v + i] = c;
      }
    }
  }
}

}  // namespace

extern "C" {

int32_t kmls_popcount_abi_version() { return kAbiVersion; }

// Scatter membership rows into (v, w64) row-major uint64 bitsets: bit
// (p & 63) of word bt[t][p >> 6] set for each (p, t) pair. bt must be
// zeroed by the caller. Single-threaded on purpose: the |= is not atomic,
// and one linear pass at ~4 ns/row beats any numpy route by ~50x (a
// python-side np.bitwise_or.at took 13 s for 50M rows; this takes ~0.2 s).
// Duplicate membership rows OR idempotently.
void kmls_bitpack_rows(const int64_t* playlist_rows, const int32_t* track_ids,
                       int64_t n_rows, int64_t w64, uint64_t* bt) {
  for (int64_t r = 0; r < n_rows; ++r) {
    bt[static_cast<int64_t>(track_ids[r]) * w64 + (playlist_rows[r] >> 6)] |=
        1ull << (playlist_rows[r] & 63);
  }
}

// bt: (v, w64) row-major uint64 bitsets; out: (v, v) int32 (fully written).
// n_threads <= 0 means hardware concurrency (capped at 16).
void kmls_pair_counts(const uint64_t* bt, int32_t v, int64_t w64,
                      int32_t* out, int32_t n_threads) {
  if (v <= 0) return;
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = static_cast<int32_t>(hc ? (hc > 16 ? 16 : hc) : 4);
  }
  if (n_threads == 1 || v < 2 * n_threads) {
    count_blocks_strided(bt, v, w64, out, 0, 1);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int32_t t = 0; t < n_threads; ++t) {
    threads.emplace_back(count_blocks_strided, bt, v, w64, out, t, n_threads);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
