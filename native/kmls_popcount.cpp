// kmls_popcount — native CPU pair-support counter over bit-packed baskets.
//
// The CPU-fallback analogue of the Pallas popcount kernel
// (kmlserver_tpu/ops/popcount.py): when no TPU is reachable, the mining
// bracket otherwise spends ~75% of its time in XLA:CPU's int8 one-hot
// matmul. Bit-packing the playlist axis and counting pair supports with
// the POPCNT unit does the same exact computation an order of magnitude
// faster:
//
//     C[i][j] = sum_w popcount(bt[i][w] & bt[j][w])
//
// over row-major bitsets bt (v rows, w64 uint64 words per row); C is
// symmetric with singleton supports on the diagonal, exactly the XᵀX
// matrix of ops/support.py pair_counts (int32).
//
// Threaded with a strided row partition (row i costs v-i pair loops, so
// contiguous blocks would load-imbalance). C ABI only, consumed via
// ctypes; the caller owns all buffers.

#include <cstdint>
#include <thread>
#include <vector>

namespace {

constexpr int32_t kAbiVersion = 1;

void count_rows_strided(const uint64_t* bt, int32_t v, int64_t w64,
                        int32_t* out, int32_t start, int32_t stride) {
  for (int32_t i = start; i < v; i += stride) {
    const uint64_t* row_i = bt + static_cast<int64_t>(i) * w64;
    for (int32_t j = i; j < v; ++j) {
      const uint64_t* row_j = bt + static_cast<int64_t>(j) * w64;
      int64_t acc = 0;
      for (int64_t w = 0; w < w64; ++w) {
        acc += __builtin_popcountll(row_i[w] & row_j[w]);
      }
      const int32_t c = static_cast<int32_t>(acc);
      out[static_cast<int64_t>(i) * v + j] = c;
      out[static_cast<int64_t>(j) * v + i] = c;
    }
  }
}

}  // namespace

extern "C" {

int32_t kmls_popcount_abi_version() { return kAbiVersion; }

// bt: (v, w64) row-major uint64 bitsets; out: (v, v) int32 (fully written).
// n_threads <= 0 means hardware concurrency (capped at 16).
void kmls_pair_counts(const uint64_t* bt, int32_t v, int64_t w64,
                      int32_t* out, int32_t n_threads) {
  if (v <= 0) return;
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = static_cast<int32_t>(hc ? (hc > 16 ? 16 : hc) : 4);
  }
  if (n_threads == 1 || v < 2 * n_threads) {
    count_rows_strided(bt, v, w64, out, 0, 1);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int32_t t = 0; t < n_threads; ++t) {
    threads.emplace_back(count_rows_strided, bt, v, w64, out, t, n_threads);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
