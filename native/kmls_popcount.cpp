// kmls_popcount — native CPU pair-support counters.
//
// The CPU-fallback analogue of the Pallas popcount kernel
// (kmlserver_tpu/ops/popcount.py): when no TPU is reachable, the mining
// bracket otherwise spends ~75% of its time in XLA:CPU's int8 one-hot
// matmul. Two exact strategies, both producing the XᵀX matrix of
// ops/support.py pair_counts (symmetric int32, singleton supports on the
// diagonal); the Python binding picks by cost model:
//
//  - BITSET: C[i][j] = sum_w popcount(bt[i][w] & bt[j][w]) over row-major
//    bitsets bt (v rows, w64 uint64 words per row), i-rows tiled into L2.
//    Cost ~ v²/2 · w64 word-ops regardless of density — wins when the
//    matrix is small or dense.
//  - SPARSE: group memberships by playlist (counting sort), then for each
//    playlist scatter-add every unordered track pair. Cost ~
//    sum_p C(k_p, 2) scatter-adds — wins at large, sparse shapes (a 10M ×
//    1M-input's bitset scan is ~5·10¹² word-ops; its pair mass is ~10¹⁰).
//
// Threaded with a strided partition (bitset path only; the sparse
// scatter's writes collide across playlists). C ABI only, consumed via
// ctypes; the caller owns all buffers. PRECONDITION (both): (playlist,
// track) pairs are deduplicated — the Baskets contract
// (kmlserver_tpu/mining/vocab.py build_baskets) — matching the one-hot
// encoder's boolean set semantics; a duplicate row would double-count.

#include <algorithm>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

constexpr int32_t kAbiVersion = 4;

// Rows per i-block: IB rows stay L2-resident while each j-row streams
// through ONCE per block, cutting DRAM traffic from V²·row_bytes to
// (V/IB)·V·row_bytes. Untiled, a 2.7k-vocab × 1M-playlist input was
// memory-bound at ~43 s; tiled it is popcnt-bound at ~3 s.
constexpr int32_t kIBlock = 16;

// target_clones (x86 only — the names are x86 ISA levels and break the
// build elsewhere): runtime-dispatched variants so one portable .so still
// uses newer ISA where the RUNNING cpu has it (a measured ~15% on an
// avx512-family host). The baseline remains the Makefile's -mpopcnt
// (POPCNT ships on every x86-64 since 2008).
#if defined(__x86_64__) || defined(__i386__)
__attribute__((target_clones("avx2", "popcnt", "default")))
#endif
void count_blocks_strided(const uint64_t* bt, int32_t v, int64_t w64,
                          int32_t* out, int32_t start_block, int32_t stride) {
  const int32_t n_blocks = (v + kIBlock - 1) / kIBlock;
  for (int32_t b = start_block; b < n_blocks; b += stride) {
    const int32_t i0 = b * kIBlock;
    const int32_t i_hi = i0 + kIBlock < v ? i0 + kIBlock : v;
    for (int32_t j = i0; j < v; ++j) {
      const uint64_t* row_j = bt + static_cast<int64_t>(j) * w64;
      const int32_t i_end = j + 1 < i_hi ? j + 1 : i_hi;
      for (int32_t i = i0; i < i_end; ++i) {
        const uint64_t* row_i = bt + static_cast<int64_t>(i) * w64;
        int64_t acc = 0;
        for (int64_t w = 0; w < w64; ++w) {
          acc += __builtin_popcountll(row_i[w] & row_j[w]);
        }
        const int32_t c = static_cast<int32_t>(acc);
        out[static_cast<int64_t>(i) * v + j] = c;
        out[static_cast<int64_t>(j) * v + i] = c;
      }
    }
  }
}

}  // namespace

extern "C" {

int32_t kmls_popcount_abi_version() { return kAbiVersion; }

// Scatter membership rows into (v, w64) row-major uint64 bitsets: bit
// (p & 63) of word bt[t][p >> 6] set for each (p, t) pair. bt must be
// zeroed by the caller. Single-threaded on purpose: the |= is not atomic,
// and one linear pass at ~4 ns/row beats any numpy route by ~50x (a
// python-side np.bitwise_or.at took 13 s for 50M rows; this takes ~0.2 s).
// Duplicate membership rows OR idempotently.
void kmls_bitpack_rows(const int64_t* playlist_rows, const int32_t* track_ids,
                       int64_t n_rows, int64_t w64, uint64_t* bt) {
  for (int64_t r = 0; r < n_rows; ++r) {
    bt[static_cast<int64_t>(track_ids[r]) * w64 + (playlist_rows[r] >> 6)] |=
        1ull << (playlist_rows[r] & 63);
  }
}

// SPARSE pair counting: counting-sort memberships by playlist, then for
// each playlist scatter-add all C(k, 2) unordered track pairs into the
// upper triangle, finally mirror. out: (v, v) int32, caller-zeroed.
// Single-threaded: scatter targets collide across playlists.
void kmls_pair_counts_sparse(const int64_t* playlist_rows,
                             const int32_t* track_ids, int64_t n_rows,
                             int64_t n_playlists, int32_t v, int32_t* out) {
  if (v <= 0 || n_rows <= 0) return;
  // counting sort by playlist (rows arrive in arbitrary order)
  std::vector<int64_t> offs(n_playlists + 1, 0);
  for (int64_t r = 0; r < n_rows; ++r) offs[playlist_rows[r] + 1]++;
  for (int64_t p = 0; p < n_playlists; ++p) offs[p + 1] += offs[p];
  std::vector<int32_t> grouped(n_rows);
  {
    std::vector<int64_t> cursor(offs.begin(), offs.end() - 1);
    for (int64_t r = 0; r < n_rows; ++r)
      grouped[cursor[playlist_rows[r]]++] = track_ids[r];
  }
  for (int64_t p = 0; p < n_playlists; ++p) {
    const int32_t* t = grouped.data() + offs[p];
    const int64_t k = offs[p + 1] - offs[p];
    for (int64_t a = 0; a < k; ++a) {
      const int32_t ta = t[a];
      out[static_cast<int64_t>(ta) * v + ta] += 1;  // singleton support
      for (int64_t b = a + 1; b < k; ++b) {
        const int32_t tb = t[b];
        if (ta < tb) {
          out[static_cast<int64_t>(ta) * v + tb] += 1;
        } else {
          out[static_cast<int64_t>(tb) * v + ta] += 1;
        }
      }
    }
  }
  for (int32_t i = 0; i < v; ++i) {
    for (int32_t j = i + 1; j < v; ++j) {
      out[static_cast<int64_t>(j) * v + i] =
          out[static_cast<int64_t>(i) * v + j];
    }
  }
}

// Rule emission: per-row top-k of the count matrix by (count desc, column
// asc) — EXACTLY lax.top_k's tie order (ops/rules.py emit_rule_tensors) —
// over valid entries (off-diagonal, count >= min_count). For each row:
// out_ids (v, k) int32 consequent columns (-1 padded), out_counts (v, k)
// int32 (0 padded), out_row_valid (v) int32 = TRUE valid count (may
// exceed k; truncation-overflow detection happens in Python). A bounded
// ascending scan with a composite int64 key (count·v + (v-1-j), strictly
// totally ordered) and a min-heap of size k replaces a (V, V) numpy
// argpartition pass (~82 ms -> ~5 ms at ds2 shape).
void kmls_emit_topk(const int32_t* counts, int32_t v, int32_t min_count,
                    int32_t k, int32_t* out_ids, int32_t* out_counts,
                    int32_t* out_row_valid) {
  std::vector<int64_t> heap;  // min-heap on the composite key
  heap.reserve(k > 0 ? k : 1);
  const auto key_of = [v](int32_t count, int32_t j) {
    return static_cast<int64_t>(count) * v + (v - 1 - j);
  };
  for (int32_t i = 0; i < v; ++i) {
    const int32_t* row = counts + static_cast<int64_t>(i) * v;
    heap.clear();
    int32_t n_valid = 0;
    for (int32_t j = 0; j < v; ++j) {
      const int32_t c = row[j];
      if (c < min_count || j == i) continue;
      ++n_valid;
      // the twins drop count-0 entries even when min_count <= 0
      // (emit_rule_tensors' `keep = top_counts > 0`) — match exactly
      if (k <= 0 || c <= 0) continue;
      const int64_t key = key_of(c, j);
      if (static_cast<int32_t>(heap.size()) < k) {
        heap.push_back(key);
        std::push_heap(heap.begin(), heap.end(), std::greater<int64_t>());
      } else if (key > heap.front()) {
        std::pop_heap(heap.begin(), heap.end(), std::greater<int64_t>());
        heap.back() = key;
        std::push_heap(heap.begin(), heap.end(), std::greater<int64_t>());
      }
    }
    out_row_valid[i] = n_valid;
    // sort_heap with greater<> leaves the keys in DESCENDING order —
    // exactly the emit order (highest count first, ties by smaller j)
    std::sort_heap(heap.begin(), heap.end(), std::greater<int64_t>());
    int32_t* ids_row = out_ids + static_cast<int64_t>(i) * k;
    int32_t* cnt_row = out_counts + static_cast<int64_t>(i) * k;
    const int32_t filled = static_cast<int32_t>(heap.size());
    for (int32_t s = 0; s < filled; ++s) {
      const int64_t key = heap[s];
      ids_row[s] = static_cast<int32_t>(v - 1 - (key % v));
      cnt_row[s] = static_cast<int32_t>(key / v);
    }
    for (int32_t s = filled; s < k; ++s) {
      ids_row[s] = -1;
      cnt_row[s] = 0;
    }
  }
}

// bt: (v, w64) row-major uint64 bitsets; out: (v, v) int32 (fully written).
// n_threads <= 0 means hardware concurrency (capped at 16).
void kmls_pair_counts(const uint64_t* bt, int32_t v, int64_t w64,
                      int32_t* out, int32_t n_threads) {
  if (v <= 0) return;
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = static_cast<int32_t>(hc ? (hc > 16 ? 16 : hc) : 4);
  }
  if (n_threads == 1 || v < 2 * n_threads) {
    count_blocks_strided(bt, v, w64, out, 0, 1);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int32_t t = 0; t < n_threads; ++t) {
    threads.emplace_back(count_blocks_strided, bt, v, w64, out, t, n_threads);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
