// Native CPU serving kernel: batched rule lookup = gather + scatter-max +
// top-k, the exact work of ops/serve.py's recommend_batch.
//
// Why it exists: XLA:CPU lowers the (B, L, K) -> (B, V) scatter-max to
// ~190ns per update — 12ms for a 32-row ds2 batch, which IS the serving
// tail on a CPU pod (measured this round; the same scatter is fine on
// TPU). The straight C++ loop below does the same updates at ~2ns each.
// This is the serving twin of kmls_popcount.cpp's mining fallback: exact,
// CPU-only, loaded via ctypes, gracefully absent.
//
// Semantics parity with ops/serve.py recommend_batch:
// - seeds < 0 are padding; rule rows are -1-padded AFTER their valid
//   prefix (emit order: descending, then -1 fill), so the inner loop may
//   break at the first -1;
// - merge is max over per-seed confidences; only conf > 0 entries can
//   surface (top_ids -1 where top_confs <= 0);
// - top-k tie order matches jax.lax.top_k: higher conf first, equal confs
//   by LOWER consequent id first.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int32_t kAbiVersion = 1;

struct Entry {
  float conf;
  int32_t id;
};

// min-heap comparator: a is "better" than b when it has higher conf, or
// equal conf and LOWER id — the heap keeps the worst entry on top
inline bool better(const Entry& a, const Entry& b) {
  return a.conf > b.conf || (a.conf == b.conf && a.id < b.id);
}
struct WorstOnTop {
  bool operator()(const Entry& a, const Entry& b) const {
    return better(a, b);  // std::*_heap with this puts the WORST first
  }
};

}  // namespace

extern "C" {

int32_t kmls_serve_abi_version() { return kAbiVersion; }

// rule_ids: (v, kmax) int32, -1 padded (trailing); rule_confs: (v, kmax)
// float32; seed_ids: (b, l) int32, -1 padded. Outputs: out_ids (b,
// k_best) int32 with -1 padding, out_confs (b, k_best) float32 with 0.
void kmls_serve_topk(const int32_t* rule_ids, const float* rule_confs,
                     const int32_t* seed_ids, int32_t v, int32_t kmax,
                     int32_t b, int32_t l, int32_t k_best, int32_t* out_ids,
                     float* out_confs) {
  std::vector<float> scores(static_cast<size_t>(v));
  std::vector<int32_t> touched;
  touched.reserve(static_cast<size_t>(l) * kmax);
  std::vector<Entry> heap;
  heap.reserve(k_best > 0 ? k_best : 1);
  for (int32_t r = 0; r < b; ++r) {
    // reset only the slots the previous row touched: a row touches at
    // most l*kmax ids, typically far fewer than v
    for (const int32_t t : touched) scores[t] = 0.0f;
    touched.clear();
    const int32_t* seeds = seed_ids + static_cast<int64_t>(r) * l;
    for (int32_t s = 0; s < l; ++s) {
      const int32_t seed = seeds[s];
      if (seed < 0 || seed >= v) continue;
      const int32_t* ids = rule_ids + static_cast<int64_t>(seed) * kmax;
      const float* confs = rule_confs + static_cast<int64_t>(seed) * kmax;
      for (int32_t k = 0; k < kmax; ++k) {
        const int32_t tid = ids[k];
        if (tid < 0) break;  // trailing padding — rest of the row is empty
        const float c = confs[k];
        if (c > scores[tid]) {
          if (scores[tid] == 0.0f) touched.push_back(tid);
          scores[tid] = c;
        }
      }
    }
    heap.clear();
    for (const int32_t t : touched) {
      const Entry e{scores[t], t};
      if (e.conf <= 0.0f) continue;
      if (static_cast<int32_t>(heap.size()) < k_best) {
        heap.push_back(e);
        std::push_heap(heap.begin(), heap.end(), WorstOnTop{});
      } else if (k_best > 0 && better(e, heap.front())) {
        std::pop_heap(heap.begin(), heap.end(), WorstOnTop{});
        heap.back() = e;
        std::push_heap(heap.begin(), heap.end(), WorstOnTop{});
      }
    }
    // sort_heap leaves best-first (the comparator inverts as in sort)
    std::sort_heap(heap.begin(), heap.end(), WorstOnTop{});
    int32_t* ids_row = out_ids + static_cast<int64_t>(r) * k_best;
    float* conf_row = out_confs + static_cast<int64_t>(r) * k_best;
    const int32_t filled = static_cast<int32_t>(heap.size());
    for (int32_t s = 0; s < filled; ++s) {
      ids_row[s] = heap[s].id;
      conf_row[s] = heap[s].conf;
    }
    for (int32_t s = filled; s < k_best; ++s) {
      ids_row[s] = -1;
      conf_row[s] = 0.0f;
    }
  }
}

}  // extern "C"
