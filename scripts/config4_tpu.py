#!/usr/bin/env python
"""BASELINE.json config 4 on a single TPU chip: 10M playlists × 1M tracks,
500M membership rows, mined EXACTLY through the bit-packed path.

Two modes:

- default (host generation): the lean sibling of ``scripts/scale_demo.py``
  — host generation (~645 s at this shape) + prune + exactly TWO mine()
  calls (cold, then warm); every extra mine re-pays a multi-GB
  host→device transfer through the tunnel. HBM at the default shape
  (v5e, 16 GiB): bitset (8192 × 312832 words) ≈ 9.56 GiB + pruned
  membership operands ≈ 2×1.4 GiB + (F_pad)² int32 counts ≈ 0.26 GiB +
  an unpacked slab ≈ 0.13 GiB.
- ``--device-gen``: the workload is born IN HBM as a Bernoulli-Zipf
  bitset (data/device_synthetic.py) — no host generation, no prune step
  (the Apriori cut is analytic), no bulk transfer; generation takes
  seconds on device, so the whole config fits an opportunistic pool
  window. HBM: bitset ≈ 9.56 GiB + ~2.6 GiB transient uniforms during
  generation + counts/slab as above.

Either way the MXU unpack-matmul impl carries the contraction:
≈1.3·10¹⁵ int8 ops ≈ 3.4 s at the chip's 394 TOPS peak.
``CONFIG4_CPU_r03.json`` documents the same shape on one CPU core
(77.8 s); this script produces the TPU twin.

Prints one JSON line (stdout); narrative on stderr. Exits 3 off-TPU
unless --allow-cpu (the CPU artifact already exists — rerunning it here
just burns ~15 min), and refuses shapes whose XLA:CPU contraction would
take hours even then.
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as `python scripts/<name>.py` from anywhere: the repo root
# (not scripts/) is what must be importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--playlists", type=int, default=10_000_000)
    parser.add_argument("--tracks", type=int, default=1_000_000)
    parser.add_argument("--rows", type=int, default=500_000_000)
    parser.add_argument("--min-support", type=float, default=0.0005)
    parser.add_argument("--k-max", type=int, default=64)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--allow-cpu", action="store_true")
    parser.add_argument(
        "--skip-warm", action="store_true",
        help="stop after the cold mine (half the tunnel transfers)",
    )
    parser.add_argument(
        "--device-gen", action="store_true",
        help="generate the workload ON DEVICE as a Bernoulli-Zipf bitset "
        "(data/device_synthetic.py): no host generation (645 s at this "
        "shape), no host->device bulk transfer — the config-4 mechanics "
        "timed with zero tunnel involvement",
    )
    parser.add_argument(
        "--mesh", default="none",
        help="device-gen only: 'none' = single chip; 'auto' or 'DPx1' = "
        "each chip births its own word slab, counts psum over ICI "
        "(the v5e-4 path)",
    )
    args = parser.parse_args()

    import jax

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}) x{len(jax.devices())}")
    if dev.platform != "tpu":
        if not args.allow_cpu:
            log("not a TPU backend (CONFIG4_CPU_r03.json already covers "
                "CPU); pass --allow-cpu to run anyway")
            return 3
        # off-TPU the host-gen path's only carrier that finishes in
        # minutes is the native POPCNT counter; without it the miner would
        # take the bitset-mxu route, which is memory-safe but ~10¹⁵ int8
        # ops on XLA:CPU (hours) — refuse rather than wedge the session.
        # (--device-gen never uses the native library; its own shape-based
        # guard lives in run_device_gen.)
        if not args.device_gen:
            from kmlserver_tpu.ops import cpu_popcount

            if not cpu_popcount.available():
                log("native pair-count library unavailable; the XLA:CPU "
                    "bitset route would take hours at this shape — refusing")
                return 3

    import numpy as np

    from kmlserver_tpu.config import MiningConfig
    from kmlserver_tpu.data.synthetic import synthetic_baskets
    from kmlserver_tpu.mining.miner import mine, prune_infrequent
    from kmlserver_tpu.ops import popcount as pc
    from kmlserver_tpu.ops.support import min_count_for

    if args.device_gen:
        return run_device_gen(args, dev)

    t0 = time.perf_counter()
    baskets = synthetic_baskets(
        n_playlists=args.playlists, n_tracks=args.tracks,
        target_rows=args.rows, seed=args.seed,
    )
    rows = len(baskets.playlist_rows)
    gen_s = time.perf_counter() - t0
    log(f"workload: {rows:,} memberships, {args.playlists:,} playlists, "
        f"{args.tracks:,} tracks (generated in {gen_s:.1f}s host-side)")

    # prune OUTSIDE the device bracket so the transferred operands are the
    # pruned ones (~60-70% of rows) — at this shape the tunnel transfer is
    # the dominant non-compute cost and the unpruned operands are 4 GB
    min_count = min_count_for(args.min_support, baskets.n_playlists)
    t0 = time.perf_counter()
    pruned, _ = prune_infrequent(baskets, min_count)
    prune_s = time.perf_counter() - t0
    f = pruned.n_tracks
    f_pad, w_pad = pc.padded_shape(f, args.playlists)
    log(f"Apriori prune @ min_support {args.min_support} (min_count "
        f"{min_count}): {args.tracks:,} -> {f:,} frequent items in "
        f"{prune_s:.1f}s host-side; {len(pruned.playlist_rows):,} rows kept")
    log(f"HBM plan: bitset {f_pad}x{w_pad} uint32 = "
        f"{f_pad * w_pad * 4 / (1 << 30):.2f} GiB; counts "
        f"{f_pad * f_pad * 4 / (1 << 30):.2f} GiB; operands "
        f"{2 * len(pruned.playlist_rows) * 4 / (1 << 30):.2f} GiB")

    del baskets  # host RAM: the unpruned copy is no longer needed

    # skip re-pruning inside mine(); force bitpack (dense cannot fit)
    cfg = MiningConfig(
        min_support=args.min_support,
        k_max_consequents=args.k_max,
        bitpack_threshold_elems=1,
        prune_vocab_threshold=10**9,
    )

    def one_mine(label: str):
        res = mine(pruned, cfg)
        log(f"mine[{label}]: {res.duration_s:.2f}s rule generation "
            f"({rows / res.duration_s:,.0f} membership rows/s of the "
            f"original {rows:,}; path {res.count_path}; phase timings: "
            + ", ".join(f"{k} {v:.2f}s"
                        for k, v in (res.phase_timings or {}).items())
            + ")")
        return res

    result = one_mine("cold")
    n_rules = int((np.asarray(result.tensors.rule_ids) >= 0).sum())
    log(f"{n_rules:,} rules over {f:,} frequent items")
    out = {
        "playlists": args.playlists,
        "tracks": args.tracks,
        "rows": rows,
        "min_support": args.min_support,
        "frequent_items": f,
        "bitset_gib": round(f_pad * w_pad * 4 / (1 << 30), 3),
        "gen_s": round(gen_s, 1),
        "prune_host_s": round(prune_s, 2),
        "mine_cold_s": round(result.duration_s, 3),
        # CONFIG4_CPU_r03.json's 77.8 s bracket INCLUDES its 19.2 s Apriori
        # prune (scale_demo.py prunes inside mine()); here the prune runs
        # outside the device bracket so the transferred operands are the
        # pruned ones — prune_plus_mine keys are the apples-to-apples
        # comparison against that artifact, mine_* keys are device-only
        "prune_plus_mine_cold_s": round(prune_s + result.duration_s, 3),
        "n_rules": n_rules,
        "count_path": result.count_path,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
    }
    if not args.skip_warm:
        result_w = one_mine("warm")
        out["mine_s"] = round(result_w.duration_s, 3)
        out["rows_per_s"] = round(rows / result_w.duration_s, 1)
        out["prune_plus_mine_s"] = round(prune_s + result_w.duration_s, 3)

    print(json.dumps(out))
    return 0


def run_device_gen(args, dev) -> int:
    """Config 4 with the workload born in HBM: Bernoulli-Zipf bitset
    generation on device (exact-by-construction set semantics, analytic
    Apriori candidate cut — data/device_synthetic.py), then the production
    counting + emission paths. The mine bracket (counts + emission) is the
    apples-to-apples twin of CONFIG4_CPU's count+emit phases; generation
    is timed separately like the host path's excluded 645 s."""
    import numpy as np

    from kmlserver_tpu.data.device_synthetic import (
        candidate_frequent_count, device_synthetic_bitset, zipf_bit_probs,
    )
    from kmlserver_tpu.ops import popcount as pc
    from kmlserver_tpu.ops import rules as rules_mod
    from kmlserver_tpu.ops.support import min_count_for

    min_count = min_count_for(args.min_support, args.playlists)
    if dev.platform != "tpu":
        # shape guard: the unpack-matmul is ~2·P·F² int8 ops; past ~10¹²
        # XLA:CPU needs many minutes and the default shape needs hours —
        # refuse instead of wedging (small smoke shapes pass)
        f_est = candidate_frequent_count(
            zipf_bit_probs(args.tracks, args.playlists, args.rows),
            args.playlists, min_count,
        )
        est_ops = 2.0 * args.playlists * f_est * f_est
        if est_ops > 1e12:
            log(f"--device-gen on a CPU backend at this shape needs "
                f"~{est_ops:.1e} int8 ops on XLA:CPU (hours) — refusing; "
                "use a smaller --playlists/--tracks/--rows for smoke runs")
            return 3
    mesh = None
    if args.mesh != "none":
        from kmlserver_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh)
        log(f"mesh: {dict(mesh.shape)} ({mesh.devices.size} devices) — "
            "each chip births its own word slab")
    t0 = time.perf_counter()
    bitset, f_cand, info = device_synthetic_bitset(
        args.playlists, args.tracks, args.rows, min_count, seed=args.seed,
        mesh=mesh,
    )
    bitset.block_until_ready()
    gen_cold_s = time.perf_counter() - t0
    log(
        f"device-gen bitset: {info['v_pad']}x{info['w_pad']} uint32 "
        f"({info['bitset_bytes'] / (1 << 30):.2f} GiB), {f_cand:,} "
        f"candidate-frequent tracks of {args.tracks:,} "
        f"(analytic cut at {info['margin_sigmas']:.0f} sigma), expected "
        f"{info['expected_rows_total']:,.0f} memberships model-wide — "
        f"generated in {gen_cold_s:.2f}s on device (cold)"
    )

    # the sharded path resolves its counting impl from KMLS_BITPACK_IMPL;
    # resolve it HERE too so the emitted count_path label cannot lie about
    # which kernel the timings belong to (the single-chip branch is
    # hardcoded mxu)
    counts_impl = pc.resolve_counts_impl() if mesh is not None else "mxu"

    def mine_bracket():
        t = time.perf_counter()
        if mesh is not None:
            from kmlserver_tpu.parallel.support import (
                counts_from_sharded_bitset,
            )

            counts = counts_from_sharded_bitset(bitset, mesh, impl=counts_impl)
        else:
            counts = pc.mxu_pair_counts_padded(bitset)
        counts.block_until_ready()
        count_s = time.perf_counter() - t
        t = time.perf_counter()
        mined = rules_mod.mine_rules_from_counts(
            counts, n_playlists=args.playlists,
            min_support=args.min_support, k_max=args.k_max,
            n_total_songs=args.tracks,
        )
        emit_s = time.perf_counter() - t
        return counts, mined, count_s, emit_s

    counts, mined, count_s, emit_s = mine_bracket()
    n_rules = int((np.asarray(mined.rule_ids) >= 0).sum())
    measured_rows = int(mined.item_counts.astype(np.int64).sum())
    log(
        f"mine[cold]: counts {count_s:.2f}s + emission {emit_s:.2f}s; "
        f"{mined.n_frequent_items:,} empirically frequent items, "
        f"{n_rules:,} rules; {measured_rows:,} candidate memberships "
        "measured on device"
    )
    out = {
        "playlists": args.playlists,
        "tracks": args.tracks,
        "rows": round(info["expected_rows_total"]),
        "rows_basis": "expected-model-rows (bernoulli-zipf); "
        "candidate memberships measured on device in rows_measured",
        "rows_measured": measured_rows,
        "min_support": args.min_support,
        "workload_model": info["model"],
        "candidate_tracks": f_cand,
        "frequent_items": mined.n_frequent_items,
        "bitset_gib": round(info["bitset_bytes"] / (1 << 30), 3),
        "gen_device_s": round(gen_cold_s, 3),
        "mine_cold_s": round(count_s + emit_s, 3),
        "count_cold_s": round(count_s, 3),
        "emit_cold_s": round(emit_s, 3),
        "n_rules": n_rules,
        "count_path": (
            f"bitpack-{counts_impl}-devicegen"
            + ("-sharded" if mesh is not None else "")
        ),
        "mesh": args.mesh,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
    }
    print(json.dumps(out), flush=True)  # checkpoint before the warm pass

    if not args.skip_warm:
        del counts
        _, _, count_w, emit_w = mine_bracket()
        out["mine_s"] = round(count_w + emit_w, 3)
        out["count_s"] = round(count_w, 3)
        out["emit_s"] = round(emit_w, 3)
        # normalize by the memberships the mine actually counted, keeping
        # the key comparable with host-path rows/s (ADVICE r4 #1); the
        # model-wide expectation travels separately, unmistakably named
        out["rows_per_s"] = round(measured_rows / (count_w + emit_w), 1)
        out["model_rows_per_s"] = round(
            info["expected_rows_total"] / (count_w + emit_w), 1
        )
        log(f"mine[warm]: counts {count_w:.2f}s + emission {emit_w:.2f}s")
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
