#!/usr/bin/env python
"""kmls-tracejoin — merge client replay records with server trace spans.

The header contract has been in place since the span-tracing PR: the
serving front ends echo ``X-KMLS-Trace`` on every response while the
recorder is armed, and ``GET /debug/traces`` serves the retained spans
(tail-based: shed/degraded/error + slowest-N + a sampled slice). The
replay harness's :class:`~kmlserver_tpu.serving.replay.ClientTraceLog`
is the client half: one JSONL record per echoed id with send/recv wall
clocks. This tool is the consumer both sides were waiting for — it joins
the two halves on the trace id into ONE per-request timeline:

    client_send ──▶ [server: queue span, device span, ...] ──▶ client_recv

and derives the number neither side can compute alone:
``client_overhead_ms = client RTT − server-observed duration`` — the
wire + loadgen + front-end-parse slice of every request, which is what
separates "the server got slow" from "the path to the server got slow".

Inputs:
  --client PATH        ClientTraceLog JSONL (bench replay / --trace-log)
  --traces PATH|URL    /debug/traces JSON: a saved file, or a live
                       http(s) URL to fetch (loopback-only endpoint —
                       run this next to the pod, e.g. kubectl exec)

Output: one JSON object per joined request on stdout (a JSONL timeline,
newest last), and a summary line on stderr. Retention is tail-based by
design, so most client records have no server half — the summary names
both counts; ``--all`` also emits client-only records (server: null).

Exit codes: 0 = joined at least one request, 1 = nothing joined,
2 = bad input.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_client_records(path: str) -> list[dict]:
    records = []
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as exc:
                raise SystemExit(
                    f"{path}:{lineno}: not JSON ({exc})"
                ) from exc
            if "trace_id" in rec:
                records.append(rec)
    return records


def load_server_traces(source: str) -> list[dict]:
    """``/debug/traces`` payload from a file or a live URL → trace list."""
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            payload = json.load(resp)
    else:
        with open(source, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
    if isinstance(payload, dict):
        traces = payload.get("traces", [])
    elif isinstance(payload, list):  # already a bare trace list
        traces = payload
    else:
        raise SystemExit(f"{source}: not a /debug/traces payload")
    return [t for t in traces if isinstance(t, dict) and t.get("trace_id")]


def join_timeline(client: dict, server: dict | None) -> dict:
    """One per-request timeline record. All times are wall-clock unix
    seconds except spans, which stay relative to the server's request
    start (the recorder's own convention)."""
    out = {
        "trace_id": client["trace_id"],
        "client": {
            "send_unix": client.get("client_send_unix"),
            "recv_unix": client.get("client_recv_unix"),
            "rtt_ms": client.get("client_rtt_ms"),
            "status": client.get("status"),
        },
        "server": None,
    }
    if server is not None:
        attrs = server.get("attrs", {})
        out["server"] = {
            "status": server.get("status"),
            "start_unix": server.get("start_unix"),
            "duration_ms": server.get("duration_ms"),
            "attrs": attrs,
            "spans": server.get("spans", []),
        }
        # gray-failure spine (ISSUE 18): the hedge outcome
        # (won/lost/cancelled) and the forwarded deadline budget ride
        # span attrs — lift them to first-class fields so a jq over the
        # timeline can split hedged tails from plain ones without
        # knowing the attr names
        if isinstance(attrs, dict):
            if "hedged" in attrs:
                out["hedged"] = attrs["hedged"]
            if "deadline_budget_ms" in attrs:
                out["deadline_budget_ms"] = attrs["deadline_budget_ms"]
        rtt = client.get("client_rtt_ms")
        dur = server.get("duration_ms")
        if rtt is not None and dur is not None:
            # wire + loadgen queue + front-end parse: the slice between
            # what the client saw and what the server's recorder saw
            out["client_overhead_ms"] = round(rtt - dur, 4)
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--client", required=True, help="ClientTraceLog JSONL")
    parser.add_argument(
        "--traces", required=True,
        help="/debug/traces JSON file, or a live URL to fetch it from",
    )
    parser.add_argument(
        "--all", action="store_true",
        help="also emit client records with no retained server trace",
    )
    args = parser.parse_args(argv)

    try:
        client_records = load_client_records(args.client)
        server_traces = load_server_traces(args.traces)
    except OSError as exc:
        print(f"kmls-tracejoin: {exc}", file=sys.stderr)
        return 2

    # newest retained trace wins a duplicated id (a client re-sending an
    # id is driving the propagation path on purpose)
    by_id = {t["trace_id"]: t for t in server_traces}
    joined = 0
    hedged = 0
    for rec in client_records:
        server = by_id.get(rec["trace_id"])
        if server is None and not args.all:
            continue
        timeline = join_timeline(rec, server)
        print(json.dumps(timeline))
        if server is not None:
            joined += 1
            if timeline.get("hedged") is not None:
                hedged += 1
    print(
        f"kmls-tracejoin: {joined}/{len(client_records)} client records "
        f"joined against {len(server_traces)} retained server traces"
        + (f", {hedged} hedged" if hedged else "")
        + ("" if joined or not client_records else
           " (tail-based retention keeps only shed/degraded/error/"
           "slowest-N + a sampled slice — raise KMLS_TRACE_SAMPLE or "
           "drive a tail to retain more)"),
        file=sys.stderr,
    )
    return 0 if joined else 1


if __name__ == "__main__":
    sys.exit(main())
