#!/usr/bin/env python3
"""kmls-verify CLI — run the project-invariant static analyzer.

Usage (from the repo root)::

    python scripts/kmls_verify.py                 # all eleven checkers
    python scripts/kmls_verify.py --checker knobs --checker loopblock
    python scripts/kmls_verify.py --json          # machine-readable
    python scripts/kmls_verify.py --write-baseline  # accept current findings

Exit codes: 0 = clean (no non-baselined findings), 1 = findings, 2 =
usage/internal error. CI runs this as the `verify` job gate; see README
"Static invariants" for what each checker enforces and how suppressions
work (inline `# kmls-verify: allow[<checker>]` pragma, or a pinned entry
in kmlserver_tpu/analysis/baseline.json — prefer fixing the finding).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from kmlserver_tpu.analysis import (  # noqa: E402  (path bootstrap above)
    AnalysisConfig,
    load_baseline,
    run_analysis,
    write_baseline,
)
from kmlserver_tpu.analysis.core import (  # noqa: E402
    all_checkers,
    load_baseline_entries,
)

DEFAULT_BASELINE = os.path.join(
    "kmlserver_tpu", "analysis", "baseline.json"
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kmls_verify", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--root", default=_REPO_ROOT, help="repo root (default: auto)"
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help=f"suppression file (default: <root>/{DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--checker",
        action="append",
        choices=sorted(all_checkers()),
        help="run only these checkers (repeatable; default: all)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept every current finding into the baseline file",
    )
    parser.add_argument(
        "--json", action="store_true", help="JSON output instead of text"
    )
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root)
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    baseline = load_baseline(baseline_path)
    try:
        result = run_analysis(
            root,
            AnalysisConfig(),
            checkers=args.checker,
            baseline=baseline,
        )
    except ValueError as exc:
        print(f"kmls-verify: {exc}", file=sys.stderr)
        return 2

    new = result["findings"]
    if args.write_baseline:
        # with a --checker subset, carry the UNSELECTED checkers' pins
        # over verbatim — a partial run must not un-pin what it didn't
        # even look at
        keep = []
        if args.checker:
            selected = set(args.checker)
            keep = [
                e
                for e in load_baseline_entries(baseline_path)
                if e["fingerprint"].split("::", 1)[0] not in selected
            ]
        write_baseline(
            baseline_path, new + result["baselined"], keep_entries=keep
        )
        print(
            f"kmls-verify: baseline written to {baseline_path} "
            f"({len(new) + len(result['baselined']) + len(keep)} pinned "
            "finding(s))"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    key: [f.__dict__ for f in result[key]]
                    for key in ("findings", "baselined", "suppressed")
                },
                indent=1,
            )
        )
    else:
        for finding in new:
            print(finding.render())
        summary = (
            f"kmls-verify: {len(new)} new finding(s), "
            f"{len(result['baselined'])} baselined, "
            f"{len(result['suppressed'])} pragma-suppressed"
        )
        print(summary)
        if new:
            print(
                "Fix the findings, or (rarely) suppress: inline "
                "`# kmls-verify: allow[<checker>]` on the flagged line, "
                "or pin in kmlserver_tpu/analysis/baseline.json "
                "(see README 'Static invariants').",
                file=sys.stderr,
            )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
