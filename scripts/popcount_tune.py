#!/usr/bin/env python
"""On-hardware sweep for the bit-packed counting impls (Pallas VPU
kernel tiles + the MXU unpack-matmul).

The kernel's tiles are env-tunable (``KMLS_POPCOUNT_TILE_I/TILE_J/
WORD_CHUNK``, ops/popcount.py) precisely so they can be tuned on real
hardware without a code change — this script is the tuner. Each config runs
in its OWN subprocess (the tile constants bind at module import from the
env), asserts count equality against the dense MXU path once, then reports
amortized kernel time (pipelined dispatches — per-blocked-call time is
floored by the host<->device round trip, ~65 ms through this environment's
remote-TPU tunnel, which would drown sub-100ms kernels).

Prints one JSON line: every config's (ms, words/s) plus the winner. Run on
TPU; off-TPU the kernel interprets and the sweep measures Python, so the
script refuses unless --allow-interpret.

Usage (ds2 shape by default):
    python scripts/popcount_tune.py
    python scripts/popcount_tune.py --playlists 1000000 --tracks 4096 \
        --rows 5000000 --configs 32x128x512 64x128x512 32x256x256
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as `python scripts/<name>.py` from anywhere: the repo root
# (not scripts/) is what must be importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json
import subprocess

DEFAULT_CONFIGS = (
    "32x128x512",   # the shipped default
    "64x128x512",
    "128x128x512",
    "32x128x1024",
    "64x256x512",
    "8x128x512",
)

_WORKER = r"""
import json, statistics, sys, time
import numpy as np
import jax, jax.numpy as jnp
from kmlserver_tpu.data.synthetic import synthetic_baskets
from kmlserver_tpu.ops import encode, support
from kmlserver_tpu.ops import popcount as pc

n_playlists, n_tracks, target_rows = map(int, sys.argv[1:4])
variant = sys.argv[4]
check = sys.argv[5] == "1"
allow_interpret = sys.argv[6] == "1"

dev = jax.devices()[0]
interpret = dev.platform != "tpu"
if interpret and not allow_interpret:
    print("SKIP: not a TPU backend", file=sys.stderr)
    sys.exit(3)
_ti, _tj, _wk = pc.resolve_tiles()
print(f"device: {dev.platform} ({dev.device_kind}) tiles "
      f"{_ti}x{_tj}x{_wk}", file=sys.stderr, flush=True)

baskets = synthetic_baskets(
    n_playlists=n_playlists, n_tracks=n_tracks, target_rows=target_rows,
    seed=123)
kw = dict(n_playlists=baskets.n_playlists, n_tracks=baskets.n_tracks)
if variant == "mxu":
    # the blocked unpack-matmul impl: tiles are XLA's business, only
    # WORD_CHUNK (slab width) applies — pure XLA, never interpreted
    fn = lambda: pc.popcount_pair_counts(
        baskets.playlist_rows, baskets.track_ids, impl="mxu", **kw)
else:
    fn = lambda: pc.popcount_pair_counts(
        baskets.playlist_rows, baskets.track_ids, impl="vpu",
        interpret=interpret, variant=variant, **kw)
out = fn()
out.block_until_ready()  # compile
if check:
    pr, ti = jnp.asarray(baskets.playlist_rows), jnp.asarray(baskets.track_ids)
    dense = jax.jit(
        lambda a, b: support.pair_counts(encode.onehot_matrix(a, b, **kw))
    )(pr, ti)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(out))
    print("counts == dense: EXACT", file=sys.stderr, flush=True)

n_amort = 3 if interpret else 20
t0 = time.perf_counter()
jax.block_until_ready([fn() for _ in range(n_amort)])
ms = (time.perf_counter() - t0) / n_amort * 1e3

v_pad, w_pad = pc.padded_shape(baskets.n_tracks, baskets.n_playlists)
word_ops = v_pad * v_pad * w_pad
print(json.dumps({
    "ms": ms, "words_per_s": word_ops / (ms / 1e3),
    "v_pad": v_pad, "w_pad": w_pad,
}))
"""


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--playlists", type=int, default=2246)
    parser.add_argument("--tracks", type=int, default=2171)
    parser.add_argument("--rows", type=int, default=240249)
    parser.add_argument(
        "--configs", nargs="+", default=list(DEFAULT_CONFIGS),
        help="TIxTJxWORD_CHUNK triples",
    )
    parser.add_argument(
        "--variants", nargs="+", default=["mxu", "bcast", "row"],
        help="VPU kernel variants and/or 'mxu' (the unpack-matmul impl; "
        "only the WORD_CHUNK third of each config applies to it)",
    )
    parser.add_argument(
        "--allow-interpret", action="store_true",
        help="permit running off-TPU (measures the interpreter, not the chip)",
    )
    parser.add_argument("--timeout", type=float, default=600.0)
    args = parser.parse_args()

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = []
    mxu_chunks_seen: set[int] = set()
    for config in args.configs:
        ti, tj, wk = (int(x) for x in config.split("x"))
        for variant in args.variants:
            if variant == "mxu":
                # only WORD_CHUNK matters to the unpack-matmul impl;
                # don't re-measure it per tile pair
                if wk in mxu_chunks_seen:
                    continue
                mxu_chunks_seen.add(wk)
            env = os.environ.copy()
            env.update(
                KMLS_POPCOUNT_TILE_I=str(ti),
                KMLS_POPCOUNT_TILE_J=str(tj),
                KMLS_POPCOUNT_WORD_CHUNK=str(wk),
            )
            label = f"{config}/{variant}"
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", _WORKER,
                     str(args.playlists), str(args.tracks), str(args.rows),
                     variant, "1", "1" if args.allow_interpret else "0"],
                    capture_output=True, text=True, timeout=args.timeout,
                    env=env, cwd=repo_root,
                )
            except subprocess.TimeoutExpired:
                print(f"{label}: TIMEOUT (backend hang?)", file=sys.stderr)
                continue
            for line in proc.stderr.splitlines():
                print(f"[{label}] {line}", file=sys.stderr)
            if proc.returncode == 3:
                print("not a TPU backend; pass --allow-interpret to sweep "
                      "the interpreter anyway", file=sys.stderr)
                return 3
            if proc.returncode != 0:
                print(f"{label}: FAILED (exit {proc.returncode})",
                      file=sys.stderr)
                continue
            r = json.loads(proc.stdout.strip().splitlines()[-1])
            r["config"] = config
            r["variant"] = variant
            results.append(r)
            print(
                f"{label}: {r['ms']:.2f}ms amortized, "
                f"{r['words_per_s'] / 1e9:.2f} Gwords/s",
                file=sys.stderr,
            )
            # checkpoint after every measured config: a harness that
            # kills a half-done sweep (short pool window) salvages the
            # last line instead of losing every measurement
            print(json.dumps(_summary(args, results, partial=True)),
                  flush=True)
    if not results:
        print(json.dumps({"error": "no config succeeded"}))
        return 1
    print(json.dumps(_summary(args, results, partial=False)))
    return 0


def _summary(args, results: list[dict], *, partial: bool) -> dict:
    best = min(results, key=lambda r: r["ms"])
    out = {
        "shape": f"{args.playlists}x{args.tracks}",
        "best_config": best["config"],
        "best_variant": best["variant"],
        "best_ms": round(best["ms"], 3),
        "best_words_per_s": round(best["words_per_s"]),
        "results": [
            {"config": r["config"], "variant": r["variant"],
             "ms": round(r["ms"], 3),
             "words_per_s": round(r["words_per_s"])}
            for r in results
        ],
    }
    if partial:
        out["partial"] = True
    return out


if __name__ == "__main__":
    sys.exit(main())
