#!/usr/bin/env python
"""Config-4 scale mechanics: large-vocabulary mining through the
Apriori-prune → bit-packed counting path, with explicit HBM math.

BASELINE.json config 4 is synthetic 10M playlists × 1M tracks on v5e-4 —
far beyond the dense one-hot path (the (P, V) int8 matrix alone would be
10 TB). The feasible route, demonstrated end to end here at a bounded
shape, is exactly the one the miner takes automatically
(mining/miner.py pair_count_fn):

1. Apriori prune: items below min_count cannot appear in any frequent
   itemset (exact), collapsing V to the frequent vocabulary F.
2. Bit-pack the playlist axis: (F, ceil(P/32)) uint32 bitsets — 32× below
   int8 — built on device by one scatter (ops/popcount.py bitpack_by_track).
3. Pair counts from the bitset (single chip: ops/popcount.py — MXU
   unpack-matmul by default, Pallas VPU kernel via KMLS_BITPACK_IMPL=vpu;
   on a mesh: dp-sharded bitset slabs + psum over ICI,
   parallel/support.py sharded_bitpack_pair_counts).
4. Rule emission on the (F, F) count matrix.

Prints one JSON line with the measured numbers and the HBM accounting;
stderr carries the narrative. Run on TPU for real timings (bench.py runs
this as its `scale` phase); on CPU the kernel is interpreted, so keep
shapes small with --playlists/--tracks/--rows.
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as `python scripts/<name>.py` from anywhere: the repo root
# (not scripts/) is what must be importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def gib(n_bytes: float) -> float:
    return n_bytes / (1 << 30)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--playlists", type=int, default=1_000_000)
    parser.add_argument("--tracks", type=int, default=100_000)
    parser.add_argument("--rows", type=int, default=50_000_000)
    parser.add_argument("--min-support", type=float, default=0.001)
    parser.add_argument(
        "--mesh", default="none",
        help="'none' = single chip; 'auto' or 'DPx1' = dp-sharded bitset slabs",
    )
    parser.add_argument("--k-max", type=int, default=64)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument(
        "--require-native", action="store_true",
        help="exit 3 unless the native CPU pair-count path is available — "
        "at large shapes the dense fallback would allocate a V x P one-hot "
        "(tens of GB) instead of failing fast",
    )
    args = parser.parse_args()

    if args.require_native:
        from kmlserver_tpu.ops import cpu_popcount

        if not cpu_popcount.available():
            log("native pair-count library unavailable; refusing to fall "
                "back to the dense path at this shape (--require-native)")
            return 3

    import numpy as np

    from kmlserver_tpu.config import MiningConfig
    from kmlserver_tpu.data.synthetic import synthetic_baskets
    from kmlserver_tpu.mining.miner import mine, prune_infrequent
    from kmlserver_tpu.ops import popcount as pc
    from kmlserver_tpu.ops.support import min_count_for

    import jax

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}) x{len(jax.devices())}")

    t0 = time.perf_counter()
    baskets = synthetic_baskets(
        n_playlists=args.playlists, n_tracks=args.tracks,
        target_rows=args.rows, seed=args.seed,
    )
    rows = len(baskets.playlist_rows)
    log(
        f"workload: {rows:,} memberships, {args.playlists:,} playlists, "
        f"{args.tracks:,} tracks (generated in "
        f"{time.perf_counter() - t0:.1f}s host-side)"
    )

    # ---- the HBM math (the argument that the path fits) ----
    min_count = min_count_for(args.min_support, baskets.n_playlists)
    pruned, _ = prune_infrequent(baskets, min_count)
    f = pruned.n_tracks
    # exactly what popcount_pair_counts allocates — never re-derived here
    f_pad, w_pad = pc.padded_shape(f, args.playlists)
    dense_unpruned = args.playlists * args.tracks  # int8 bytes
    dense_pruned = args.playlists * f
    bitset_bytes = f_pad * w_pad * 4
    counts_bytes = f_pad * f_pad * 4
    log(
        f"Apriori prune @ min_support {args.min_support} "
        f"(min_count {min_count}): {args.tracks:,} -> {f:,} frequent items"
    )
    log(
        f"HBM: dense unpruned one-hot {gib(dense_unpruned):.2f} GiB; "
        f"dense pruned {gib(dense_pruned):.2f} GiB; "
        f"bitset (F_pad {f_pad} x W_pad {w_pad} uint32) "
        f"{gib(bitset_bytes):.3f} GiB ({dense_pruned / bitset_bytes:.0f}x "
        f"below dense-pruned); counts {gib(counts_bytes):.3f} GiB"
    )

    # ---- the measured runs ----
    mesh = None
    if args.mesh != "none":
        from kmlserver_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh)
        log(f"mesh: {dict(mesh.shape)} ({mesh.devices.size} devices)")

    def timed_mine(label, cfg, b, warm=False):
        """One logged mine() call; with ``warm`` a first untimed run
        absorbs every jit/Mosaic compile (like the bench's mining phase —
        compilation is environment preparation, not rule generation)."""
        if warm:
            mine(b, cfg, mesh=mesh)
        res = mine(b, cfg, mesh=mesh)
        log(
            f"mine[{label}]: {res.duration_s:.2f}s rule generation "
            f"({rows / res.duration_s:,.0f} membership rows/s; phase "
            "timings: "
            + ", ".join(
                f"{k} {v:.2f}s" for k, v in (res.phase_timings or {}).items()
            )
            + ")"
        )
        return res

    # 1. the bit-packed path, forced — the config-4 mechanics this demo
    # exists to prove (at TRUE config-4 shape dense cannot fit; here the
    # same code runs at a bounded shape). Cold: includes kernel compiles.
    cfg_bitpack = MiningConfig(
        min_support=args.min_support,
        k_max_consequents=args.k_max,
        bitpack_threshold_elems=1,  # force the bit-packed path
        prune_vocab_threshold=1,  # force the Apriori prune
    )
    result = timed_mine("bitpack cold", cfg_bitpack, baskets)
    assert result.pruned_vocab == f
    dur = result.duration_s
    n_rules = int((np.asarray(result.tensors.rule_ids) >= 0).sum())
    log(f"{n_rules:,} rules over {f:,} frequent items")

    out = {
        "playlists": args.playlists,
        "tracks": args.tracks,
        "rows": rows,
        "min_support": args.min_support,
        "frequent_items": f,
        "bitset_gib": round(gib(bitset_bytes), 4),
        "dense_pruned_gib": round(gib(dense_pruned), 3),
        "mine_s": round(dur, 3),
        "rows_per_s": round(rows / dur, 1),
        "n_rules": n_rules,
        "mesh": args.mesh,
        "platform": dev.platform,
    }
    # checkpoint after EVERY section: the consumer (bench.py) parses the
    # LAST stdout line, so if a later run blows the phase timeout the
    # richest checkpoint that finished still carries the headline keys
    print(json.dumps(out), flush=True)

    # 2. auto dispatch — what the miner actually does at this shape with
    # default config (HBM-fit dense/bitpack decision, mining/miner.py
    # bitpack_wanted). Warm: compile excluded, like the bench's headline.
    cfg_auto = MiningConfig(
        min_support=args.min_support, k_max_consequents=args.k_max
    )
    result_auto = timed_mine("auto warm", cfg_auto, baskets, warm=True)
    auto_rules = int((np.asarray(result_auto.tensors.rule_ids) >= 0).sum())
    if auto_rules != n_rules:
        log(f"WARNING: auto path emitted {auto_rules:,} rules vs "
            f"{n_rules:,} on the bitpack path")
    out["auto_mine_s"] = round(result_auto.duration_s, 3)
    out["auto_path"] = result_auto.count_path
    out["auto_rows_per_s"] = round(rows / result_auto.duration_s, 1)
    print(json.dumps(out), flush=True)  # checkpoint (see above)

    # 3. device-resident (TPU only): membership arrays pre-staged in HBM,
    # Apriori prune done — isolates on-chip compute + the rule fetch from
    # the host->device input transfer (through this environment's tunnel
    # the ~300 MB transfer dominates; a production pod's local PCIe/ICI
    # link would not). Labeled separately, never the headline.
    if dev.platform == "tpu":
        import dataclasses as _dc

        pruned_dev = _dc.replace(
            pruned,
            playlist_rows=jax.device_put(pruned.playlist_rows),
            track_ids=jax.device_put(pruned.track_ids),
        )
        jax.block_until_ready(
            (pruned_dev.playlist_rows, pruned_dev.track_ids)
        )
        cfg_res = MiningConfig(
            min_support=args.min_support,
            k_max_consequents=args.k_max,
            prune_vocab_threshold=10**9,  # already pruned
        )
        result_res = timed_mine("device-resident warm", cfg_res, pruned_dev, warm=True)
        out["device_resident_mine_s"] = round(result_res.duration_s, 3)
        out["device_resident_path"] = result_res.count_path

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
