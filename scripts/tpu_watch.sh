#!/bin/bash
# Opportunistic TPU capture: probe the flaky remote pool on a schedule and,
# whenever a reachability window opens, run the full bench against the chip,
# saving every emitted artifact line (bench.py prints checkpoints + a final
# line; the last JSON line is the artifact). Windows are short (~15 min) and
# sporadic, so the probe is bounded and the bench deadline stays under the
# window length. Captures COMPOUND: every run shares one KMLS_BENCH_STATE
# bank, so a second window skips the phases a first window already banked
# and spends its minutes on the still-missing ones.
cd "$(dirname "$0")/.." || exit 1
N=0
ROUND=${TPU_WATCH_ROUND:-r05}
MAX_CAPTURES=${TPU_WATCH_MAX_CAPTURES:-4}
LOG=${TPU_WATCH_LOG:-artifacts/tpu_watch.log}
mkdir -p "$(dirname "$LOG")"
STATE=${TPU_WATCH_STATE:-bench_state_${ROUND}_tpu.json}
OUTDIR=${TPU_WATCH_OUTDIR:-.}
while true; do
  if timeout 120 python -c "import jax; d = jax.devices()[0]; assert d.platform != 'cpu', d" 2>>"$LOG"; then
    N=$((N + 1))
    OUT="$OUTDIR/BENCH_PREVIEW_${ROUND}_tpu_${N}.jsonl"
    echo "$(date -u +%FT%TZ) pool UP — bench capture $N -> $OUT (state bank $STATE)" >>"$LOG"
    KMLS_BENCH_DEADLINE_S=${TPU_WATCH_DEADLINE_S:-900} \
    KMLS_BENCH_STATE="$STATE" \
      timeout 1100 python bench.py >"$OUT" 2>>"$LOG"
    echo "$(date -u +%FT%TZ) capture $N done rc=$?" >>"$LOG"
    [ "$N" -ge "$MAX_CAPTURES" ] && exit 0
    sleep 1800
  else
    # a down-probe burns its 120 s timeout, so this cycles every ~6 min —
    # reachability windows are ~15 min, and a 12-min cadence (the old
    # sleep 600) could eat most of one before the capture started
    echo "$(date -u +%FT%TZ) pool down" >>"$LOG"
    sleep 240
  fi
done
