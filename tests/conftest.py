"""Test harness: force JAX onto a virtual 8-device CPU platform.

Multi-chip sharding is tested without TPU hardware by asking XLA's host
platform for 8 virtual devices — this must happen before jax is imported
anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def tiny_baskets():
    """A hand-written transaction DB with known frequent pairs.

    5 playlists over 6 tracks (t0..t5):
      p0: t0 t1 t2
      p1: t0 t1
      p2: t0 t1 t3
      p3: t2 t3
      p4: t0 t4
    Pair counts: (t0,t1)=3, (t0,t2)=1, (t0,t3)=1, (t0,t4)=1,
                 (t1,t2)=1, (t1,t3)=1, (t2,t3)=2.
    t5 never appears.
    """
    return [
        ["t0", "t1", "t2"],
        ["t0", "t1"],
        ["t0", "t1", "t3"],
        ["t2", "t3"],
        ["t0", "t4"],
    ]
