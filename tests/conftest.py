"""Test harness: force JAX onto a virtual 8-device CPU platform.

Multi-chip sharding is tested without TPU hardware via XLA's host platform
with 8 virtual devices. The how and the why (the image's site hook registers
a remote-TPU backend that hangs when probed) live in ONE place:
``kmlserver_tpu.utils.virtualcpu`` — conftest import is early enough for the
env half of that recipe to beat the first backend initialization.
"""

import os

from kmlserver_tpu.utils.virtualcpu import force_virtual_cpu

# session-wide and deliberately permanent: env mutations are inherited by
# any python subprocess a test spawns
force_virtual_cpu(8)

# hermetic against ambient config: a developer shell with the env-var
# contract exported (BASE_DIR=..., MIN_SUPPORT=...) must not leak into
# tests that construct configs from env/defaults
for _var in (
    "BASE_DIR", "DATASETS_DIR", "PICKLE_DIR", "PICKLES_FOLDER",
    "MIN_SUPPORT", "REGEX_FILENAME", "SAMPLE_RATIO", "K_BEST_TRACKS",
    "POLLING_WAIT_IN_MINUTES", "VERSION", "RECOMMENDATIONS_FILE",
    "BEST_TRACKS_FILE", "DATA_INVALIDATION_FILE",
):
    os.environ.pop(_var, None)
for _var in [v for v in os.environ if v.startswith("KMLS_")]:
    os.environ.pop(_var, None)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def tiny_baskets():
    """A hand-written transaction DB with known frequent pairs.

    5 playlists over 6 tracks (t0..t5):
      p0: t0 t1 t2
      p1: t0 t1
      p2: t0 t1 t3
      p3: t2 t3
      p4: t0 t4
    Pair counts: (t0,t1)=3, (t0,t2)=1, (t0,t3)=1, (t0,t4)=1,
                 (t1,t2)=1, (t1,t3)=1, (t2,t3)=2.
    t5 never appears.
    """
    return [
        ["t0", "t1", "t2"],
        ["t0", "t1"],
        ["t0", "t1", "t3"],
        ["t2", "t3"],
        ["t0", "t4"],
    ]
