"""Brute-force CPU oracle for the mining semantics.

An independent from-scratch implementation of the reference fast path's
OBSERVABLE behavior (machine-learning/main.py:262-313): enumerate ALL frequent
itemsets (every length) by explicit subset counting, then walk every itemset
and max-merge its support into each member's recommendation row symmetrically.
mlxtend is not in this image; on the tiny inputs used in tests exhaustive
enumeration is exact, which is all an oracle needs.

Deliberately naive (itertools + dicts, float64 arithmetic like mlxtend) so it
shares no code and no failure modes with the device path under test.
"""

from __future__ import annotations

from itertools import combinations


def itemset_supports(
    baskets: list[list[str]], max_len: int | None = None
) -> dict[frozenset, int]:
    """Counts of every itemset (up to max_len) that occurs in >= 1 basket."""
    counts: dict[frozenset, int] = {}
    for basket in baskets:
        items = sorted(set(basket))
        top = len(items) if max_len is None else min(max_len, len(items))
        for size in range(1, top + 1):
            for combo in combinations(items, size):
                key = frozenset(combo)
                counts[key] = counts.get(key, 0) + 1
    return counts


def frequent_itemsets(
    baskets: list[list[str]], min_support: float, max_len: int | None = None
) -> dict[frozenset, int]:
    """Itemsets with support count/P >= min_support (float64, mlxtend-style)."""
    p = len(baskets)
    return {
        s: c
        for s, c in itemset_supports(baskets, max_len).items()
        if c / p >= min_support
    }


def reference_fast_rules(
    baskets: list[list[str]], min_support: float, max_len: int | None = None
) -> dict[str, dict[str, float]]:
    """The reference fast path's rule dict: for every frequent itemset, every
    member recommends every other member with the ITEMSET SUPPORT stored as
    the confidence, max-merged across itemsets
    (machine-learning/main.py:284-296, support-as-confidence quirk at :286)."""
    p = len(baskets)
    rules: dict[str, dict[str, float]] = {}
    for itemset, count in frequent_itemsets(baskets, min_support, max_len).items():
        support = count / p
        for a in itemset:
            # every member of every frequent itemset becomes a KEY — a
            # frequent singleton yields an empty row (main.py:289-291)
            row = rules.setdefault(a, {})
            for b in itemset:
                if a == b:
                    continue
                if support > row.get(b, 0.0):
                    row[b] = support
    return rules


def reference_slow_rules(
    baskets: list[list[str]],
    min_support: float,
    min_confidence: float,
    max_len: int | None = None,
) -> dict[str, dict[str, float]]:
    """The reference SLOW path's true-confidence semantics
    (machine-learning/main.py:224-260): standard association-rule generation
    — for every frequent itemset S and every non-empty proper subset A,
    conf = count(S)/count(A); if conf ≥ min_confidence, every song in A
    recommends every song in S\\A at that confidence, max-merged
    (the reference's per-rule loop at main.py:247-255). Keys exist only
    where a rule landed (unlike the fast path's empty-row keys)."""
    supports = itemset_supports(baskets, max_len)
    p = len(baskets)
    freq = {s: c for s, c in supports.items() if c / p >= min_support}
    rules: dict[str, dict[str, float]] = {}
    for itemset, count in freq.items():
        if len(itemset) < 2:
            continue
        members = sorted(itemset)
        for a_size in range(1, len(members)):
            for antecedent in combinations(members, a_size):
                c_a = freq.get(frozenset(antecedent))
                if not c_a:
                    continue
                conf = count / c_a
                if conf < min_confidence:
                    continue
                consequents = [m for m in members if m not in antecedent]
                for song in antecedent:
                    row = rules.setdefault(song, {})
                    for c in consequents:
                        if conf > row.get(c, 0.0):
                            row[c] = conf
    return rules


def reference_recommend(
    rules: dict[str, dict[str, float]], seeds: list[str], k_best: int
) -> list[tuple[str, float]]:
    """The serving max-merge + sort + top-k (rest_api/app/main.py:224-254),
    returning (name, confidence) pairs sorted by confidence descending."""
    merged: dict[str, float] = {}
    for seed in seeds:
        for other, conf in rules.get(seed, {}).items():
            if conf > merged.get(other, 0.0):
                merged[other] = conf
    ranked = sorted(merged.items(), key=lambda kv: -kv[1])
    return ranked[:k_best]


def random_baskets(rng, n_playlists: int, n_tracks: int, mean_len: float):
    """Random transaction DB with a popularity skew (quadratic rank decay)."""
    names = [f"s{i:03d}" for i in range(n_tracks)]
    weights = 1.0 / (1.0 + (rng.permutation(n_tracks) ** 1.5))
    weights = weights / weights.sum()
    baskets = []
    for _ in range(n_playlists):
        size = max(1, rng.poisson(mean_len))
        size = min(size, n_tracks)
        chosen = rng.choice(n_tracks, size=size, replace=False, p=weights)
        baskets.append([names[i] for i in chosen])
    return baskets
