"""kmls-verify static analyzer: per-checker fixture proofs + the
real-tree acceptance gate.

Every checker gets one KNOWN-BAD fixture (a seeded violation it must
flag) and one KNOWN-GOOD fixture (the compliant twin it must stay quiet
on) — the analyzer parses trees rather than importing them, so fixtures
are tiny synthetic repos written into tmp_path. The acceptance test then
runs the full default configuration against the REAL repository and
requires zero non-baselined findings: the CI `verify` job is this test,
twice (once here, once as the CLI gate).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from kmlserver_tpu.analysis import (
    AnalysisConfig,
    ProjectIndex,
    load_baseline,
    run_analysis,
    write_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(
    REPO_ROOT, "kmlserver_tpu", "analysis", "baseline.json"
)


# ---------------------------------------------------------------------------
# fixture scaffolding
# ---------------------------------------------------------------------------


def write_tree(root, files: dict[str, str]) -> None:
    for relpath, content in files.items():
        path = os.path.join(root, relpath)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(textwrap.dedent(content))


def run_fixture(
    root, cfg: AnalysisConfig, checkers: list[str], baseline=None
):
    index = ProjectIndex.from_config(str(root), cfg)
    return run_analysis(
        str(root), cfg, checkers=checkers, baseline=baseline, index=index
    )


def fixture_cfg(**overrides) -> AnalysisConfig:
    cfg = AnalysisConfig(
        package_dir="pkg",
        extra_code=(),
        tests_dir="tests",
        readme="README.md",
        manifest_files=("k8s/deploy.yaml", "k8s/job.yaml"),
        config_file="pkg/config.py",
        faults_file="pkg/faults.py",
        job_file="pkg/job.py",
        job_manifests=("k8s/job.yaml",),
        atomic_allowed_modules=("pkg/writer.py",),
        atomic_allowed_functions=(),
        durable_rename_function="pkg/writer.py::save_pickle",
        rename_allowed_modules=(),
        hotpath_entries=("pkg/serve.py::Batcher.dispatch",),
        hot_locks=("Cache._lock",),
    )
    for key, value in overrides.items():
        setattr(cfg, key, value)
    cfg.knob_scope_manifests = {
        "serving": ("k8s/deploy.yaml",),
        "mining": ("k8s/job.yaml",),
        "both": ("k8s/deploy.yaml", "k8s/job.yaml"),
        "tool": (),
        "fault": (),
    }
    return cfg


def keys(result, checker=None):
    return {
        f.key
        for f in result["findings"]
        if checker is None or f.checker == checker
    }


# ---------------------------------------------------------------------------
# checker 1: hot-path purity
# ---------------------------------------------------------------------------

_HOTPATH_BAD = """
    import time
    import numpy as np

    def helper(x):
        time.sleep(0.1)
        return np.asarray(x)

    class Batcher:
        def dispatch(self, batch):
            return helper(batch)
    """

_HOTPATH_GOOD = """
    import numpy as np

    def helper(x):
        return [len(s) for s in x]

    class Batcher:
        def dispatch(self, batch):
            # defining (not calling) a blocking closure is fine: the
            # completion side blocks BY DESIGN and must not be flagged
            def finish():
                return np.asarray(batch)

            helper(batch)
            return finish
    """


def test_hotpath_flags_seeded_violation(tmp_path):
    write_tree(tmp_path, {"pkg/serve.py": _HOTPATH_BAD})
    result = run_fixture(tmp_path, fixture_cfg(), ["hotpath"])
    got = keys(result, "hotpath")
    assert "time.sleep@helper" in got
    assert any(k.startswith("numpy.asarray@helper") for k in got), got


def test_hotpath_quiet_on_good_tree_and_closures(tmp_path):
    write_tree(tmp_path, {"pkg/serve.py": _HOTPATH_GOOD})
    result = run_fixture(tmp_path, fixture_cfg(), ["hotpath"])
    assert result["findings"] == []


def test_hotpath_pragma_suppresses(tmp_path):
    bad = _HOTPATH_BAD.replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # kmls-verify: allow[hotpath] fixture",
    )
    write_tree(tmp_path, {"pkg/serve.py": bad})
    result = run_fixture(tmp_path, fixture_cfg(), ["hotpath"])
    assert "time.sleep@helper" not in keys(result)
    assert any(
        f.key == "time.sleep@helper" for f in result["suppressed"]
    )


# ---------------------------------------------------------------------------
# checker 2: lock order + blocking under lock
# ---------------------------------------------------------------------------

_LOCKS_BAD = """
    import threading
    import time

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._other = threading.Lock()

        def slow_get(self):
            with self._lock:
                time.sleep(0.5)

        def ab(self):
            with self._lock:
                with self._other:
                    pass

        def ba(self):
            with self._other:
                with self._lock:
                    pass
    """

_LOCKS_GOOD = """
    import threading
    import time

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._other = threading.Lock()

        def fast_get(self):
            with self._lock:
                value = 1
            time.sleep(0.0)  # outside the critical section: fine
            return value

        def ordered_a(self):
            with self._lock:
                with self._other:
                    pass

        def ordered_b(self):
            # same global order as ordered_a: no cycle
            with self._lock:
                with self._other:
                    pass
    """

_LOCKS_INTERPROC_BAD = """
    import threading

    def do_io(path):
        with open(path, "r") as fh:
            return fh.read()

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()

        def get(self, path):
            with self._lock:
                return do_io(path)
    """


def test_locks_flags_blocking_and_cycle(tmp_path):
    write_tree(tmp_path, {"pkg/serve.py": _LOCKS_BAD})
    result = run_fixture(tmp_path, fixture_cfg(), ["locks"])
    got = keys(result, "locks")
    assert "block:Cache._lock:time.sleep@Cache.slow_get" in got
    assert any(k.startswith("cycle:") for k in got), got


def test_locks_flags_blocking_through_calls(tmp_path):
    write_tree(tmp_path, {"pkg/serve.py": _LOCKS_INTERPROC_BAD})
    result = run_fixture(tmp_path, fixture_cfg(), ["locks"])
    assert "block:Cache._lock:open@Cache.get" in keys(result, "locks")


def test_locks_quiet_on_good_tree(tmp_path):
    write_tree(tmp_path, {"pkg/serve.py": _LOCKS_GOOD})
    result = run_fixture(tmp_path, fixture_cfg(), ["locks"])
    assert result["findings"] == []


# ---------------------------------------------------------------------------
# checker 3: atomic-write enforcement
# ---------------------------------------------------------------------------

_ATOMIC_BAD = """
    import pickle

    def publish(obj, path):
        with open(path, "wb") as fh:
            pickle.dump(obj, fh)
    """

_ATOMIC_GOOD_WRITER = """
    import os
    import pickle

    def save_pickle(obj, path):
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            pickle.dump(obj, fh)
        os.replace(tmp, path)
    """

_ATOMIC_GOOD_CALLER = """
    from .writer import save_pickle

    def publish(obj, path):
        save_pickle(obj, path)

    def read(path):
        with open(path, "rb") as fh:
            return fh.read()
    """


def test_atomic_flags_bare_pickle_dump(tmp_path):
    write_tree(tmp_path, {"pkg/mine.py": _ATOMIC_BAD})
    result = run_fixture(tmp_path, fixture_cfg(), ["atomic-write"])
    got = keys(result, "atomic-write")
    assert "open(mode='wb')@publish" in got
    assert "pickle.dump@publish" in got


def test_atomic_allows_writer_module_and_reads(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/writer.py": _ATOMIC_GOOD_WRITER,
            "pkg/mine.py": _ATOMIC_GOOD_CALLER,
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["atomic-write"])
    assert result["findings"] == []


_ATOMIC_ROGUE_RENAME = """
    import os

    def publish(tmp, path):
        os.replace(tmp, path)
    """


def test_atomic_flags_rename_outside_durable_function(tmp_path):
    """ISSUE 19: a publication-critical rename anywhere but the
    designated durable-rename function is an ERROR — even inside an
    atomic-ALLOWED writer module (the rename rule is stricter than the
    direct-write rule)."""
    write_tree(
        tmp_path,
        {
            "pkg/writer.py": _ATOMIC_GOOD_WRITER,
            "pkg/rogue.py": _ATOMIC_ROGUE_RENAME,
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["atomic-write"])
    got = keys(result, "atomic-write")
    assert got == {"os.replace@publish"}
    # ...and a rename-allowed module is exempt from the rename rule only
    result = run_fixture(
        tmp_path,
        fixture_cfg(rename_allowed_modules=("pkg/rogue.py",)),
        ["atomic-write"],
    )
    assert result["findings"] == []


# ---------------------------------------------------------------------------
# checker 4: env-knob registry
# ---------------------------------------------------------------------------

_KNOBS_CONFIG = """
    KNOB_REGISTRY: dict[str, str] = {
        "KMLS_GOOD_KNOB": "serving",
        "KMLS_ORPHAN_KNOB": "tool",
    }
    """

_KNOBS_CODE = """
    import os

    def read():
        good = os.getenv("KMLS_GOOD_KNOB", "1")
        rogue = os.getenv("KMLS_ROGUE_KNOB")
        return good, rogue
    """


def _knobs_tree(tmp_path, readme="KMLS_GOOD_KNOB KMLS_ORPHAN_KNOB",
                deploy="env: KMLS_GOOD_KNOB"):
    write_tree(
        tmp_path,
        {
            "pkg/config.py": _KNOBS_CONFIG,
            "pkg/serve.py": _KNOBS_CODE,
            "README.md": readme + "\n",
            "k8s/deploy.yaml": deploy + "\n",
            "k8s/job.yaml": "restartPolicy: Never\n",
        },
    )


def test_knobs_flags_undeclared_orphan_and_undocumented(tmp_path):
    _knobs_tree(tmp_path, readme="KMLS_ORPHAN_KNOB only", deploy="x: y")
    result = run_fixture(tmp_path, fixture_cfg(), ["knobs"])
    got = keys(result, "knobs")
    assert "undeclared:KMLS_ROGUE_KNOB" in got
    assert "orphan:KMLS_ORPHAN_KNOB" in got
    assert "undocumented:KMLS_GOOD_KNOB" in got
    assert "unbound:KMLS_GOOD_KNOB:k8s/deploy.yaml" in got


def test_knobs_quiet_when_registries_agree(tmp_path):
    _knobs_tree(tmp_path)
    write_tree(
        tmp_path,
        {
            "pkg/serve.py": """
                import os

                def read():
                    return os.getenv("KMLS_GOOD_KNOB", "1")
                """,
            "pkg/config.py": """
                KNOB_REGISTRY: dict[str, str] = {
                    "KMLS_GOOD_KNOB": "serving",
                }
                """,
            "README.md": "KMLS_GOOD_KNOB\n",
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["knobs"])
    assert result["findings"] == []


def test_knobs_sees_literals_inside_embedded_scripts(tmp_path):
    # bench.py-style phase bracket: the knob read lives inside a string
    _knobs_tree(tmp_path)
    write_tree(
        tmp_path,
        {
            "pkg/serve.py": (
                "SCRIPT = '''\n"
                "import os\n"
                'qps = os.environ.get("KMLS_EMBEDDED_KNOB", "1")\n'
                "'''\n"
            ),
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["knobs"])
    assert "undeclared:KMLS_EMBEDDED_KNOB" in keys(result, "knobs")


# ---------------------------------------------------------------------------
# checker 5: fault-site registry
# ---------------------------------------------------------------------------

_FAULTS_GOOD = """
    import os

    def inject(site, times=1):
        pass

    def fire(site, replica=None):
        pass

    def load_env():
        raw = os.getenv("KMLS_FAULT_WIRED")
        if raw:
            inject("engine.boom", times=int(raw))
    """

_FAULTS_FIRE_SITE = """
    from .faults import fire

    def load():
        fire("engine.boom")
    """

_FAULTS_DEAD_KNOB = """
    import os

    def inject(site, times=1):
        pass

    def fire(site, replica=None):
        pass

    def load_env():
        raw = os.getenv("KMLS_FAULT_DEAD")
        if raw:
            inject("nowhere.fired", times=int(raw))
    """


def test_fault_sites_quiet_when_wired_and_tested(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/faults.py": _FAULTS_GOOD,
            "pkg/engine.py": _FAULTS_FIRE_SITE,
            "tests/test_chaos.py": (
                'def test_boom(monkeypatch):\n'
                '    monkeypatch.setenv("KMLS_FAULT_WIRED", "1")\n'
            ),
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["fault-sites"])
    assert result["findings"] == []


def test_fault_sites_flags_dead_knob_and_untested(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/faults.py": _FAULTS_DEAD_KNOB,
            "pkg/engine.py": _FAULTS_FIRE_SITE,
            "tests/test_chaos.py": "def test_nothing():\n    pass\n",
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["fault-sites"])
    got = keys(result, "fault-sites")
    assert "dead-knob:KMLS_FAULT_DEAD" in got
    # engine.boom is fired but no knob arms it -> dead chaos surface
    assert "unarmed-site:engine.boom" in got


def test_fault_sites_flags_untested_knob(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/faults.py": _FAULTS_GOOD,
            "pkg/engine.py": _FAULTS_FIRE_SITE,
            "tests/test_chaos.py": "def test_nothing():\n    pass\n",
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["fault-sites"])
    assert "untested:KMLS_FAULT_WIRED" in keys(result, "fault-sites")


# ---------------------------------------------------------------------------
# checker 6: exit-code contract
# ---------------------------------------------------------------------------

_JOB_PY = """
    EXIT_OK = 0
    EXIT_FATAL_CONFIG = 64
    EXIT_RESUMABLE = 75
    EXIT_RANK_DEAD = 76
    RETRYABLE_EXIT_CODES = (EXIT_RESUMABLE, EXIT_RANK_DEAD)
    """

_JOB_YAML_GOOD = """
    spec:
      podFailurePolicy:
        rules:
          - action: FailJob
            onExitCodes:
              operator: In
              values: [64]
          - action: Ignore
            onExitCodes:
              operator: In
              values: [75, 76]
      template:
        spec:
          restartPolicy: Never
    """


def test_exit_codes_quiet_when_contract_matches(tmp_path):
    write_tree(
        tmp_path,
        {"pkg/job.py": _JOB_PY, "k8s/job.yaml": _JOB_YAML_GOOD},
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["exit-codes"])
    assert result["findings"] == []


def test_exit_codes_flags_drifted_policy(tmp_path):
    drifted = _JOB_YAML_GOOD.replace("[75, 76]", "[75]").replace(
        "restartPolicy: Never", "restartPolicy: OnFailure"
    )
    write_tree(
        tmp_path, {"pkg/job.py": _JOB_PY, "k8s/job.yaml": drifted}
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["exit-codes"])
    got = keys(result, "exit-codes")
    assert any(k.startswith("ignore-mismatch") for k in got), got
    assert "restart-policy" in got


def test_exit_codes_flags_new_code_without_policy(tmp_path):
    # a NEW resumable code in job.py the manifest does not Ignore: the
    # exact drift class this checker exists for
    job = _JOB_PY.replace(
        "RETRYABLE_EXIT_CODES = (EXIT_RESUMABLE, EXIT_RANK_DEAD)",
        "EXIT_LEASE_LOST = 77\n"
        "    RETRYABLE_EXIT_CODES = "
        "(EXIT_RESUMABLE, EXIT_RANK_DEAD, EXIT_LEASE_LOST)",
    )
    write_tree(
        tmp_path, {"pkg/job.py": job, "k8s/job.yaml": _JOB_YAML_GOOD}
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["exit-codes"])
    assert any(
        k.startswith("ignore-mismatch") for k in keys(result, "exit-codes")
    )


# ---------------------------------------------------------------------------
# checker 7: metric-series registry (ISSUE 9)
# ---------------------------------------------------------------------------

_METRICS_GOOD = '''
    """Docstring naming kmls_prose_only_series must demand nothing."""

    METRIC_REGISTRY: dict[str, str] = {
        "kmls_good_total": "counter:serving",
        "kmls_lat_seconds": "histogram:serving",
        "kmls_job_thing": "gauge:mining",
        "kmls_dyn_state": "gauge:serving",
    }

    def render(n):
        return "\\n".join([
            "# TYPE kmls_good_total counter",
            f"kmls_good_total {n}",
            # histogram children are implementation suffixes, never
            # their own declarations
            "# TYPE kmls_lat_seconds histogram",
            'kmls_lat_seconds_bucket{le="+Inf"} 1',
            "kmls_lat_seconds_sum 0.5",
            "kmls_lat_seconds_count 1",
        ])
    '''

_JOBM_GOOD = """
    def render(v):
        return f"# TYPE kmls_job_thing gauge\\nkmls_job_thing {v}"
    """

_DYN_APP_GOOD = """
    class App:
        def state(self):
            out = {"dyn_state": 1.0}
            return out
    """


def _metrics_cfg(**overrides):
    return fixture_cfg(
        metrics_file="pkg/metrics.py",
        metric_exposition_files={
            "pkg/metrics.py": "serving",
            "pkg/jobm.py": "mining",
        },
        metric_dynamic_sources=(
            ("pkg/app.py::App.state", "kmls_", "serving"),
        ),
        **overrides,
    )


def _metrics_tree(tmp_path, metrics=_METRICS_GOOD, jobm=_JOBM_GOOD,
                  app=_DYN_APP_GOOD,
                  readme="kmls_good_total kmls_lat_seconds "
                         "kmls_job_thing kmls_dyn_state"):
    write_tree(
        tmp_path,
        {
            "pkg/metrics.py": metrics,
            "pkg/jobm.py": jobm,
            "pkg/app.py": app,
            "README.md": readme + "\n",
        },
    )


def test_metrics_quiet_when_registry_and_exposition_agree(tmp_path):
    _metrics_tree(tmp_path)
    result = run_fixture(tmp_path, _metrics_cfg(), ["metrics"])
    assert result["findings"] == []


def test_metrics_flags_unregistered_orphan_and_undocumented(tmp_path):
    _metrics_tree(
        tmp_path,
        metrics=_METRICS_GOOD.replace(
            '"kmls_good_total": "counter:serving",',
            '"kmls_orphan_gauge": "gauge:serving",',
        ),
        readme="kmls_lat_seconds kmls_job_thing kmls_dyn_state "
               "kmls_orphan_gauge",
    )
    got = keys(run_fixture(tmp_path, _metrics_cfg(), ["metrics"]), "metrics")
    assert "unregistered:kmls_good_total" in got
    assert "orphan:kmls_orphan_gauge" in got
    # registered + rendered but missing its README row
    _metrics_tree(tmp_path, readme="kmls_lat_seconds kmls_job_thing "
                                   "kmls_dyn_state")
    got = keys(run_fixture(tmp_path, _metrics_cfg(), ["metrics"]), "metrics")
    assert got == {"undocumented:kmls_good_total"}


def test_metrics_flags_malformed_entry_and_swapped_scope(tmp_path):
    _metrics_tree(
        tmp_path,
        metrics=_METRICS_GOOD.replace(
            '"kmls_job_thing": "gauge:mining",',
            '"kmls_job_thing": "gauge:serving",\n'
            '        "kmls_bad": "histo:everywhere",',
        ),
        readme="kmls_good_total kmls_lat_seconds kmls_job_thing "
               "kmls_dyn_state kmls_bad",
    )
    got = keys(run_fixture(tmp_path, _metrics_cfg(), ["metrics"]), "metrics")
    assert "bad-entry:kmls_bad" in got
    # the mining textfile module renders a series registered as serving
    assert "scope-mismatch:kmls_job_thing" in got


def test_metrics_flags_mismatch_on_second_exposition_surface(tmp_path):
    """A series BOTH surfaces render is checked at each surface: the
    serving-registered series leaking into the mining textfile must be
    flagged even though the serving module renders it first (and
    legitimately)."""
    _metrics_tree(
        tmp_path,
        jobm='''
    def render(v):
        return (f"# TYPE kmls_job_thing gauge\\nkmls_job_thing {v}\\n"
                "# TYPE kmls_good_total counter\\nkmls_good_total 0")
    ''',
    )
    got = keys(run_fixture(tmp_path, _metrics_cfg(), ["metrics"]), "metrics")
    assert got == {"scope-mismatch:kmls_good_total"}


def test_metrics_sees_dynamically_rendered_dict_keys(tmp_path):
    """The robustness-dict path: a key added to the dynamic source's
    dict is an exported series (prefixed at render time) and must be
    registered like any literal."""
    _metrics_tree(
        tmp_path,
        app=_DYN_APP_GOOD.replace(
            'out = {"dyn_state": 1.0}',
            'out = {"dyn_state": 1.0}\n'
            '            out["dyn_rogue"] = 2.0',
        ),
    )
    got = keys(run_fixture(tmp_path, _metrics_cfg(), ["metrics"]), "metrics")
    assert got == {"unregistered:kmls_dyn_rogue"}


def test_metrics_registry_keys_do_not_keep_themselves_alive(tmp_path):
    """The registry dict's own span is excluded from exposition
    collection — an entry whose only mention is its own key line is an
    orphan, not a live series."""
    _metrics_tree(
        tmp_path,
        metrics=_METRICS_GOOD.replace(
            '"kmls_dyn_state": "gauge:serving",',
            '"kmls_dyn_state": "gauge:serving",\n'
            '        "kmls_self_ref": "gauge:serving",',
        ),
        app=_DYN_APP_GOOD,
        readme="kmls_good_total kmls_lat_seconds kmls_job_thing "
               "kmls_dyn_state kmls_self_ref",
    )
    got = keys(run_fixture(tmp_path, _metrics_cfg(), ["metrics"]), "metrics")
    assert got == {"orphan:kmls_self_ref"}


# ---------------------------------------------------------------------------
# checker 8: kernel cost-spec registry (ISSUE 12)
# ---------------------------------------------------------------------------

_COSTMODEL_GOOD = """
    KERNEL_COST_SPECS = {
        "serve_fast": None,
        "mine_count": None,
    }

    METRIC_REGISTRY_STUB = True
    """

_COSTMODEL_SERIES = """
    KERNEL_COST_SPECS = {
        "serve_fast": None,
    }

    def render():
        return ["kmls_mfu 1", "kmls_unknown_series 2"]
    """

_DISPATCH_GOOD = """
    def run(cm, shape):
        cm.observe_kernel("serve_fast", 0.5, b=shape)

    def mine(jm):
        return phase_cost("mine_count", p=10, v=4)
    """

_DISPATCH_BAD = """
    def run(cm, shape):
        cm.observe_kernel("serve_renamed", 0.5, b=shape)

    def forward(cm, kernel):
        cm.observe_kernel(kernel, 0.1)
    """


def _costspec_cfg(**overrides):
    return fixture_cfg(
        costmodel_file="pkg/costmodel.py",
        costspec_required=("serve_fast",),
        metrics_file="pkg/metrics.py",
        metric_exposition_files={"pkg/metrics.py": "serving"},
        metric_dynamic_sources=(),
        **overrides,
    )


def test_costspec_quiet_when_specs_and_sites_agree(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/costmodel.py": _COSTMODEL_GOOD,
            "pkg/engine.py": _DISPATCH_GOOD,
            "pkg/metrics.py": 'METRIC_REGISTRY = {"kmls_mfu": "gauge:serving"}\n',
        },
    )
    result = run_fixture(tmp_path, _costspec_cfg(), ["costspec"])
    assert keys(result, "costspec") == set()


def test_costspec_flags_unregistered_orphan_unresolvable_and_required(
    tmp_path,
):
    write_tree(
        tmp_path,
        {
            "pkg/costmodel.py": (
                'KERNEL_COST_SPECS = {\n    "mine_count": None,\n}\n'
            ),
            "pkg/engine.py": _DISPATCH_BAD,
            "pkg/metrics.py": 'METRIC_REGISTRY = {"kmls_mfu": "gauge:serving"}\n',
        },
    )
    result = run_fixture(tmp_path, _costspec_cfg(), ["costspec"])
    got = keys(result, "costspec")
    # observed-but-unregistered kernel; spec nothing observes; variable
    # kernel name; the required anchor gone from the registry
    assert "unregistered:serve_renamed" in got
    assert "orphan:mine_count" in got
    assert any(k.startswith("unresolvable:") for k in got), got
    assert "required-missing:serve_fast" in got


def test_costspec_flags_series_missing_from_metric_registry(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/costmodel.py": _COSTMODEL_SERIES,
            "pkg/engine.py": (
                'def run(cm):\n'
                '    cm.observe_kernel("serve_fast", 0.5)\n'
            ),
            "pkg/metrics.py": 'METRIC_REGISTRY = {"kmls_mfu": "gauge:serving"}\n',
        },
    )
    result = run_fixture(tmp_path, _costspec_cfg(), ["costspec"])
    got = keys(result, "costspec")
    assert got == {"series-unregistered:kmls_unknown_series"}


def test_costspec_missing_registry_is_one_loud_finding(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/costmodel.py": "PEAKS = {}\n",
            "pkg/engine.py": _DISPATCH_GOOD,
        },
    )
    result = run_fixture(tmp_path, _costspec_cfg(), ["costspec"])
    assert keys(result, "costspec") == {"registry-missing"}


def test_costspec_pragma_suppresses_forwarding_helper(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/costmodel.py": _COSTMODEL_GOOD,
            "pkg/engine.py": (
                'def run(cm, shape):\n'
                '    cm.observe_kernel("serve_fast", 0.5)\n'
                '    phase_cost("mine_count", p=1)\n'
                'def forward(cm, kernel):\n'
                '    # kmls-verify: allow[costspec] forwarding helper\n'
                '    cm.observe_kernel(kernel, 0.1)\n'
            ),
            "pkg/metrics.py": 'METRIC_REGISTRY = {"kmls_mfu": "gauge:serving"}\n',
        },
    )
    result = run_fixture(tmp_path, _costspec_cfg(), ["costspec"])
    assert keys(result, "costspec") == set()
    assert any(
        f.checker == "costspec" for f in result["suppressed"]
    ), "the forwarding site must be pragma-suppressed, not invisible"


# ---------------------------------------------------------------------------
# checker 9: event-loop blocking (ISSUE 20)
# ---------------------------------------------------------------------------

# the PR 18 regression, reconstructed: an asyncio.Protocol callback
# dispatches into a project helper whose body blocks the loop
_LOOPBLOCK_FAULTS = """
    import time

    def fire(site, replica=None):
        time.sleep(0.05)

    def take(site, replica=None):
        return 0.05
    """

_LOOPBLOCK_BAD = """
    import asyncio

    from .faults import fire

    class _Conn(asyncio.Protocol):
        def connection_made(self, transport):
            self.transport = transport

        def data_received(self, data):
            self._dispatch(data)

        def _dispatch(self, data):
            fire("fleet.peer")
            self.transport.write(data)
    """

# the compliant twin — the PR 18 hot-fix shape: take() the delay and
# schedule delivery with loop.call_later instead of sleeping inline
_LOOPBLOCK_GOOD = """
    import asyncio

    from .faults import take

    class _Conn(asyncio.Protocol):
        def connection_made(self, transport):
            self.transport = transport

        def data_received(self, data):
            self._dispatch(data)

        def _dispatch(self, data):
            delay = take("fleet.peer")
            loop = asyncio.get_running_loop()
            loop.call_later(delay, self._deliver, data)

        def _deliver(self, data):
            self.transport.write(data)
    """

_LOOPBLOCK_ASYNC = """
    import asyncio
    import pickle

    def load_model(path):
        with open(path, "rb") as fh:
            return pickle.load(fh)

    async def handler(pool, state, fut, path):
        # awaited calls yield, they don't block: exempt — including the
        # coroutine FACTORY handed to an awaited combinator
        await asyncio.wait_for(state.idle.wait(), timeout=1.0)
        # an executor hop ends the loop-context walk: load_model runs
        # on a worker thread even though it blocks
        pool.submit(load_model, path)
        # ...but an inline un-awaited result() IS a loop stall
        return fut.result()
    """


def test_loopblock_flags_protocol_dispatch_blocking(tmp_path):
    """The PR 18 `_dispatch` stall: blocking reached FROM an asyncio
    protocol callback is flagged with the entry path and root reason."""
    write_tree(
        tmp_path,
        {"pkg/aio.py": _LOOPBLOCK_BAD, "pkg/faults.py": _LOOPBLOCK_FAULTS},
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["loopblock"])
    got = {f.key: f.message for f in result["findings"]}
    assert "time.sleep@fire" in got, got
    message = got["time.sleep@fire"]
    assert "_dispatch -> fire" in message
    assert "asyncio protocol callback on _Conn" in message


def test_loopblock_quiet_on_call_later_shape(tmp_path):
    write_tree(
        tmp_path,
        {"pkg/aio.py": _LOOPBLOCK_GOOD, "pkg/faults.py": _LOOPBLOCK_FAULTS},
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["loopblock"])
    assert result["findings"] == []


def test_loopblock_awaited_exempt_executor_escapes_result_flagged(
    tmp_path,
):
    write_tree(tmp_path, {"pkg/aio.py": _LOOPBLOCK_ASYNC})
    result = run_fixture(tmp_path, fixture_cfg(), ["loopblock"])
    got = keys(result, "loopblock")
    # the inline fut.result() on the loop is the ONLY finding: the
    # awaited .wait() is exempt and load_model escaped to the executor
    assert got == {".result()@handler"}, got


def test_loopblock_pragma_suppresses(tmp_path):
    bad = _LOOPBLOCK_FAULTS.replace(
        "time.sleep(0.05)",
        "time.sleep(0.05)  # kmls-verify: allow[loopblock] fixture",
    )
    write_tree(
        tmp_path, {"pkg/aio.py": _LOOPBLOCK_BAD, "pkg/faults.py": bad}
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["loopblock"])
    assert "time.sleep@fire" not in keys(result)
    assert any(
        f.key == "time.sleep@fire" for f in result["suppressed"]
    )


def test_loopblock_baseline_round_trip(tmp_path):
    write_tree(
        tmp_path,
        {"pkg/aio.py": _LOOPBLOCK_BAD, "pkg/faults.py": _LOOPBLOCK_FAULTS},
    )
    cfg = fixture_cfg()
    first = run_fixture(tmp_path, cfg, ["loopblock"])
    assert first["findings"]
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, first["findings"])
    second = run_fixture(
        tmp_path, cfg, ["loopblock"], baseline=load_baseline(baseline_path)
    )
    assert second["findings"] == []
    assert len(second["baselined"]) == len(first["findings"])


# ---------------------------------------------------------------------------
# checker 10: lock-ownership race inference (ISSUE 20)
# ---------------------------------------------------------------------------

_LOCKOWN_BAD = """
    import threading

    class Tracker:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._label = ""

        def incr(self):
            with self._lock:
                self._count += 1

        def read(self):
            with self._lock:
                return self._count

        def reset(self):
            self._count = 0
    """

_LOCKOWN_GOOD = """
    import threading

    class Tracker:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._hint = 0

        def incr(self):
            with self._lock:
                self._count += 1
                self._roll_locked()

        def read(self):
            with self._lock:
                return self._count

        def _roll_locked(self):
            # `*_locked` handoff convention: caller holds the lock
            self._count = 0

        def hint(self):
            with self._lock:
                self._hint = 1

        def guess(self):
            # one guarded access is below the evidence bar: no owner is
            # inferred for _hint, so this write must NOT be flagged
            self._hint = 2
    """


def test_lockown_flags_unguarded_write_with_majority_owner(tmp_path):
    write_tree(tmp_path, {"pkg/state.py": _LOCKOWN_BAD})
    result = run_fixture(tmp_path, fixture_cfg(), ["lockown"])
    got = {f.key: f.message for f in result["findings"]}
    assert set(got) == {"unguarded:_count@Tracker.reset"}, got
    # the message names the inferred owning lock and the evidence count
    assert "Tracker._lock" in got["unguarded:_count@Tracker.reset"]
    # _label has no post-__init__ accesses: never voted, never flagged
    assert not any("_label" in k for k in got)


def test_lockown_quiet_on_locked_suffix_and_thin_evidence(tmp_path):
    write_tree(tmp_path, {"pkg/state.py": _LOCKOWN_GOOD})
    result = run_fixture(tmp_path, fixture_cfg(), ["lockown"])
    assert result["findings"] == []


def test_lockown_unguarded_reads_are_not_findings(tmp_path):
    # a snapshot read outside the lock is deliberate policy, not a race
    bad = _LOCKOWN_BAD.replace(
        "def reset(self):\n            self._count = 0",
        "def reset(self):\n            return self._count + 1",
    )
    write_tree(tmp_path, {"pkg/state.py": bad})
    result = run_fixture(tmp_path, fixture_cfg(), ["lockown"])
    assert result["findings"] == []


def test_lockown_pragma_suppresses(tmp_path):
    bad = _LOCKOWN_BAD.replace(
        "self._count = 0",
        "self._count = 0  # kmls-verify: allow[lockown] fixture",
    )
    write_tree(tmp_path, {"pkg/state.py": bad})
    result = run_fixture(tmp_path, fixture_cfg(), ["lockown"])
    assert result["findings"] == []
    assert any(
        f.key == "unguarded:_count@Tracker.reset"
        for f in result["suppressed"]
    )


# ---------------------------------------------------------------------------
# checker 11: env reads at import/jit time (ISSUE 20)
# ---------------------------------------------------------------------------

_ENVREAD_CONFIG = """
    KNOB_REGISTRY: dict[str, str] = {
        "KMLS_DEADLINE_S": "serving",
        "KMLS_TOPK": "serving",
    }
    """

# the PR 12 bug class: module-level reads freeze the knob at import
_ENVREAD_BAD = """
    import os

    DEADLINE = float(os.environ.get("KMLS_DEADLINE_S", "1200"))
    MODE = os.getenv("KMLS_MODE", "hybrid")

    def fn():
        return DEADLINE
    """

_ENVREAD_JIT = """
    import os

    import jax

    @jax.jit
    def kernel(x):
        k = int(os.environ["KMLS_TOPK"])
        return x * k

    def outer(x):
        return jax.jit(impl)(x)

    def impl(x):
        return float(os.getenv("KMLS_SCALE", "1.0")) * x
    """

_ENVREAD_GOOD = """
    import os

    DEADLINE_DEFAULT = 1200.0

    def deadline():
        return float(
            os.environ.get("KMLS_DEADLINE_S", str(DEADLINE_DEFAULT))
        )

    def kernel_host(x):
        return deadline() * x
    """


def test_envread_flags_import_time_reads_with_knob_scope(tmp_path):
    write_tree(
        tmp_path,
        {"pkg/bench.py": _ENVREAD_BAD, "pkg/config.py": _ENVREAD_CONFIG},
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["envread"])
    got = {f.key: f.message for f in result["findings"]}
    assert set(got) == {
        "import-time:KMLS_DEADLINE_S",
        "import-time:KMLS_MODE",
    }, got
    # the registered knob's scope is cross-checked into the message; the
    # unregistered one is called out as missing from KNOB_REGISTRY
    assert "serving-scope knob" in got["import-time:KMLS_DEADLINE_S"]
    assert "not in KNOB_REGISTRY" in got["import-time:KMLS_MODE"]


def test_envread_flags_reads_inside_jit_traced_functions(tmp_path):
    write_tree(
        tmp_path,
        {"pkg/ops.py": _ENVREAD_JIT, "pkg/config.py": _ENVREAD_CONFIG},
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["envread"])
    got = keys(result, "envread")
    # both root shapes: @jax.jit decorator AND in-function jax.jit(fn)
    assert got == {"jit:KMLS_TOPK@kernel", "jit:KMLS_SCALE@impl"}, got


def test_envread_quiet_on_lazy_call_time_reads(tmp_path):
    write_tree(
        tmp_path,
        {"pkg/bench.py": _ENVREAD_GOOD, "pkg/config.py": _ENVREAD_CONFIG},
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["envread"])
    assert result["findings"] == []


def test_envread_sees_project_helper_calls_at_module_scope(tmp_path):
    write_tree(
        tmp_path,
        {
            "pkg/config.py": (
                "import os\n\n"
                "def getenv_int(name, default):\n"
                "    return int(os.getenv(name, str(default)))\n"
            ),
            "pkg/serve.py": (
                "from .config import getenv_int\n\n"
                'LIMIT = getenv_int("KMLS_LIMIT", 4)\n\n'
                "def ok():\n"
                '    return getenv_int("KMLS_LIMIT", 4)\n'
            ),
        },
    )
    result = run_fixture(
        tmp_path,
        fixture_cfg(
            envread_helper_functions=("pkg/config.py::getenv_int",)
        ),
        ["envread"],
    )
    # the module-scope helper call is flagged; the call-time one is not
    assert keys(result, "envread") == {"import-time:KMLS_LIMIT"}


# ---------------------------------------------------------------------------
# baseline round-trip + CLI gate
# ---------------------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    write_tree(tmp_path, {"pkg/serve.py": _HOTPATH_BAD})
    cfg = fixture_cfg()
    first = run_fixture(tmp_path, cfg, ["hotpath"])
    assert first["findings"]
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, first["findings"])
    baseline = load_baseline(baseline_path)
    second = run_fixture(tmp_path, cfg, ["hotpath"], baseline=baseline)
    assert second["findings"] == []
    assert len(second["baselined"]) == len(first["findings"])
    # the baseline pins EXISTING findings only: a fresh violation in the
    # same tree must still fail the gate
    write_tree(
        tmp_path,
        {
            "pkg/serve.py": _HOTPATH_BAD.replace(
                "return helper(batch)",
                "open('/tmp/x', 'r')\n            return helper(batch)",
            )
        },
    )
    third = run_fixture(tmp_path, cfg, ["hotpath"], baseline=baseline)
    assert "open@Batcher.dispatch" in keys(third)


def test_write_baseline_keeps_unselected_checkers_pins(tmp_path):
    """--write-baseline with a --checker subset must not un-pin the
    checkers it didn't run (CLI passes them via keep_entries)."""
    path = str(tmp_path / "baseline.json")
    write_tree(tmp_path, {"pkg/serve.py": _HOTPATH_BAD})
    first = run_fixture(tmp_path, fixture_cfg(), ["hotpath"])
    write_baseline(path, first["findings"])
    from kmlserver_tpu.analysis.core import load_baseline_entries

    prior = load_baseline_entries(path)
    assert prior
    # a "knobs-only" rewrite with no knobs findings must keep them
    write_baseline(path, [], keep_entries=prior)
    assert load_baseline(path) == {e["fingerprint"] for e in prior}


def test_atomic_flags_writes_in_closures_and_module_level(tmp_path):
    """A bare pickle.dump hidden in a nested closure (or at module
    level) must still fail the gate — the closure exemption is a hotpath
    design stance, not an atomic-write one."""
    write_tree(
        tmp_path,
        {
            "pkg/mine.py": """
                import pickle

                def publish(obj, path):
                    def _w():
                        with open(path, "wb") as fh:
                            pickle.dump(obj, fh)
                    _w()

                with open("/tmp/side-effect", "a") as fh:
                    fh.write("x")
                """
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["atomic-write"])
    got = keys(result, "atomic-write")
    assert "pickle.dump@publish" in got
    assert "open(mode='a')@<module>" in got


def test_knobs_docstring_mentions_do_not_count_as_reads(tmp_path):
    """A knob mentioned only in prose is an orphan (nothing reads it),
    and a knob-shaped token in a docstring demands no registry entry."""
    _knobs_tree(tmp_path)
    write_tree(
        tmp_path,
        {
            "pkg/serve.py": '''
                """Module docs mention KMLS_GOOD_KNOB and invent
                KMLS_DOCSTRING_ONLY_KNOB — neither is a read."""

                def helper():
                    """KMLS_ORPHAN_KNOB in prose is not a read either."""
                    return 1
                ''',
        },
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["knobs"])
    got = keys(result, "knobs")
    assert "orphan:KMLS_GOOD_KNOB" in got
    assert "orphan:KMLS_ORPHAN_KNOB" in got
    assert not any("KMLS_DOCSTRING_ONLY_KNOB" in k for k in got)


def test_fault_sites_pairs_nested_getenv_inject(tmp_path):
    """`inject("s", times=int(os.getenv(...)))` on one line must pair
    the knob with ITS OWN inject, not drift to a neighbor."""
    write_tree(
        tmp_path,
        {
            "pkg/faults.py": """
                import os

                def inject(site, times=1):
                    pass

                def fire(site, replica=None):
                    pass

                def load_env():
                    inject("engine.boom", times=int(os.getenv("KMLS_FAULT_WIRED") or 1))
                    raw = os.getenv("KMLS_FAULT_OTHER")
                    if raw:
                        inject("other.site", times=int(raw))
                """,
            "pkg/engine.py": _FAULTS_FIRE_SITE,
            "tests/test_chaos.py": (
                'X = ("KMLS_FAULT_WIRED", "KMLS_FAULT_OTHER")\n'
            ),
        },
    )
    from kmlserver_tpu.analysis.registries import collect_fault_env_map

    cfg = fixture_cfg()
    index = ProjectIndex.from_config(str(tmp_path), cfg)
    env_map = collect_fault_env_map(index, cfg)
    assert env_map["KMLS_FAULT_WIRED"][0] == "engine.boom"
    assert env_map["KMLS_FAULT_OTHER"][0] == "other.site"


def test_exit_codes_accepts_second_fatal_code_when_policied(tmp_path):
    """A new fatal code with a matching FailJob rule is NOT a finding;
    the fatal set is derived (non-zero, non-retryable), not name-bound
    to EXIT_FATAL_CONFIG."""
    job = _JOB_PY.replace(
        "EXIT_FATAL_CONFIG = 64", "EXIT_FATAL_CONFIG = 64\n    EXIT_FATAL_DATA = 65"
    )
    good = _JOB_YAML_GOOD.replace("[64]", "[64, 65]")
    write_tree(tmp_path, {"pkg/job.py": job, "k8s/job.yaml": good})
    result = run_fixture(tmp_path, fixture_cfg(), ["exit-codes"])
    assert result["findings"] == []
    # …and without the manifest rule, it IS a finding
    write_tree(
        tmp_path, {"pkg/job.py": job, "k8s/job.yaml": _JOB_YAML_GOOD}
    )
    result = run_fixture(tmp_path, fixture_cfg(), ["exit-codes"])
    assert any(
        k.startswith("failjob-mismatch")
        for k in keys(result, "exit-codes")
    )


def test_baseline_file_is_valid_and_documented():
    with open(BASELINE, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    assert data["version"] == 1
    for entry in data["findings"]:
        assert entry["fingerprint"].count("::") == 2


# ---------------------------------------------------------------------------
# the real tree
# ---------------------------------------------------------------------------


def test_real_tree_runs_clean():
    """Acceptance: the shipped configuration + baseline yields zero new
    findings on the repository itself — the exact CI gate."""
    result = run_analysis(
        REPO_ROOT, AnalysisConfig(), baseline=load_baseline(BASELINE)
    )
    assert result["findings"] == [], "\n".join(
        f.render() for f in result["findings"]
    )


def test_real_tree_indexes_the_things_checkers_depend_on():
    """Guard the analyzer's blind spots: if renames move these anchors,
    the checkers would silently check nothing — fail loudly instead."""
    cfg = AnalysisConfig()
    index = ProjectIndex.from_config(REPO_ROOT, cfg)
    for entry in cfg.hotpath_entries:
        assert index.function(entry) is not None, entry
    from kmlserver_tpu.analysis.locking import discover_locks
    from kmlserver_tpu.analysis.registries import (
        collect_code_knobs,
        collect_fault_env_map,
        collect_fire_sites,
        parse_knob_registry,
    )

    locks, aliases = discover_locks(index)
    assert len(locks) >= 14, sorted(lk.render() for lk in locks)
    # the Condition wraps _n_lock: acquiring it IS acquiring the lock
    assert any(
        c.attr == "_pipe_cond" and aliases[c].attr == "_n_lock"
        for c in aliases
    )
    scopes, _lines, _line = parse_knob_registry(index, cfg)
    refs = collect_code_knobs(index, cfg)
    assert len(refs) >= 70 and set(refs) <= set(scopes)
    env_map = collect_fault_env_map(index, cfg)
    assert len(env_map) == 15, env_map
    assert env_map["KMLS_FAULT_EMBED_CORRUPT"][0] == "embed.artifact"
    assert env_map["KMLS_FAULT_DELTA_CORRUPT"][0] == "delta.apply"
    # the gray-failure delay sites (ISSUE 18)
    assert env_map["KMLS_FAULT_FLEET_PEER_DELAY_MS"][0] == "fleet.peer"
    assert env_map["KMLS_FAULT_MESH_PEER_DELAY_MS"][0] == "mesh.peer"
    # the storage gray-failure sites (ISSUE 19)
    assert env_map["KMLS_FAULT_IO_WRITE"][0] == "io.write"
    assert env_map["KMLS_FAULT_IO_READ"][0] == "io.read"
    assert env_map["KMLS_FAULT_IO_FSYNC"][0] == "io.fsync"
    assert env_map["KMLS_FAULT_IO_WRITE_STALL_MS"][0] == "io.write"
    assert env_map["KMLS_FAULT_IO_READ_STALL_MS"][0] == "io.read"
    sites = collect_fire_sites(index, cfg)
    assert {
        "engine.load", "replica.kernel", "ckpt.corrupt", "embed.artifact",
        "delta.apply", "fleet.peer", "mesh.peer",
        "io.write", "io.read", "io.fsync",
    } <= sites
    # checker 7 anchors (ISSUE 9): the registry parses without import,
    # both exposition modules are indexed, and the dynamic robustness
    # source still resolves — a rename would silently hollow the checker
    from kmlserver_tpu.analysis.metricsreg import (
        collect_exposed_series,
        parse_metric_registry,
    )

    entries, _lines, _line = parse_metric_registry(index, cfg)
    assert len(entries) >= 40, sorted(entries)
    refs = collect_exposed_series(index, cfg)
    assert set(refs) == set(entries), (
        set(refs) ^ set(entries)
    )  # the real tree has no orphans in either direction
    for ref, _prefix, _scope in cfg.metric_dynamic_sources:
        assert index.function(ref) is not None, ref
    assert any(
        relpath == "kmlserver_tpu/observability/jobmetrics.py"
        for surfaces in refs.values()
        for relpath, _line2, _scope in surfaces
    ), "the mining textfile exposition module fell out of the index"
    # checker 8 anchors (ISSUE 12): the cost-spec registry parses
    # without import, every required (dispatched jitted) kernel is
    # registered, and the serving/mining dispatch sites are visible —
    # a rename would otherwise hollow the checker silently
    from kmlserver_tpu.analysis.costspec import (
        collect_observe_sites,
        parse_cost_specs,
    )

    specs, _reg_line = parse_cost_specs(index, cfg)
    assert set(cfg.costspec_required) <= set(specs), (
        set(cfg.costspec_required) - set(specs)
    )
    sites, unresolved = collect_observe_sites(index)
    assert {
        "serve_rules", "serve_sharded", "serve_native", "embed_topk",
        "support_count", "als_sweep", "delta_recount",
    } <= set(sites), sorted(sites)
    assert any(
        relpath == "kmlserver_tpu/serving/engine.py"
        for relpath, _line3 in sites["serve_rules"]
    ), "the engine's dispatch observation fell out of the index"
    assert unresolved == [], unresolved


def test_real_tree_concurrency_anchors():
    """ISSUE 20 anchors: the execution-context model's configured refs
    and structural roots must keep resolving on the real tree — a rename
    would otherwise silently hollow loopblock/lockown/envread."""
    cfg = AnalysisConfig()
    index = ProjectIndex.from_config(REPO_ROOT, cfg)
    # configured loop entries/cuts and env-helper refs all resolve
    for ref in (
        cfg.loop_entries
        + cfg.loop_cut_functions
        + cfg.envread_helper_functions
    ):
        assert index.function(ref) is not None, ref
    from kmlserver_tpu.analysis.callgraph import (
        _is_protocol_class,
        classify_contexts,
    )

    # the PR 18 anchor: _Conn is an asyncio protocol subclass and its
    # _dispatch is classified event-loop — the acceptance scenario
    # (re-introducing a blocking fire() there) depends on exactly this
    assert _is_protocol_class(index, "_Conn")
    ctx = classify_contexts(index, cfg)
    dispatch = "kmlserver_tpu/serving/aioserver.py::_Conn._dispatch"
    assert dispatch in ctx.loop, sorted(ctx.loop)[:20]
    assert "protocol callback" in ctx.loop_roots[ctx.loop[dispatch][0]]
    # the engine pool keeps a worker-thread context too
    assert ctx.thread, "no thread roots found on the real tree"
    # module singletons resolve (lockown/loopblock see MONITOR.method())
    assert (
        index.module_attr_types.get(
            ("kmlserver_tpu/io/iohealth.py", "MONITOR")
        )
        == "IoHealthMonitor"
    )
    # envread's jit roots: the ops/ kernels keep their traced shapes
    from kmlserver_tpu.analysis.envread import jit_roots

    roots = jit_roots(index)
    assert any(
        ref.startswith("kmlserver_tpu/ops/") for ref in roots
    ), sorted(roots)
    # lockown's marquee cross-context classes still own discovered locks
    from kmlserver_tpu.analysis.locking import discover_locks

    locks, _aliases = discover_locks(index)
    owners = {lock.owner for lock in locks}
    assert {"IoHealthMonitor", "TrafficForecaster"} <= owners, sorted(
        owners
    )


def test_cli_exit_codes(tmp_path):
    """The CLI is the CI gate: clean tree -> 0, violation -> 1."""
    script = os.path.join(REPO_ROOT, "scripts", "kmls_verify.py")
    clean = subprocess.run(
        [sys.executable, script, "--checker", "exit-codes"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    # seed a violation into a COPY of the tree shape the checker reads
    write_tree(
        tmp_path,
        {
            "pkg/job.py": _JOB_PY,
            "k8s/job.yaml": _JOB_YAML_GOOD.replace("[64]", "[63]"),
        },
    )
    cfg = fixture_cfg()
    result = run_fixture(tmp_path, cfg, ["exit-codes"])
    assert result["findings"], "seeded manifest drift must be caught"


@pytest.mark.parametrize(
    "checker",
    ["hotpath", "locks", "atomic-write", "knobs", "fault-sites",
     "exit-codes", "metrics", "costspec", "loopblock", "lockown",
     "envread"],
)
def test_every_checker_registered(checker):
    from kmlserver_tpu.analysis.core import all_checkers

    assert checker in all_checkers()


def test_checker_count_ratchet():
    """Eleven checkers as of ISSUE 20 — a dropped registration must
    fail loudly, not silently shrink the gate."""
    from kmlserver_tpu.analysis.core import all_checkers

    assert len(all_checkers()) == 11, sorted(all_checkers())
