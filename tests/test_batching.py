"""The tail-latency serving layer: shape-bucketed pre-warm (no compile on
the serving path), adaptive deadline-aware batching, load shedding (429 +
Retry-After), staging-buffer reuse exactness, and queue/device latency
attribution."""

import dataclasses
import json
import threading
import time

import jax
import numpy as np
import pytest

from kmlserver_tpu.config import ServingConfig
from kmlserver_tpu.io import artifacts
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.batcher import (
    AdmissionController,
    MicroBatcher,
    Overloaded,
    OverloadDegraded,
)
from kmlserver_tpu.serving.engine import RecommendEngine, _staging_is_safe
from kmlserver_tpu.serving.metrics import ServingMetrics
from kmlserver_tpu.serving.replay import replay, sample_seed_sets

from .test_serving import mined_pvc  # noqa: F401  (fixture re-export)


def _rule_seeds(cfg) -> list[str]:
    rules_dict = artifacts.load_pickle(
        f"{cfg.base_dir}/pickles/{cfg.recommendations_file}"
    )
    return [s for s, row in rules_dict.items() if row]


class TestBucketedCompilation:
    def test_batch_bucket_math(self, tmp_path):
        engine = RecommendEngine(
            ServingConfig(base_dir=str(tmp_path), batch_max_size=32)
        )
        assert engine._batch_buckets() == [1, 2, 4, 8, 16, 32]
        assert engine._bucket_batch(1) == 1
        assert engine._bucket_batch(3) == 4
        assert engine._bucket_batch(17) == 32
        assert engine._bucket_batch(32) == 32
        # oversized (direct recommend_many callers only): multiples of cap
        assert engine._bucket_batch(33) == 64
        assert engine._bucket_batch(65) == 96
        # a non-power-of-two cap is always its own bucket
        engine24 = RecommendEngine(
            ServingConfig(base_dir=str(tmp_path), batch_max_size=24)
        )
        assert engine24._batch_buckets() == [1, 2, 4, 8, 16, 24]
        assert engine24._bucket_batch(20) == 24

    def test_prewarm_covers_every_bucket_no_compile_when_serving(
        self, mined_pvc
    ):
        """Acceptance: after the engine reports ready, no jit compilation
        happens on the serving path — proven by the jitted kernel's compile
        cache not growing AND the engine's unwarmed-dispatch counter
        staying zero across every batch size a request can produce."""
        from kmlserver_tpu.ops import serve as serve_ops

        cfg, _, _ = mined_pvc
        # device path under test: the native host kernel (which never
        # compiles anything) must be off, as it is on every accelerator
        engine = RecommendEngine(dataclasses.replace(cfg, native_serve=False))
        assert engine.load()
        bundle = engine.bundle
        assert bundle.host_rule_ids is None
        for batch in engine._batch_buckets():
            for length in engine._len_buckets():
                assert (batch, length) in bundle.warmed_shapes
        seeds = _rule_seeds(cfg)
        counter = getattr(serve_ops.recommend_batch, "_cache_size", None)
        n0 = counter() if counter else None
        for b in (1, 2, 3, 5, 8, 13, 27, 32):
            results = engine.recommend_many(
                [[seeds[i % len(seeds)]] for i in range(b)]
            )
            assert len(results) == b
        engine.recommend(seeds[:2])
        engine.recommend(["totally-unknown"])  # fallback path, no kernel
        assert engine.unwarmed_dispatches == 0
        if counter:
            assert counter() == n0, "a serving request compiled a kernel"

    def test_unwarmed_shape_is_counted_not_silent(self, mined_pvc):
        cfg, _, _ = mined_pvc
        engine = RecommendEngine(dataclasses.replace(cfg, native_serve=False))
        assert engine.load()
        seeds = _rule_seeds(cfg)
        # an oversized direct batch (> batch_max_size) has no warmed bucket
        engine.recommend_many([[seeds[0]]] * (cfg.batch_max_size + 1))
        assert engine.unwarmed_dispatches == 1


class TestStagingReuse:
    def test_staging_buffers_are_misaligned_so_device_put_copies(self):
        """Regression for the reuse-corruption flake: jax's CPU client
        ZERO-COPIES device_put of a 64-byte-aligned host array, so a
        np.empty staging buffer that happened to land page-aligned was
        aliased into the device array — the next same-shape dispatch's
        refill corrupted the in-flight batch (answers swapped between
        batches, allocator-luck-dependent). The allocator must produce
        addresses that defeat every power-of-two alignment gate >= 8,
        and device_put of its buffers must genuinely copy."""
        from kmlserver_tpu.serving.engine import _staging_buffer

        for shape in ((2, 2), (2, 64), (8, 128), (64, 256)):
            arr = _staging_buffer(shape)
            assert arr.shape == shape and arr.dtype == np.int32
            addr = arr.ctypes.data
            assert addr % 64 == 4, f"{shape}: addr % 64 == {addr % 64}"
            arr.fill(-1)
            on_device = jax.device_put(arr)
            arr[0, 0] = 123
            assert int(np.asarray(on_device)[0, 0]) == -1, (
                f"{shape}: device_put aliased the staging buffer"
            )

    def test_overlapping_same_shape_dispatches_stay_exact(self, mined_pvc):
        """The aliasing hazard the probe guards: two in-flight batches of
        the SAME padded shape share (refill) one staging buffer. Results
        must match the per-request oracle — if the device transfer aliased
        the host buffer, batch 1 would answer with batch 2's seeds."""
        cfg, _, _ = mined_pvc
        engine = RecommendEngine(dataclasses.replace(cfg, native_serve=False))
        assert engine.load()
        seeds = _rule_seeds(cfg)
        assert len(seeds) >= 4
        sets_a = [[seeds[0]], [seeds[1]]]
        sets_b = [[seeds[2]], [seeds[3]]]
        expected = {s: engine.recommend([s]) for s in seeds[:4]}
        finish_a = engine.recommend_many_async(sets_a)
        finish_b = engine.recommend_many_async(sets_b)  # same (2, L) bucket
        if _staging_is_safe():
            # reuse is actually active on this backend: both dispatches
            # went through ONE buffer, and it now sits in the pool
            assert any(
                shape[0] == 2 for shape in engine._staging
            ), "staging pool never populated"
        for seed_sets, finish in ((sets_a, finish_a), (sets_b, finish_b)):
            for (got, source), (seed,) in zip(finish(), seed_sets):
                assert set(got) == set(expected[seed][0])
                assert source == expected[seed][1]

    def test_fallback_rows_survive_buffer_refill(self, mined_pvc):
        # the known-row mask is snapshotted before the buffer can be
        # refilled — an all-unknown row must still fall back correctly
        # even with another dispatch in between
        cfg, _, _ = mined_pvc
        engine = RecommendEngine(cfg)
        assert engine.load()
        seeds = _rule_seeds(cfg)
        f1 = engine.recommend_many_async([["unknown-x"], [seeds[0]]])
        f2 = engine.recommend_many_async([[seeds[1]], [seeds[2]]])
        r1, r2 = f1(), f2()
        assert r1[0][1] == "fallback"
        assert r1[1][1] in ("rules", "empty")
        assert all(src in ("rules", "empty") for _, src in r2)


class TestAdaptiveWindow:
    class _InstantEngine:
        def recommend_many_async(self, seed_sets):
            def finish():
                return [(list(s), "rules") for s in seed_sets]

            return finish

    def test_window_tracks_arrival_rate(self):
        b = MicroBatcher(
            self._InstantEngine(), max_size=32, window_ms=10.0,
            adaptive=True, window_min_ms=1.0,
        )
        from kmlserver_tpu.serving.batcher import _Pending
        from concurrent.futures import Future

        now = time.perf_counter()
        batch = [_Pending(["x"], Future(), now)]
        # no arrivals observed yet: fall back to the fixed ceiling
        assert b._busy_window_s(batch, now) == pytest.approx(0.010)
        # sparse traffic (10 ms mean gap): filling 31 slots needs ~310 ms
        # — clamped to the ceiling, same as the fixed window
        b._arrivals.clear()
        b._arrivals.extend(i * 0.010 for i in range(10))
        assert b._arrival_gap_s() == pytest.approx(0.010)
        assert b._busy_window_s(batch, now) == pytest.approx(0.010)
        # dense traffic (0.1 ms mean gap): a nearly-full batch stops
        # waiting at the floor instead of burning the ceiling on one
        # straggler
        b._arrivals.clear()
        b._arrivals.extend(i * 0.0001 for i in range(10))
        nearly_full = batch + [
            _Pending(["y"], Future(), now) for _ in range(30)
        ]
        assert b._busy_window_s(nearly_full, now) == pytest.approx(0.001)

    def test_window_capped_by_shed_budget_deadline(self):
        b = MicroBatcher(
            self._InstantEngine(), max_size=32, window_ms=10.0,
            adaptive=True, window_min_ms=1.0, shed_queue_budget_ms=50.0,
        )
        from kmlserver_tpu.serving.batcher import _Pending
        from concurrent.futures import Future

        now = time.perf_counter()
        # the batch leader has already waited 45 of its 50 ms budget: the
        # window must shrink to the 5 ms remaining, ceiling notwithstanding
        leader = _Pending(["x"], Future(), now - 0.045)
        got = b._busy_window_s([leader], now)
        assert got == pytest.approx(0.005, abs=0.001)
        # budget exhausted → no wait at all
        overdue = _Pending(["x"], Future(), now - 0.100)
        assert b._busy_window_s([overdue], now) == 0.0

    def test_tail_bounded_under_poisson_load(self, mined_pvc):
        """Seeded Poisson arrivals through the full engine + batcher: the
        p99/p50 ratio stays bounded (the r05 replay showed 5.4x with the
        fixed window + single 32-wide kernel shape)."""
        cfg, _, _ = mined_pvc
        engine = RecommendEngine(cfg)
        assert engine.load()
        metrics = ServingMetrics()
        batcher = MicroBatcher(
            engine, max_size=cfg.batch_max_size, window_ms=2.0,
            max_inflight=4, adaptive=True, metrics=metrics,
        )
        payloads = sample_seed_sets(engine.bundle.vocab, 1200, rng_seed=9)
        report = replay(
            lambda seeds: batcher.recommend(seeds)[1], payloads, qps=600.0
        )
        assert report.n_errors == 0
        assert sum(report.by_source.values()) == 1200
        if report.offered_qps < 0.8 * 600.0:
            # the thread-per-request loadgen couldn't sustain the target —
            # the HOST is degraded, and a tail measured through a degraded
            # harness asserts nothing about the batcher
            pytest.skip(
                f"loadgen degraded ({report.offered_qps:.0f} of 600 QPS "
                "offered); host too noisy for a tail assertion"
            )
        # generous bounds (CI hosts are noisy); the bench pins the tight
        # 3x/25ms acceptance on a quiet host
        assert report.p99_ms <= max(6.0 * report.p50_ms, 30.0), (
            f"tail blowup: p50 {report.p50_ms:.2f}ms "
            f"p99 {report.p99_ms:.2f}ms"
        )
        # attribution flowed through: every completed request observed
        n99 = metrics.queue_wait.percentiles(0.99)[0]
        assert metrics.e2e.percentiles(0.5)[0] > 0
        assert np.isfinite(n99)


class TestLoadShedding:
    class _SlowEngine:
        """Every batch takes a fixed 50 ms on the 'device'."""

        def recommend_many_async(self, seed_sets):
            def finish():
                time.sleep(0.05)
                return [(list(s), "rules") for s in seed_sets]

            return finish

    def test_sheds_before_queue_wait_budget_breached(self):
        budget_ms = 120.0
        metrics = ServingMetrics()
        batcher = MicroBatcher(
            self._SlowEngine(), max_size=4, window_ms=1.0, max_inflight=1,
            shed_queue_budget_ms=budget_ms, metrics=metrics,
        )
        # one sequential request first: the projection needs device-time
        # evidence (a fully cold controller deliberately never sheds, and
        # its first-batch learning window would admit a deep queue)
        batcher.recommend(["warm"], timeout=10.0)
        outcomes = {"ok": 0, "shed": 0, "degraded": 0, "other": 0}
        lock = threading.Lock()

        def worker(i):
            try:
                batcher.recommend([f"s{i}"], timeout=30.0)
                key = "ok"
            except Overloaded as exc:
                # Retry-After carries bounded jitter: base 1s ± 50%
                assert 0.5 <= exc.retry_after_s <= 1.5
                key = "shed"
            except OverloadDegraded:
                # the ladder rung before any 429: the app layer answers
                # these from the popularity fallback with HTTP 200
                key = "degraded"
            except Exception:
                key = "other"
            with lock:
                outcomes[key] += 1

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(150)
        ]
        for t in threads:
            t.start()
            time.sleep(0.001)  # sustained pressure, not one instant burst
        for t in threads:
            t.join()
        assert outcomes["other"] == 0
        assert outcomes["shed"] > 0, "overload never shed"
        assert outcomes["ok"] > 0, "shedding rejected everything"
        # this workload drives pressure well past the budget, so the
        # degrade band must have fired on the way up
        assert outcomes["degraded"] > 0, "ladder never degraded"
        assert batcher.shed_total == outcomes["shed"]
        assert metrics.shed_total == outcomes["shed"]
        assert batcher.degrade_total == outcomes["degraded"]
        # the point of shedding: ADMITTED requests keep a bounded queue
        # wait. Unshed, 150 requests at 4-per-50ms mean the last admitted
        # would wait ~1.9 s; with the budget the observed p99 stays within
        # a couple of service times of it.
        (qw_p99,) = metrics.queue_wait.percentiles(0.99)
        assert qw_p99 * 1e3 <= budget_ms + 150.0, (
            f"admitted queue wait p99 {qw_p99 * 1e3:.0f}ms far exceeds "
            f"the {budget_ms:.0f}ms budget"
        )

    def test_cold_batcher_never_sheds(self):
        # no device-time evidence yet → no shedding, however long the queue
        batcher = MicroBatcher(
            self._SlowEngine(), max_size=4, window_ms=1.0,
            shed_queue_budget_ms=1e-6,
        )
        assert batcher.projected_queue_wait_s() == 0.0
        got, _ = batcher.recommend(["x"])
        assert got == ["x"]

    def test_app_returns_429_with_retry_after(self, tmp_path):
        app = RecommendApp(ServingConfig(base_dir=str(tmp_path)))

        class SheddingBatcher:
            def recommend(self, seeds, timeout=30.0):
                raise Overloaded(
                    retry_after_s=1.3, projected_wait_ms=500.0
                )

        app.batcher = SheddingBatcher()
        status, headers, payload = app.handle(
            "POST", "/api/recommend/", json.dumps({"songs": ["x"]}).encode()
        )
        assert status == 429
        # RFC 9110 delay-seconds: integer ONLY (a decimal crashes
        # urllib3's Retry.parse_retry_after); the batcher's sub-second
        # jitter survives as a ceil onto adjacent whole seconds
        assert headers["Retry-After"] == "2"
        body = json.loads(payload)
        assert "overloaded" in body["detail"]

    def test_app_degrades_overload_band_to_fallback(self, tmp_path):
        """The ladder rung before any 429: OverloadDegraded from the
        batcher answers 200 + X-KMLS-Degraded: overload from the
        popularity fallback, and the degraded counter moves."""
        from kmlserver_tpu.config import MiningConfig
        from kmlserver_tpu.data.csv import write_tracks_csv
        from kmlserver_tpu.mining.pipeline import run_mining_job

        from .oracle import random_baskets
        from .test_ops import table_from_baskets

        rng = np.random.default_rng(21)
        ds_dir = tmp_path / "datasets"
        ds_dir.mkdir()
        write_tracks_csv(
            str(ds_dir / "2023_spotify_ds1.csv"),
            table_from_baskets(
                random_baskets(rng, n_playlists=40, n_tracks=12, mean_len=5)
            ),
        )
        run_mining_job(MiningConfig(
            base_dir=str(tmp_path), datasets_dir=str(ds_dir),
            min_support=0.05, k_max_consequents=16,
            top_tracks_save_percentile=0.5,
        ))
        app = RecommendApp(ServingConfig(
            base_dir=str(tmp_path), polling_wait_in_minutes=60.0,
        ))
        assert app.engine.load()

        class DegradingBatcher:
            def submit(self, seeds, deadline=None):
                raise OverloadDegraded(0.8)

            def recommend(self, seeds, timeout=30.0, deadline=None):
                raise OverloadDegraded(0.8)

        app.batcher = DegradingBatcher()
        status, headers, payload = app.handle(
            "POST", "/api/recommend/", json.dumps({"songs": ["x"]}).encode()
        )
        assert status == 200
        assert headers.get("X-KMLS-Degraded") == "overload"
        assert json.loads(payload)["songs"]
        assert app.metrics.degraded_by_reason.get("overload", 0) == 1


class TestAdmissionController:
    """Unit coverage for the pressure ladder, the Retry-After jitter
    bounds, and the queue-wait EWMA's time decay."""

    def test_bands_admit_degrade_shed(self):
        ctrl = AdmissionController(
            1.0, soft_ratio=0.5, hard_ratio=2.0, rng=__import__(
                "random").Random(7),
        )
        decision, pressure = ctrl.decide(0.2)  # below soft
        assert decision == "admit" and pressure == 0.2
        assert ctrl.decide(5.0)[0] == "shed"   # past hard
        # mid-degrade band: over many draws, a MIX of admit and degrade,
        # never a shed
        mid = [ctrl.decide(0.75)[0] for _ in range(400)]
        assert set(mid) == {"admit", "degrade"}
        # between budget and hard: shed and degrade mix, never full admit
        upper = [ctrl.decide(1.5)[0] for _ in range(400)]
        assert set(upper) == {"degrade", "shed"}
        # probability ramps: deeper into the band sheds more often
        deep = [ctrl.decide(1.9)[0] for _ in range(400)]
        assert deep.count("shed") > upper.count("shed")

    def test_legacy_cliff_ratios(self):
        # soft=hard=1.0 reproduces the pre-controller cliff exactly
        ctrl = AdmissionController(1.0, soft_ratio=1.0, hard_ratio=1.0)
        assert ctrl.decide(0.999)[0] == "admit"
        assert ctrl.decide(1.0)[0] == "shed"

    def test_retry_after_jitter_bounded_and_varied(self):
        ctrl = AdmissionController(
            1.0, retry_after_s=1.0, retry_jitter=0.5,
            rng=__import__("random").Random(3),
        )
        draws = [ctrl.retry_after_jittered_s() for _ in range(200)]
        assert all(0.5 <= d <= 1.5 for d in draws)
        assert len({round(d, 3) for d in draws}) > 50, "jitter is constant"
        # jitter off restores the constant hint
        flat = AdmissionController(1.0, retry_after_s=2.0, retry_jitter=0.0)
        assert flat.retry_after_jittered_s() == 2.0

    def test_queue_wait_ewma_decays_after_burst(self):
        ctrl = AdmissionController(0.1, soft_ratio=0.5, hard_ratio=1.5)
        t0 = 100.0
        ctrl.note_queue_wait(0.5, now=t0)  # 5x the budget: hard overload
        assert ctrl.pressure(0.0, now=t0) > 1.5
        # with no new completions, time alone brings pressure back down
        # (half-life = max(budget, 0.25s))
        assert ctrl.pressure(0.0, now=t0 + 2.0) < ctrl.pressure(0.0, now=t0)
        assert ctrl.pressure(0.0, now=t0 + 30.0) < 0.05

    def test_pressure_zero_with_shedding_off(self):
        ctrl = AdmissionController(0.0)
        ctrl.note_queue_wait(10.0, now=1.0)
        assert ctrl.pressure(10.0, now=1.0) == 0.0

    def test_utilization_signal_rises_with_inflight(self):
        """The HPA signal: 0 idle, >0 with a batch in flight, and queue
        pressure lifts it past occupancy alone."""
        release = threading.Event()

        class GateEngine:
            def recommend_many_async(self, seed_sets):
                def finish():
                    release.wait(timeout=10.0)
                    return [(list(s), "rules") for s in seed_sets]

                return finish

        batcher = MicroBatcher(
            GateEngine(), max_size=2, window_ms=1.0, max_inflight=2,
            shed_queue_budget_ms=100.0,
        )
        assert batcher.utilization() == 0.0
        fut = batcher.submit(["x"])
        deadline = time.perf_counter() + 2.0
        while batcher.utilization() == 0.0 and time.perf_counter() < deadline:
            time.sleep(0.005)
        busy = batcher.utilization()
        assert busy > 0.0
        release.set()
        fut.result(timeout=5.0)


class TestAttributionMetrics:
    def test_metrics_render_attribution_summaries(self):
        m = ServingMetrics()
        m.record_attribution(
            queue_wait_s=0.002, device_s=0.004, e2e_s=0.006
        )
        m.record_shed()
        text = m.render(reload_counter=1, finished_loading=True)
        assert 'kmls_queue_wait_ms{quantile="0.99"} 2.0000' in text
        assert 'kmls_device_ms{quantile="0.5"} 4.0000' in text
        assert 'kmls_e2e_ms{quantile="0.999"} 6.0000' in text
        assert "kmls_requests_shed_total 1" in text

    def test_reset_clears_attribution_too(self):
        m = ServingMetrics()
        m.record("rules", 0.001)
        m.record_attribution(0.001, 0.002, 0.003)
        assert m.reset_latency() == 1
        text = m.render(reload_counter=0, finished_loading=True)
        assert 'kmls_queue_wait_ms{quantile="0.99"} 0.0000' in text
        assert "kmls_requests_total 1" in text  # counters stay cumulative

    def test_batcher_threads_timestamps_through(self, mined_pvc):
        cfg, _, _ = mined_pvc
        engine = RecommendEngine(cfg)
        assert engine.load()
        metrics = ServingMetrics()
        batcher = MicroBatcher(
            engine, max_size=8, window_ms=5.0, metrics=metrics
        )
        seeds = _rule_seeds(cfg)
        for s in seeds[:6]:
            batcher.recommend([s])
        (e2e50,) = metrics.e2e.percentiles(0.5)
        (dv50,) = metrics.device.percentiles(0.5)
        (qw50,) = metrics.queue_wait.percentiles(0.5)
        assert e2e50 > 0 and dv50 > 0
        assert qw50 >= 0
        # e2e ⊇ device ⊇ (most of) the pipeline: sanity ordering
        assert e2e50 >= dv50


class TestLoopbackNormalization:
    def test_ipv6_mapped_loopback_accepted(self, tmp_path):
        app = RecommendApp(ServingConfig(base_dir=str(tmp_path)))
        assert app.handle(
            "POST", "/metrics/reset", b"", client_host="::ffff:127.0.0.1"
        )[0] == 200
        assert app.handle(
            "POST", "/metrics/reset", b"", client_host="::1"
        )[0] == 200

    def test_mapped_non_loopback_still_rejected(self, tmp_path):
        app = RecommendApp(ServingConfig(base_dir=str(tmp_path)))
        assert app.handle(
            "POST", "/metrics/reset", b"", client_host="::ffff:10.2.3.4"
        )[0] == 403
        # 'localhost' never appears as a client_address value — dropped
        # from the allowlist (ADVICE r5 #3)
        assert app.handle(
            "POST", "/metrics/reset", b"", client_host="localhost"
        )[0] == 403


class TestNativeServeKernel:
    def test_native_matches_device_kernel_exactly(self, mined_pvc):
        """The native serve kernel must be bit-identical to the jitted
        device kernel — ids AND order (lax.top_k tie semantics), across
        random batches including unknown-seed rows."""
        from kmlserver_tpu.serving import native_serve

        if not native_serve.available():
            pytest.skip("native serve kernel unavailable (no toolchain)")
        cfg, _, _ = mined_pvc
        eng_native = RecommendEngine(cfg)
        assert eng_native.load()
        assert eng_native.bundle.host_rule_ids is not None
        assert eng_native.host_kernel_active
        eng_device = RecommendEngine(
            dataclasses.replace(cfg, native_serve=False)
        )
        assert eng_device.load()
        assert not eng_device.host_kernel_active
        vocab = eng_native.bundle.vocab
        rng = np.random.default_rng(3)
        for trial in range(20):
            n = int(rng.integers(1, 12))
            sets = []
            for _ in range(n):
                k = int(rng.integers(1, 6))
                s = [vocab[i] for i in rng.integers(0, len(vocab), k)]
                if rng.random() < 0.15:
                    s = [f"unknown-{trial}"]
                sets.append(s)
            got_n = eng_native.recommend_many(sets)
            got_d = eng_device.recommend_many(sets)
            assert got_n == got_d  # exact: same songs, same ORDER, same source

    def test_native_skips_warmup_and_never_compiles(self, mined_pvc):
        from kmlserver_tpu.ops import serve as serve_ops
        from kmlserver_tpu.serving import native_serve

        if not native_serve.available():
            pytest.skip("native serve kernel unavailable (no toolchain)")
        cfg, _, _ = mined_pvc
        counter = getattr(serve_ops.recommend_batch, "_cache_size", None)
        n0 = counter() if counter else None
        engine = RecommendEngine(cfg)
        assert engine.load()
        seeds = _rule_seeds(cfg)
        engine.recommend_many([[s] for s in seeds[:5]])
        engine.recommend(seeds[:2])
        if counter:
            assert counter() == n0  # the native path never touches the jit

    def test_kill_switch_falls_back_to_device_path(self, mined_pvc, monkeypatch):
        monkeypatch.setenv("KMLS_NATIVE", "0")
        cfg, _, _ = mined_pvc
        engine = RecommendEngine(cfg)
        assert engine.load()
        assert engine.bundle.host_rule_ids is None  # device path active
        seeds = _rule_seeds(cfg)
        recs, source = engine.recommend([seeds[0]])
        assert source in ("rules", "empty")


class TestAsyncMicroBatcher:
    class _InstantNativeEngine:
        host_kernel_active = True

        def __init__(self):
            self.batch_sizes = []

        def recommend_many_async(self, seed_sets):
            self.batch_sizes.append(len(seed_sets))

            def finish():
                return [(list(s), "rules") for s in seed_sets]

            return finish

    def test_inline_results_and_batching(self):
        import asyncio
        from kmlserver_tpu.serving.batcher import AsyncMicroBatcher

        async def scenario():
            engine = self._InstantNativeEngine()
            metrics = ServingMetrics()
            batcher = AsyncMicroBatcher(
                engine, max_size=8, window_ms=20.0, metrics=metrics
            )
            futures = [batcher.submit([f"s{i}"]) for i in range(8)]
            # the leader dispatches immediately (no rate evidence yet);
            # the rest coalesce into the scheduled window flush
            results = [await f for f in futures]
            assert [g for g, _ in results] == [[f"s{i}"] for i in range(8)]
            assert metrics.e2e.percentiles(0.5)[0] >= 0
            return engine

        engine = asyncio.run(scenario())
        assert sum(engine.batch_sizes) == 8
        assert len(engine.batch_sizes) <= 3, engine.batch_sizes
        assert max(engine.batch_sizes) >= 6  # aggregation actually happened

    def test_sparse_traffic_dispatches_immediately(self):
        import asyncio
        from kmlserver_tpu.serving.batcher import AsyncMicroBatcher

        async def scenario():
            engine = self._InstantNativeEngine()
            batcher = AsyncMicroBatcher(engine, max_size=8, window_ms=400.0)
            t0 = time.perf_counter()
            got, _ = await batcher.submit(["lone"])
            dt = time.perf_counter() - t0
            assert got == ["lone"]
            assert dt < 0.2, f"lone request waited {dt:.3f}s"

        asyncio.run(scenario())

    def test_shedding_raises_overloaded(self):
        import asyncio
        from kmlserver_tpu.serving.batcher import AsyncMicroBatcher

        class SlowEngine:
            host_kernel_active = False

            def recommend_many_async(self, seed_sets):
                def finish():
                    time.sleep(0.05)
                    return [(list(s), "rules") for s in seed_sets]

                return finish

        async def scenario():
            metrics = ServingMetrics()
            batcher = AsyncMicroBatcher(
                SlowEngine(), max_size=2, window_ms=1.0, max_inflight=1,
                shed_queue_budget_ms=60.0, metrics=metrics,
            )
            await batcher.submit(["warm"])  # teach the device-time EWMA
            futures = []
            sheds = degrades = 0
            for i in range(40):
                try:
                    futures.append(batcher.submit([f"s{i}"]))
                except Overloaded as exc:
                    # Retry-After carries bounded jitter: base 1s ± 50%
                    assert 0.5 <= exc.retry_after_s <= 1.5
                    sheds += 1
                except OverloadDegraded:
                    degrades += 1
            for f in futures:
                await f
            assert sheds > 0
            assert batcher.shed_total == sheds == metrics.shed_total
            assert batcher.degrade_total == degrades

        asyncio.run(scenario())

    def test_executor_path_matches_engine(self, mined_pvc):
        """Device-path (executor) flow end to end against the real
        engine, results exact vs the sync oracle."""
        import asyncio
        from kmlserver_tpu.serving.batcher import AsyncMicroBatcher

        cfg, _, _ = mined_pvc
        engine = RecommendEngine(dataclasses.replace(cfg, native_serve=False))
        assert engine.load()
        seeds = _rule_seeds(cfg)[:4]
        expected = {s: engine.recommend([s]) for s in seeds}

        async def scenario():
            batcher = AsyncMicroBatcher(engine, max_size=4, window_ms=5.0)
            futures = [batcher.submit([s]) for s in seeds]
            return [await f for f in futures]

        for (got, source), s in zip(asyncio.run(scenario()), seeds):
            assert set(got) == set(expected[s][0])
            assert source == expected[s][1]


class TestAsyncTransport:
    @pytest.fixture
    def served(self, mined_pvc):
        """The real aioserver on an ephemeral port, loop in a daemon
        thread (signal handlers are skipped off the main thread)."""
        import asyncio
        from kmlserver_tpu.serving.aioserver import run_async

        cfg, _, _ = mined_pvc
        app = RecommendApp(cfg, defer_batcher=True)
        app.engine.load()
        port_box: list[int] = []
        ready = threading.Event()

        def runner():
            asyncio.run(
                run_async(
                    app, 0,
                    ready=lambda p: (port_box.append(p), ready.set()),
                )
            )

        threading.Thread(target=runner, daemon=True).start()
        assert ready.wait(timeout=30)
        return app, port_box[0]

    def test_recommend_roundtrip_and_routes(self, served):
        import http.client

        app, port = served
        seeds = _rule_seeds(app.cfg)[:2]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request(
            "POST", "/api/recommend/",
            body=json.dumps({"songs": seeds}).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        data = json.loads(resp.read())
        assert resp.status == 200
        assert set(data) == {"songs", "model_date", "version"}
        single, _ = app.engine.recommend(seeds)
        assert set(data["songs"]) == set(single)
        for path, want in (
            ("/healthz", 200), ("/readyz", 200), ("/metrics", 200),
            ("/nope", 404),
        ):
            conn.request("GET", path)
            r = conn.getresponse()
            r.read()
            assert r.status == want, path
        conn.request(
            "POST", "/api/recommend/",
            body=json.dumps({"songs": []}).encode(),
        )
        r = conn.getresponse()
        r.read()
        assert r.status == 400

    def test_batcherless_mode_stays_responsive(self, mined_pvc):
        """KMLS_BATCH_WINDOW_MS=0 under the async transport: the blocking
        engine call must run off-loop — health probes stay live while a
        recommendation is in flight."""
        import asyncio
        import http.client
        from kmlserver_tpu.serving.aioserver import run_async

        cfg, _, _ = mined_pvc
        app = RecommendApp(
            dataclasses.replace(cfg, batch_window_ms=0.0), defer_batcher=True
        )
        app.engine.load()
        assert app.batcher is None
        port_box: list[int] = []
        ready = threading.Event()

        def runner():
            asyncio.run(
                run_async(
                    app, 0,
                    ready=lambda p: (port_box.append(p), ready.set()),
                )
            )

        threading.Thread(target=runner, daemon=True).start()
        assert ready.wait(timeout=30)
        port = port_box[0]
        seeds = _rule_seeds(app.cfg)[:2]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request(
            "POST", "/api/recommend/",
            body=json.dumps({"songs": seeds}).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        data = json.loads(resp.read())
        assert resp.status == 200 and data["songs"]
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        r.read()
        assert r.status == 200

    def test_pipelined_requests_answered_in_order(self, served):
        import socket

        app, port = served
        seeds = _rule_seeds(app.cfg)
        bodies = [json.dumps({"songs": [s]}).encode() for s in seeds[:3]]
        raw = b"".join(
            b"POST /api/recommend/ HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(b)).encode() + b"\r\n\r\n" + b
            for b in bodies
        )
        with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            s.sendall(raw)
            buf = b""
            responses = []
            while len(responses) < 3:
                chunk = s.recv(65536)
                assert chunk, "connection closed early"
                buf += chunk
                while True:
                    end = buf.find(b"\r\n\r\n")
                    if end < 0:
                        break
                    head = buf[:end]
                    clen = int(
                        [ln for ln in head.lower().split(b"\r\n")
                         if ln.startswith(b"content-length")][0].split(b":")[1]
                    )
                    if len(buf) < end + 4 + clen:
                        break
                    responses.append(
                        (int(head.split(b" ", 2)[1]),
                         buf[end + 4: end + 4 + clen])
                    )
                    buf = buf[end + 4 + clen:]
        for (status, body), seed in zip(responses, seeds[:3]):
            assert status == 200
            got = json.loads(body)["songs"]
            single, _ = app.engine.recommend([seed])
            assert set(got) == set(single)
